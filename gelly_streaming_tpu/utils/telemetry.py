"""Flight recorder: unified span/counter/gauge telemetry with a
crash-safe run ledger.

Three generations of ad-hoc instrumentation grew side by side —
`utils/tracing.StepTimer`, `ops/ingress_pipeline.StageTimers`,
`utils/resilience` event dicts, and raw perf_counter() spans in the
autotuned round loops — none sharing a schema, a correlation ID, or a
durable sink, so a wedged tunnel session still died as a "dead queue
hour" with no post-mortem evidence. This module is the ONE recorder
they all feed:

- **Spans** (named timed intervals with attributes), **events**
  (discrete happenings: demotions, injected faults, checkpoints,
  resumes), **counters** and **gauges** — every record carries the
  process-wide run *trace ID* plus whatever correlation attributes
  the caller binds (chunk index, window range), so a chaos run reads
  as one coherent timeline across the pipeline's threads.
- Span *nesting* is tracked per thread (a span opened inside another
  records its parent span id); cross-thread stages (the ingress prep
  pool) attach to their chunk span via an explicit ctx handle instead
  (`chunk_ctx`/`close_chunk` — thread-locals do not cross the pool).
- A bounded in-memory **ring buffer** (`GS_TRACE_RING`, default 4096
  records) holds the recent history at near-zero cost.
- A **crash-safe JSONL ledger** (`GS_TRACE_DIR`): durable-class
  events (fault kills, demotions, stage timeouts, checkpoints,
  resumes) are appended AND fsync'd the moment they close
  (`GS_TRACE_DURABLE=0` drops the fsync); ordinary spans ride the
  ring and are flushed by `flush()`, `atexit`, a fatal injected
  fault (utils/faults hooks `on_fatal`), or SIGTERM — so a
  kill-adjacent wedge still leaves the last N spans on disk. The
  ledger is append-only with one JSON object per line; readers
  (tools/trace_report.py) skip a torn final line, the same
  damage-tolerant discipline as utils/checkpoint.

Zero-overhead contract: with `GS_TELEMETRY=0` (the default) every
recording call is a guarded no-op and `span()` degrades to a bare
perf_counter stopwatch — exactly the measurement the migrated call
sites performed before — so the hot path is bit-identical armed or
not (asserted by tests/test_telemetry.py digest parity).

Knobs:
    GS_TELEMETRY      0 (default) = disarmed no-ops; 1 = record
    GS_TRACE_DIR      ledger directory (unset = ring only, no disk)
    GS_TRACE_RING     ring capacity in records (default 4096)
    GS_TRACE_DURABLE  1 (default) = fsync durable-class appends
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

from . import knobs

_SAMPLE_CAP = 2048  # per-span-name duration reservoir for summary()

clock = time.perf_counter  # the one monotonic clock every record uses


# ----------------------------------------------------------------------
# env knobs (read per call through the utils/knobs registry: tests and
# tools flip them mid-process)
# ----------------------------------------------------------------------
def enabled() -> bool:
    """GS_TELEMETRY arms the recorder; off (the default) every hook is
    a guarded no-op and span() is a bare stopwatch."""
    return knobs.get_bool("GS_TELEMETRY")


def trace_dir() -> Optional[str]:
    """Ledger directory (GS_TRACE_DIR); None = ring only."""
    return knobs.get_path("GS_TRACE_DIR")


def ring_size() -> int:
    return knobs.get_int("GS_TRACE_RING")


def durable_sync() -> bool:
    """GS_TRACE_DURABLE=0 drops the per-durable-event fsync (append
    still happens; only the power-loss window widens)."""
    return knobs.get_bool("GS_TRACE_DURABLE")


# ----------------------------------------------------------------------
# the process-global recorder
# ----------------------------------------------------------------------
class _Recorder:
    """All mutable state behind one lock: the ring, the per-name
    aggregates summary() renders, the ledger file, and the id
    counters. One instance per process (rebuilt by reset())."""

    def __init__(self):
        self.lock = threading.RLock()
        self.trace = "%x-%x" % (os.getpid(),
                                int(time.time() * 1e3) & 0xFFFFFFFF)
        self.epoch = time.time()
        self.mono = clock()
        self.ring = collections.deque(maxlen=ring_size())
        self.next_sid = 1
        self.agg: Dict[str, dict] = {}
        self.counters: Dict[str, float] = {}
        self.gauges: Dict[str, float] = {}
        self.ledger = None        # open file object, lazily created
        self.ledger_path = None
        self.ledger_failed = False  # sticky: disk broke, stop trying

    # -- ledger --------------------------------------------------------
    def _ensure_ledger(self):
        """Open (once) the append-only JSONL ledger under
        GS_TRACE_DIR, writing the meta anchor line readers use to map
        monotonic span timestamps back to wall time."""
        if self.ledger is not None:
            return self.ledger
        if self.ledger_failed:
            return None
        d = trace_dir()
        if d is None:
            return None
        # an unwritable/full trace dir degrades to ring-only recording:
        # the flight recorder must never take down the stream it traces
        try:
            os.makedirs(d, exist_ok=True)
            self.ledger_path = os.path.join(
                d, "trace_%s.jsonl" % self.trace)
            self.ledger = open(self.ledger_path, "a")
            self.ledger.write(json.dumps({
                "t": "meta", "trace": self.trace, "pid": os.getpid(),
                "epoch": self.epoch, "mono": self.mono,
                "ring": self.ring.maxlen}) + "\n")
            self.ledger.flush()
        except OSError:
            self._ledger_broke()
            return None
        _install_exit_hooks()
        return self.ledger

    def _ledger_broke(self) -> None:
        self.ledger_failed = True
        self.ledger_path = None
        if self.ledger is not None:
            try:
                self.ledger.close()
            except OSError:
                pass
            self.ledger = None

    def _append(self, rec: dict, sync: bool) -> None:
        f = self._ensure_ledger()
        if f is None:
            return
        try:
            f.write(json.dumps(rec, default=str) + "\n")
            rec["_w"] = True  # private written mark, stripped on flush
            if sync:
                f.flush()
                if durable_sync():
                    try:
                        os.fsync(f.fileno())
                    except OSError:
                        pass
        except OSError:
            self._ledger_broke()

    def flush(self) -> None:
        """Drain every not-yet-written ring record to the ledger (the
        atexit / fatal-fault / operator path)."""
        with self.lock:
            f = self._ensure_ledger()
            if f is None:
                return
            try:
                for rec in self.ring:
                    if not rec.get("_w"):
                        f.write(json.dumps(
                            {k: v for k, v in rec.items() if k != "_w"},
                            default=str) + "\n")
                        rec["_w"] = True
                f.flush()
                try:
                    os.fsync(f.fileno())
                except OSError:
                    pass
            except OSError:
                self._ledger_broke()

    # -- recording -----------------------------------------------------
    def add(self, rec: dict, durable: bool = False) -> None:
        with self.lock:
            self.ring.append(rec)
            if rec["t"] == "span":
                a = self.agg.setdefault(rec["name"], {
                    "count": 0, "total": 0.0,
                    "samples": collections.deque(maxlen=_SAMPLE_CAP)})
                a["count"] += 1
                a["total"] += rec["dur"]
                a["samples"].append(rec["dur"])
            elif rec["t"] == "counter":
                self.counters[rec["name"]] = (
                    self.counters.get(rec["name"], 0) + rec["value"])
            elif rec["t"] == "gauge":
                self.gauges[rec["name"]] = rec["value"]
            if durable:
                self._append(rec, sync=True)

    def sid(self) -> int:
        with self.lock:
            s = self.next_sid
            self.next_sid += 1
            return s


_REC: Optional[_Recorder] = None
_REC_LOCK = threading.Lock()
_TLS = threading.local()
_HOOKS_INSTALLED = False

# Downstream consumers of the record stream (the metrics registry,
# utils/metrics.py): each entry is (sink_fn, active_fn). A sink sees
# every record the hooks produce while ITS active_fn says so, even
# with GS_TELEMETRY=0 — the flight-recorder hooks are the one
# instrumentation surface every layer already feeds, so the metrics
# plane rides them instead of duplicating call sites. With telemetry
# AND every sink disarmed the hooks stay guarded no-ops.
_SINKS: List[tuple] = []


def register_sink(sink, active) -> None:
    """Attach `sink(record_dict)` to the record stream, consulted
    while `active()` is true. Idempotent per (sink, active) pair."""
    with _REC_LOCK:
        if (sink, active) not in _SINKS:
            _SINKS.append((sink, active))


def _sinks_active() -> bool:
    for _fn, active in _SINKS:
        if active():
            return True
    return False


def _active() -> bool:
    """True when anything consumes records: the recorder itself
    (GS_TELEMETRY) or an armed sink (the metrics registry)."""
    return enabled() or _sinks_active()


def _rec() -> _Recorder:
    global _REC
    if _REC is None:
        with _REC_LOCK:
            if _REC is None:
                _REC = _Recorder()
    return _REC


def _install_exit_hooks() -> None:
    """atexit + SIGTERM flush, installed once on first ledger open (a
    ring-only recorder has nothing to save). SIGTERM chains any prior
    handler; SIGKILL is of course uncatchable — the durable-class
    immediate appends are what bound that loss to the ring."""
    global _HOOKS_INSTALLED
    if _HOOKS_INSTALLED:
        return
    _HOOKS_INSTALLED = True
    atexit.register(flush)
    try:
        import signal

        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            flush()
            # preserve the prior disposition EXACTLY: chain a callable
            # handler, die the default way for SIG_DFL, and keep the
            # process alive when it deliberately ignored SIGTERM
            # (SIG_IGN / unknown) — the flush must never change
            # whether SIGTERM is survivable
            if callable(prev):
                prev(signum, frame)
            elif prev is signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass  # non-main thread / exotic platform: atexit still covers


def reset() -> None:
    """Test/tool hook: drop all recorded state and start a fresh trace
    (closes the current ledger; a new one opens on the next record)."""
    global _REC
    with _REC_LOCK:
        if _REC is not None and _REC.ledger is not None:
            try:
                _REC.flush()
                _REC.ledger.close()
            except (OSError, ValueError):
                pass
        _REC = None
    _TLS.__dict__.clear()


def trace_id() -> str:
    """The process-wide run trace ID every record carries."""
    return _rec().trace


def ledger_path() -> Optional[str]:
    """Path of this run's ledger file (None when GS_TRACE_DIR is
    unset or nothing has been recorded to disk yet)."""
    r = _rec()
    if r.ledger_path is None and trace_dir() is not None:
        with r.lock:
            r._ensure_ledger()
    return r.ledger_path


def flush() -> None:
    """Drain the ring to the ledger (no-op without GS_TRACE_DIR)."""
    if _REC is not None:
        _REC.flush()


# ----------------------------------------------------------------------
# context / correlation
# ----------------------------------------------------------------------
def _ctx_attrs() -> dict:
    return getattr(_TLS, "ctx", None) or {}


def _parent_sid() -> Optional[int]:
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def context(**attrs):
    """Bind correlation attributes (chunk=..., window=...) to every
    record made by THIS thread inside the scope; explicit per-record
    attrs win on collision. Thread-local — pool workers need their
    chunk identity passed explicitly (see chunk_ctx)."""
    prev = getattr(_TLS, "ctx", None)
    merged = dict(prev or {})
    merged.update(attrs)
    _TLS.ctx = merged
    try:
        yield
    finally:
        _TLS.ctx = prev


def _record(kind: str, name: str, durable: bool = False,
            **fields) -> Optional[dict]:
    rec = {"t": kind, "name": name, "trace": _rec().trace,
           "tid": threading.get_ident()}
    ctx = _ctx_attrs()
    if ctx:
        a = dict(ctx)
        a.update(fields.pop("a", None) or {})
        fields["a"] = a
    rec.update({k: v for k, v in fields.items() if v is not None})
    if not rec.get("a"):
        rec.pop("a", None)
    if enabled():
        if not _HOOKS_INSTALLED and trace_dir() is not None:
            # a ledger-destined run must flush its ring at exit even if
            # no durable event ever opens the file earlier
            _install_exit_hooks()
        _rec().add(rec, durable=durable)
    dropped = []
    for sink, active in list(_SINKS):
        if active():
            try:
                sink(rec)
            except Exception as exc:  # gslint: disable=except-hygiene (a broken metrics sink must never take down the stream it observes; it is dropped from the record path with a durable marker below)
                with _REC_LOCK:
                    if (sink, active) in _SINKS:
                        _SINKS.remove((sink, active))
                        dropped.append(exc)
    for exc in dropped:
        # the armed plane going dark must leave a visible scar, not
        # silently freeze its gauges: stamp a durable event (the
        # failed sink is already removed, so this re-entry terminates)
        # AND a registry counter — with GS_TELEMETRY=0 the event
        # no-ops (no ledger), but /metrics still shows the drop
        event("metrics_sink_dropped", durable=True,
              error=repr(exc)[:200])
        try:
            from . import metrics as _metrics

            _metrics.counter_inc("gs_metrics_sink_dropped_total")
        except Exception:  # gslint: disable=except-hygiene (the scar write itself must never take down the record path it marks)
            pass
    return rec


# ----------------------------------------------------------------------
# spans
# ----------------------------------------------------------------------
class _Span:
    """Context manager AND stopwatch. Always measures (callers like
    the autotune round loops need `.elapsed` whether or not telemetry
    is armed); records only when armed at __exit__ time. Nesting is
    tracked per thread via the span-id stack."""

    __slots__ = ("name", "attrs", "t0", "elapsed", "sid", "_pushed")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.t0 = clock()
        self.elapsed = 0.0
        self.sid = None
        self._pushed = False

    def __enter__(self):
        self.t0 = clock()
        if enabled():
            self.sid = _rec().sid()
            stack = getattr(_TLS, "stack", None)
            if stack is None:
                stack = _TLS.stack = []
            stack.append(self.sid)
            self._pushed = True
        return self

    def __exit__(self, exc_type, exc, tb):
        self.elapsed = clock() - self.t0
        if self._pushed:
            _TLS.stack.pop()
            self._pushed = False
        if _active():
            par = _parent_sid()
            a = dict(self.attrs) if self.attrs else {}
            if exc_type is not None:
                a["error"] = exc_type.__name__
            _record("span", self.name, ts=self.t0, dur=self.elapsed,
                    sid=self.sid, par=par, a=a or None)
        return False


def span(name: str, **attrs) -> _Span:
    """A named span: `with telemetry.span("step.intern", records=n)
    as sp: ...`; sp.elapsed holds the measured seconds either way."""
    return _Span(name, attrs)


class _Stopwatch:
    """Deferred span: started at construction, recorded by stop() —
    for intervals that cross scopes (the driver's dispatch-to-dispatch
    autotune rounds). Unstopped stopwatches record nothing."""

    __slots__ = ("name", "attrs", "t0", "_done")

    def __init__(self, name: Optional[str], attrs: dict):
        self.name = name
        self.attrs = attrs
        self.t0 = clock()
        self._done = False

    def stop(self, **extra) -> float:
        """Close the interval; returns elapsed seconds (idempotent —
        later calls return the first measurement without
        re-recording)."""
        if self._done:
            return self.attrs.get("_elapsed", 0.0)
        self._done = True
        elapsed = clock() - self.t0
        self.attrs["_elapsed"] = elapsed
        if self.name is not None and _active():
            a = dict(self.attrs)
            a.pop("_elapsed", None)
            a.update(extra)
            _record("span", self.name, ts=self.t0, dur=elapsed,
                    sid=_rec().sid() if enabled() else None,
                    par=_parent_sid(), a=a or None)
        return elapsed


def stopwatch(name: Optional[str] = None, **attrs) -> _Stopwatch:
    return _Stopwatch(name, attrs)


def record_span(name: str, t0: float, dur: float,
                parent: Optional[int] = None,
                sid: Optional[int] = None, **attrs) -> None:
    """Record an already-measured interval (the worker-side ingress
    stages time themselves and report after the fact)."""
    if not _active():
        return
    if sid is None and enabled():
        sid = _rec().sid()
    _record("span", name, ts=t0, dur=dur, sid=sid,
            par=parent if parent is not None else _parent_sid(),
            a=attrs or None)


# -- program-signature dispatch tags (the cost observatory) ------------
def tag_dispatch(**tags) -> None:
    """Bind program-identity attributes (program=..., sig=...) to THIS
    thread's next dispatch-span record. Set by the dispatch wrappers
    (utils/costmodel via metrics.wrap_jit / costmodel.wrap_exec, which
    run INSIDE the dispatch call), consumed by the dispatch-span
    record sites (ops/ingress_pipeline, the driver's snapshot-scan
    step) via pop_dispatch_tags — so ledger spans carry the program
    and abstract-shape signature the cost registry is keyed by."""
    _TLS.dispatch_tags = tags


def pop_dispatch_tags() -> dict:
    """Take (and clear) the pending dispatch tags of this thread; {}
    when none are bound. Cheap enough for disarmed hot paths: one
    thread-local read."""
    tags = getattr(_TLS, "dispatch_tags", None)
    if tags is None:
        return {}
    _TLS.dispatch_tags = None
    return tags


# -- cross-thread chunk correlation (the ingress pipeline) -------------
def chunk_ctx(chunk) -> Optional[dict]:
    """Open a chunk span handle the pool workers can parent their
    stage spans to (thread-local nesting cannot cross the pool). The
    span itself is recorded by close_chunk once the chunk's finalize
    lands."""
    if not enabled():
        return None
    return {"sid": _rec().sid(), "chunk": chunk, "t0": clock()}


def close_chunk(ctx: Optional[dict], **attrs) -> None:
    if ctx is None or not enabled():
        return
    _record("span", "ingress.chunk", ts=ctx["t0"],
            dur=clock() - ctx["t0"], sid=ctx["sid"],
            par=_parent_sid(),
            a=dict(attrs, chunk=ctx["chunk"]))


def chunk_key(item):
    """A compact correlation id for a pipeline chunk descriptor:
    ints (window starts) pass through; anything else is opaque."""
    import numbers

    if isinstance(item, numbers.Integral):
        return int(item)
    if isinstance(item, tuple) and item \
            and isinstance(item[0], numbers.Integral):
        return int(item[0])
    return None


# ----------------------------------------------------------------------
# events / counters / gauges
# ----------------------------------------------------------------------
def event(name: str, durable: bool = False, **attrs) -> None:
    """A discrete happening. durable=True appends + fsyncs the record
    to the ledger immediately (demotions, kills, checkpoints, resumes
    — the post-mortem class that must survive a wedge)."""
    if not _active():
        return
    _record("event", name, ts=clock(), durable=durable,
            a=attrs or None)


def counter(name: str, value: float = 1, **attrs) -> None:
    if not _active():
        return
    _record("counter", name, ts=clock(), value=value, a=attrs or None)


def gauge(name: str, value: float, **attrs) -> None:
    if not _active():
        return
    _record("gauge", name, ts=clock(), value=value, a=attrs or None)


def on_fatal(site: str = "") -> None:
    """The simulated-hard-kill hook (utils/faults fatal InjectedFault):
    stamp a durable event and flush the ring, so the post-kill ledger
    still holds the pre-kill spans — the flight-recorder contract
    tools/chaos_run.py asserts end-to-end."""
    if not enabled():
        return
    event("fatal", durable=True, site=site)
    flush()


# ----------------------------------------------------------------------
# aggregation (PERF.json `telemetry` section; shared histogram math)
# ----------------------------------------------------------------------
def percentiles(samples, ps=(50, 95, 99)) -> Dict[int, float]:
    """Nearest-rank percentiles over `samples` (exact, no
    interpolation: the p-th percentile is the ceil(p/100*n)-th
    smallest sample) — the one histogram definition the recorder,
    tools/trace_report.py, and the tests all share."""
    xs = sorted(samples)
    if not xs:
        return {p: 0.0 for p in ps}
    n = len(xs)
    out = {}
    for p in ps:
        rank = max(1, -(-p * n // 100))  # ceil(p*n/100), 1-based
        out[p] = float(xs[min(rank, n) - 1])
    return out


def summary(top: int = 0) -> List[dict]:
    """Per-span-name latency rows (count, total, p50/p95/p99 over the
    bounded sample reservoir), sorted by total time — the
    schema-validated `telemetry` section tools commit to PERF.json."""
    r = _rec()
    with r.lock:
        rows = []
        for name, a in r.agg.items():
            pct = percentiles(a["samples"])
            rows.append({
                "span": name,
                "count": a["count"],
                "total_ms": round(a["total"] * 1e3, 3),
                "p50_ms": round(pct[50] * 1e3, 3),
                "p95_ms": round(pct[95] * 1e3, 3),
                "p99_ms": round(pct[99] * 1e3, 3),
            })
    rows.sort(key=lambda x: -x["total_ms"])
    return rows[:top] if top else rows


def records() -> List[dict]:
    """Snapshot of the ring (tests / diagnostics), private marks
    stripped."""
    r = _rec()
    with r.lock:
        return [{k: v for k, v in rec.items() if k != "_w"}
                for rec in r.ring]

"""Program cost observatory — the attribution pillar of the
observability plane (tracing → metrics → **attribution**).

The flight recorder (utils/telemetry.py) says *when* time was spent
and the health plane (utils/metrics.py) says *how much right now*;
neither says *why* the device path sits at ~1M edges/s behind the
dispatch wall. This module closes that gap: for every compiled
program the streaming layers dispatch — the fused scan, its compact
twin, the resident super-batch, the snapshot scan, the triangle
stream programs, the sharded table-mode stream — it captures XLA's
own compiled cost model (`cost_analysis()`: FLOPs, bytes accessed;
`memory_analysis()`: argument/output/temp bytes) keyed by the same
abstract-shape signature the compile watch (metrics.wrap_jit) already
counts compiles by, and joins it with the measured dispatch spans the
flight recorder collects, yielding per program per shape:

- a **bytes-vs-FLOPs boundedness verdict**: arithmetic intensity
  (FLOPs/byte) against the machine balance (peak FLOP/s ÷ peak B/s) —
  below balance the roofline says the program is bytes-bound, above
  it FLOPs-bound;
- an **achieved-vs-roofline fraction**: the roofline-implied minimum
  seconds per dispatch (max of FLOPs/peak and bytes/bandwidth) over
  the measured mean dispatch seconds — a small fraction means the
  time went somewhere the cost model doesn't see (launch overhead,
  host sync, transfer), which is exactly the drill-down
  tools/explain_perf.py ranks suspects for.

Capture paths:

- jit-path programs (wrapped by `metrics.wrap_jit`) call `on_call`
  per dispatch: the FIRST call at a new signature AOT-lowers and
  compiles the function once more to read its analyses (jit's
  internal cache is not reachable from the outside; the extra
  compile is the armed price, documented on GS_COSTMODEL), then every
  call tags the current thread's pending dispatch-span attributes
  (telemetry.tag_dispatch) so the ledger's `ingress.dispatch` /
  `step.snapshot_scan` spans carry `program`/`sig`.
- AOT-path programs (triangles/sharded `_stream_exec`, which already
  hold the compiled executable) are wrapped by `wrap_exec`: capture
  is FREE there (the analyses are read off the existing executable).

A telemetry sink (the same `register_sink` mechanism the metrics
plane rides) accumulates measured seconds per tagged program, so
`report()` serves joined rows live; tools/explain_perf.py performs
the same join offline against a run ledger.

Zero-overhead contract: with `GS_COSTMODEL=0` (the default) every
entry point is a guarded no-op, no tags are bound, and the hot path
is bit-identical — asserted by tests/test_costmodel.py digest parity
on the 524K/32768 CPU row.

Knobs (utils/knobs.py):
    GS_COSTMODEL              0 (default) = disarmed no-ops; 1 = capture
    GS_COSTMODEL_PEAK_GFLOPS  compute roofline peak (GFLOP/s)
    GS_COSTMODEL_PEAK_GBPS    memory-bandwidth roofline peak (GB/s)
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from . import knobs
from . import telemetry


def enabled() -> bool:
    """GS_COSTMODEL arms the observatory; off (the default) every
    entry point — including the telemetry sink — is a guarded no-op."""
    return knobs.get_bool("GS_COSTMODEL")


def peaks() -> Tuple[float, float]:
    """(peak FLOP/s, peak bytes/s) of the roofline the verdicts are
    computed against (GS_COSTMODEL_PEAK_GFLOPS/_GBPS)."""
    return (knobs.get_float("GS_COSTMODEL_PEAK_GFLOPS") * 1e9,
            knobs.get_float("GS_COSTMODEL_PEAK_GBPS") * 1e9)


# ----------------------------------------------------------------------
# signature rendering (the join key the ledger tags carry)
# ----------------------------------------------------------------------
_DTYPE_ABBR = {
    "int32": "i32", "int64": "i64", "uint16": "u16", "uint32": "u32",
    "float32": "f32", "float64": "f64", "bfloat16": "bf16",
    "bool": "b1", "bool_": "b1", "int8": "i8", "uint8": "u8",
}


def _render_leaf(leaf) -> str:
    if isinstance(leaf, tuple) and leaf:
        if leaf[0] == "arr":
            _tag, shape, dtype = leaf
            return "%s[%s]" % (_DTYPE_ABBR.get(dtype, dtype),
                               ",".join(str(d) for d in shape))
        if leaf[0] == "seq":
            return "(%s)" % ",".join(_render_leaf(e) for e in leaf[1:])
        if leaf[0] == "map":
            return "{%s}" % ",".join(
                "%s=%s" % (k, _render_leaf(v)) for k, v in leaf[1:])
        if leaf[0] == "py":
            return leaf[1]
    return str(leaf)


def sig_key(sig: tuple) -> str:
    """Compact deterministic string of a `metrics.abstract_sig`
    signature — the `sig` attribute dispatch spans carry and the
    cost-registry rows are keyed by (e.g.
    ``i32[64,32768],i32[64,32768],b1[64,32768]``)."""
    return ",".join(_render_leaf(leaf) for leaf in sig)


def _sig_bytes(sig) -> int:
    """Total argument bytes under one abstract signature (used only
    as a fallback when memory_analysis is unavailable)."""
    import numpy as np

    if not isinstance(sig, tuple):
        return 0
    if sig and sig[0] == "arr":
        n = 1
        for d in sig[1]:
            n *= max(int(d), 1)
        try:
            return n * np.dtype(sig[2]).itemsize
        except TypeError:
            return n
    return sum(_sig_bytes(s) for s in sig)


# ----------------------------------------------------------------------
# the process-global registry
# ----------------------------------------------------------------------
class _Registry:
    """All mutable state behind one lock. One instance per process
    (rebuilt by reset())."""

    def __init__(self):
        self.lock = threading.RLock()
        # (program, sig_key) -> cost entry dict
        self.programs: Dict[Tuple[str, str], dict] = {}
        # (program, sig_key) -> {"count": n, "total_s": s} measured
        # dispatch spans (fed by the telemetry sink)
        self.dispatches: Dict[Tuple[str, str], dict] = {}
        # program -> analytic cost template (record_analytic): for
        # these programs the compiler's introspection is KNOWN wrong
        # (a Pallas kernel's interpret lowering, an opaque Mosaic
        # binary), so per-signature capture instantiates the stated
        # model instead of compiling for analyses
        self.analytic: Dict[str, dict] = {}


_REG: Optional[_Registry] = None
_REG_LOCK = threading.Lock()


def _reg() -> _Registry:
    global _REG
    if _REG is None:
        with _REG_LOCK:
            if _REG is None:
                _REG = _Registry()
    return _REG


def reset() -> None:
    """Test/tool hook: drop every captured program and measurement."""
    global _REG
    with _REG_LOCK:
        _REG = None


# ----------------------------------------------------------------------
# capture
# ----------------------------------------------------------------------
def _extract(compiled) -> dict:
    """FLOPs/bytes entry from one AOT-compiled executable's
    cost_analysis()/memory_analysis() (None fields where the backend
    doesn't report them)."""
    out = {"flops": None, "bytes_accessed": None,
           "argument_bytes": None, "output_bytes": None,
           "temp_bytes": None, "generated_code_bytes": None}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            flops = ca.get("flops")
            bts = ca.get("bytes accessed")
            out["flops"] = None if flops is None else int(flops)
            out["bytes_accessed"] = None if bts is None else int(bts)
    except Exception as e:  # gslint: disable=except-hygiene (capability probe: a backend without cost_analysis contributes None fields; the miss is visible in the entry itself)
        out["cost_analysis_error"] = "%s: %s" % (type(e).__name__, e)
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            for field, attr in (
                    ("argument_bytes", "argument_size_in_bytes"),
                    ("output_bytes", "output_size_in_bytes"),
                    ("temp_bytes", "temp_size_in_bytes"),
                    ("generated_code_bytes",
                     "generated_code_size_in_bytes")):
                val = getattr(ma, attr, None)
                out[field] = None if val is None else int(val)
    except Exception as e:  # gslint: disable=except-hygiene (capability probe: memory_analysis is backend-optional; the miss is visible in the entry itself)
        out["memory_analysis_error"] = "%s: %s" % (type(e).__name__, e)
    return out


def classify(entry: dict) -> dict:
    """Attach the roofline verdict to one cost entry IN PLACE:
    arithmetic intensity, the machine balance it is judged against,
    the bytes/FLOPs `bound` verdict, and the roofline-implied minimum
    seconds per dispatch. Entries without both FLOPs and bytes get
    verdict `unknown` — with the flops/bytes keys still PRESENT
    (null), so every row classify() touches satisfies the committed
    cost_model schema's required keys (error-path and
    armed-mid-stream rows included: "not reported" must stay
    distinguishable from "silently dropped")."""
    entry.setdefault("flops", None)
    entry.setdefault("bytes_accessed", None)
    flops, bts = entry.get("flops"), entry.get("bytes_accessed")
    peak_f, peak_b = peaks()
    entry["machine_balance_flops_per_byte"] = round(peak_f / peak_b, 3)
    if flops and bts:
        intensity = flops / bts
        entry["arith_intensity_flops_per_byte"] = round(intensity, 4)
        entry["bound"] = ("bytes" if intensity < peak_f / peak_b
                          else "flops")
        entry["roofline_s"] = max(flops / peak_f, bts / peak_b)
    else:
        entry["arith_intensity_flops_per_byte"] = None
        entry["bound"] = "unknown"
        entry["roofline_s"] = None
    return entry


def record_compiled(program: str, compiled, sig: tuple) -> None:
    """Register the cost model of an already-AOT-compiled executable
    (the triangles/sharded `_stream_exec` caches) under
    (program, sig). Idempotent per key; armed only."""
    if not enabled():
        return
    key = (program, sig_key(sig))
    reg = _reg()
    with reg.lock:
        if key in reg.programs:
            return
        # reserve the key before the (lock-free) extraction so a
        # concurrent dispatcher never double-captures
        reg.programs[key] = {"program": program, "sig": key[1],
                             "pending": True}
    if _instantiate_analytic(reg, key):
        return
    entry = _extract(compiled)
    entry.update(program=program, sig=key[1])
    classify(entry)
    with reg.lock:
        reg.programs[key] = entry
    telemetry.event("costmodel.capture", program=program, sig=key[1],
                    flops=entry.get("flops"),
                    bytes_accessed=entry.get("bytes_accessed"),
                    bound=entry.get("bound"))


def record_analytic(program: str, sig_text: str, flops,
                    bytes_accessed, **extra) -> None:
    """Register a hand-computed cost entry for a program XLA's
    introspection can't see through — the Pallas window megakernel:
    its interpret-mode lowering bears no relation to the chip
    kernel's HBM traffic, and a Mosaic executable exposes no
    cost_analysis — under the same registry/joins as captured
    entries. Rows carry model="analytic" (plus whatever `extra`
    provenance the caller stamps, e.g. the slab-read byte model) so
    a reader can tell a stated model from a compiler measurement.

    Registers TWICE: the documentation row under the caller's
    free-text `sig_text`, AND a program-level TEMPLATE that
    on_call/record_compiled instantiate at each dispatch signature —
    so the ledger spans wrap_jit tags (keyed by the abstract-shape
    sig) join the STATED model, never a capture the registrant just
    declared meaningless (and the armed extra-compile is skipped for
    these programs). Idempotent per (program, sig); armed only."""
    if not enabled():
        return
    key = (program, str(sig_text))
    reg = _reg()
    with reg.lock:
        if key in reg.programs:
            return
        reg.programs[key] = {"program": program, "sig": key[1],
                             "pending": True}
    entry = {"program": program, "sig": key[1], "model": "analytic",
             "flops": None if flops is None else int(flops),
             "bytes_accessed": (None if bytes_accessed is None
                                else int(bytes_accessed))}
    entry.update(extra)
    classify(entry)
    with reg.lock:
        reg.programs[key] = entry
        reg.analytic[program] = {
            k: v for k, v in entry.items() if k != "sig"}
    telemetry.event("costmodel.capture", program=program, sig=key[1],
                    flops=entry.get("flops"),
                    bytes_accessed=entry.get("bytes_accessed"),
                    bound=entry.get("bound"), model="analytic")


def _instantiate_analytic(reg, key: Tuple[str, str]) -> bool:
    """If `key[0]` has an analytic template, store its instance at
    `key` (the dispatch signature the spans carry) and return True —
    the capture paths then skip compiler introspection entirely."""
    with reg.lock:
        template = reg.analytic.get(key[0])
        if template is None:
            return False
        if key not in reg.programs or \
                reg.programs[key].get("pending"):
            entry = dict(template)
            entry["sig"] = key[1]
            reg.programs[key] = entry
    return True


def on_call(program: str, fn, sig: tuple, args, kwargs) -> None:
    """Per-dispatch hook of a jit-path program (called by
    metrics.wrap_jit with the signature it already computed): tag the
    pending dispatch-span attributes, and on the FIRST call at a new
    signature capture the program's cost model by AOT-lowering and
    compiling `fn` once more (the armed price — jit's internal
    executable cache is not reachable)."""
    if not enabled():
        return
    key = (program, sig_key(sig))
    telemetry.tag_dispatch(program=program, sig=key[1])
    reg = _reg()
    with reg.lock:
        if key in reg.programs:
            return
        reg.programs[key] = {"program": program, "sig": key[1],
                             "pending": True}
    if _instantiate_analytic(reg, key):
        # a stated-model program: no extra AOT compile, the spans
        # join the analytic entry at this very signature
        return
    lower = getattr(fn, "lower", None)
    if lower is None:
        entry = {"program": program, "sig": key[1],
                 "error": "not AOT-lowerable (no .lower)"}
        classify(entry)
        with reg.lock:
            reg.programs[key] = entry
        return
    try:
        compiled = lower(*args, **kwargs).compile()
        entry = _extract(compiled)
    except Exception as e:
        entry = {"error": "%s: %s" % (type(e).__name__, str(e)[:200])}
        telemetry.event("costmodel.capture_failed", program=program,
                        sig=key[1], error=entry["error"])
    entry.update(program=program, sig=key[1])
    classify(entry)
    with reg.lock:
        reg.programs[key] = entry
    if "error" not in entry:
        telemetry.event("costmodel.capture", program=program,
                        sig=key[1], flops=entry.get("flops"),
                        bytes_accessed=entry.get("bytes_accessed"),
                        bound=entry.get("bound"))


def wrap_exec(program: str, ex, sig: tuple):
    """Wrap an AOT-compiled executable: armed, each call tags the
    pending dispatch-span attributes and the first call registers the
    executable's cost model (free — no recompile). Disarmed the
    wrapper is one knob check + passthrough, and arming mid-stream
    still captures (the compiled handle rides the closure)."""

    def wrapped(*args, **kwargs):
        if enabled():
            record_compiled(program, ex, sig)
            telemetry.tag_dispatch(program=program, sig=sig_key(sig))
        return ex(*args, **kwargs)

    wrapped.__name__ = program
    wrapped.__wrapped__ = ex
    return wrapped


# ----------------------------------------------------------------------
# the telemetry sink: measured dispatch spans tagged with program/sig
# accumulate here, so report() serves the live join
# ----------------------------------------------------------------------
def _sink(rec: dict) -> None:
    if rec.get("t") != "span":
        return
    attrs = rec.get("a") or {}
    program = attrs.get("program")
    if not program:
        return
    key = (program, attrs.get("sig", "?"))
    reg = _reg()
    with reg.lock:
        d = reg.dispatches.setdefault(key, {"count": 0, "total_s": 0.0})
        d["count"] += 1
        d["total_s"] += float(rec.get("dur", 0.0))


telemetry.register_sink(_sink, enabled)


# ----------------------------------------------------------------------
# reporting
# ----------------------------------------------------------------------
def programs() -> Dict[Tuple[str, str], dict]:
    reg = _reg()
    with reg.lock:
        return {k: dict(v) for k, v in reg.programs.items()}


def join_measure(entry: dict, count: int, total_s: float) -> dict:
    """Attach measured-dispatch economics to one classified cost
    entry (shared by the live report and the offline ledger join in
    tools/explain_perf.py): mean seconds per dispatch, achieved
    GFLOP/s / GB/s, and the achieved-vs-roofline fraction."""
    entry["dispatches"] = count
    entry["measured_total_s"] = round(total_s, 6)
    if not count or total_s <= 0:
        return entry
    mean_s = total_s / count
    entry["measured_mean_s"] = round(mean_s, 6)
    flops, bts = entry.get("flops"), entry.get("bytes_accessed")
    if flops:
        entry["achieved_gflops"] = round(flops / mean_s / 1e9, 3)
    if bts:
        entry["achieved_gbps"] = round(bts / mean_s / 1e9, 3)
    roof = entry.get("roofline_s")
    if roof:
        entry["roofline_frac"] = round(roof / mean_s, 6)
    return entry


def report() -> List[dict]:
    """Joined per-program-per-shape rows: the captured cost model plus
    whatever measured dispatch seconds the sink has accumulated,
    sorted by measured time then program name — the `programs` rows
    the profiler commits to PERF.json's `cost_model` section."""
    reg = _reg()
    with reg.lock:
        progs = {k: dict(v) for k, v in reg.programs.items()}
        disp = {k: dict(v) for k, v in reg.dispatches.items()}
    rows = []
    for key, entry in progs.items():
        entry.pop("pending", None)
        if "bound" not in entry:
            # a capture still in flight on another thread: serve the
            # row classified (null cost fields) rather than bare
            classify(entry)
        d = disp.pop(key, None)
        if d:
            join_measure(entry, d["count"], d["total_s"])
        else:
            entry["dispatches"] = 0
            entry["measured_total_s"] = 0.0
        rows.append(entry)
    # measured dispatches whose program was never captured (e.g. armed
    # mid-stream after the compile): still reported, cost-less
    for key, d in disp.items():
        rows.append(join_measure(
            classify({"program": key[0], "sig": key[1]}),
            d["count"], d["total_s"]))
    rows.sort(key=lambda r: (-r.get("measured_total_s", 0.0),
                             r.get("program") or "", r.get("sig") or ""))
    return rows

"""Host/native twins of the sharded engines — the mesh's demotion
floor and CPU parity oracle.

The single-chip path earned its twin ladder in the resilience round
(device scan → native C++ → `ops/host_snapshot.py` numpy); the sharded
engines had none: a multi-chip session that lost its mesh WEDGED
instead of demoting (ROADMAP, ISSUE 2 note). This module closes that
hole with bit-exact equivalents of `parallel/sharded.py`'s three
engines, reconstructed from the gathered per-shard slabs the same way
`ops/host_snapshot.py` twins the single-chip scan:

- `HostWindowEngine`        — numpy `ShardedWindowEngine`
- `HostTriangleWindowKernel`— numpy `ShardedTriangleWindowKernel`
- `HostSummaryEngine`       — numpy `ShardedSummaryEngine` (and
                              therefore also of the single-chip
                              `StreamSummaryEngine`: the carried
                              layout is shared)

Gathering is trivial BY CONSTRUCTION: every sharded merge ends in a
psum/pmin/pmax, so the carried slabs are replicated across the mesh
and one d2h of shard 0's copy is already the shard-count-independent
host layout. A checkpoint taken on a 4-way mesh therefore loads into
a 1-device engine or any of these twins unchanged, and a twin's
checkpoint loads back onto any mesh — the cross-mesh resume contract
`tests/test_checkpoint_roundtrip.py` pins.

Bit-exactness is by construction, not coincidence (the
`ops/host_snapshot.py` argument): degrees are integer sums, triangle
counts are exact in every tier (`ops/host_triangles.window_count`,
the pure-numpy form of the count every sharded ladder rung must
reproduce), and the carried min-label
fixpoints converge to the canonical labeling whatever the iteration
schedule — `host_snapshot._fixpoint` replays the same scatter-min +
pointer-jump rounds in numpy. The one deliberate divergence: the
sharded DEGREE kernel folds its mesh padding into the [vb+2] slab's
sentinel slot (a mesh-width-dependent count), which the twins leave at
zero — the slot feeds no output and no `[:vb]` read, and baking a mesh
width into a host twin would break exactly the shard-count
independence the twins exist for.

These twins exist for availability and verification, not speed: no
compiler, no device, no mesh — only numpy. A stream that lands here is
degraded and LABELED as such (`utils/resilience.record_demotion`
carries the mesh shape into PERF.json's `degradations` section). They
double as the CPU-mesh parity oracle the planned Pallas ICI
collectives verify against (ROADMAP).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..ops import host_triangles, scan_analytics
from ..ops import segment as seg_ops
from ..ops import unionfind
from ..ops.host_snapshot import _fixpoint


# ----------------------------------------------------------------------
# ShardedWindowEngine twin
# ----------------------------------------------------------------------

class HostWindowEngine:
    """Numpy twin of `ShardedWindowEngine`: same per-window analytics
    (running degrees, carried min-label CC, double-cover
    bipartiteness), same carried-state layouts (`degree_state`/`labels`
    [vb+2], `bip_labels` [2vb+2]) and `state_dict` keys, so state
    hands off in BOTH directions across any mesh width."""

    def __init__(self, num_vertices_bucket: int = 1 << 16):
        self.vb = num_vertices_bucket
        self.reset()

    @classmethod
    def from_sharded(cls, engine) -> "HostWindowEngine":
        """Twin a live `ShardedWindowEngine`, adopting its gathered
        state — the mid-stream demotion hand-off."""
        twin = cls(num_vertices_bucket=engine.vb)
        twin.load_state_dict(engine.state_dict())
        return twin

    def reset(self) -> None:
        self._degree_state = np.zeros(self.vb + 2, np.int32)
        self._labels = np.arange(self.vb + 2, dtype=np.int32)
        self._bip_labels: Optional[np.ndarray] = None

    @staticmethod
    def _edges(src, dst):
        return (np.asarray(src, np.int64).ravel(),
                np.asarray(dst, np.int64).ravel())

    def degrees(self, src, dst) -> np.ndarray:
        s, d = self._edges(src, dst)
        np.add.at(self._degree_state, s, 1)
        np.add.at(self._degree_state, d, 1)
        return self._degree_state[: self.vb].copy()

    def cc_labels(self, src, dst, carry: bool = True) -> np.ndarray:
        s, d = self._edges(src, dst)
        labels = (self._labels if carry
                  else np.arange(self.vb + 2, dtype=np.int32))
        self._labels = _fixpoint(labels, s, d)
        return self._labels[: self.vb].copy()

    def bipartite(self, src, dst, carry: bool = True):
        fresh = np.arange(2 * self.vb + 2, dtype=np.int32)
        labels = (self._bip_labels
                  if (carry and self._bip_labels is not None)
                  else fresh)
        s, d = self._edges(src, dst)
        s2, d2 = unionfind.double_cover_edges(s, d, self.vb)
        self._bip_labels = _fixpoint(labels, s2, d2)
        return unionfind.decode_double_cover(self._bip_labels, self.vb)

    def state_dict(self) -> dict:
        state = {
            "vb": self.vb,
            "mesh_shape": None,  # the twin IS the no-mesh floor  # gslint: disable=ckpt-symmetry (provenance only; load ignores it)
            "degree_state": self._degree_state.copy(),
            "labels": self._labels.copy(),
        }
        if self._bip_labels is not None:
            state["bip_labels"] = self._bip_labels.copy()
        return state

    def load_state_dict(self, state: dict) -> None:
        if state["vb"] != self.vb:
            raise ValueError(
                f"vertex bucket mismatch: checkpoint has {state['vb']}, "
                f"twin built with {self.vb}")
        self._degree_state = np.asarray(state["degree_state"],
                                        np.int32).copy()
        self._labels = np.asarray(state["labels"], np.int32).copy()
        self._bip_labels = (np.asarray(state["bip_labels"],
                                       np.int32).copy()
                            if "bip_labels" in state else None)


# ----------------------------------------------------------------------
# ShardedTriangleWindowKernel twin
# ----------------------------------------------------------------------

class HostTriangleWindowKernel:
    """Numpy twin of `ShardedTriangleWindowKernel`: exact per-window
    triangle counts via the pure-numpy window counter
    (`ops/host_triangles.window_count` — no device, no compiler, same
    counts as every ladder rung), with the twin constructed at the
    SHARDED kernel's resolved buckets so window boundaries cut
    identically. Stateless, like its twin."""

    def __init__(self, edge_bucket: int, vertex_bucket: int):
        # verbatim buckets: from_sharded passes the mesh kernel's
        # already-resolved (eb, vb) so `count_stream` cuts the exact
        # same tumbling windows; standalone use buckets like the
        # single-chip kernel does
        self.eb = int(edge_bucket)
        self.vb = int(vertex_bucket)

    @classmethod
    def from_sharded(cls, kernel) -> "HostTriangleWindowKernel":
        return cls(edge_bucket=kernel.eb, vertex_bucket=kernel.vb)

    def count(self, src: np.ndarray, dst: np.ndarray) -> int:
        if len(src) > self.eb:
            raise ValueError(f"window of {len(src)} edges exceeds edge "
                             f"bucket {self.eb}")
        return host_triangles.window_count(src, dst)

    def count_stream(self, src: np.ndarray, dst: np.ndarray) -> list:
        src = np.asarray(src, np.int32)
        dst = np.asarray(dst, np.int32)
        eb = self.eb
        return [self.count(src[i:i + eb], dst[i:i + eb])
                for i in range(0, len(src), eb)]

    def count_windows(self, windows) -> list:
        return [self.count(np.asarray(s), np.asarray(d))
                for s, d in windows]


# ----------------------------------------------------------------------
# ShardedSummaryEngine / StreamSummaryEngine twin
# ----------------------------------------------------------------------

class HostSummaryEngine(scan_analytics.SummaryEngineBase):
    """Numpy twin of the fused summary engines: the same
    `SummaryEngineBase` chunk loop, window cuts, checkpoint layout and
    summary dicts, with every device stage replaced by a host fold —
    `_h2d` is the identity, `_dispatch_async` replays the scan body
    per window row in numpy (degrees fold, `_fixpoint` min-label CC
    and double cover, exact sparse triangle count), and overflow
    signals are identically zero (the host count is already exact).
    The carry is plain numpy (`_init_carry`/`_to_carry` overrides), so
    the twin runs with no compiler and no live device — the demotion
    floor of a mesh session, loadable straight from a
    `ShardedSummaryEngine` (or single-chip) checkpoint of equal
    buckets."""

    AUTOTUNE = False
    TUNABLE_INGRESS = False
    ingress = "standard"
    METRICS_TIER = "host"

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 k_bucket: int = 0):
        # k_bucket accepted (and ignored) for constructor parity with
        # the engines this twin stands in for: the host count has no K
        self.eb = seg_ops.bucket_size(edge_bucket)
        self.vb = seg_ops.bucket_size(vertex_bucket)
        self.reset()

    @classmethod
    def from_state(cls, state: dict) -> "HostSummaryEngine":
        """Build a twin directly from a fused-engine checkpoint state
        (sharded or single-chip — the layout is shared) and adopt it."""
        twin = cls(edge_bucket=int(state["edge_bucket"]),
                   vertex_bucket=int(state["vertex_bucket"]))
        twin.load_state_dict(state)
        return twin

    @classmethod
    def from_sharded(cls, engine) -> "HostSummaryEngine":
        """Twin a live `ShardedSummaryEngine` mid-stream: gather its
        replicated carry and continue from `resume_offset()` — the
        demotion hand-off (combine with `engine.drained_partial` after
        an escaping error)."""
        return cls.from_state(engine.state_dict())

    # -- carry representation: numpy, never the device ---------------
    def _init_carry(self):
        return (
            np.zeros(self.vb + 1, np.int32),
            np.arange(self.vb + 1, dtype=np.int32),
            np.arange(2 * (self.vb + 1), dtype=np.int32),
        )

    def _to_carry(self, a):
        return np.asarray(a).copy()

    # -- the host "device" stages -------------------------------------
    def _h2d(self, args):
        return args

    def _dispatch_async(self, s, d, valid):
        vb = self.vb
        deg, labels, cover = (a.copy() for a in self._carry)
        s = np.asarray(s)
        d = np.asarray(d)
        valid = np.asarray(valid)
        num_w = s.shape[0]
        mdeg = np.zeros(num_w, np.int32)
        ncomp = np.zeros(num_w, np.int32)
        odd = np.zeros(num_w, bool)
        tri = np.zeros(num_w, np.int64)
        vidx = np.arange(vb)
        for i in range(num_w):
            v = valid[i]
            # sentinel-mapped edges, exactly as the scan body's
            # jnp.where(valid, src, sent): cc sees (vb, vb) self-loops
            # (no-ops), the cover sees the (vb, 2vb+1) sentinel join
            si = np.where(v, s[i], vb).astype(np.int64)
            di = np.where(v, d[i], vb).astype(np.int64)
            # degrees: padding contributes ZERO on device (masked
            # ones), so only real edges fold here — bit-exact slabs
            np.add.at(deg, si[v], 1)
            np.add.at(deg, di[v], 1)
            mdeg[i] = deg[:vb].max() if vb else 0
            labels = _fixpoint(labels, si, di)
            touched = deg[:vb] > 0
            ncomp[i] = int(np.sum(touched & (labels[:vb] == vidx)))
            cover = _fixpoint(
                cover,
                np.concatenate([si, si + (vb + 1)]),
                np.concatenate([di + (vb + 1), di]))
            odd[i] = bool(np.any(
                touched & (cover[:vb] == cover[vb + 1:2 * vb + 1])))
            tri[i] = host_triangles.window_count(s[i][v], d[i][v])
        self._carry = (deg, labels, cover)
        zeros = np.zeros(num_w, np.int32)
        return mdeg, ncomp, odd, tri, zeros, zeros

    def _materialize(self, raw):
        return tuple(np.asarray(x) for x in raw)

    def _redo(self, src, dst, b_ovf: int, k_ovf: int) -> int:
        # unreachable in normal operation (the host fold never
        # overflows); kept exact for warm_fallback and API parity
        return host_triangles.window_count(src, dst)

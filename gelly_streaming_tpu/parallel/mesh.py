"""Device mesh construction — the communication substrate.

TPU-native replacement for the reference's Flink network stack
(SURVEY.md §2.4 P6: hash `keyBy` exchange, broadcast replication,
parallelism-1 funnels over Netty TCP): a 1-D `jax.sharding.Mesh` over
the available chips; exchanges become XLA collectives (`psum`, `pmin`,
`all_gather`) riding ICI inside a pod and DCN across slices.
"""

from __future__ import annotations

import functools
import inspect
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

SHARD_AXIS = "shard"

try:
    from jax import shard_map as _shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# The replication-check kwarg was renamed across jax releases
# (check_rep through 0.5.x, check_vma from 0.6); resolve whichever this
# install accepts once at import.
_NOCHECK_KW = None
for _kw in ("check_rep", "check_vma"):
    try:
        if _kw in inspect.signature(_shard_map).parameters:
            _NOCHECK_KW = _kw
            break
    except (ValueError, TypeError):  # C-level signature: try the old name
        _NOCHECK_KW = "check_rep"
        break


def shard_map_norep(mesh: Mesh, in_specs, out_specs):
    """`shard_map` partial with the replication/vma check DISABLED —
    for bodies that trace a `lax.while_loop` (ops/unionfind.cc_fixpoint's
    fixpoint iteration; arbitrary user associative fns), which jax's
    checker has no replication rule for ("No replication rule for
    while"). Every site using this wrapper makes its outputs replicated
    EXPLICITLY with collectives (psum/pmin/pmax/all_gather), so the
    check is redundant there — disabling it changes what is verified,
    never what is computed."""
    kw = {_NOCHECK_KW: False} if _NOCHECK_KW else {}
    return functools.partial(_shard_map, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)


def make_mesh(n_devices: Optional[int] = None,
              axis_name: str = SHARD_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))  # gslint: disable=host-sync (device HANDLES into a mesh layout, no device value in sight)


def edge_sharding(mesh: Mesh) -> NamedSharding:
    """Edges: sharded along the batch dimension (strategy P1)."""
    return NamedSharding(mesh, P(SHARD_AXIS))

def replicated(mesh: Mesh) -> NamedSharding:
    """Vertex state / summaries: replicated (strategy P3)."""
    return NamedSharding(mesh, P())


def shard_count(mesh: Mesh) -> int:
    return mesh.shape[SHARD_AXIS]


def mesh_padded_len(n_items: int, mesh: Mesh) -> int:
    """Smallest multiple of the mesh size ≥ n_items (≥ one per shard)."""
    n = shard_count(mesh)
    return ((n_items + n - 1) // n) * n if n_items else n


def pad_edges_for_mesh(src: np.ndarray, dst: np.ndarray, mesh: Mesh,
                       sentinel: int):
    """Pad a COO batch so its length divides the mesh; padding slots point
    at the sentinel vertex (one past the real vertex range)."""
    target = mesh_padded_len(len(src), mesh)
    pad = target - len(src)
    if pad:
        src = np.concatenate([src, np.full(pad, sentinel, src.dtype)])
        dst = np.concatenate([dst, np.full(pad, sentinel, dst.dtype)])
    return src, dst

"""Sharded window kernels: the multi-chip execution of the hot paths.

Implements the parallelism strategies of SURVEY.md §2.4 as `shard_map`
programs over a 1-D mesh:

- P1 (vertex-keyed data parallelism): a window's COO batch is sharded
  across chips along the edge dimension; per-vertex grouping happens in
  dense vertex space so no cross-chip regrouping is needed.
- P2 (partition-local fold + merge): per-shard partial aggregates are
  merged with collectives — `psum` for monoid summaries (degrees,
  counts, triangle partials), elementwise `pmin` label exchange for
  union-find — replacing the reference's parallelism-1 merger funnel
  (WindowGraphAggregation.java:58) with an ICI tree-reduce.
- P3 (broadcast replication): vertex state (labels, degree vectors,
  adjacency rows) is replicated; edges never move.

Every kernel is a single XLA program per window: the collective merge
is fused into the same computation as the local fold.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .mesh import (SHARD_AXIS, make_mesh, mesh_padded_len,
                   pad_edges_for_mesh, shard_count, shard_map_norep)
from ..ops import ingress_pipeline, scan_analytics
from ..ops import segment as seg_ops
from ..ops import triangles, unionfind
from ..utils import costmodel, faults, metrics, resilience, telemetry


# ----------------------------------------------------------------------
# mesh fault hooks + stage guards (utils/faults, utils/resilience)
#
# The single-chip stages earned their watchdogs and fault sites in the
# resilience round; these are the mesh-scoped twins: every sharded
# shard_map dispatch, replicated-output gather, and h2d wire passes a
# hook a fault plan can poison (dead shard / ICI stall / corrupt
# wire), and dispatches run under resilience.call_guarded when the
# stage knobs are armed. With no plan and inert knobs every helper is
# one dict lookup (or a plain call) — the hot path is unchanged.
# ----------------------------------------------------------------------

def fire_shard_dispatch(n_shards: int) -> None:
    """Fault hook before a sharded shard_map dispatch. One firing per
    SPMD dispatch (not per shard): a dead chip fails the whole
    program, so the fault plan models shard death via FaultSpec.shard
    metadata, not per-shard call counting."""
    faults.fire("shard_dispatch", n_shards)


def fire_shard_gather(n_shards: int) -> None:
    """Fault hook before the d2h gather of replicated sharded outputs
    (window stacks, engine state slabs)."""
    faults.fire("shard_gather", n_shards)


def guard_wire(arrays, n_shards: int, limit: int):
    """The mesh h2d wire hook: run the arrays through the fault plan
    (corrupt_shard poisons one shard's slice) and, when
    GS_MESH_WIRE_CHECK=1, validate every shard slice's vertex ids
    against `limit` (the bucket sentinel — the largest id any honest
    stack can carry). Returns the (possibly fault-transformed) arrays;
    raises RuntimeError naming the offending shard on a corrupt wire,
    which the stage guards surface as a typed StageFailed feeding the
    demotion ladder."""
    payload = faults.fire("shard_wire", (tuple(arrays), n_shards))
    if isinstance(payload, tuple) and len(payload) == 2:
        arrays = payload[0]
    if resilience.mesh_wire_check_enabled():
        _check_wire(arrays, n_shards, limit)
    return arrays


def _check_wire(arrays, n_shards: int, limit: int) -> None:
    """Range-check each shard's slice of the mesh-bound stacks: any
    integer id above `limit` is a corrupt wire (ids are interned dense
    slots ≤ bucket sentinel by construction)."""
    for a in arrays:
        a = np.asarray(a)
        if not np.issubdtype(a.dtype, np.integer) or not a.size:
            continue
        width = a.shape[-1] // n_shards
        if not width:
            continue
        for k in range(n_shards):
            sl = a[..., k * width:(k + 1) * width]
            if sl.size and int(sl.max()) > limit:
                raise RuntimeError(
                    "corrupt shard wire: shard %d of %d carries vertex"
                    " id %d > bucket sentinel %d (GS_MESH_WIRE_CHECK)"
                    % (k, n_shards, int(sl.max()), limit))


def _guarded_dispatch(chunk, fn, retries=None):
    """Run one PURE sharded dispatch under the stage watchdog/retry
    policy when the knobs are armed (resilience.call_guarded —
    retryable because every guarded sharded dispatch is
    carry-in/carry-out pure and rebinds state only on success); the
    bare call otherwise — exact legacy behavior and exception types
    with inert knobs."""
    if resilience.guard_active():
        return resilience.call_guarded("dispatch", chunk, fn,
                                       retries=retries)
    return fn()


# ----------------------------------------------------------------------
# sharded continuous degrees (P1 + P2: segment-sum + psum)
# ----------------------------------------------------------------------

def make_sharded_degree_fn(mesh, num_vertices_bucket: int):
    """Returns jitted fn(src, dst, counts) -> counts' where src/dst are
    edge-sharded and counts is the replicated running [V+1] degree
    vector (continuous-degree semantics of SimpleEdgeStream.java:465-482,
    batched)."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=P(),
    )
    def step(src, dst, counts):
        ones = jnp.ones_like(src, jnp.int32)
        # vb+2 rows: [0, vb) real vertices, vb+1 the padding sentinel
        local = jax.ops.segment_sum(ones, src, num_vertices_bucket + 2)
        local = local + jax.ops.segment_sum(ones, dst, num_vertices_bucket + 2)
        return counts + jax.lax.psum(local, SHARD_AXIS)

    return jax.jit(step)


# ----------------------------------------------------------------------
# sharded connected components (P1 + P2: scatter-min + pmin exchange)
# ----------------------------------------------------------------------

def make_sharded_cc_fn(mesh, num_vertices_bucket: int):
    """Returns jitted fn(src, dst, labels) -> labels' running min-label
    propagation to the fixpoint: each chip folds its edge shard into the
    replicated label vector, shards exchange labels with an elementwise
    `pmin` every round (the collective merge tree), then pointer-jump.
    `labels` holds [V+1] int32 (slot V = padding sentinel); pass
    arange for a fresh window or carry the previous state for the
    streaming-iteration semantics of IterativeConnectedComponents."""

    # shard_map_norep: cc_fixpoint's while_loop has no replication rule
    # in the checker; the pmin exchange makes the output replicated
    @shard_map_norep(
        mesh, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=P(),
    )
    def step(src, dst, labels):
        assert labels.shape[0] == num_vertices_bucket + 2, labels.shape
        return unionfind.cc_fixpoint(
            labels, src, dst,
            exchange=lambda lab: jax.lax.pmin(lab, SHARD_AXIS),  # ICI merge
        )

    return jax.jit(step)


# ----------------------------------------------------------------------
# sharded triangle count (P1 edges + P3 replicated adjacency + psum)
# ----------------------------------------------------------------------

def make_sharded_triangle_fn(mesh):
    """Returns jitted fn(nbr, ea, eb, emask) -> count with the oriented
    edge list sharded across chips and the sorted-adjacency matrix
    replicated; per-shard intersection partials reduce with one psum."""

    # NOT resolve_intersect_impl(): pl.pallas_call inside shard_map
    # fails jax 0.9's check_vma at trace time (vma=None on the
    # out_shape), so sharded bodies use the XLA-only selection
    # (compare on chip, binary search on CPU meshes)
    intersect = triangles.resolve_xla_intersect()

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(),
    )
    def step(nbr, ea, eb, emask):
        local = intersect(nbr, ea, eb, emask)
        return jax.lax.psum(local, SHARD_AXIS)

    return jax.jit(step)


# ----------------------------------------------------------------------
# sharded sliding-window pane reduce (P1 + P2 over (pane, vertex) cells)
# ----------------------------------------------------------------------

def make_sharded_pane_reduce(mesh, vertex_bucket: int, pane_bucket: int,
                             panes_per_window: int, name: str = None,
                             fn=None):
    """Sliding-window reduce at multi-chip scale — the sharded form of
    the single-chip pane path (ops/neighborhood.py _make_pane_reduce;
    see docs/DESIGN.md §1.1): edges sharded across chips (P1), each
    shard reduces its slice over flattened (pane, vertex) cell ids
    into a full [pane_bucket, V+1] partial, the partials merge across
    the mesh (P2), and every window is a static stack of
    panes_per_window shifted pane slices combined elementwise — all
    windows from one program, no edge duplication.

    Two tiers, mirroring the single-chip pane path:
    - `name` ('sum'|'min'|'max'): segment kernels per shard, ONE
      psum/pmin/pmax collective merge, identity-padded window combine.
    - `fn` + nothing else (a user fn DECLARED associative): flagged
      associative scan per shard over cell-sorted values, an
      all_gather of the [pb, V+1] cell partials + a left-fold over the
      shard axis with a presence mask (no identity element exists for
      a general fn, and no psum-style collective applies a custom
      combine), then the masked window combine. Shard index order =
      edge-position order (the edge axis splits contiguously), so the
      cross-shard fold preserves arrival order up to the reordering
      associativity licenses.

    Returns jitted fn(src, pane, val, valid) -> (win_vals, win_counts),
    both [pane_bucket + panes_per_window - 1, vertex_bucket + 1]; a
    (window, vertex) cell is meaningful iff win_counts[w, v] > 0.
    win_counts are real edge counts in BOTH tiers — counts have an
    identity (0) even when values don't, so the fn tier psums a
    segment_sum of valid edges alongside its value fold (ADVICE r3:
    switching name='min' to fn=jnp.minimum must not silently change
    count semantics). Window w covers dense panes
    [w - panes_per_window + 1, w];
    src/pane/val/valid are edge-sharded arrays (pad with valid=False).
    """
    assert (name is None) != (fn is None)
    assert name in (None, "sum", "min", "max"), name
    vbp = vertex_bucket + 1
    pb = pane_bucket
    wp = panes_per_window
    n_cells = pb * vbp
    n = shard_count(mesh)

    if name is not None:
        coll = {"sum": jax.lax.psum, "min": jax.lax.pmin,
                "max": jax.lax.pmax}[name]

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                      P(SHARD_AXIS)),
            out_specs=(P(), P()),
        )
        def partials(src, pane, val, valid):
            ids = jnp.where(valid, pane * vbp + src, n_cells)
            # segment_min/max fill empty cells with dtype extremes
            # (+/-inf for floats — NOT _pane_identity); per-shard fills
            # absorb in pmin/pmax, and window_stack_combine
            # re-normalizes globally empty (count==0) cells to the
            # documented identity
            cells = seg_ops.segment_reduce(val, ids, n_cells + 1,
                                           name)[:-1].reshape(pb, vbp)
            counts = jax.ops.segment_sum(
                jnp.where(valid, 1, 0), ids,
                n_cells + 1)[:-1].reshape(pb, vbp)
            return coll(cells, SHARD_AXIS), jax.lax.psum(counts,
                                                         SHARD_AXIS)

        def run(src, pane, val, valid):
            from ..ops.neighborhood import window_stack_combine

            cells, counts = partials(src, pane, val, valid)
            return window_stack_combine(cells, counts, wp, name)

        return jax.jit(run)

    # shard_map_norep: a user fn declared associative may trace a
    # while_loop (e.g. np.gcd lowers to one), which the replication
    # checker has no rule for; outputs are made replicated explicitly
    # (psum counts, the no-op pmax on accv below)
    @shard_map_norep(
        mesh, in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                        P(SHARD_AXIS)),
        out_specs=(P(), P()),
    )
    def assoc_partials(src, pane, val, valid):
        ids = jnp.where(valid, pane * vbp + src, n_cells)
        # real per-cell edge counts (identity 0 exists for counts even
        # when fn has none): ONE extra psum next to the all_gather
        # below, and the fn tier's win_counts match the monoid tier's
        # (ADVICE r3)
        counts = jax.lax.psum(
            jax.ops.segment_sum(jnp.where(valid, 1, 0), ids,
                                n_cells + 1)[:-1].reshape(pb, vbp),
            SHARD_AXIS)
        order = jnp.argsort(ids, stable=True)
        ids_s = ids[order]
        vals_s = val[order]
        flags = jnp.concatenate(
            [jnp.ones(1, bool), ids_s[1:] != ids_s[:-1]])

        def comb(x, y):
            fx, vx = x
            fy, vy = y
            # flagged associative scan: a cell-start flag resets the
            # running combine (same kernel shape as
            # seg_ops._jit_assoc_reduce, inlined here because this
            # body must trace inside shard_map)
            return fx | fy, jnp.where(fy, vy, fn(vx, vy))

        _, scanned = jax.lax.associative_scan(comb, (flags, vals_s))
        idx = jnp.arange(ids_s.shape[0])
        last = jax.ops.segment_max(
            jnp.where(ids_s < n_cells, idx, -1), ids_s,
            n_cells + 1)[:-1]
        present = (last >= 0).reshape(pb, vbp)
        cells = scanned[jnp.maximum(last, 0)].reshape(pb, vbp)

        # cross-shard merge: gather every shard's partials and fold in
        # shard order with a presence mask — no collective applies a
        # custom fn, and a general fn has no identity to pad with
        from ..ops.neighborhood import masked_combine

        allc = jax.lax.all_gather(cells, SHARD_AXIS)     # [n, pb, vbp]
        allp = jax.lax.all_gather(present, SHARD_AXIS)
        # balanced tree over the shard axis (O(log n) depth — exactly
        # what associativity licenses); adjacent pairs combine left-to-
        # right, so shard order (= edge-position order) is preserved
        vals = [allc[i] for i in range(n)]
        pres = [allp[i] for i in range(n)]
        while len(vals) > 1:
            nxt_v, nxt_p = [], []
            for i in range(0, len(vals) - 1, 2):
                v, p2 = masked_combine(fn, vals[i], pres[i],
                                       vals[i + 1], pres[i + 1])
                nxt_v.append(v)
                nxt_p.append(p2)
            if len(vals) % 2:
                nxt_v.append(vals[-1])
                nxt_p.append(pres[-1])
            vals, pres = nxt_v, nxt_p
        accv = vals[0]
        # every shard folded the same gathered partials, so accv is
        # value-identical everywhere; the no-op pmax makes that
        # replication explicit for shard_map's vma check (the [pb, vbp]
        # payload is tiny next to the all_gather above). counts is
        # already replicated by its psum.
        accv = jax.lax.pmax(accv, SHARD_AXIS)
        return accv, counts

    def run(src, pane, val, valid):
        from ..ops.neighborhood import _jit_assoc_combine

        cells, counts = assoc_partials(src, pane, val, valid)
        return _jit_assoc_combine(fn, wp)(cells, counts)

    return jax.jit(run)


# ----------------------------------------------------------------------
# full sharded window triangle pipeline (P1 + P6: all_to_all + pmax + psum)
# ----------------------------------------------------------------------

_TABLE_MODE = None  # resolved once per process (reset: _reset_table_mode)


def _reset_table_mode() -> None:
    """Test hook: forget the memoized table-mode selection so a test
    can re-resolve against a different committed PERF.json."""
    global _TABLE_MODE
    _TABLE_MODE = None


def resolve_table_mode() -> str:
    """Neighbor-row distribution mode for the sharded window counter
    ("replicated" pmax table vs "owner" row gather), selected from
    committed backend-matched measurements (PERF.json `sharded_table`
    section, tools/profile_kernels.py) — the same measured-default
    policy as the kernel selections in ops/triangles.py. Until a
    committed measurement shows the owner gather ≥5% faster, the
    proven replicated table stands. The mode only matters on n>1
    meshes (the virtual CPU mesh here; real ICI when multi-chip
    hardware exists — window_collective_bytes models that side).
    Memoized per process like the other measurement-driven selections
    (the driver rebuilds kernels on reconfiguration; re-reading
    PERF.json each time is needless I/O — ADVICE r3)."""
    global _TABLE_MODE
    if _TABLE_MODE is not None:
        return _TABLE_MODE
    _TABLE_MODE = _resolve_table_mode_uncached()
    return _TABLE_MODE


def _resolve_table_mode_uncached() -> str:
    perf = triangles._load_matching_perf()
    if perf is not None:
        row = perf.get("sharded_table", {})
        # the section records ITS OWN backend next to the file-level
        # one ("cpu-virtual-mesh" rows ride along inside a chip-labeled
        # PERF.json): require it to match the LIVE backend, so virtual-
        # mesh rows can never drive a TPU process's replicated-vs-owner
        # selection (ADVICE r5 medium finding). The virtual mesh IS the
        # cpu backend, so "cpu-virtual-mesh" matches a cpu process.
        try:
            import jax as _jax

            live = _jax.default_backend()
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="sharded_table",
                            fallback="replicated",
                            error="%s: %s" % (type(e).__name__, e))
            return "replicated"
        row_backend = row.get("backend")
        if row_backend not in (live, "%s-virtual-mesh" % live):
            return "replicated"
        owner = row.get("owner_edges_per_s") or 0
        repl = row.get("replicated_edges_per_s") or 0
        # parity gate first, same as the dense selection: a fast mode
        # whose own committed evidence says it miscounted never wins
        if (row.get("counts_match") is True
                and owner and repl and owner >= 1.05 * repl):
            return "owner"
    return "replicated"


def window_collective_bytes(n: int, vb: int, kb: int, cap: int,
                            table: str = "replicated") -> dict:
    """Analytic per-chip ICI traffic (bytes) per window for every
    collective in build_sharded_window_counter — static shapes make
    this exact, not sampled (VERDICT r2 weak-4: the 'cheap on real ICI'
    claim must be accounted, not argued). The edge bucket enters only
    through `cap` (the per-(shard→shard) exchange capacity the kernel
    derives from it). Models: ring all-reduce (psum/pmax) moves
    2·(n-1)/n × payload per chip; all_gather and all_to_all move
    (n-1)/n × the full gathered/exchanged buffer."""
    assert table in ("replicated", "owner"), table
    i32 = 4
    f = (n - 1) / n if n > 1 else 0.0
    m = n * cap   # owned-edge slots per shard after the exchange
    out = {
        "psum_degrees": 2 * f * (vb + 1) * i32,
        "all_to_all_pairs": 2 * f * m * i32,          # send_a + send_b
        "psum_count_and_overflow": 2 * f * 3 * i32,
    }
    if table == "replicated":
        out["pmax_table"] = 2 * f * (vb + 1) * kb * i32
    else:
        out["all_gather_row_ids"] = f * n * 2 * m * i32
        out["all_to_all_row_slices"] = f * 2 * m * kb * i32
    out["total"] = sum(out.values())
    return out


# Public one-way ICI bandwidth figures (GB/s per chip) for the time
# model — the v5e value follows the public scaling-book figure of
# ~4.5e10 B/s per link with the single-link worst case taken (ring
# collectives bottleneck on one link direction). Overridable per call:
# the model is for DESIGN comparisons, not a measurement substitute.
ICI_GBPS = {"v5e": 45.0}


def ici_time_model(bytes_dict: dict, gbps: float = ICI_GBPS["v5e"]) -> dict:
    """Seconds per window each collective spends on the ICI at the
    modeled bandwidth (latency terms ignored — payloads here are KBs to
    MBs, far above the latency-bound regime)."""
    return {k: v / (gbps * 1e9) for k, v in bytes_dict.items()}


def make_sharded_window_triangle_fn(mesh, eb: int, vb: int, kb: int,
                                    cap: int, table: str = "replicated"):
    """The COMPLETE window triangle pipeline as one shard_map program
    over raw sharded COO — the multi-chip form of
    TriangleWindowKernel._build (ops/triangles.py), replacing the
    reference's three keyBy shuffles (WindowTriangles.java:61-66) with
    three ICI collectives:

    1. psum      — global degree vector for the (degree, id) orientation
    2. all_to_all — hash-partition each oriented edge (a,b) to its owner
       shard (the "keyBy(pair)" exchange): global dedup becomes a local
       sort on the owner, and every surviving edge is counted exactly
       once, on exactly one shard
    3. pmax      — merge the per-shard CSR column slices into the
       replicated neighbor table (each shard writes its own kb/n-wide
       slice, so slices never collide and elementwise max merges them)
    then a final psum of the per-shard intersection partials.

    Per-(shard→shard) bucket capacity is `cap`; a hub row overflowing
    its kb/n column slice or a bucket overflowing `cap` raises the
    overflow count, and the host escalates — exactness is never
    sacrificed.
    """
    n = shard_count(mesh)
    step = build_sharded_window_counter(n, eb, vb, kb, cap, table=table)
    return jax.jit(functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(), P(), P()),
    )(step))


def build_sharded_window_counter(n: int, eb: int, vb: int, kb: int,
                                 cap: int, axis: str = SHARD_AXIS,
                                 table: str = "replicated"):
    """Pure per-shard one-window body (unwrapped): callable inside any
    shard_map over `axis` — directly (make_sharded_window_triangle_fn)
    or within a lax.scan over window stacks (ShardedSummaryEngine).

    `table` picks how each owned edge reaches its endpoints' full
    neighbor rows (window_collective_bytes accounts both):

    - "replicated": every shard scatters its kb/n column slice of the
      [V+1, kb] table and ONE pmax all-reduce replicates it — O(V·kb)
      ICI bytes and O(V·kb) HBM per chip per window, independent of
      the edge count.
    - "owner": no replication — each shard keeps only its [V+1, kb/n]
      column slice, all shards all_gather the row ids their owned
      edges touch (aligned to owned-edge slots, so no index remap),
      take their local slices of every requested row, and one
      all_to_all returns each shard the full rows of exactly its own
      edges — O(owned_edges·kb) ICI bytes, which beats the replicated
      table whenever owned edges per shard ≪ V (the sparse-window
      regime the 10M-scale buckets live in: vb/(n·cap) ≈ 16× less
      traffic at vb=262144, eb=65536, n=8)."""
    assert table in ("replicated", "owner"), table
    assert eb % n == 0 and kb % n == 0, (eb, kb, n)
    sent = vb
    kslice = kb // n
    # Pinned to the broadcast compare. Not resolve_intersect_impl():
    # pallas_call in shard_map trips check_vma. Not
    # resolve_xla_intersect() either: the nbr table below is assembled
    # as per-shard kslice column runs merged by pmax, so each row is a
    # CONCATENATION of sorted runs, not globally sorted — the binary
    # search's searchsorted contract doesn't hold (the equality compare
    # doesn't care about order).
    intersect = triangles.intersect_local

    def step(src, dst, valid):
        me = jax.lax.axis_index(axis)
        el = src.shape[0]  # = eb // n

        # ---- clean: drop self-loops and padding
        valid = valid & (src != dst)
        s = jnp.where(valid, src, sent)
        d = jnp.where(valid, dst, sent)

        # ---- global degrees for orientation (collective #1: psum)
        ones = jnp.where(valid, 1, 0)
        local_deg = (jax.ops.segment_sum(ones, s, vb + 1)
                     + jax.ops.segment_sum(ones, d, vb + 1))
        deg = jax.lax.psum(local_deg, axis)

        # ---- orient low(deg, id) -> high(deg, id)
        a, b = triangles.orient_by_degree(s, d, deg, sent)
        a, b = a.astype(jnp.int32), b.astype(jnp.int32)

        # ---- owner shard by multiplicative pair hash: duplicates of an
        # edge land on one shard regardless of origin, so dedup is local
        h = (a.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
             + b.astype(jnp.uint32) * jnp.uint32(0x85EBCA77))
        owner = ((h >> 8) % jnp.uint32(n)).astype(jnp.int32)

        # ---- bucket by owner: sort (owner, a, b), position within run
        owner = jnp.where(a < sent, owner, n)  # padding sorts last
        owner, a, b = jax.lax.sort((owner, a, b), num_keys=3)
        idx = jnp.arange(el)
        run_first = jax.ops.segment_min(
            jnp.where(owner < n, idx, el), owner, n + 1)
        pos = idx - run_first[owner]
        ok = (a < sent) & (pos < cap)
        bucket_overflow = jnp.sum((pos >= cap) & (a < sent))
        slot = jnp.where(ok, owner * cap + jnp.clip(pos, 0, cap - 1),
                         n * cap)  # trash slot for overflow/padding
        send_a = jnp.full(n * cap + 1, sent, jnp.int32).at[slot].set(a)
        send_b = jnp.full(n * cap + 1, sent, jnp.int32).at[slot].set(b)

        # ---- collective #2: all_to_all pair exchange over ICI
        recv_a = jax.lax.all_to_all(
            send_a[:n * cap].reshape(n, cap), axis,
            split_axis=0, concat_axis=0, tiled=True).reshape(n * cap)
        recv_b = jax.lax.all_to_all(
            send_b[:n * cap].reshape(n, cap), axis,
            split_axis=0, concat_axis=0, tiled=True).reshape(n * cap)

        # ---- local dedupe of owned edges (global dedup by ownership)
        # + CSR positions, fused into one sort (duplicates stay in
        # place behind rvalid)
        ra, rb, rvalid, pos2 = triangles.dedupe_and_positions(
            recv_a, recv_b, sent, vb)
        k_overflow = jnp.sum((pos2 >= kslice) & rvalid)
        ok2 = rvalid & (pos2 < kslice)
        rows = jnp.where(ok2, ra, vb)
        cols_local = jnp.clip(pos2, 0, kslice - 1)

        if table == "replicated":
            # ---- collective #3: pmax slice merge -> replicated table
            partial = jnp.full((vb + 1, kb), -1, jnp.int32)
            partial = partial.at[rows, me * kslice + cols_local].set(
                jnp.where(ok2, rb, -1))
            nbr = jax.lax.pmax(partial, axis)
            nbr = jnp.where(nbr < 0, sent, nbr)
            local = intersect(nbr, ra, rb, rvalid)
        else:
            # ---- collective #3 (owner-local): gather only the rows
            # this shard's owned edges touch. Requests are ALIGNED to
            # owned-edge slots (row ids = concat(ra, rb)), so the
            # returned rows index directly per edge — no remap, no
            # dedup (a hub row travels once per touching edge; still
            # O(owned·kb) ≪ O(V·kb) in the sparse-window regime).
            local_tab = jnp.full((vb + 1, kslice), -1, jnp.int32)
            local_tab = local_tab.at[rows, cols_local].set(
                jnp.where(ok2, rb, -1))
            m = ra.shape[0]
            req = jnp.concatenate([ra, rb])              # [2m]
            all_req = jax.lax.all_gather(req, axis)      # [n, 2m]
            send = local_tab[all_req]                    # [n, 2m, kb/n]
            recv = jax.lax.all_to_all(
                send, axis, split_axis=0, concat_axis=0, tiled=True)
            # [2m, n, kb/n] -> [2m, kb]: columns shard-major, the same
            # layout the replicated table's rows carry
            rows_full = jnp.transpose(recv, (1, 0, 2)).reshape(2 * m, kb)
            rows_full = jnp.where(rows_full < 0, sent, rows_full)
            local = triangles.intersect_rows(
                rows_full[:m], rows_full[m:], rvalid, sent)
        count = jax.lax.psum(local, axis)
        # separate signals so the host widens only the dimension that
        # overflowed (cap vs K): each (kb, cap) pair is a fresh compile
        bucket_overflow = jax.lax.psum(bucket_overflow, axis)
        k_overflow = jax.lax.psum(k_overflow, axis)
        return count, bucket_overflow, k_overflow

    return step


class ShardedTriangleWindowKernel:
    """Multi-chip TriangleWindowKernel: same exact counts, edges sharded
    across the mesh (P1), merges over ICI (P6). Escalates the neighbor
    table width and exchange capacity on overflow, ending at the exact
    host path — mirrors TriangleWindowKernel's ladder."""

    def __init__(self, mesh, edge_bucket: int, vertex_bucket: int,
                 k_bucket: int = 0, cap_factor: int = 2,
                 table: str = None):
        self.mesh = mesh
        self.n = n = shard_count(mesh)
        self.table = table if table else resolve_table_mode()

        def _mult_of_n(x: int) -> int:  # shard_map splits the leading
            return -(-x // n) * n       # dim; K splits into n slices

        self.eb = _mult_of_n(seg_ops.bucket_size(edge_bucket))
        self.vb = seg_ops.bucket_size(vertex_bucket)
        # same measured starting K as the single-chip kernel (rounded
        # to a shard multiple); escalation still guards exactness
        kb0 = k_bucket if k_bucket else triangles._tuned_kb(self.eb)
        self.kb = _mult_of_n(seg_ops.bucket_size(kb0))
        self.kb_max = max(
            _mult_of_n(seg_ops.bucket_size(2 * int(np.sqrt(self.eb)))),
            self.kb)
        self.cap = min(max(8, cap_factor * (self.eb // n) // n),
                       self.eb // n)
        # per-stage counters of the shared ingress pipeline (same
        # contract as TriangleWindowKernel.stage_timers)
        self.stage_timers = ingress_pipeline.StageTimers()
        # counts finalized before the last escaping _run_stack error
        # (None = clean): the demoting caller's re-entry cursor
        self.drained_counts = None
        self._fns = {}

    def _fn(self, kb, cap):
        key = (kb, cap)
        if key not in self._fns:
            self._fns[key] = make_sharded_window_triangle_fn(
                self.mesh, self.eb, self.vb, kb, cap, table=self.table)
        return self._fns[key]

    def _next_kb(self, kb: int) -> int:
        return min(-(-(kb * 4) // self.n) * self.n, self.kb_max)

    def _next_cap(self, cap: int) -> int:
        return min(cap * 2, self.eb // self.n)

    def count(self, src: np.ndarray, dst: np.ndarray,
              failed_kb: int = 0, failed_cap: int = 0) -> int:
        """failed_kb/failed_cap mark rungs a batched count_stream
        dispatch already saw overflow, so the ladder starts past them
        (or goes straight to the exact host path when that dimension
        was already saturated)."""
        n = len(src)
        if n == 0:
            return 0
        if n > self.eb:
            raise ValueError(f"window of {n} edges exceeds edge bucket "
                             f"{self.eb}")
        if ((failed_kb and failed_kb >= self.kb_max)
                or (failed_cap and failed_cap >= self.eb // self.n)):
            return triangles.triangle_count_sparse(src, dst, self.vb)
        s = seg_ops.pad_to(np.asarray(src, np.int32), self.eb, fill=self.vb)
        d = seg_ops.pad_to(np.asarray(dst, np.int32), self.eb, fill=self.vb)
        valid = seg_ops.pad_to(np.ones(n, bool), self.eb, fill=False)
        s, d, valid = jnp.asarray(s), jnp.asarray(d), jnp.asarray(valid)
        kb = self._next_kb(failed_kb) if failed_kb else self.kb
        cap = self._next_cap(failed_cap) if failed_cap else self.cap
        while True:
            def _disp(kb=kb, cap=cap):
                # fire + MATERIALIZE inside the guard: a dead shard's
                # dispatch failure and a hung gather both surface as
                # the typed stage error the demotion ladder feeds on
                fire_shard_dispatch(self.n)
                got = self._fn(kb, cap)(s, d, valid)
                fire_shard_gather(self.n)
                return tuple(int(x) for x in got)

            count, bucket_ovf, k_ovf = _guarded_dispatch(
                ("sharded_window", kb, cap), _disp)
            if not bucket_ovf and not k_ovf:
                return int(count)
            kb_sat = kb >= self.kb_max
            cap_sat = cap >= self.eb // self.n
            if (kb_sat or not k_ovf) and (cap_sat or not bucket_ovf):
                break  # nothing left to widen: exact host path instead
            if k_ovf and not kb_sat:
                kb = self._next_kb(kb)
            if bucket_ovf and not cap_sat:
                cap = self._next_cap(cap)
        return triangles.triangle_count_sparse(src, dst, self.vb)

    MAX_STREAM_WINDOWS = 64

    def warm_chunks(self) -> None:
        """Compile every stream-chunk program _run_stack can dispatch
        at the current (K, cap) — same compile-only contract and
        shared body (seg_ops.warm_stream_buckets) as
        TriangleWindowKernel.warm_chunks."""
        seg_ops.warm_stream_buckets(self)

    def _stream_fn(self, kb, cap):
        key = ("stream", kb, cap)
        if key not in self._fns:
            window = self._fn(kb, cap)  # reuse the per-window compile cache

            @jax.jit
            def run_stream(src, dst, valid):  # [W, eb] each, edge-sharded
                return jax.lax.map(lambda t: window(*t), (src, dst, valid))

            self._fns[key] = run_stream
        return self._fns[key]

    def _chunk_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, P(None, SHARD_AXIS))

    def _stream_exec(self, wb: int):
        """AOT-compiled stream program for a [wb, eb] edge-sharded
        chunk at the current (K, cap) — the kernel's own executable
        cache, so warm_chunks is compile-only (same design as
        TriangleWindowKernel._stream_exec)."""
        key = ("exec", self.kb, self.cap, wb)
        ex = self._fns.get(key)
        if ex is None:
            sharding = self._chunk_sharding()
            sds_i = jax.ShapeDtypeStruct((wb, self.eb), jnp.int32,
                                         sharding=sharding)
            sds_b = jax.ShapeDtypeStruct((wb, self.eb), jnp.bool_,
                                         sharding=sharding)
            ex = self._stream_fn(self.kb, self.cap).lower(
                sds_i, sds_i, sds_b).compile()
            # cost observatory (utils/costmodel): register the
            # table-mode stream program's cost model (free off the AOT
            # executable) and tag armed dispatches program/sig
            ex = costmodel.wrap_exec(
                "sharded_table_stream", ex,
                metrics.abstract_sig((sds_i, sds_i, sds_b)))
            self._fns[key] = ex
        return ex

    def _run_stack(self, s, d, valid, get_window) -> list:
        """Dispatch a [W, eb] window stack in MAX_STREAM_WINDOWS chunks
        (edge axis sharded over the mesh) through the SAME three-stage
        ingress pipeline as the single-chip kernel
        (ops/ingress_pipeline.run_pipeline) — the sharded path keeps
        its own table contract and mesh sharding, only the chunk loop
        is shared: prep (pad_window_chunk) runs on the worker pool,
        h2d is the mesh-sharded device_put, and each chunk's d2h +
        overflow recount materializes one chunk behind its dispatch.
        `get_window(w)` returns the raw (src, dst) of window w for the
        rare exact overflow recount. Ragged final chunks pad the
        window axis to a power-of-two bucket so varying stream lengths
        reuse O(log) compiled programs.

        On ANY escaping error the pipeline drains the in-flight
        chunk's finalize first (ops/ingress_pipeline), and the counts
        finalized before the failure are stashed on
        `self.drained_counts` — the re-entry cursor a demoting caller
        (core/driver, the host twin hand-off) combines with, instead
        of recomputing delivered windows."""
        sharding = self._chunk_sharding()
        num_w = s.shape[0]
        counts: list = []
        self.drained_counts = None

        def prep(at):
            hi = min(at + self.MAX_STREAM_WINDOWS, num_w)
            sc, dc, vc, n = seg_ops.pad_window_chunk(
                s, d, valid, at, hi, self.MAX_STREAM_WINDOWS, self.eb,
                self.vb)
            return at, n, (sc, dc, vc)

        def h2d(payload):
            at, n, args = payload
            sc, dc = guard_wire(args[:2], self.n, self.vb)
            return at, n, tuple(jax.device_put(a, sharding)
                                for a in (sc, dc) + args[2:])

        def dispatch(dev_payload):
            at, n, dev = dev_payload
            fire_shard_dispatch(self.n)
            telemetry.event("sharded.round", engine="triangles",
                            window=at, windows=n, mesh=self.n)
            fn = self._stream_exec(dev[0].shape[0])
            return (at, n) + tuple(fn(*dev))

        def finalize(raw):
            at, n = raw[:2]
            fire_shard_gather(self.n)
            # np.array (not asarray): device outputs are read-only views
            c, b_ovf, k_ovf = (np.array(x)[:n] for x in raw[2:])  # gslint: disable=host-sync (sanctioned finalize boundary: the sharded chunk's ONE batched gather of replicated [W] scalars)
            for w in np.nonzero(b_ovf + k_ovf)[0]:  # rare: exact redo
                ws, wd = get_window(at + int(w))
                c[w] = self.count(
                    ws, wd,
                    failed_kb=self.kb if int(k_ovf[w]) else 0,  # gslint: disable=host-sync (k_ovf is a host numpy array — materialized by the finalize gather above, no device sync)
                    failed_cap=self.cap if int(b_ovf[w]) else 0)  # gslint: disable=host-sync (b_ovf is a host numpy array — materialized by the finalize gather above, no device sync)
            counts.extend(int(x) for x in c)

        try:
            ingress_pipeline.run_pipeline(
                range(0, num_w, self.MAX_STREAM_WINDOWS),
                prep, h2d, dispatch, finalize,
                timers=self.stage_timers)
        except Exception:
            self.drained_counts = list(counts)
            raise
        return counts

    def count_stream(self, src: np.ndarray, dst: np.ndarray) -> list:
        """Exact counts of every tumbling `edge_bucket`-sized window,
        batched into one sharded program per MAX_STREAM_WINDOWS windows
        (the multi-chip form of TriangleWindowKernel.count_stream): the
        COO chunk is laid out [W, eb] with the edge axis sharded over
        the mesh, a lax.map folds the windows, and overflowing windows
        are recounted individually down the escalation ladder."""
        src = np.asarray(src, np.int32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/python COO, never device arrays)
        dst = np.asarray(dst, np.int32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/python COO, never device arrays)
        if len(src) == 0:
            return []
        num_w, s, d, valid = seg_ops.window_stack(src, dst, self.eb,
                                                  sentinel=self.vb)
        eb = self.eb
        with telemetry.span("sharded.stream", tier="sharded",
                            engine="triangles", mesh=self.n,
                            windows=num_w, edges=len(src)):
            counts = self._run_stack(
                s, d, valid,
                lambda w: (src[w * eb:(w + 1) * eb],
                           dst[w * eb:(w + 1) * eb]))
        # health-plane mark at this top-level entry only: _run_stack
        # is shared with count_windows — the driver's flush path —
        # whose windows the driver already marks at its chunk boundary
        metrics.mark_window(len(counts), len(src),
                            engine="sharded_triangles",
                            tier="sharded", mesh_shape=[self.n])
        return counts

    def count_windows(self, windows) -> list:
        """Exact counts of a list of (src, dst) window batches of
        varying lengths (each ≤ edge_bucket) in chunked sharded
        dispatches — the multi-chip form of
        TriangleWindowKernel.count_windows (used by the driver's
        batched event-time windows)."""
        if not windows:
            return []
        s, d, valid = seg_ops.stack_window_list(windows, self.eb,
                                                self.vb)
        with telemetry.span("sharded.stream", tier="sharded",
                            engine="triangles", mesh=self.n,
                            windows=len(windows),
                            edges=sum(len(w[0]) for w in windows)):
            return self._run_stack(s, d, valid, lambda w: windows[w])


# ----------------------------------------------------------------------
# host-facing wrappers
# ----------------------------------------------------------------------

class ShardedWindowEngine:
    """Per-mesh compiled kernels for sharded window analytics.

    One engine per (mesh, vertex-bucket): the jitted programs are reused
    across windows, so steady-state streaming pays zero recompilation.
    """

    def __init__(self, mesh=None, num_vertices_bucket: int = 1 << 16):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n = shard_count(self.mesh)
        self.vb = num_vertices_bucket
        self.degree_fn = make_sharded_degree_fn(self.mesh, self.vb)
        self.cc_fn = make_sharded_cc_fn(self.mesh, self.vb)
        self.tri_fn = make_sharded_triangle_fn(self.mesh)
        self._degree_state = jnp.zeros(self.vb + 2, jnp.int32)
        self._labels = jnp.arange(self.vb + 2, dtype=jnp.int32)
        # bipartite double cover runs CC over 2·vb cover vertices
        # (ops/unionfind.bipartite_labels, sharded): built lazily
        self._bip_fn = None
        self._bip_labels = None
        # sliding pane-reduce programs, per (pane_bucket, wp, monoid)
        self._pane_fns = {}

    def reset(self) -> None:
        """Clear carried analytics state; compiled programs are kept, so
        a reset engine re-streams with zero recompilation (used by
        measurement warmup)."""
        self._degree_state = jnp.zeros(self.vb + 2, jnp.int32)
        self._labels = jnp.arange(self.vb + 2, dtype=jnp.int32)
        self._bip_labels = None

    def _prep(self, src, dst, sentinel: int = None):
        src, dst = pad_edges_for_mesh(
            np.asarray(src, np.int32), np.asarray(dst, np.int32),
            self.mesh,
            sentinel=self.vb + 1 if sentinel is None else sentinel,
        )
        src, dst = guard_wire(
            (src, dst), self.n,
            self.vb + 1 if sentinel is None else sentinel)
        return jnp.asarray(src), jnp.asarray(dst)

    def _dispatch(self, analytic: str, edges: int, fn):
        """One per-window sharded dispatch: tier-labeled span, the
        shard_dispatch fault hook, and the stage watchdog/retry when
        armed (every fn here is pure — state rebinds on success
        only)."""
        def _call():
            fire_shard_dispatch(self.n)
            return fn()

        with telemetry.span("sharded.window", tier="sharded",
                            analytic=analytic, mesh=self.n,
                            edges=edges):
            return _guarded_dispatch(("sharded", analytic), _call)

    def degrees(self, src, dst) -> np.ndarray:
        """Fold a window batch into the running degree vector."""
        s, d = self._prep(src, dst)
        # sentinel slot vb+1 absorbs padding; the kernel buckets to vb+1
        # rows plus sentinel, so state length is vb+2
        self._degree_state = self._dispatch(
            "degrees", len(np.atleast_1d(src)),
            lambda: self.degree_fn(s, d, self._degree_state))
        return np.asarray(self._degree_state[: self.vb])

    def cc_labels(self, src, dst, carry: bool = True) -> np.ndarray:
        """Label propagation over a window batch; carry=True keeps labels
        across windows (streaming iteration P5)."""
        s, d = self._prep(src, dst)
        labels = self._labels if carry else jnp.arange(
            self.vb + 2, dtype=jnp.int32
        )
        self._labels = self._dispatch(
            "cc", len(np.atleast_1d(src)),
            lambda: self.cc_fn(s, d, labels))
        return np.asarray(self._labels[: self.vb])

    def bipartite(self, src, dst, carry: bool = True):
        """Sharded bipartiteness via the double cover: edge u~w joins
        (u,+)-(w,-) and (u,-)-(w,+) over 2·vb cover vertices; a vertex
        whose covers share a component sits on an odd cycle
        (ops/unionfind.bipartite_labels, distributed with the same
        pmin label exchange as cc; replaces the reference's O(C²·V)
        Candidates.merge, example/util/Candidates.java:76-138).

        Returns (labels[vb], signs[vb], odd[vb]); carry=True folds the
        window into the running cover labeling (streaming semantics of
        the merge tree)."""
        if self._bip_fn is None:
            self._bip_fn = make_sharded_cc_fn(self.mesh, 2 * self.vb)
        fresh = jnp.arange(2 * self.vb + 2, dtype=jnp.int32)
        labels = self._bip_labels if (carry and self._bip_labels
                                      is not None) else fresh
        s2, d2 = unionfind.double_cover_edges(src, dst, self.vb)
        s2, d2 = pad_edges_for_mesh(s2.astype(np.int32),
                                    d2.astype(np.int32), self.mesh,
                                    sentinel=2 * self.vb + 1)
        s2, d2 = guard_wire((s2, d2), self.n, 2 * self.vb + 1)
        s2j, d2j = jnp.asarray(s2), jnp.asarray(d2)
        self._bip_labels = self._dispatch(
            "bipartite", len(np.atleast_1d(src)),
            lambda: self._bip_fn(s2j, d2j, labels))
        return unionfind.decode_double_cover(
            np.asarray(self._bip_labels), self.vb)

    # ------------------------------------------------------------------
    # checkpoint / resume (utils/checkpoint.py)
    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        """Gathered-host snapshot of the carried analytics state. The
        slabs are REPLICATED across the mesh (every merge ends in a
        psum/pmin), so the d2h gather of shard 0's copy IS the
        shard-count-independent layout: a checkpoint taken on a 4-way
        mesh loads into any mesh width, the single-chip driver
        mirrors, or the numpy twin (parallel/host_twin) unchanged.
        `mesh_shape` records provenance only — load ignores it."""
        fire_shard_gather(self.n)
        state = {
            "vb": self.vb,
            "mesh_shape": [self.n],  # gslint: disable=ckpt-symmetry (provenance only — load adopts any mesh width)
            "degree_state": np.asarray(self._degree_state),  # gslint: disable=host-sync (sanctioned checkpoint boundary: state_dict's batched gather, same discipline as scan_analytics.state_dict)
            "labels": np.asarray(self._labels),  # gslint: disable=host-sync (sanctioned checkpoint boundary: state_dict's batched gather)
        }
        if self._bip_labels is not None:
            state["bip_labels"] = np.asarray(self._bip_labels)  # gslint: disable=host-sync (sanctioned checkpoint boundary: state_dict's batched gather)
        return state

    def load_state_dict(self, state: dict) -> None:
        if state["vb"] != self.vb:
            raise ValueError(
                f"vertex bucket mismatch: checkpoint has {state['vb']}, "
                f"engine built with {self.vb}")
        self._degree_state = jnp.asarray(state["degree_state"])
        self._labels = jnp.asarray(state["labels"])
        # restore must be symmetric: a checkpoint taken before any
        # bipartite call clears post-checkpoint cover state
        self._bip_labels = (jnp.asarray(state["bip_labels"])
                            if "bip_labels" in state else None)

    @staticmethod
    def _pad_mesh_arrays(target: int, *arr_fill_pairs):
        """Pad each (array, fill) pair to `target` — the shared
        pad-to-mesh idiom of triangles() and sliding_reduce()."""
        return [seg_ops.pad_to(a, target, fill=f)
                for a, f in arr_fill_pairs]

    def triangles(self, nbr, ea, eb, emask) -> int:
        target = mesh_padded_len(len(ea), self.mesh)
        sentinel = nbr.shape[0] - 1
        ea, eb, emask = self._pad_mesh_arrays(
            target,
            (np.asarray(ea, np.int32), sentinel),
            (np.asarray(eb, np.int32), sentinel),
            (np.asarray(emask, bool), False))
        ea, eb = guard_wire((ea, eb), self.n, sentinel)
        nbr_j, ea_j, eb_j, em_j = (jnp.asarray(nbr), jnp.asarray(ea),
                                   jnp.asarray(eb), jnp.asarray(emask))
        return self._dispatch(
            "triangles", len(ea),
            lambda: int(self.tri_fn(nbr_j, ea_j, eb_j, em_j)))

    def sliding_reduce(self, src, pane, val, num_panes: int,
                       panes_per_window: int, name: str = "sum",
                       fn=None):
        """Sliding-window reduce over the mesh (the engine form of
        make_sharded_pane_reduce; docs/DESIGN.md §1.1): `pane` gives
        each edge's dense slide-index, windows cover panes_per_window
        consecutive panes. Pass `name` for a monoid, or `fn` (with
        name=None) for a user fn declared associative — the same two
        tiers as the single-chip pane path. Returns numpy
        (win_vals, win_counts), both
        [pane_bucket + panes_per_window - 1, vb + 1]; a (w, v) cell is
        meaningful iff win_counts[w, v] > 0 (real edge counts in both
        tiers), window w covering panes
        [w - panes_per_window + 1, w]. Programs are cached per
        (pane_bucket, panes_per_window, combine), so steady-state
        streaming pays zero recompilation."""
        if fn is not None:
            name = None
        pb = seg_ops.bucket_size(num_panes)
        key = (pb, panes_per_window, name or fn)
        pane_fn = self._pane_fns.get(key)
        if pane_fn is None:
            pane_fn = make_sharded_pane_reduce(self.mesh, self.vb, pb,
                                               panes_per_window,
                                               name=name, fn=fn)
            self._pane_fns[key] = pane_fn
        src = np.asarray(src, np.int32)
        pane = np.asarray(pane, np.int32)
        val = np.asarray(val)
        n = len(src)
        # power-of-two edge bucket FIRST, then the mesh multiple:
        # varying window edge counts reuse O(log E) compiled programs
        # (the docstring's zero-steady-state-recompilation claim) —
        # padded lanes are valid=False, routed to the trash cell
        target = mesh_padded_len(seg_ops.bucket_size(n), self.mesh)
        src, pane, val, valid = self._pad_mesh_arrays(
            target, (src, 0), (pane, 0), (val, 0),
            (np.ones(n, bool), False))
        src, pane = guard_wire((src, pane), self.n,
                               max(self.vb, pb))
        args = tuple(jnp.asarray(a) for a in (src, pane, val, valid))
        wv, wc = self._dispatch("sliding_reduce", n,
                                lambda: pane_fn(*args))
        return np.asarray(wv), np.asarray(wc)


# ----------------------------------------------------------------------
# sharded fused analytics scan: every analytic, every window, one
# multi-chip dispatch per chunk (the sharded ops/scan_analytics.py)
# ----------------------------------------------------------------------

def make_sharded_summary_scan(mesh, eb: int, vb: int, kb: int, cap: int,
                              table: str = "replicated"):
    """shard_map( lax.scan( per-window fused body ) ): the carry
    (degree vector, CC labels, double-cover labels) is replicated; each
    window's edges are sharded; all merges ride ICI collectives inside
    the scan. Cover layout matches ops/scan_analytics.py: (+) = v,
    (−) = vb+1+v, shared sentinel slot vb."""
    n = shard_count(mesh)
    sent = vb
    tri_body = build_sharded_window_counter(n, eb, vb, kb, cap,
                                            table=table)
    pmin_exchange = functools.partial(jax.lax.pmin, axis_name=SHARD_AXIS)

    def body(carry, xs):
        deg, labels, cover = carry
        src, dst, valid = xs
        s = jnp.where(valid, src, sent)
        d = jnp.where(valid, dst, sent)
        ones = jnp.where(valid, 1, 0)

        local = (jax.ops.segment_sum(ones, s, vb + 1)
                 + jax.ops.segment_sum(ones, d, vb + 1))
        deg = deg + jax.lax.psum(local, SHARD_AXIS)
        max_degree = jnp.max(deg[:vb])

        labels = unionfind.cc_fixpoint(labels, s, d,
                                       exchange=pmin_exchange)
        touched = deg[:vb] > 0
        num_components = jnp.sum(
            touched & (labels[:vb] == jnp.arange(vb)), dtype=jnp.int32)

        cover = unionfind.cc_fixpoint(
            cover, jnp.concatenate([s, s + (vb + 1)]),
            jnp.concatenate([d + (vb + 1), d]), exchange=pmin_exchange)
        odd = jnp.any(touched & (cover[:vb] == cover[vb + 1:2 * vb + 1]))

        tri, b_ovf, k_ovf = tri_body(src, dst, valid)
        return (deg, labels, cover), (
            max_degree, num_components, odd, tri, b_ovf, k_ovf)

    # shard_map_norep: the scan body runs cc_fixpoint (a while_loop
    # the replication checker cannot type); psum/pmin exchanges make
    # every output replicated
    @shard_map_norep(
        mesh, in_specs=(
            (P(), P(), P()),                               # carry
            P(None, SHARD_AXIS), P(None, SHARD_AXIS),      # [W, eb]
            P(None, SHARD_AXIS),
        ),
        out_specs=((P(), P(), P()),
                   (P(), P(), P(), P(), P(), P())),
    )
    def run(carry, src_w, dst_w, valid_w):
        return jax.lax.scan(body, carry, (src_w, dst_w, valid_w))

    return jax.jit(run)


def make_sharded_snapshot_scan(mesh, vb: int, analytics: tuple,
                               deltas: bool = False):
    """Sharded form of the driver's batched snapshot scan
    (core/driver._build_snapshot_scan): lax.scan over [W, eb] window
    stacks with the edge axis sharded over the mesh, carrying the
    ShardedWindowEngine's state layouts — degrees [vb+2] (sentinel
    vb+1), cc labels [vb+2], double cover [2vb+2] ((+) = v,
    (−) = vb + v, sentinels 2vb/2vb+1) — and emitting per-window
    replicated snapshots. Merges ride psum (degrees) and pmin (labels)
    over ICI inside the scan, so a whole chunk of windows costs one
    multi-chip dispatch."""
    want_deg = "degrees" in analytics
    want_cc = "cc" in analytics
    want_bip = "bipartite" in analytics
    pmin_ex = functools.partial(jax.lax.pmin, axis_name=SHARD_AXIS)

    def body(carry, xs):
        deg, labels, cover = carry
        src, dst, valid = xs          # local shard slice [eb / n]
        sent = vb + 1
        s = jnp.where(valid, src, sent)
        d = jnp.where(valid, dst, sent)
        outs = {}
        if want_deg:
            ones = jnp.where(valid, 1, 0)
            local = (jax.ops.segment_sum(ones, s, vb + 2)
                     + jax.ops.segment_sum(ones, d, vb + 2))
            new_deg = deg + jax.lax.psum(local, SHARD_AXIS)
            if deltas:
                outs["deg_chg"] = new_deg[:vb] != deg[:vb]
            deg = new_deg
            outs["deg"] = deg
        if want_cc:
            new_labels = unionfind.cc_fixpoint(labels, s, d,
                                               exchange=pmin_ex)
            if deltas:
                outs["labels_chg"] = new_labels[:vb] != labels[:vb]
            labels = new_labels
            outs["labels"] = labels
        if want_bip:
            sent2 = 2 * vb + 1
            s2 = jnp.concatenate([
                jnp.where(valid, src, sent2),
                jnp.where(valid, src + vb, sent2)])
            d2 = jnp.concatenate([
                jnp.where(valid, dst + vb, sent2),
                jnp.where(valid, dst, sent2)])
            new_cover = unionfind.cc_fixpoint(cover, s2, d2,
                                              exchange=pmin_ex)
            if deltas:
                # mask tracks the consumer-visible odd flag (the
                # same decode _run_batched applies), not raw labels
                outs["cover_chg"] = (
                    (new_cover[:vb] == new_cover[vb:2 * vb])
                    != (cover[:vb] == cover[vb:2 * vb]))
            cover = new_cover
            outs["cover"] = cover
        return (deg, labels, cover), outs

    out_tree = {}
    if want_deg:
        out_tree["deg"] = P()
        if deltas:
            out_tree["deg_chg"] = P()
    if want_cc:
        out_tree["labels"] = P()
        if deltas:
            out_tree["labels_chg"] = P()
    if want_bip:
        out_tree["cover"] = P()
        if deltas:
            out_tree["cover_chg"] = P()

    # shard_map_norep: same while_loop (cc_fixpoint) shape as the
    # summary scan above; psum/pmin make the outputs replicated
    @shard_map_norep(
        mesh, in_specs=((P(), P(), P()),
                        P(None, SHARD_AXIS), P(None, SHARD_AXIS),
                        P(None, SHARD_AXIS)),
        out_specs=((P(), P(), P()), out_tree),
    )
    def run(carry, s_w, d_w, valid_w):
        return jax.lax.scan(body, carry, (s_w, d_w, valid_w))

    return jax.jit(run)


class ShardedSummaryEngine(scan_analytics.SummaryEngineBase):
    """Multi-chip StreamSummaryEngine (ops/scan_analytics.py): carried-
    state fused analytics over [W, eb] window stacks with the edge axis
    sharded over the mesh — one dispatch per MAX_WINDOWS windows.
    Triangle windows that overflow K or the exchange capacity are
    recounted exactly by the escalating per-window sharded kernel."""

    METRICS_TIER = "sharded"

    def __init__(self, mesh, edge_bucket: int, vertex_bucket: int,
                 k_bucket: int = 0):
        self.mesh = mesh
        self.n = shard_count(mesh)
        self._tri = ShardedTriangleWindowKernel(
            mesh, edge_bucket=edge_bucket, vertex_bucket=vertex_bucket,
            k_bucket=k_bucket)
        self.eb = self._tri.eb
        self.vb = self._tri.vb
        # summaries finalized before the last escaping process() error
        # (None = clean): the demoting caller's hand-off — see process
        self.drained_partial = None
        # same compile-size cap as the single-chip FUSED engine — this
        # is the multi-analytic scan program class that wedges the
        # remote compiler at sizes the triangle program compiles (the
        # PER-DEVICE slice is eb/n, but the tunnel compiles the whole
        # program; conservative is cheap here)
        self.MAX_WINDOWS = min(type(self).MAX_WINDOWS,
                               triangles.capped_chunk(self.eb,
                                                      "fused_scan"))
        self._run = make_sharded_summary_scan(
            mesh, self.eb, self.vb, self._tri.kb, self._tri.cap,
            table=self._tri.table)
        self.reset()

    def _h2d(self, args):
        from jax.sharding import NamedSharding

        s, d = guard_wire(args[:2], self.n, self.vb)
        sharding = NamedSharding(self.mesh, P(None, SHARD_AXIS))
        return tuple(jax.device_put(a, sharding)
                     for a in (s, d) + tuple(args[2:]))

    def _dispatch_async(self, s, d, valid):
        fire_shard_dispatch(self.n)
        telemetry.event("sharded.round", engine="summary",
                        window=self.windows_done, windows=s.shape[0],
                        mesh=self.n)
        self._carry, res = self._run(self._carry, s, d, valid)
        return res

    def _materialize(self, raw):
        fire_shard_gather(self.n)
        return tuple(np.array(x) for x in raw)  # gslint: disable=host-sync (sanctioned finalize boundary: the engine's ONE batched d2h gather per chunk, pipelined one chunk behind dispatch)

    def _redo(self, src, dst, b_ovf: int, k_ovf: int) -> int:
        return self._tri.count(
            src, dst,
            failed_kb=self._tri.kb if k_ovf else 0,
            failed_cap=self._tri.cap if b_ovf else 0)

    # ------------------------------------------------------------------
    # error-path drain + mesh provenance
    # ------------------------------------------------------------------
    def _finalize_summaries(self, item, src, dst, out) -> None:
        # tee the finalized prefix by ALIAS (out only ever grows, and
        # process() snapshots it on the error path): the drain stays
        # O(W) total, not O(W²) of per-chunk copies
        super()._finalize_summaries(item, src, dst, out)
        self._partial_out = out

    def process(self, src: np.ndarray, dst: np.ndarray) -> list:
        """SummaryEngineBase.process over the mesh, with the sharded
        drain contract: when an error escapes (dead shard, ICI stall,
        corrupt wire), the in-flight chunk's finalize has already
        drained (ops/ingress_pipeline) and the summaries of every
        window finalized before the failure land on
        `self.drained_partial` — windows_done/resume_offset() then sit
        exactly past them, so a caller can hand the drained prefix
        over and re-enter on a twin (parallel/host_twin) or a fresh
        mesh from the last finalized window instead of recomputing
        delivered work."""
        self._partial_out = []
        self.drained_partial = None
        with telemetry.span("sharded.stream", tier="sharded",
                            engine="summary", mesh=self.n,
                            edges=len(np.atleast_1d(src))):
            try:
                return super().process(src, dst)
            except Exception:
                self.drained_partial = list(self._partial_out)
                raise
            finally:
                self._partial_out = []

    def state_dict(self) -> dict:
        fire_shard_gather(self.n)
        state = super().state_dict()
        # provenance only (load ignores it): the carry d2h'd above is
        # replicated, so the layout is shard-count independent — the
        # single-chip engine and the host twin load it unchanged
        state["mesh_shape"] = [self.n]
        return state

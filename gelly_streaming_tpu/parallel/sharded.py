"""Sharded window kernels: the multi-chip execution of the hot paths.

Implements the parallelism strategies of SURVEY.md §2.4 as `shard_map`
programs over a 1-D mesh:

- P1 (vertex-keyed data parallelism): a window's COO batch is sharded
  across chips along the edge dimension; per-vertex grouping happens in
  dense vertex space so no cross-chip regrouping is needed.
- P2 (partition-local fold + merge): per-shard partial aggregates are
  merged with collectives — `psum` for monoid summaries (degrees,
  counts, triangle partials), elementwise `pmin` label exchange for
  union-find — replacing the reference's parallelism-1 merger funnel
  (WindowGraphAggregation.java:58) with an ICI tree-reduce.
- P3 (broadcast replication): vertex state (labels, degree vectors,
  adjacency rows) is replicated; edges never move.

Every kernel is a single XLA program per window: the collective merge
is fused into the same computation as the local fold.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from .mesh import SHARD_AXIS, make_mesh, mesh_padded_len, pad_edges_for_mesh
from ..ops import segment as seg_ops
from ..ops import triangles, unionfind


# ----------------------------------------------------------------------
# sharded continuous degrees (P1 + P2: segment-sum + psum)
# ----------------------------------------------------------------------

def make_sharded_degree_fn(mesh, num_vertices_bucket: int):
    """Returns jitted fn(src, dst, counts) -> counts' where src/dst are
    edge-sharded and counts is the replicated running [V+1] degree
    vector (continuous-degree semantics of SimpleEdgeStream.java:465-482,
    batched)."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=P(),
    )
    def step(src, dst, counts):
        ones = jnp.ones_like(src, jnp.int32)
        # vb+2 rows: [0, vb) real vertices, vb+1 the padding sentinel
        local = jax.ops.segment_sum(ones, src, num_vertices_bucket + 2)
        local = local + jax.ops.segment_sum(ones, dst, num_vertices_bucket + 2)
        return counts + jax.lax.psum(local, SHARD_AXIS)

    return jax.jit(step)


# ----------------------------------------------------------------------
# sharded connected components (P1 + P2: scatter-min + pmin exchange)
# ----------------------------------------------------------------------

def make_sharded_cc_fn(mesh, num_vertices_bucket: int):
    """Returns jitted fn(src, dst, labels) -> labels' running min-label
    propagation to the fixpoint: each chip folds its edge shard into the
    replicated label vector, shards exchange labels with an elementwise
    `pmin` every round (the collective merge tree), then pointer-jump.
    `labels` holds [V+1] int32 (slot V = padding sentinel); pass
    arange for a fresh window or carry the previous state for the
    streaming-iteration semantics of IterativeConnectedComponents."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=P(),
    )
    def step(src, dst, labels):
        assert labels.shape[0] == num_vertices_bucket + 2, labels.shape
        return unionfind.cc_fixpoint(
            labels, src, dst,
            exchange=lambda lab: jax.lax.pmin(lab, SHARD_AXIS),  # ICI merge
        )

    return jax.jit(step)


# ----------------------------------------------------------------------
# sharded triangle count (P1 edges + P3 replicated adjacency + psum)
# ----------------------------------------------------------------------

def make_sharded_triangle_fn(mesh):
    """Returns jitted fn(nbr, ea, eb, emask) -> count with the oriented
    edge list sharded across chips and the sorted-adjacency matrix
    replicated; per-shard intersection partials reduce with one psum."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(),
    )
    def step(nbr, ea, eb, emask):
        local = triangles.intersect_local(nbr, ea, eb, emask)
        return jax.lax.psum(local, SHARD_AXIS)

    return jax.jit(step)


# ----------------------------------------------------------------------
# host-facing wrappers
# ----------------------------------------------------------------------

class ShardedWindowEngine:
    """Per-mesh compiled kernels for sharded window analytics.

    One engine per (mesh, vertex-bucket): the jitted programs are reused
    across windows, so steady-state streaming pays zero recompilation.
    """

    def __init__(self, mesh=None, num_vertices_bucket: int = 1 << 16):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.vb = num_vertices_bucket
        self.degree_fn = make_sharded_degree_fn(self.mesh, self.vb)
        self.cc_fn = make_sharded_cc_fn(self.mesh, self.vb)
        self.tri_fn = make_sharded_triangle_fn(self.mesh)
        self._degree_state = jnp.zeros(self.vb + 2, jnp.int32)
        self._labels = jnp.arange(self.vb + 2, dtype=jnp.int32)

    def _prep(self, src, dst):
        src, dst = pad_edges_for_mesh(
            np.asarray(src, np.int32), np.asarray(dst, np.int32),
            self.mesh, sentinel=self.vb + 1,
        )
        return jnp.asarray(src), jnp.asarray(dst)

    def degrees(self, src, dst) -> np.ndarray:
        """Fold a window batch into the running degree vector."""
        s, d = self._prep(src, dst)
        # sentinel slot vb+1 absorbs padding; the kernel buckets to vb+1
        # rows plus sentinel, so state length is vb+2
        self._degree_state = self.degree_fn(s, d, self._degree_state)
        return np.asarray(self._degree_state[: self.vb])

    def cc_labels(self, src, dst, carry: bool = True) -> np.ndarray:
        """Label propagation over a window batch; carry=True keeps labels
        across windows (streaming iteration P5)."""
        s, d = self._prep(src, dst)
        labels = self._labels if carry else jnp.arange(
            self.vb + 2, dtype=jnp.int32
        )
        self._labels = self.cc_fn(s, d, labels)
        return np.asarray(self._labels[: self.vb])

    # ------------------------------------------------------------------
    # checkpoint / resume (utils/checkpoint.py)
    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "vb": self.vb,
            "degree_state": np.asarray(self._degree_state),
            "labels": np.asarray(self._labels),
        }

    def load_state_dict(self, state: dict) -> None:
        if state["vb"] != self.vb:
            raise ValueError(
                f"vertex bucket mismatch: checkpoint has {state['vb']}, "
                f"engine built with {self.vb}")
        self._degree_state = jnp.asarray(state["degree_state"])
        self._labels = jnp.asarray(state["labels"])

    def triangles(self, nbr, ea, eb, emask) -> int:
        target = mesh_padded_len(len(ea), self.mesh)
        sentinel = nbr.shape[0] - 1
        ea = seg_ops.pad_to(np.asarray(ea, np.int32), target, fill=sentinel)
        eb = seg_ops.pad_to(np.asarray(eb, np.int32), target, fill=sentinel)
        emask = seg_ops.pad_to(np.asarray(emask, bool), target, fill=False)
        return int(self.tri_fn(jnp.asarray(nbr), jnp.asarray(ea),
                               jnp.asarray(eb), jnp.asarray(emask)))

"""Multi-host (multi-slice) deployment: the DCN half of the
communication backend.

The reference scales out through Flink's TaskManager network over TCP
(SURVEY.md §5.8); the TPU-native equivalent is a hybrid device mesh
whose inner axis rides ICI (chips within a slice) and whose outer axis
rides DCN (between slices/hosts), with the same `shard_map` kernels and
collectives running unchanged on top (parallel/sharded.py):

- edge shards (P1) split over ('dcn', 'shard') — a window's batch is
  first striped across slices, then across a slice's chips;
- collective merges (P2/P6) are sequenced by XLA so the psum/pmin/pmax
  tree reduces over ICI first and crosses DCN once per window, the
  same slice-then-global shape as the reference's per-TaskManager
  pre-aggregation funnels.

`initialize_runtime` wires `jax.distributed` (one process per host,
coordinator at process 0) — the launch contract that replaces the
reference's cluster deployment descriptors.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from .mesh import SHARD_AXIS

DCN_AXIS = "dcn"


def initialize_runtime(coordinator_address: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> None:
    """Join the multi-host runtime (call once per host before any jax
    computation; single-host callers never need this). Thin, explicit
    wrapper over `jax.distributed.initialize` so deployments have one
    framework entry point."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_hybrid_mesh(ici_shards: Optional[int] = None,
                     dcn_shards: Optional[int] = None,
                     devices: Optional[Sequence] = None) -> Mesh:
    """2-D ('dcn', 'shard') mesh: inner axis = chips connected by ICI,
    outer axis = slices connected by DCN. Defaults: one DCN group per
    process, all local devices on the ICI axis.

    The sharded kernels (parallel/sharded.py) operate over the flat
    edge axis; flatten_for_edges() gives the 1-D view whose collectives
    XLA lowers to an ICI-first, DCN-once reduction tree.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if dcn_shards is None:
        dcn_shards = max(jax.process_count(), 1)
    if ici_shards is None:
        ici_shards = len(devices) // dcn_shards
    if ici_shards * dcn_shards != len(devices):
        raise ValueError(
            f"{ici_shards} ICI x {dcn_shards} DCN shards != "
            f"{len(devices)} devices")
    if dcn_shards > 1 and jax.process_count() > 1:
        # process_is_granule=True: a DCN group is one process (host),
        # matching the dcn_shards default above — slice_index-based
        # granules would require dcn_shards == number of slices and the
        # attribute to exist at all
        arr = mesh_utils.create_hybrid_device_mesh(
            (ici_shards,), (dcn_shards,), devices=devices,
            process_is_granule=True)
        arr = np.asarray(arr).reshape(dcn_shards, ici_shards)  # gslint: disable=host-sync (device HANDLES into a mesh layout, no device value in sight)
        # the reshape assumes granule-major flat ordering; if
        # mesh_utils ever lays the array out differently, ICI neighbors
        # would silently land across DCN — fail loudly instead
        for row in arr:
            procs = {d.process_index for d in row}
            if len(procs) != 1:
                raise RuntimeError(
                    "hybrid mesh layout mismatch: ICI row spans "
                    f"processes {sorted(procs)}; expected one process "
                    "per DCN granule (granule-major ordering)")
    else:  # single process: any contiguity works, DCN axis is logical
        arr = np.asarray(devices).reshape(dcn_shards, ici_shards)  # gslint: disable=host-sync (device HANDLES into a mesh layout, no device value in sight)
    return Mesh(arr, (DCN_AXIS, SHARD_AXIS))


def flatten_for_edges(mesh: Mesh) -> Mesh:
    """1-D view of a hybrid mesh for the edge-sharded kernels: the
    SHARD axis enumerates (dcn, ici) lexicographically, so consecutive
    edge shards stay on ICI neighbors and cross-slice traffic happens
    only at the collective tree's top level."""
    devices = mesh.devices.reshape(-1)
    return Mesh(devices, (SHARD_AXIS,))

"""Array union-find: min-label propagation with pointer jumping.

The device-side replacement for the reference's pointer-chasing
`DisjointSet` hot loop (example/util/DisjointSet.java:71-123, the
per-edge `find`/`union` in UpdateCC, library/ConnectedComponents.java:87-90)
and for `Candidates`' O(C²·V) merge (example/util/Candidates.java:76-138):

- `cc_labels`: per-window weakly-connected-component labels for a COO
  edge batch as one XLA program — scatter-min both directions plus
  `labels = labels[labels]` compression inside a `lax.while_loop`,
  converging in O(log diameter) rounds.
- `bipartite_labels`: 2-coloring via the bipartite double cover — the
  graph is bipartite iff (v,+) and (v,−) never share a component —
  which reduces bipartiteness to the same cc kernel (idiomatic
  vectorizable replacement per SURVEY.md §7 step 4).

Padded edge slots must point at the sentinel vertex `num_vertices`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import segment as seg_ops


@functools.partial(jax.jit, static_argnames=("num_vertices",))
def cc_labels(src: jax.Array, dst: jax.Array, num_vertices: int) -> jax.Array:
    """Labels[v] = smallest vertex index in v's component.

    src/dst: int32 [E] with padding slots set to `num_vertices`.
    Returns int32 [num_vertices + 1] (last row is the padding sentinel).
    """
    labels0 = jnp.arange(num_vertices + 1, dtype=jnp.int32)

    def cond(state):
        _, changed = state
        return changed

    def body(state):
        labels, _ = state
        m = jnp.minimum(labels[src], labels[dst])
        new = labels.at[src].min(m).at[dst].min(m)
        # pointer jumping: jump each label to its label's label
        new = new[new]
        return new, jnp.any(new != labels)

    labels, _ = jax.lax.while_loop(cond, body, (labels0, jnp.array(True)))
    return labels


def connected_components(src: np.ndarray, dst: np.ndarray,
                         num_vertices: int) -> np.ndarray:
    """Host wrapper: pads to buckets and returns labels[:num_vertices]."""
    e = len(src)
    eb = seg_ops.bucket_size(e)
    vb = seg_ops.bucket_size(num_vertices)
    s = seg_ops.pad_to(np.asarray(src, np.int32), eb, fill=vb)
    d = seg_ops.pad_to(np.asarray(dst, np.int32), eb, fill=vb)
    labels = np.asarray(cc_labels(jnp.asarray(s), jnp.asarray(d), vb))
    # bucket-padding vertices are isolated; compress to true vertex range
    return labels[:num_vertices]


def bipartite_labels(src: np.ndarray, dst: np.ndarray, num_vertices: int):
    """2-coloring via the double cover.

    Returns (labels[num_vertices], signs[num_vertices], odd[num_vertices]):
    `labels` are component labels of the underlying graph, `signs` the
    side of the bipartition relative to the component's minimum vertex,
    and `odd[v]` True iff v's component contains an odd cycle.
    """
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    v = num_vertices
    # double cover: (u,+)=u, (u,-)=u+v; edge u~w joins (u,+)-(w,-), (u,-)-(w,+)
    s2 = np.concatenate([src, src + v])
    d2 = np.concatenate([dst + v, dst])
    lab2 = connected_components(s2, d2, 2 * v)
    plus, minus = lab2[:v], lab2[v:]
    odd = plus == minus
    # For a bipartite component with min vertex m: the (+) cover of m's
    # side and the (−) cover of the other side form one cover component
    # whose min index is m itself; the other cover component's min index
    # is the other side's min vertex m2 > m. Hence both cover labels are
    # < v, their min is the component's min vertex, and a vertex is on
    # the root's side iff its (+) cover carries the smaller label.
    labels = np.minimum(plus, minus)
    signs = plus <= minus
    return labels, signs, odd

"""Array union-find: min-label propagation with pointer jumping.

The device-side replacement for the reference's pointer-chasing
`DisjointSet` hot loop (example/util/DisjointSet.java:71-123, the
per-edge `find`/`union` in UpdateCC, library/ConnectedComponents.java:87-90)
and for `Candidates`' O(C²·V) merge (example/util/Candidates.java:76-138):

- `cc_labels`: per-window weakly-connected-component labels for a COO
  edge batch as one XLA program — scatter-min both directions plus
  `labels = labels[labels]` compression inside a `lax.while_loop`,
  converging in O(log diameter) rounds.
- `bipartite_labels`: 2-coloring via the bipartite double cover — the
  graph is bipartite iff (v,+) and (v,−) never share a component —
  which reduces bipartiteness to the same cc kernel (idiomatic
  vectorizable replacement per SURVEY.md §7 step 4).

Padded edge slots must point at the sentinel vertex `num_vertices`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import segment as seg_ops


def cc_round(labels: jax.Array, src: jax.Array, dst: jax.Array) -> jax.Array:
    """One local min-label sweep: scatter-min each edge's smaller label
    to both endpoints AND to both endpoints' current roots
    (Shiloach-Vishkin hooking). The root hook matters for carried
    state: when a new edge merges two already-flat forests through
    non-root members, only the root relink lets the losing component's
    untouched members reach the smaller label via pointer jumping.
    Shared by the single-chip loop, the sharded loop (which adds a pmin
    exchange per round), and the fused entry step."""
    ls = labels[src]
    ld = labels[dst]
    m = jnp.minimum(ls, ld)
    return (labels.at[src].min(m).at[dst].min(m)
            .at[ls].min(m).at[ld].min(m))


def cc_fixpoint(labels0: jax.Array, src: jax.Array, dst: jax.Array,
                exchange=None, carried: bool = True) -> jax.Array:
    """Run cc_round + pointer jumping to the fixpoint inside a
    while_loop; `exchange` (e.g. a pmin over the mesh axis) merges
    labels across shards each round.

    With `carried` (labels0 is a prior forest, not a fresh arange), the
    forest's parent links (v, labels0[v]) participate as edges in every
    round. Without them, carried state can SPLIT a component: if an old
    root simultaneously merges into two different trees in one round
    (e.g. batch edges (child_of_r, x) with label m1 and (r, y) with
    label m2 < m1), the scatter-min keeps only the m2 link — the
    m1-side island stays separate forever, because the evidence
    connecting it ran through prior batches' edges that are not
    replayed. The forest edges re-expose exactly that connectivity.
    Fresh-labeling callers pass carried=False to skip the dead
    self-loop edges (the flag is trace-time static)."""
    if carried:
        fsrc = jnp.arange(labels0.shape[0], dtype=jnp.int32)
        fdst = labels0.astype(jnp.int32)
        src = jnp.concatenate([src.astype(jnp.int32), fsrc])
        dst = jnp.concatenate([dst.astype(jnp.int32), fdst])

    def cond(state):
        _, changed = state
        return changed

    def body(state):
        labels, _ = state
        new = cc_round(labels, src, dst)
        if exchange is not None:
            new = exchange(new)
        # pointer jumping: jump each label to its label's label
        new = new[new]
        return new, jnp.any(new != labels)

    labels, _ = jax.lax.while_loop(cond, body, (labels0, jnp.array(True)))
    return labels


@functools.partial(jax.jit, static_argnames=("num_vertices",))
def cc_labels(src: jax.Array, dst: jax.Array, num_vertices: int) -> jax.Array:
    """Labels[v] = smallest vertex index in v's component.

    src/dst: int32 [E] with padding slots set to `num_vertices`.
    Returns int32 [num_vertices + 1] (last row is the padding sentinel).
    """
    labels0 = jnp.arange(num_vertices + 1, dtype=jnp.int32)
    return cc_fixpoint(labels0, src, dst, carried=False)


def connected_components(src: np.ndarray, dst: np.ndarray,
                         num_vertices: int) -> np.ndarray:
    """Host wrapper: pads to buckets and returns labels[:num_vertices]."""
    e = len(src)
    eb = seg_ops.bucket_size(e)
    vb = seg_ops.bucket_size(num_vertices)
    s = seg_ops.pad_to(np.asarray(src, np.int32), eb, fill=vb)
    d = seg_ops.pad_to(np.asarray(dst, np.int32), eb, fill=vb)
    labels = np.asarray(cc_labels(jnp.asarray(s), jnp.asarray(d), vb))
    # bucket-padding vertices are isolated; compress to true vertex range
    return labels[:num_vertices]


_cc_fixpoint_jit = jax.jit(cc_fixpoint)


def connected_components_with_labels(src: np.ndarray, dst: np.ndarray,
                                     labels: np.ndarray,
                                     num_vertices: int,
                                     vertex_bucket: int = 0,
                                     edge_bucket: int = 0) -> np.ndarray:
    """Carried-state variant: fold a batch of edges into an existing
    labeling (streaming-iteration semantics, strategy P5). `labels` is a
    dense int32 [num_vertices] forest pointing at equal-or-smaller
    slots; returns the converged labels of the same length.

    BOTH dimensions are bucketed — edges to the edge bucket, the label
    vector to the vertex bucket (padding slots are isolated identity
    labels; slot vb is the edge-padding sentinel) — so a stream whose
    vertex count grows every window compiles O(log² ) programs, not one
    per distinct count (a steady-state-recompile bug caught by
    tools/scale_run.py's jax_log_compiles assert in round 2). Callers
    that already hold a grown bucket (the streaming driver) pass it as
    `vertex_bucket` so every window reuses ONE program; passing
    `edge_bucket` likewise clamps smaller batches UP to the steady
    program (a stream's final partial window must not compile a fresh
    tiny-bucket ladder at the tail — caught by tools/endurance_run.py's
    steady-state compile assert)."""
    e = len(src)
    eb = seg_ops.bucket_size(max(e, edge_bucket))
    vb = seg_ops.bucket_size(max(num_vertices, vertex_bucket))
    s = seg_ops.pad_to(np.asarray(src, np.int32), eb, fill=vb)
    d = seg_ops.pad_to(np.asarray(dst, np.int32), eb, fill=vb)
    lab = np.concatenate([np.asarray(labels, np.int32),
                          np.arange(num_vertices, vb + 1, dtype=np.int32)])
    out = np.asarray(_cc_fixpoint_jit(jnp.asarray(lab), jnp.asarray(s),
                                      jnp.asarray(d)))
    return out[:num_vertices]


def double_cover_edges(src: np.ndarray, dst: np.ndarray,
                       num_vertices: int):
    """Build the bipartite double cover's edge list: (u,+)=u, (u,-)=u+v;
    edge u~w joins (u,+)-(w,-) and (u,-)-(w,+). Shared by the host
    and sharded bipartiteness paths."""
    src = np.asarray(src, np.int64)  # gslint: disable=host-sync (host-input normalization: cover construction is numpy-on-numpy)
    dst = np.asarray(dst, np.int64)  # gslint: disable=host-sync (host-input normalization: cover construction is numpy-on-numpy)
    v = num_vertices
    return np.concatenate([src, src + v]), np.concatenate([dst + v, dst])


def decode_double_cover(lab2: np.ndarray, num_vertices: int):
    """(labels, signs, odd) from converged cover labels [>= 2·v].

    For a bipartite component with min vertex m: the (+) cover of m's
    side and the (−) cover of the other side form one cover component
    whose min index is m itself; the other cover component's min index
    is the other side's min vertex m2 > m. Hence both cover labels are
    < v, their min is the component's min vertex, and a vertex is on
    the root's side iff its (+) cover carries the smaller label. An odd
    cycle collapses both covers into one component (plus == minus)."""
    v = num_vertices
    plus, minus = lab2[:v], lab2[v:2 * v]
    return np.minimum(plus, minus), plus <= minus, plus == minus


def bipartite_labels(src: np.ndarray, dst: np.ndarray, num_vertices: int):
    """2-coloring via the double cover.

    Returns (labels[num_vertices], signs[num_vertices], odd[num_vertices]):
    `labels` are component labels of the underlying graph, `signs` the
    side of the bipartition relative to the component's minimum vertex,
    and `odd[v]` True iff v's component contains an odd cycle.
    """
    s2, d2 = double_cover_edges(src, dst, num_vertices)
    lab2 = connected_components(s2, d2, 2 * num_vertices)
    return decode_double_cover(lab2, num_vertices)

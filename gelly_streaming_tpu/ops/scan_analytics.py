"""Fused multi-analytics streaming scan: every analytic, every window,
ONE device dispatch per chunk.

The driver's per-window calls (core/driver.py) pay one host↔device
round trip per window per analytic — the dominant cost through a
tunneled chip (ops/triangles.py docstring: ~0.2s/window). This engine
generalizes `count_stream`'s batching to the full analytics suite: a
`lax.scan` carries (degree vector, CC labels, double-cover labels)
across a `[W, eb]` stack of windows and emits per-window summary
scalars, so an entire chunk of stream costs one h2d of COO, one fused
program, one d2h of `[W]` summaries.

Summaries per window (all cumulative over the stream so far, matching
the carried-state semantics of the reference's continuous aggregates):
  max_degree      — max running degree (SimpleEdgeStream.java:465-482)
  num_components  — count of touched roots (ConnectedComponents)
  odd_cycle       — any odd cycle seen (BipartitenessCheck)
  triangles       — exact count of THIS window (WindowTriangles)
  tri_overflow    — hub outran the K bucket (host recounts exactly)

Full per-vertex snapshots remain the driver's job; this engine is the
CHIP-side throughput path. On CPU backends the driver wins instead —
measured, not argued (FUSED_BREAKDOWN.json, tools/
profile_fused_breakdown.py): the triangle stage compiled INTO this
scan is the XLA stream program, which a single core runs ~15x slower
than the measurement-selected numpy tier the driver routes through,
while the dispatch latency fusion saves is ~µs off-chip. One program
per chunk only pays when dispatches cost ~0.2s (the tunneled chip)
and the MXU/VPU runs the intersect — exactly the regime this engine
was built for.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import ingress_pipeline
from . import segment as seg_ops
from . import triangles as tri_ops
from . import unionfind
from ..utils import checkpoint
from ..utils import faults
from ..utils import latency
from ..utils import metrics
from ..utils import provenance
from ..utils import sanitize as sanitize_mod
from ..utils import telemetry
from ..utils import wal as wal_mod


def _build_scan(eb: int, vb: int, kb: int, pallas_ok: bool = True):
    """Scan body over fixed buckets. Cover layout: (+) side = v,
    (−) side = vb+1+v, so the shared sentinel slot vb (edge padding)
    maps to the two cover sentinels (vb, 2vb+1) and never touches real
    slots.

    When the fused Pallas window megakernel is selected
    (ops/pallas_window.resolve_pallas_window — GS_PALLAS_WINDOW pin or
    committed parity+≥1.05× `pallas_ab` chip rows) AND its build/trace
    probe succeeds, the returned body is the megakernel instead: one
    VMEM-tiled pallas_call per window computing ALL analytics from a
    single HBM read of the edge slab, same carry layout, same
    per-window outputs, bit-identical by construction. `pallas_ok`
    lets callers whose composition the kernel doesn't support opt
    out — build_cohort_scan's vmap form needs a pure-XLA body (its
    tenant-axis Pallas variant is its own kernel with the tenant axis
    as a grid dimension, ops/pallas_window.maybe_cohort_body, gated
    on its own evidence)."""
    if pallas_ok:
        from . import pallas_window

        pbody = pallas_window.maybe_window_body(eb, vb, kb)
        if pbody is not None:
            return pbody
    sent = vb
    # pallas_ok propagates INTO the embedded triangle counter: a
    # pallas_ok=False caller (the vmapped cohort) must get a pure-XLA
    # body all the way down — a pallas_call smuggled in through
    # tri_body would be vmapped over the tenant axis anyway
    tri_body = tri_ops.build_window_counter(vb, kb,
                                            pallas_ok=pallas_ok)

    def body(carry, xs):
        deg, labels, cover = carry
        src, dst, valid = xs
        s = jnp.where(valid, src, sent)
        d = jnp.where(valid, dst, sent)
        ones = jnp.where(valid, 1, 0)

        deg = deg + (jax.ops.segment_sum(ones, s, vb + 1)
                     + jax.ops.segment_sum(ones, d, vb + 1))
        max_degree = jnp.max(deg[:vb])

        labels = unionfind.cc_fixpoint(labels, s, d)
        touched = deg[:vb] > 0
        num_components = jnp.sum(
            touched & (labels[:vb] == jnp.arange(vb)), dtype=jnp.int32)

        cover = unionfind.cc_fixpoint(
            cover, jnp.concatenate([s, s + (vb + 1)]),
            jnp.concatenate([d + (vb + 1), d]))
        odd = jnp.any(touched & (cover[:vb] == cover[vb + 1:2 * vb + 1]))

        tri_count, tri_overflow = tri_body(src, dst, valid)

        return (deg, labels, cover), (
            max_degree, num_components, odd, tri_count, tri_overflow)

    return body


def build_cohort_scan(eb: int, vb: int, kb: int, nb: int = None):
    """The multi-tenant cohort entry (core/tenancy.py): the SAME scan
    body as every fused summary engine, lifted over a leading tenant
    axis — carries are [N, ...] slabs, edge slabs are [N, W, eb], and
    one dispatch folds one window cohort across all N streams (the
    trick the sharded path already plays for panes, applied to
    tenants). Rows are independent by construction: a padded tenant
    row (all-invalid windows) folds as a no-op against its carry, so
    per-tenant results are bit-identical to N separate
    StreamSummaryEngine runs — the parity contract tools/tenancy_ab.py
    and tests/test_tenancy.py assert window by window.

    Two lowerings, same contract:

    - default: `jax.vmap` of the pure-XLA scan body over the tenant
      axis (pallas_ok=False all the way down — a pallas_call smuggled
      into the vmapped body would be batch-lowered, not
      tenant-gridded).
    - when `nb` is given AND the TENANT-AXIS Pallas megakernel
      clears its own gate+probe (ops/pallas_window.maybe_cohort_body
      — GS_COHORT_PALLAS pin or committed non-interpret
      `cohort_pallas` rows), the window loop scans ONE pallas_call
      whose second grid dimension is the tenant axis: the whole
      cohort's carries VMEM-resident, one slab pass per window round.
      Refusal (gate off, VMEM budget, trace probe) degrades to the
      vmap form with a durable `selection.fallback` event — digests
      are bit-identical either way."""
    if nb is not None:
        from . import pallas_window

        cbody = pallas_window.maybe_cohort_body(eb, vb, kb, nb)
        if cbody is not None:
            def run_pallas(carries, src, dst, valid):
                # [N, W, eb] -> [W, N, eb]: the window axis is the
                # scan axis, the tenant axis rides into the kernel
                xs = tuple(jnp.moveaxis(a, 0, 1)
                           for a in (src, dst, valid))
                carries, ys = jax.lax.scan(cbody, carries, xs)
                # per-window outputs come back [W, N] — restore the
                # vmap form's [N, W] leading tenant axis
                return carries, tuple(jnp.moveaxis(y, 0, 1)
                                      for y in ys)

            run_pallas.pallas_window = True
            return run_pallas

    body = _build_scan(eb, vb, kb, pallas_ok=False)

    def one_tenant(carry, src_w, dst_w, valid_w):
        return jax.lax.scan(body, carry, (src_w, dst_w, valid_w))

    def run(carries, src, dst, valid):
        return jax.vmap(one_tenant)(carries, src, dst, valid)

    return run


class SummaryEngineBase:
    """Shared scaffolding of the single-chip and sharded fused scan
    engines: carried-state reset/snapshot, the chunk loop, the
    partial-window-must-be-final guard, and summary assembly.
    Subclasses provide `_dispatch_async` (enqueue one [W, eb] chunk
    against the device-resident carry, returning raw un-materialized
    outputs), `_materialize` (d2h those outputs into the writable
    summary tuple (mdeg, ncomp, odd, tri, b_ovf, k_ovf)), and `_redo`
    (exact triangle recount of one overflowing window)."""

    MAX_WINDOWS = 64
    # tier label of this engine's mark_window health-plane marks —
    # subclasses on another tier (sharded mesh, numpy host twin)
    # override it so /healthz never claims the single-chip scan tier
    # for a demoted or mesh-resident stream
    METRICS_TIER = "fused_scan"
    # stream-chunk wire format; StreamSummaryEngine resolves it from
    # committed evidence (tri_ops.resolve_ingress), the sharded engine
    # keeps the standard format (its chunks are mesh-sharded)
    ingress = "standard"
    # online dispatch autotuning (ops/autotune.py): only the
    # single-chip engine opts in — the sharded engine's jit programs
    # have no AOT warm cache, so an arm change there would compile
    # mid-measurement
    AUTOTUNE = False
    TUNABLE_INGRESS = False
    # cache-identity prefix of the dispatch tuner (ops/autotune): the
    # resident engine re-keys its own family so its learned
    # windows-per-superbatch never cross-seeds the scan tier's
    TUNER_FAMILY = "fused_scan"
    # max prepped+transferred chunks in flight ahead of dispatch; None
    # = the global GS_PIPELINE_INFLIGHT. The resident engine narrows
    # it to its GS_RESIDENT_SLOTS ingest ring.
    INGEST_SLOTS = None

    def reset(self) -> None:
        self._closed_partial = False
        self.windows_done = 0  # resume cursor (checkpoint/resume)
        if not hasattr(self, "stage_timers"):
            # per-stage pipeline counters (ops/ingress_pipeline);
            # survive reset() so a timed run's snapshot is cumulative
            # until explicitly .reset()
            self.stage_timers = ingress_pipeline.StageTimers()
        if not hasattr(self, "_ckpt_path"):
            # auto-checkpoint config survives reset() like the timers
            self._ckpt_path = None
            self._ckpt_policy = None
        if not hasattr(self, "_lat_lane"):
            # latency-plane lane of this engine's windows; a cohort
            # demotion re-points it at the tenant (core/tenancy),
            # clears _lat_admit (the cohort's feed() already stamped
            # admission at the serving boundary) and mirrors the
            # cohort's delivery deferral per pump
            self._lat_lane = None
            self._lat_admit = True
            self._lat_defer = False
        # per-chunk stage-boundary stamps keyed by chunk start
        # (filled by the dispatch closure, drained by
        # _finalize_summaries). Cleared on EVERY reset — a stamp
        # stranded by a mid-call failure must never join a later
        # run's window at the same chunk offset.
        self._lat_stamps = {}
        # cumulative fed edges incl. sanitizer rejects — the DLQ's
        # source-offset domain for this engine's admission boundary
        self._fed_edges = 0
        if not hasattr(self, "_wal"):
            # write-ahead journal config survives reset() too
            self._wal = None
            self._wal_dir = None
            self._wal_tenant = "engine"
            # GS_WAL_RETAIN bookkeeping (utils/wal.RetentionCursor):
            # remembers the last two flushed checkpoint offsets so
            # truncation never outruns a rotation-fallback recovery
            self._wal_retention = wal_mod.RetentionCursor()
        elif self._ckpt_policy is not None:
            # re-anchor the cadence with the rewound cursor: a stale
            # high-water mark would suppress every due() until the new
            # stream re-passed it (same fix as the driver's reset)
            self._ckpt_policy.mark(0)
        self._carry = self._init_carry()

    def _init_carry(self):
        """Fresh carried state in the shared layout (degrees [vb+1],
        cc labels [vb+1], double cover [2(vb+1)]; sentinel slot vb).
        Device engines carry jnp arrays; the numpy host twin
        (parallel/host_twin.HostSummaryEngine) overrides this and
        `_to_carry` to stay off the device entirely — the layout (and
        therefore checkpoint interchangeability) is identical."""
        return (
            jnp.zeros(self.vb + 1, jnp.int32),
            jnp.arange(self.vb + 1, dtype=jnp.int32),
            jnp.arange(2 * (self.vb + 1), dtype=jnp.int32),
        )

    def _to_carry(self, a):
        """Lift one restored checkpoint leaf into this engine's carry
        representation (device array by default; numpy on the host
        twin)."""
        return jnp.asarray(a)

    def state(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(degrees[vb], cc_labels[vb], odd[vb]) snapshots."""
        deg, labels, cover = (np.asarray(x) for x in self._carry)  # gslint: disable=host-sync (sanctioned snapshot boundary: the engine's state() d2h)
        odd = cover[: self.vb] == cover[self.vb + 1: 2 * self.vb + 1]
        return deg[: self.vb], labels[: self.vb], odd

    # ------------------------------------------------------------------
    # checkpoint / resume (utils/checkpoint.py)
    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        """Full resumable state: the carried vectors (d2h'd to host
        arrays) plus the windows_done cursor. The layout is the
        carry's own, shared by the single-chip and sharded engines, so
        checkpoints are engine-interchangeable at equal buckets. When
        the online tuner is live, its learned state rides along so a
        resumed stream keeps its configuration."""
        carry = tuple(np.array(x) for x in self._carry)  # gslint: disable=host-sync (sanctioned checkpoint boundary: state_dict's one d2h)
        state = {
            "edge_bucket": self.eb,
            "vertex_bucket": self.vb,
            "windows_done": int(self.windows_done),
            "closed_partial": bool(self._closed_partial),
            # journal offset at this finalized-window boundary (edges
            # folded into the carry): resume_and_replay() re-feeds the
            # WAL strictly past it (DESIGN.md §18)
            "wal_offset": int(self.windows_done) * self.eb,
            "carry": carry,
        }
        if getattr(self, "_tuner", None) is not None:
            state["autotune"] = self._tuner.state_dict()
        return state

    def load_state_dict(self, state: dict) -> None:
        if state["edge_bucket"] != self.eb \
                or state["vertex_bucket"] != self.vb:
            raise ValueError(
                "bucket mismatch: checkpoint was taken at eb=%d vb=%d, "
                "engine runs eb=%d vb=%d — count-based windows are cut "
                "by eb, so resuming across buckets would shift every "
                "window boundary" % (state["edge_bucket"],
                                     state["vertex_bucket"],
                                     self.eb, self.vb))
        self.windows_done = int(state["windows_done"])  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
        self._closed_partial = bool(state["closed_partial"])
        woff = state.get("wal_offset")
        if woff is not None and int(woff) > self.windows_done * self.eb:
            raise ValueError(
                "checkpoint wal_offset %d exceeds its own window "
                "coverage (%d windows x eb=%d)" % (
                    int(woff), self.windows_done, self.eb))
        self._carry = tuple(self._to_carry(a) for a in state["carry"])
        # .get: checkpoints from before the autotune key (and engines
        # with the tuner off) restore without it
        if state.get("autotune") is not None and self.AUTOTUNE:
            from . import autotune

            if autotune.enabled():
                self._ensure_tuner().load_state_dict(state["autotune"])

    def enable_auto_checkpoint(self, path: str,
                               every_n_windows: int = 16,
                               every_seconds: float = 0.0,
                               policy=None) -> None:
        """Auto-snapshot on a CheckpointPolicy cadence, evaluated at
        chunk DISPATCH boundaries (where the device carry exactly
        covers the finalized-or-dispatched prefix) and flushed to disk
        only once every covered window's summary has been handed to
        the caller — the driver's staged at-least-once contract."""
        if policy is None:
            policy = checkpoint.CheckpointPolicy(
                every_n_windows=max(0, every_n_windows),
                every_seconds=every_seconds)
        if not policy.enabled():
            raise ValueError("checkpoint policy has no trigger enabled")
        self._ckpt_path = path
        self._ckpt_policy = policy

    def try_resume(self, path: str) -> bool:
        """Restore from the newest intact checkpoint generation
        (rotation fallback on corruption — utils/checkpoint.
        load_latest); False when nothing usable exists. After a True
        return, feed the stream from `resume_offset()` edges in."""
        import warnings

        try:
            got = checkpoint.load_latest(path)
        except checkpoint.CheckpointCorrupt as e:
            warnings.warn(f"{e}; no intact generation — starting fresh")
            return False
        if got is None:
            return False
        state, used = got
        if used != path:
            warnings.warn(
                f"checkpoint {path!r} is corrupt; resumed from the "
                f"rotated previous generation {used!r}")
        self.load_state_dict(state)
        # durable stamp: the resume point pairs with the pre-kill
        # spans under the process's one trace ID, so a crash/resume
        # reads as a single timeline in the run ledger
        telemetry.event("resume", durable=True, component="engine",
                        path=used, windows_done=self.windows_done)
        return True

    def enable_wal(self, directory: str,
                   tenant: str = "engine") -> bool:
        """Journal every process() call's edges under `directory`
        BEFORE they fold (utils/wal.py), making this live-fed engine
        a replayable source: after a kill, `resume_and_replay()`
        restores the newest checkpoint and re-feeds the journal
        suffix, reproducing the lost windows bit-exactly. Returns
        False (a no-op) under the GS_WAL=0 kill switch."""
        if not wal_mod.enabled():
            return False
        self._wal_dir = directory
        self._wal_tenant = str(tenant)
        self._wal = wal_mod.WriteAheadLog(directory)
        return True

    def seal_wal(self) -> None:
        """Durably close the journal (the clean-drain marker)."""
        if self._wal is not None:
            self._wal.seal()

    def resume_and_replay(self, ckpt_path: str) -> list:
        """Kill recovery for a journal-armed engine: try_resume the
        newest checkpoint, then replay the journal suffix past the
        checkpointed `wal_offset` through process(). Returns the
        replayed windows' summaries — everything the crashed process
        computed (or had accepted) but never delivered, bit-identical
        to the fault-free run's same windows."""
        self.try_resume(ckpt_path)
        if self._wal_dir is None:
            return []
        off = self.resume_offset()
        parts_s, parts_d = [], []
        for tid, _start, src, dst, ts in wal_mod.replay(
                self._wal_dir, {self._wal_tenant: off}):
            if tid != self._wal_tenant:
                continue
            parts_s.append(src)
            parts_d.append(dst)
            # re-seed the latency plane's admission marks with the
            # journaled ORIGINAL stamps (latency.window records of the
            # replayed windows report honest, larger latency)
            latency.on_replay(self._lat_lane or self._wal_tenant,
                              len(src), ts)
        edges = sum(len(s) for s in parts_s)
        telemetry.event("wal_replayed", durable=True,
                        component="engine", dir=self._wal_dir,
                        edges=edges)
        metrics.counter_inc("gs_wal_replayed_edges_total", edges)
        if not edges:
            return []
        # suspend journaling for the replay feed: these edges are
        # already in the journal — re-appending would double them on
        # the NEXT recovery. Admission is likewise suspended: the
        # marks above already carry the ORIGINAL stamps.
        live, self._wal = self._wal, None
        admit_prev, self._lat_admit = self._lat_admit, False
        try:
            return self.process(np.concatenate(parts_s),
                                np.concatenate(parts_d))
        finally:
            self._wal = live
            self._lat_admit = admit_prev

    def resume_offset(self) -> int:
        """Edges already folded into the carried state: a resumed
        caller feeds `src[offset:], dst[offset:]` and gets exactly the
        uninterrupted run's remaining summaries (the windows_done
        cursor — windows are count-based eb-sized)."""
        return self.windows_done * self.eb

    def _h2d(self, args):
        """Transfer one chunk's prepped host stacks to device arrays
        (the pipeline's timed h2d stage; the sharded engine overrides
        with its mesh-sharded device_put)."""
        return tuple(jnp.asarray(a) for a in args)

    def _dispatch_async(self, s, d, valid):
        """Enqueue one chunk (updating the device-resident carry) and
        return the raw per-window outputs WITHOUT materializing them —
        process()'s depth-2 pipeline defers the d2h to _materialize so
        it overlaps the next chunk's execution."""
        raise NotImplementedError

    def _dispatch_async_compact(self, s16, d16, nvalid):
        """Compact-wire-format twin of _dispatch_async (uint16 stacks +
        per-window valid counts; widening fused into the scan
        program). Only engines whose `ingress` resolves compact need
        it."""
        raise NotImplementedError

    def _materialize(self, raw):
        """d2h one chunk's raw outputs into writable numpy arrays
        (mdeg, ncomp, odd, tri, b_ovf, k_ovf)."""
        raise NotImplementedError

    def _redo(self, src, dst, b_ovf: int, k_ovf: int) -> int:
        raise NotImplementedError

    def warm_fallback(self) -> None:
        """Compile the overflow-recount path's base program so a skewed
        stream's first hub window doesn't compile mid-measurement."""
        self._redo(np.array([0]), np.array([1]), 1, 1)  # gslint: disable=host-sync (host constants, not a device sync)

    def process(self, src: np.ndarray, dst: np.ndarray) -> list:
        """Fold the stream's `edge_bucket`-sized windows; returns one
        summary dict per window.

        A call whose length is not a multiple of `edge_bucket` CLOSES
        its partial trailing window (count-based tumbling semantics),
        so it must be the stream's final call — feed mid-stream chunks
        in edge_bucket multiples (enforced below)."""
        lat = latency.enabled()
        t_admit = latency.clock() if lat else 0.0
        metrics.on_stream_start(type(self).__name__)
        # "admit" fault site + armed sanitizer: the engine's admission
        # boundary mirrors the cohort's feed() — garbage ids peel off
        # to the dead-letter journal BEFORE the journal/fold see them;
        # GS_SANITIZE=off (default) skips straight to the legacy path
        got = faults.fire("admit", (self._wal_tenant, src, dst))
        if got is not None:
            _t, src, dst = got
        if sanitize_mod.enabled():
            try:
                rep = sanitize_mod.sanitize(
                    src, dst, self.vb, tenant=self._wal_tenant,
                    origin="engine", offset=self._fed_edges,
                    dlq=sanitize_mod.resolve_dlq())
            except sanitize_mod.BatchRejected as e:
                self._fed_edges += e.size
                raise
            self._fed_edges += rep.accepted + rep.rejected
            src, dst = rep.src, rep.dst
        else:
            self._fed_edges += len(np.atleast_1d(np.asarray(src)))  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        src = np.asarray(src, np.int32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        dst = np.asarray(dst, np.int32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        n = len(src)
        if n == 0:
            return []
        if self._closed_partial:
            raise ValueError(
                "a previous process() call closed a partial window "
                "(length not a multiple of edge_bucket); reset() before "
                "feeding more of the stream")
        if self._wal is not None:
            # journal-before-fold: the edges are durable before any
            # dispatch touches the carry, so a kill mid-call replays
            # them from resume_offset() (the wal_enqueue fault site
            # pins the append→fold gap in tests). Armed, the batch's
            # admission stamp rides the ts column so replayed windows
            # keep their original admission time.
            self._wal.append(
                self._wal_tenant, src, dst,
                np.full(n, latency.admit_ns(t_admit), np.int64)
                if lat else None)
            faults.fire("wal_enqueue", self._wal_tenant)
        if lat and self._lat_admit:
            latency.on_admit(self._lat_lane or self._wal_tenant, n,
                             t0=t_admit)
        if self._lat_stamps:
            # stamps stranded by a failed earlier call (dispatch ran,
            # finalize never did) must not join THIS call's windows
            # at the same chunk offsets
            self._lat_stamps.clear()
        self._closed_partial = n % self.eb != 0
        num_w = -(-n // self.eb)
        out = []
        base = self.windows_done
        staged = []  # checkpoint snapshots due mid-call (see below)

        from . import autotune

        if self.AUTOTUNE and autotune.enabled() \
                and num_w > self.MAX_WINDOWS:
            # long streams: the online tuner picks each round's
            # (windows-per-dispatch, ingress) arm — identical
            # summaries, measured dispatch knobs; GS_AUTOTUNE=0 (or a
            # short call) runs the static path below bit-identically
            self._process_tuned(src, dst, num_w, base, staged, out)
        else:
            self._process_static(src, dst, num_w, base, staged, out)
        if self._ckpt_path is not None:
            if self._ckpt_policy.due(self.windows_done):
                self._ckpt_policy.mark(self.windows_done)
                staged.append(self.state_dict())
            # clean completion: deliver, then persist. Only the last
            # two snapshots can survive save's rotation anyway, so the
            # rest would be pure wasted compression + I/O.
            for snap in staged[-2:]:
                checkpoint.save(self._ckpt_path, snap)
                # journal retention at the flush boundary
                # (GS_WAL_RETAIN): the floor is the snapshot's replay
                # cursor — resume_offset() restarts at windows_done
                # windows, so every record past that must survive
                self._wal_retention.flushed(
                    self._wal, self._wal_tenant,
                    int(snap["windows_done"]) * self.eb)  # gslint: disable=host-sync (checkpoint payloads are host scalars, never device values)
        return out

    # -- shared pipeline pieces (static path + autotuned rounds) -------

    def _stage_ckpt_at(self, base: int, at: int, staged: list) -> None:
        """Stage a due checkpoint at a chunk-DISPATCH boundary: the
        device carry there covers exactly the `base + at` windows
        dispatched so far — the one point where a bit-exact
        window-boundary snapshot costs a single d2h sync. Snapshots
        are written only on clean process() return (the call is the
        delivery unit: a crash mid-call hands the caller nothing, so
        a flushed checkpoint covering this call's windows would make
        resume skip summaries never delivered — at-most-once)."""
        if (self._ckpt_path is not None and at
                and self._ckpt_policy.due(base + at)):
            self._ckpt_policy.mark(base + at)
            snap = self.state_dict()
            snap["windows_done"] = base + at
            snap["closed_partial"] = False  # never mid-call
            staged.append(snap)

    def _finalize_summaries(self, item, src, dst, out: list) -> None:
        """Materialize one chunk's raw outputs into summary dicts
        (exact overflow redo included) — the finalize stage both the
        static and the tuned pipeline share."""
        f_at, f_real, raw = item
        mdeg, ncomp, odd, tri, b_ovf, k_ovf = (
            x[:f_real] for x in self._materialize(raw))
        for w in np.nonzero(b_ovf + k_ovf)[0]:  # exact redo
            lo = (f_at + int(w)) * self.eb
            tri[w] = self._redo(src[lo:lo + self.eb],
                                dst[lo:lo + self.eb],
                                int(b_ovf[w]), int(k_ovf[w]))  # gslint: disable=host-sync (numpy-on-numpy: _materialize already d2h'd these slabs)
        for w in range(f_real):
            out.append({
                "max_degree": int(mdeg[w]),  # gslint: disable=host-sync (numpy-on-numpy after _materialize)
                "num_components": int(ncomp[w]),  # gslint: disable=host-sync (numpy-on-numpy after _materialize)
                "odd_cycle": bool(odd[w]),
                "triangles": int(tri[w]),  # gslint: disable=host-sync (numpy-on-numpy after _materialize)
            })
        if latency.enabled():
            # per-window ingest→deliver record (deliver = finalize on
            # the engine path: summaries are handed to the caller at
            # the very next return) — joined to the chunk's boundary
            # stamps collected by the pipeline closures
            st = self._lat_stamps.pop(f_at, None)
            lane = self._lat_lane or self._wal_tenant
            for w in range(f_real):
                lo_w = (f_at + w) * self.eb
                latency.on_window(
                    lane,
                    edges=min(lo_w + self.eb, len(src)) - lo_w,
                    st=st, ordinal=self.windows_done + w,
                    defer=self._lat_defer)
        if provenance.armed():
            # one ledger record per finalized window, emitted at the
            # SAME cursor arithmetic as the checkpoint's wal_offset
            # contract (windows_done × eb) — replay across the
            # recorded span re-derives exactly this summary
            tenant = self._lat_lane or self._wal_tenant
            for w in range(f_real):
                lo = (self.windows_done + w) * self.eb
                lo_c = (f_at + w) * self.eb
                n_w = min(lo_c + self.eb, len(src)) - lo_c
                provenance.emit(
                    tenant=tenant, window=self.windows_done + w,
                    wal_lo=lo, wal_hi=lo + n_w,
                    tier=self.METRICS_TIER, program="fused_scan",
                    summary=out[len(out) - f_real + w])
        self.windows_done += f_real
        # window-finalize mark (utils/metrics): throughput counters +
        # the staleness clock the health watchdog reads
        lo_e = f_at * self.eb
        metrics.mark_window(
            f_real, min((f_at + f_real) * self.eb, len(src)) - lo_e,
            engine=type(self).__name__, tier=self.METRICS_TIER)

    def _run_window_rounds(self, src, dst, at0: int, hi_w: int,
                           wb: int, compact: bool, data, base: int,
                           staged: list, out: list) -> None:
        """Windows [at0, hi_w) through the shared three-stage ingress
        pipeline (ops/ingress_pipeline) at an explicit chunk size and
        wire format: chunk prep runs on the worker pool, dispatches
        stay in chunk order on this thread (the scan carry is
        sequential), and each chunk's d2h + extraction materializes
        one chunk behind its dispatch — host work hides behind device
        execution (same discipline as the driver's _run_batched and
        the triangle _run_stack_loop). `data` is the prebuilt
        whole-stream stack in the chunk's wire format."""
        def prep(at):
            st = latency.stamps()
            latency.stamp(st, "start")  # queue-wait ends here
            hi = min(at + wb, hi_w)
            # ragged tails pad the window axis to a power-of-two bucket
            # (all-invalid rows fold as no-ops against the carry), so
            # varying stream lengths reuse O(log MAX_WINDOWS) programs
            if data is None:
                # tuned rounds: chunk stacks build from the raw COO on
                # the (pooled) prep stage — exploring the other wire
                # format must not hold a second whole-stream stack
                lo = at * self.eb
                hi_e = min(hi * self.eb, len(src))
                if compact:
                    from . import compact_ingress

                    m, s16, d16, nv = compact_ingress.window_stack(
                        src[lo:hi_e], dst[lo:hi_e], self.eb)
                    sc, dc, nvc, real = compact_ingress.pad_chunk(
                        s16, d16, nv, 0, m, wb, self.eb)
                    latency.stamp(st, "prep")
                    return at, real, (sc, dc, nvc), st
                m, s, d, valid = seg_ops.window_stack(
                    src[lo:hi_e], dst[lo:hi_e], self.eb,
                    sentinel=self.vb)
                sc, dc, vc, real = seg_ops.pad_window_chunk(
                    s, d, valid, 0, m, wb, self.eb, self.vb)
                latency.stamp(st, "prep")
                return at, real, (sc, dc, vc), st
            if compact:
                from . import compact_ingress

                s16, d16, nv = data
                sc, dc, nvc, real = compact_ingress.pad_chunk(
                    s16, d16, nv, at, hi, wb, self.eb)
                latency.stamp(st, "prep")
                return at, real, (sc, dc, nvc), st
            s, d, valid = data
            sc, dc, vc, real = seg_ops.pad_window_chunk(
                s, d, valid, at, hi, wb, self.eb, self.vb)
            latency.stamp(st, "prep")
            return at, real, (sc, dc, vc), st

        def h2d(payload):
            at, real, args, st = payload
            dev = self._h2d(args)
            latency.stamp(st, "h2d")
            return at, real, dev, st

        def dispatch(dev_payload):
            at, real, dev, st = dev_payload
            self._stage_ckpt_at(base, at, staged)
            raw = (self._dispatch_async_compact(*dev) if compact
                   else self._dispatch_async(*dev))
            latency.stamp(st, "dispatch")
            if st is not None:
                # the finalize stage runs one chunk behind dispatch:
                # park the boundary stamps for _finalize_summaries
                self._lat_stamps[at] = st
            return at, real, raw

        def finalize(item):
            self._finalize_summaries(item, src, dst, out)

        ingress_pipeline.run_pipeline(
            range(at0, hi_w, wb), prep, h2d, dispatch, finalize,
            timers=self.stage_timers, inflight=self.INGEST_SLOTS)

    def _build_stack(self, src, dst, fmt: str):
        """Whole-stream window stack in wire format `fmt` (compact
        validates ids on the MAIN thread first — a wrapped id would
        corrupt ANOTHER vertex's carried state, and callers must see
        the same ValueError every tier raises)."""
        if fmt == "compact":
            from . import compact_ingress

            compact_ingress.validate_ids(src, dst, self.vb + 1,
                                         "fused summary scan")
            return compact_ingress.window_stack(src, dst, self.eb)[1:]
        return seg_ops.window_stack(src, dst, self.eb,
                                    sentinel=self.vb)[1:]

    def _process_static(self, src, dst, num_w: int, base: int,
                        staged: list, out: list) -> None:
        """The legacy single-configuration path: one pipeline over the
        whole call at the statically resolved (MAX_WINDOWS, ingress)."""
        compact = self.ingress == "compact"
        data = self._build_stack(src, dst,
                                 "compact" if compact else "standard")
        self._run_window_rounds(src, dst, 0, num_w, self.MAX_WINDOWS,
                                compact, data, base, staged, out)

    # -- online autotuning (ops/autotune.py) ---------------------------

    def _ensure_tuner(self):
        from . import autotune
        from . import compact_ingress

        if getattr(self, "_tuner", None) is None:
            wbm = self.MAX_WINDOWS
            wbs = sorted({max(1, wbm // 4), max(1, wbm // 2), wbm})
            ing = [self.ingress]
            if self.TUNABLE_INGRESS \
                    and not getattr(self, "_pinned_ingress", False):
                ing = ["standard"]
                if compact_ingress.supports(self.vb):
                    ing.append("compact")
            init = {"wb": wbm,
                    "ingress": (self.ingress if self.ingress in ing
                                else "standard")}
            self._tuner = autotune.DispatchTuner(
                "%s:eb=%d:vb=%d" % (self.TUNER_FAMILY, self.eb,
                                    self.vb),
                {"wb": wbs, "ingress": ing}, init)
        return self._tuner

    def _warm_arm(self, arm: dict) -> None:
        """Run one ALL-PADDING chunk at the arm's shape before its
        first timed round: padded rows fold as no-ops against the
        carry (values bit-identical), so this is a pure compile+warm
        dispatch — steady-state rounds never compile mid-measurement."""
        warmed = getattr(self, "_warmed_arms", None)
        if warmed is None:
            warmed = self._warmed_arms = set()
        key = (arm["wb"], arm["ingress"])
        if key in warmed:
            return
        wb = arm["wb"]
        if arm["ingress"] == "compact":
            z16 = np.zeros((wb, self.eb), np.uint16)
            raw = self._dispatch_async_compact(
                *self._h2d((z16, z16, np.zeros(wb, np.int32))))
        else:
            zi = np.full((wb, self.eb), self.vb, np.int32)
            raw = self._dispatch_async(
                *self._h2d((zi, zi, np.zeros((wb, self.eb), bool))))
        self._materialize(raw)  # block until the compile completes
        warmed.add(key)

    def _process_tuned(self, src, dst, num_w: int, base: int,
                       staged: list, out: list) -> None:
        """The autotuned twin of _process_static: measurement rounds
        of `autotune.round_chunks()` chunks each, arm-per-round, the
        measured edges/s fed back to the tuner. Summaries are
        identical at every arm; under forced_sync the tuner freezes
        (see ingress_pipeline.forced_sync_active)."""
        from . import autotune

        tuner = self._ensure_tuner()
        freeze = ingress_pipeline.forced_sync_active()
        validated = False
        round_len = autotune.round_chunks()
        at0 = 0
        while at0 < num_w:
            arm = tuner.best() if freeze else tuner.next_round()
            self._warm_arm(arm)
            wb, fmt = arm["wb"], arm["ingress"]
            if fmt == "compact" and not validated:
                # the shared main-thread wrap-safety check, once per
                # call (prep builds compact stacks on the pool)
                from . import compact_ingress

                compact_ingress.validate_ids(src, dst, self.vb + 1,
                                             "fused summary scan")
                validated = True
            take = min(num_w - at0, round_len * wb)
            # telemetry span doubles as the round stopwatch (same
            # perf_counter measurement disarmed)
            with telemetry.span("fused_scan.round", window=base + at0,
                                wb=wb, ingress=fmt,
                                edges=take * self.eb) as sp:
                self._run_window_rounds(src, dst, at0, at0 + take, wb,
                                        fmt == "compact", None,
                                        base, staged, out)
            # full rounds (or a whole call smaller than one) only: a
            # long call's ragged tail would drag the arm's EMA with
            # tail economics
            if not freeze and take == min(round_len * wb, num_w):
                tuner.record(arm, take * self.eb, sp.elapsed)
            at0 += take
        if not freeze:
            tuner.save()


class StreamSummaryEngine(SummaryEngineBase):
    """Single-chip carried-state analytics over chunks of windows, one
    dispatch per MAX_WINDOWS windows. Exact: triangle windows whose
    hubs overflow K are recounted by the escalating per-window
    kernel."""

    AUTOTUNE = True
    TUNABLE_INGRESS = True

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 k_bucket: int = 0, ingress: str = None):
        self.eb = seg_ops.bucket_size(edge_bucket)
        self.vb = seg_ops.bucket_size(vertex_bucket)
        self.kb = seg_ops.bucket_size(
            k_bucket if k_bucket else tri_ops._tuned_kb(self.eb))
        # compile-size cap on the tunneled chip, per-PROGRAM: the fused
        # multi-analytic scan wedged the remote compiler even at the
        # triangle program's clean size, so its cap is probed
        # separately (tri_ops.compile_cap "fused_scan")
        self.MAX_WINDOWS = min(type(self).MAX_WINDOWS,
                               tri_ops.capped_chunk(self.eb,
                                                    "fused_scan"))
        # stream-chunk wire format: same committed-evidence selection
        # (and explicit-pin/vb-gate semantics) as TriangleWindowKernel
        if ingress == "compact":
            from . import compact_ingress

            if not compact_ingress.supports(self.vb):
                raise ValueError(
                    "compact ingress is lossy for vertex_bucket %d "
                    "(ids must fit uint16)" % self.vb)
        self.ingress = (ingress if ingress
                        else tri_ops.resolve_ingress(self.vb))
        # an explicit pin freezes the wire format for the tuner too
        # (the A/B tools must measure exactly what they pinned)
        self._pinned_ingress = ingress is not None
        body = _build_scan(self.eb, self.vb, self.kb)

        @jax.jit
        def run(carry, src_w, dst_w, valid_w):
            return jax.lax.scan(body, carry, (src_w, dst_w, valid_w))

        # compile watch (utils/metrics): distinct abstract signatures
        # count against the O(log V) recompile envelope. The cost
        # observatory (utils/costmodel) rides the same wrapper: armed,
        # each signature's cost_analysis is captured and dispatches
        # tag their ledger spans program="fused_scan"/sig — or
        # program="pallas_window" when the megakernel body was
        # selected, so the observatory attributes the new program
        # separately from the scan-of-gathers it replaces.
        self._pallas = bool(getattr(body, "pallas_window", False))
        self._run = metrics.wrap_jit(
            "pallas_window" if self._pallas else "fused_scan", run)
        self._body = body
        self._run_c = None  # compact twin, built on first use
        if self.ingress == "compact":
            self._ensure_compact_fn()
        self._tri_fallback = tri_ops.TriangleWindowKernel(
            edge_bucket=self.eb, vertex_bucket=self.vb,
            k_bucket=4 * self.kb)
        self.reset()

    def _ensure_compact_fn(self):
        """The compact twin of _run: the shared device-side decode
        (compact_ingress.widen_stack — widen uint16 ids + rebuild the
        suffix mask from per-window counts) fused into the same scan
        program, applied to the whole [W, eb] stack before the scan
        consumes it. Built lazily so a standard-resolved engine whose
        TUNER explores compact pays for it only when explored.

        When the Pallas megakernel is selected, the decode fuses one
        level deeper: the compact body consumes the RAW uint16 stacks
        and widens per tile INSIDE the kernel (the tentpole's
        compact-ingress-decode stage) — no [W, eb] int32
        intermediates ever materialize."""
        if self._run_c is None:
            eb_, vb_, body = self.eb, self.vb, self._body

            if getattr(body, "pallas_window", False):
                from . import pallas_window

                run_pc = pallas_window.maybe_compact_scan_fn(
                    eb_, vb_, self.kb, "pallas_window_compact")
                if run_pc is not None:
                    self._run_c = run_pc
                    return self._run_c

            from . import compact_ingress as _ci

            @jax.jit
            def run_c(carry, s16, d16, nvalid):
                s_w, d_w, valid_w = _ci.widen_stack(
                    s16, d16, nvalid, eb_, vb_)
                return jax.lax.scan(body, carry, (s_w, d_w, valid_w))

            self._run_c = metrics.wrap_jit("fused_scan_compact", run_c)
        return self._run_c

    def _dispatch_async(self, s, d, valid):
        self._carry, outs = self._run(
            self._carry, jnp.asarray(s), jnp.asarray(d),
            jnp.asarray(valid))
        return outs

    def _dispatch_async_compact(self, s16, d16, nvalid):
        self._carry, outs = self._ensure_compact_fn()(
            self._carry, jnp.asarray(s16), jnp.asarray(d16),
            jnp.asarray(nvalid))
        return outs

    def _materialize(self, raw):
        mdeg, ncomp, odd, tri, ovf = (np.array(x) for x in raw)  # gslint: disable=host-sync (sanctioned finalize boundary: the engine's ONE batched d2h per chunk)
        # single-chip scan has one overflow signal: report it as k_ovf
        return mdeg, ncomp, odd, tri, np.zeros_like(ovf), ovf

    def _redo(self, src, dst, b_ovf: int, k_ovf: int) -> int:
        return self._tri_fallback.count(src, dst)


class SlidingSummaryEngine:
    """Sliding windows on the fused scan via pane composition
    (`slide=`): an inner StreamSummaryEngine at edge_bucket=slide
    folds each edge into its pane ONCE. The cumulative analytics
    (max_degree, num_components, odd_cycle) read the carried state at
    every pane boundary — bit-identical at any pane size, so the pane
    path IS the sliding path for them. The per-window analytic
    (triangles) recomputes per emission off the composed pane edge
    slab: a ring of the last panes_per_window − 1 pane (src, dst)
    slabs plus the fresh pane runs through TriangleWindowKernel at
    the FULL window bucket, keeping its exact-redo K escalation.

    One summary dict per emission — every `slide` edges, the window
    covering the trailing `edge_bucket` edges (growing at the head of
    the stream, ragged on a final partial pane). slide == edge_bucket
    degenerates to exactly one pane per window: tumbling.

    The ring rides state_dict()/load_state_dict(), so a kill →
    resume mid-pane-ring recomposes the SAME windows the uninterrupted
    run emits (tests/test_sliding_windows.py)."""

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 slide: int, k_bucket: int = 0):
        eb = seg_ops.bucket_size(edge_bucket)
        slide = int(slide)
        if slide <= 0 or slide > eb or eb % slide \
                or slide & (slide - 1):
            raise ValueError(
                "slide must be a power of two dividing the window "
                "size (%d), got %d" % (eb, slide))
        self.eb = eb
        self.vb = seg_ops.bucket_size(vertex_bucket)
        self.slide = slide
        self.panes_per_window = eb // slide
        self.inner = StreamSummaryEngine(
            edge_bucket=slide, vertex_bucket=self.vb,
            k_bucket=k_bucket)
        # per-emission triangle recount at the FULL window bucket —
        # the composed slab holds up to eb edges
        self._tri = tri_ops.TriangleWindowKernel(
            edge_bucket=eb, vertex_bucket=self.vb,
            k_bucket=k_bucket)
        self._ring = []  # last ≤ wp−1 pane (src, dst) pairs

    # pass-throughs the serving/driver integration reads
    @property
    def windows_done(self) -> int:
        """Emissions done (the inner scan's pane cursor)."""
        return self.inner.windows_done

    def reset(self) -> None:
        self.inner.reset()
        self._ring = []

    def resume_offset(self) -> int:
        return self.inner.windows_done * self.slide

    def process(self, src, dst) -> list:
        """Fold the stream's slide-sized panes; one summary per pane
        (= per emission). Mid-stream calls must be multiples of
        `slide` (the inner engine enforces it); a ragged call closes
        the stream with a final partial emission."""
        src = np.asarray(src, np.int32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        dst = np.asarray(dst, np.int32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        if sanitize_mod.enabled():
            # sanitize HERE so the pane slabs below slice the same
            # clean arrays the inner engine folds (its own sanitize
            # pass of the already-clean batch is a no-op)
            rep = sanitize_mod.sanitize(
                src, dst, self.vb, tenant=self.inner._wal_tenant,
                origin="engine", offset=self.inner._fed_edges,
                dlq=sanitize_mod.resolve_dlq())
            src, dst = (np.asarray(rep.src, np.int32),  # gslint: disable=host-sync (sanitizer output is host numpy)
                        np.asarray(rep.dst, np.int32))  # gslint: disable=host-sync (sanitizer output is host numpy)
        summaries = self.inner.process(src, dst)
        wp, s = self.panes_per_window, self.slide
        out = []
        for i, pane_sum in enumerate(summaries):
            lo, hi = i * s, min((i + 1) * s, len(src))
            pane = (src[lo:hi], dst[lo:hi])
            slab = self._ring + [pane]
            with telemetry.span("sliding.emit",
                                panes=len(slab),
                                edges=sum(len(p[0]) for p in slab)):
                tri = self._tri.count(
                    np.concatenate([p[0] for p in slab]),
                    np.concatenate([p[1] for p in slab]))
            row = dict(pane_sum)
            row["triangles"] = int(tri)
            out.append(row)
            self._ring = (self._ring + [pane])[-(wp - 1):] \
                if wp > 1 else []
        return out

    # ------------------------------------------------------------------
    # checkpoint / resume — the pane ring rides along (R6-symmetric)
    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        return {
            "slide": self.slide,
            "edge_bucket": self.eb,
            "vertex_bucket": self.vb,
            "ring_src": [np.asarray(s) for s, _d in self._ring],  # gslint: disable=host-sync (the pane ring holds host int32 slabs, never device values)
            "ring_dst": [np.asarray(d) for _s, d in self._ring],  # gslint: disable=host-sync (the pane ring holds host int32 slabs, never device values)
            "inner": self.inner.state_dict(),
        }

    def load_state_dict(self, state: dict) -> None:
        ck_slide = int(state["slide"])  # gslint: disable=host-sync (checkpoint scalars are host values)
        ck_eb = int(state["edge_bucket"])  # gslint: disable=host-sync (checkpoint scalars are host values)
        ck_vb = int(state["vertex_bucket"])  # gslint: disable=host-sync (checkpoint scalars are host values)
        if (ck_slide, ck_eb, ck_vb) != (self.slide, self.eb, self.vb):
            raise ValueError(
                "sliding checkpoint was taken at slide=%d eb=%d "
                "vb=%d; engine runs slide=%d eb=%d vb=%d" % (
                    ck_slide, ck_eb, ck_vb,
                    self.slide, self.eb, self.vb))
        self._ring = [(np.asarray(s, np.int32), np.asarray(d, np.int32))  # gslint: disable=host-sync (checkpoint arrays are host numpy)
                      for s, d in zip(state["ring_src"],
                                      state["ring_dst"])]
        self.inner.load_state_dict(state["inner"])

"""Fused window megakernel: ONE VMEM-tiled Pallas pass per edge slab,
shared across all analytics.

The dispatch observatory's committed cost-model rows (PERF_cpu.json
`cost_model`, ISSUE 10) prove every hot program — the fused scan, the
resident fused-compact super-batch, the triangle stream — is
bytes-bound at 0.25-0.28 FLOPs/byte with the fused scan at 0.096% of
roofline, because XLA's scan-of-gathers re-reads the COO edge slab
from HBM once per analytic: the degree fold, the CC fixpoint, the
double-cover fixpoint (twice — its edge list is the concatenated
cover), and the triangle stage each gather the same [eb] src/dst
arrays. This module is the IO-aware fix the PAPERS.md GNN-systems
literature prescribes: fuse compact-ingress decode → vertex bucketing
→ neighbor intersection → monoid reduce into a single `pallas_call`
per window, so the slab leaves HBM ONCE and every analytic (CC
union-find labels, bipartiteness sign, degree counts, triangle
counts) is computed from the VMEM-resident copy.

Kernel shape (grown from the two seeds, ops/pallas_intersect.py /
ops/pallas_triangles.py):

- grid = (eb // tile_e,) edge tiles; each step's [1, tile_e] src/dst
  (uint16 on the compact wire) blocks stream HBM→VMEM under Pallas's
  own double-buffered block pipeline — the tentpole's "edge-tile
  double-buffered copy into VMEM".
- per tile: compact decode (suffix mask from the window's valid
  count + uint16→int32 widening — the exact widen_stack semantics,
  fused instead of materialized), the degree monoid fold into the
  VMEM-resident carry slab, and the decoded tile staged into a VMEM
  slab scratch.
- the LAST tile runs the remaining analytics on the now
  VMEM-resident slab: the carried CC and double-cover min-label
  fixpoints (ops/unionfind.cc_fixpoint — composition over any edge
  partition converges to the same canonical labeling, so folding at
  window grain is bit-exact), then the triangle stage —
  build_window_counter's exact pipeline (orient_by_degree →
  dedupe_and_positions → K-bucket CSR scatter) with the K-bucket
  intersection running the intersect seed's OWN inner compare loop
  (pallas_intersect.tile_intersect_count) over bounded edge tiles.
- outputs: the three carry slabs plus one [8]-scalar SMEM summary
  row (max_degree, num_components, odd, triangles, K-overflow) —
  K-overflow hands off to the call sites' existing exact-redo
  escalation, so exactness is never sacrificed.

Selection is the repo's measured-adoption gate (`resolve_*` family):
`GS_PALLAS_WINDOW` pins on/off; unset/`auto` adopts ONLY on committed
backend-matched `pallas_ab` rows (tools/pallas_ab.py) that all show
exact parity and ≥1.05×, so the XLA fused scan stands — and CPU
digests stay bit-identical — until a chip row lands. A pallas_call
that raises at build/trace time (Pallas API drift, a Mosaic lowering
gap for the in-kernel sort/scatter) degrades to the XLA body with a
durable `selection.fallback` event instead of taking the stream down
— the same honest fallback every other selection plays.

Off-TPU the kernel runs in INTERPRET mode (the seeds' convention):
bit-identical to the XLA scan and the host twins by construction —
that is tier-1's parity oracle (tests/operations/
test_pallas_window.py, ci_check gate 7) — but it times nothing real,
so interpret rows can never clear the adoption bar. Interpret also
unrolls the grid at trace time, so off-TPU the default edge tile is
the whole slab (one grid step keeps the jaxpr linear); the tiled
path is exercised by tests at small buckets and is the shape the
chip session tunes (`pallas_window` DispatchTuner family: edge-tile
× K-chunk arms).

VMEM budget (the `supports()` gate, enforced on TPU backends only —
interpret has no VMEM): slab 2·4·eb + carry in/out 2·16·(vb+1) +
K-bucket table 4·(vb+1)·kb + the bounded [it, ck, kb] compare block
+ sort temps must fit under ~12MB of the 16MB scoped VMEM. At the
canonical eb=32768 / vb=65536 / kb=32 row the table alone is 8.4MB —
chip adoption at wide vertex buckets wants kb ≤ 16 or vb ≤ 32768;
see DESIGN.md §19 for the arithmetic.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pallas_intersect
from . import triangles as tri_ops
from .pallas_triangles import _need_interpret
from ..utils import costmodel
from ..utils import knobs
from ..utils import telemetry

# default edge tile on a real chip; off-TPU the interpreter unrolls
# the grid at trace time, so the default degenerates to one whole-slab
# step (see default_tile)
TILE_E = 512
# the intersect stage's inner edge tile: bounds the [it, ck, kb]
# broadcast-compare block whatever the staging tile is (the seed's
# scoped-vmem lesson: T=256/Ck=128/K=256 already flirts with the 16M
# limit)
INTERSECT_TILE = 2048
# VMEM ceiling supports() enforces on TPU backends (headroom under the
# 16MB scoped-vmem limit for Mosaic's own temporaries)
VMEM_BUDGET = 12 * 1024 * 1024

_SUMS = 8  # [8]-int32 summary row (5 used; padded for alignment)

_CALLS = {}   # (eb,vb,kb,tile,ck,kind,interpret) -> pallas_call closure  # gslint: disable=thread-shared (idempotent memo: same key always builds the same program; a racing double-build is last-write-wins)
_PROBES = {}  # (vb,kb,kind) -> bool probe verdict  # gslint: disable=thread-shared (idempotent memo of a deterministic trace probe)


# ----------------------------------------------------------------------
# selection gate (the resolve_* family)
# ----------------------------------------------------------------------
_PALLAS = None  # "pallas" | "xla", resolved once per process
_COHORT_PALLAS = None  # "pallas" | "xla", resolved once per process
_GNN_PALLAS = None  # "pallas" | "xla", resolved once per process


def _reset_pallas_window() -> None:
    """Test hook: forget the memoized selections and probe verdicts."""
    global _PALLAS, _COHORT_PALLAS, _GNN_PALLAS
    _PALLAS = None
    _COHORT_PALLAS = None
    _GNN_PALLAS = None
    _PROBES.clear()


def resolve_pallas_window() -> bool:
    """Should the fused-scan/triangle window bodies run the Pallas
    megakernel instead of the XLA scan-of-gathers? GS_PALLAS_WINDOW
    pins (`on`/`off`); unset/`auto` adopts only when committed
    backend-matched `pallas_ab` rows (tools/pallas_ab.py) ALL show
    exact parity and ≥1.05× (ops/triangles.rows_clear_bar — the
    repo-wide measured-adoption policy). Interpret-mode rows can
    never clear that bar, so CPU behavior stays bit-identical until
    a chip row lands. Memoized per process."""
    global _PALLAS
    pin = knobs.get_str("GS_PALLAS_WINDOW")
    if pin == "on":
        return True
    if pin == "off":
        return False
    if _PALLAS is None:
        impl = "xla"
        try:
            perf = tri_ops._load_matching_perf()
            if tri_ops.rows_clear_bar(
                    (perf or {}).get("pallas_ab", []),
                    "speedup", lambda r: 1.0):
                impl = "pallas"
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="pallas_window", fallback=impl,
                            error="%s: %s" % (type(e).__name__, e))
        _PALLAS = impl
    return _PALLAS == "pallas"


def resolve_cohort_pallas() -> bool:
    """Should build_cohort_scan run the TENANT-AXIS Pallas megakernel
    (the tenant axis as a second grid dimension of one pallas_call,
    the whole cohort's carries VMEM-resident) instead of vmapping the
    XLA scan body over tenants? GS_COHORT_PALLAS pins (`on`/`off`);
    unset/`auto` adopts only when committed backend-matched
    `tenancy_ab` rows with probe `cohort_pallas` — NON-interpret rows
    only, the interpret parity rows time nothing real — ALL show
    exact per-tenant parity and ≥1.05× (ops/triangles.rows_clear_bar).
    CPU cohort digests stay bit-identical until a chip row lands.
    Memoized per process."""
    global _COHORT_PALLAS
    pin = knobs.get_str("GS_COHORT_PALLAS")
    if pin == "on":
        return True
    if pin == "off":
        return False
    if _COHORT_PALLAS is None:
        impl = "xla"
        try:
            perf = tri_ops._load_matching_perf()
            rows = [r for r in (perf or {}).get("tenancy_ab", [])
                    if r.get("probe") == "cohort_pallas"
                    and not r.get("interpret")]
            if tri_ops.rows_clear_bar(rows, "speedup",
                                      lambda r: 1.0):
                impl = "pallas"
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="cohort_pallas", fallback=impl,
                            error="%s: %s" % (type(e).__name__, e))
        _COHORT_PALLAS = impl
    return _COHORT_PALLAS == "pallas"


def resolve_gnn_pallas() -> bool:
    """Should the GNN engines (ops/gnn_window.py) run the fused
    Pallas GNN window kernel instead of the XLA gather/segment-sum
    round? GS_GNN_PALLAS pins (`on`/`off`); unset/`auto` adopts only
    when committed backend-matched `gnn_ab` rows with probe
    `gnn_pallas` — NON-interpret rows only — ALL show exact parity
    and ≥1.05× (ops/triangles.rows_clear_bar, the repo-wide
    measured-adoption policy). CPU feature slabs stay bit-identical
    until a chip row lands. Memoized per process."""
    global _GNN_PALLAS
    pin = knobs.get_str("GS_GNN_PALLAS")
    if pin == "on":
        return True
    if pin == "off":
        return False
    if _GNN_PALLAS is None:
        impl = "xla"
        try:
            perf = tri_ops._load_matching_perf()
            rows = [r for r in (perf or {}).get("gnn_ab", [])
                    if r.get("probe") == "gnn_pallas"
                    and not r.get("interpret")]
            if tri_ops.rows_clear_bar(rows, "speedup",
                                      lambda r: 1.0):
                impl = "pallas"
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="gnn_pallas", fallback=impl,
                            error="%s: %s" % (type(e).__name__, e))
        _GNN_PALLAS = impl
    return _GNN_PALLAS == "pallas"


# ----------------------------------------------------------------------
# tiling layer (shared with the seeds' committed-evidence policy)
# ----------------------------------------------------------------------
def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # gslint: disable=except-hygiene (availability probe: selects the interpret form, never correctness)
        return False


def default_tile(eb: int) -> int:
    """Edge tile when nothing pins one: min(512, eb) on a chip (the
    seed's measured ballpark); the WHOLE slab off-TPU — interpret
    mode unrolls the grid at trace time, so one step keeps the jaxpr
    linear in eb instead of quadratic-ish in tiles."""
    return min(TILE_E, eb) if _on_tpu() else eb


def tile_space(eb: int, kb: int) -> dict:
    """The `pallas_window` DispatchTuner arm space: edge-tile rungs
    under the slab size × K-chunk widths under the K bucket. Off-TPU
    the only tile is the whole slab — interpret mode unrolls the grid
    at trace time, so sub-slab tiles there trace the final stage once
    PER TILE and wedge the host compiler, measuring nothing a chip
    would ever run."""
    tiles = sorted({t for t in (256, 512, 1024, 2048) if t <= eb}
                   or {eb}) if _on_tpu() else [eb]
    cks = sorted({min(64, kb), min(128, kb)})
    return {"tile_e": tiles, "ck": cks}


def tile_tuner(eb: int, vb: int, kb: int):
    """The megakernel's autotuner family riding ops/autotune
    .DispatchTuner: `pallas_window:eb=…:vb=…:kb=…` with edge-tile ×
    K-chunk arms. tools/pallas_ab.py --sweep drives rounds offline
    (each arm is a distinct compiled program, so arms are explored
    between streams, not mid-stream); the persisted per-backend cache
    then seeds resolve_tiles for production builds."""
    from . import autotune

    space = tile_space(eb, kb)
    init = {"tile_e": (min(TILE_E, eb) if min(TILE_E, eb)
                       in space["tile_e"] else space["tile_e"][-1]),
            "ck": space["ck"][-1]}
    return autotune.DispatchTuner(tuner_key(eb, vb, kb), space, init)


def tuner_key(eb: int, vb: int, kb: int) -> str:
    return "pallas_window:eb=%d:vb=%d:kb=%d" % (eb, vb, kb)


def resolve_tiles(eb: int, kb: int, vb: int = 0,
                  tile_e: int = None, chunk_k: int = None):
    """(tile_e, ck) the megakernel builds at: explicit arguments (the
    A/B sweep) beat the GS_PALLAS_TILE/GS_PALLAS_CK pins beat the
    `pallas_window` tuner's persisted optimum for this shape beat the
    defaults — the same committed-evidence ladder as the intersect
    seed's _resolve_tile. Called at BUILD time only (knob reads must
    not freeze inside a traced body)."""
    if tile_e is None:
        tile_e = knobs.get_int("GS_PALLAS_TILE") or 0
    if chunk_k is None:
        chunk_k = knobs.get_int("GS_PALLAS_CK") or 0
    if (not tile_e or not chunk_k) and vb:
        try:
            from . import autotune

            cached = autotune.load_cached_best(tuner_key(eb, vb, kb))
            if cached:
                arm = cached.get("arm") or {}
                tile_e = tile_e or int(arm.get("tile_e") or 0)  # gslint: disable=host-sync (committed-evidence JSON ints, no device value in sight)
                chunk_k = chunk_k or int(arm.get("ck") or 0)  # gslint: disable=host-sync (committed-evidence JSON ints, no device value in sight)
        except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
            pass
    tile_e = tile_e or default_tile(eb)
    tile_e = max(8, min(tile_e, eb))
    while eb % tile_e:
        tile_e //= 2
    chunk_k = max(8, min(chunk_k or min(128, kb), kb))
    return tile_e, chunk_k


# ----------------------------------------------------------------------
# VMEM budget + analytic cost model
# ----------------------------------------------------------------------
def slab_bytes(eb: int, compact: bool = False) -> int:
    """HBM bytes of ONE edge-slab read: 4 bytes/slot on the compact
    wire (2×uint16 + the per-window valid count), 9 on the standard
    wire (2×int32 + the bool mask)."""
    return eb * (2 + 2) + 4 if compact else eb * (4 + 4 + 1)


def carry_bytes(vb: int) -> int:
    """Bytes of one carry copy (degrees + labels + double cover)."""
    return 4 * ((vb + 1) + (vb + 1) + 2 * (vb + 1))


def window_bytes(eb: int, vb: int, compact: bool = False) -> int:
    """The megakernel's HBM traffic per window: ONE slab read, the
    carry read+write, the summary row."""
    return slab_bytes(eb, compact) + 2 * carry_bytes(vb) + 4 * _SUMS


def scan_of_gathers_bytes(eb: int, vb: int,
                          analytics: int = 4) -> int:
    """HBM bytes the XLA scan-of-gathers moves for the SAME window:
    each analytic re-gathers the standard-wire slab — degrees once,
    CC once, the double cover twice (its edge list is the
    concatenated cover), triangles once — plus the same carry
    read+write. The adoption story in one subtraction: the megakernel
    replaces `reads × slab` with `1 × slab`."""
    reads = {1: 1, 2: 2, 3: 4}.get(analytics, 5)
    return reads * slab_bytes(eb, False) + 2 * carry_bytes(vb)


def window_flops(eb: int, vb: int, kb: int) -> int:
    """Stated-model FLOP estimate per window (labeled `analytic` in
    the cost registry — a model, not a compiler measurement): the
    K-bucket compare dominates (2·eb·kb), plus the monoid folds, a
    nominal 8-round fixpoint over the three slabs, and the dedupe
    sort's eb·log2(eb) compares."""
    fix = 8 * (4 * (vb + 1))
    return (2 * eb * kb + 16 * eb + 3 * fix
            + eb * int(math.log2(max(eb, 2))))  # gslint: disable=host-sync (python-int bucket math, no device value in sight)


def vmem_window_bytes(eb: int, vb: int, kb: int,
                      tile_e: int = None, ck: int = None,
                      compact: bool = False) -> int:
    """The kernel's VMEM high-water estimate (DESIGN.md §19 walks the
    arithmetic): decoded slab scratch + carry in/out blocks + the
    K-bucket table + the bounded intersect compare block + the dedupe
    sort's temporaries."""
    if tile_e is None or ck is None:
        tile_e, ck = resolve_tiles(eb, kb)
    it = min(tile_e, INTERSECT_TILE, eb)
    slab = 2 * 4 * eb
    carry = 2 * carry_bytes(vb)
    nbr = 4 * (vb + 1) * kb
    compare = 2 * 4 * it * kb + it * min(ck, kb) * kb
    sort_tmp = 6 * 4 * eb
    return slab + carry + nbr + compare + sort_tmp


def supports(eb: int, vb: int, kb: int, tile_e: int = None,
             ck: int = None, compact: bool = False) -> bool:
    """Does this (eb, vb, kb) fit the chip's VMEM budget? Enforced on
    TPU backends only — interpret mode has no VMEM, and refusing a
    CPU parity run over a budget the backend doesn't have would gate
    the oracle out of existence."""
    if not _on_tpu():
        return True
    return vmem_window_bytes(eb, vb, kb, tile_e, ck,
                             compact) <= VMEM_BUDGET


def cohort_vmem_window_bytes(eb: int, vb: int, kb: int, nb: int,
                             tile_e: int = None,
                             ck: int = None) -> int:
    """The TENANT-AXIS kernel's VMEM high-water estimate (DESIGN.md
    §19's cohort term): the single-window arithmetic with the carry
    in/out blocks multiplied by the N cohort rows held VMEM-resident
    across the whole grid — slab scratch, the K-bucket table, and the
    bounded compare block stay single-tenant (one tenant's final
    stage runs at a time)."""
    if tile_e is None or ck is None:
        tile_e, ck = resolve_tiles(eb, kb)
    it = min(tile_e, INTERSECT_TILE, eb)
    slab = 2 * 4 * eb
    carry = 2 * nb * carry_bytes(vb)
    nbr = 4 * (vb + 1) * kb
    compare = 2 * 4 * it * kb + it * min(ck, kb) * kb
    sort_tmp = 6 * 4 * eb
    return slab + carry + nbr + compare + sort_tmp


def supports_cohort(eb: int, vb: int, kb: int, nb: int,
                    tile_e: int = None, ck: int = None) -> bool:
    """Does an N-row cohort at (eb, vb, kb) fit the chip's VMEM
    budget? Same contract as supports(): enforced on TPU backends
    only — interpret mode has no VMEM. The N-row carry term tightens
    the kb-at-wide-vb frontier; see the DESIGN.md §19 table."""
    if not _on_tpu():
        return True
    return cohort_vmem_window_bytes(eb, vb, kb, nb,
                                    tile_e, ck) <= VMEM_BUDGET


def register_cost_model(eb: int, vb: int, kb: int,
                        compact: bool = False) -> None:
    """Register the megakernel's analytic cost model with the
    observatory (utils/costmodel.record_analytic, armed only), under
    EVERY program label this body can dispatch as — the scan engine's
    and the resident tier's wrap_jit names — so the ledger spans join
    the stated model (one slab read vs the scan-of-gathers' summed
    reads) at their own abstract signatures, never a compiler capture
    of the interpret lowering. explain_perf then reports achieved
    GB/s and roofline fraction for the new program on any backend.
    The template carries the most recent registration's shape — one
    engine shape per program per process is the operating regime."""
    wire = "compact" if compact else "standard"
    programs = (("pallas_window_compact", "resident_pallas_compact")
                if compact else ("pallas_window", "resident_pallas"))
    for program in programs:
        costmodel.record_analytic(
            program,
            "eb=%d,vb=%d,kb=%d,%s" % (eb, vb, kb, wire),
            flops=window_flops(eb, vb, kb),
            bytes_accessed=window_bytes(eb, vb, compact),
            slab_bytes=slab_bytes(eb, compact),
            scan_of_gathers_bytes=scan_of_gathers_bytes(eb, vb),
            model="analytic",
            # the model is PER WINDOW; a chunk dispatch folds W of
            # them, so a reader scaling against per-dispatch span
            # seconds multiplies by the sig's leading window count
            unit="window")


# ----------------------------------------------------------------------
# the kernel
# ----------------------------------------------------------------------
def _tri_stage(sa, da, va, vb: int, kb: int, it: int, ck: int):
    """build_window_counter's EXACT per-window triangle pipeline on
    the VMEM-resident slab — same cleanup, orientation
    (tri_ops.orient_by_degree), fused dedupe+CSR positions
    (tri_ops.dedupe_and_positions), K-bucket scatter, and overflow
    accounting, so counts and the K-overflow handoff are
    bit-identical to the XLA body by construction. The K-bucket
    intersection runs the intersect seed's inner compare loop
    (pallas_intersect.tile_intersect_count) over `it`-edge tiles:
    per-tile [it, kb] row gathers + one [it, ck, kb] compare block —
    never more than a tile of rows in flight."""
    sent = vb
    valid = va & (sa != da)
    s = jnp.where(valid, sa, sent)
    d = jnp.where(valid, da, sent)
    ones = jnp.where(valid, 1, 0)
    deg = jax.ops.segment_sum(ones, s, vb + 1)
    deg = deg + jax.ops.segment_sum(ones, d, vb + 1)
    a, b = tri_ops.orient_by_degree(s, d, deg, sent)
    a, b, evalid, pos = tri_ops.dedupe_and_positions(a, b, sent, vb)
    overflow = jnp.sum((pos >= kb) & evalid)
    ok = evalid & (pos < kb)
    rows = jnp.where(ok, a, vb)
    cols = jnp.clip(pos, 0, kb - 1)
    nbr = jnp.full((vb + 1, kb), sent, jnp.int32)
    nbr = nbr.at[rows, cols].set(
        jnp.where(ok, b, sent).astype(jnp.int32))
    eb = sa.shape[0]
    a32, b32 = a.astype(jnp.int32), b.astype(jnp.int32)
    count = jnp.int32(0)
    for t in range(0, eb, it):
        hi = min(t + it, eb)
        ra = nbr[a32[t:hi]]
        rb = nbr[b32[t:hi]]
        va_t = (ra < sent) & evalid[t:hi, None]
        count = count + pallas_intersect.tile_intersect_count(
            ra, rb, va_t, ck)
    return count, overflow


def _final_summaries(vb, deg, lab, cov):
    """The per-window summary scalars off the folded carries — the
    same expressions as scan_analytics._build_scan's body."""
    touched = deg[:vb] > 0
    mdeg = jnp.max(deg[:vb])
    iota_v = jax.lax.broadcasted_iota(jnp.int32, (vb, 1), 0)[:, 0]
    ncomp = jnp.sum(touched & (lab[:vb] == iota_v), dtype=jnp.int32)
    odd = jnp.any(touched & (cov[:vb] == cov[vb + 1:2 * vb + 1]))
    return mdeg, ncomp, odd


def _pack_sums(*vals):
    out = list(vals) + [jnp.int32(0)] * (_SUMS - len(vals))
    return jnp.stack([jnp.asarray(v, jnp.int32) for v in out])


def _window_call(eb: int, vb: int, kb: int, tile_e: int, ck: int,
                 compact: bool, interpret: bool):
    """The full megakernel pallas_call closure:
    (deg, lab, cov, *wire) -> (deg, lab, cov, sums[8]). Memoized per
    shape; `wire` is (s2, d2, v2) [g, tile_e] on the standard wire or
    (nv[1], s16, d16) on the compact wire."""
    key = (eb, vb, kb, tile_e, ck, "c" if compact else "s", interpret)
    got = _CALLS.get(key)
    if got is not None:
        return got
    from . import unionfind as uf

    g = eb // tile_e
    sent = vb
    it = min(tile_e, INTERSECT_TILE, eb)

    def _fold_tile(i, s, d, v, deg_ref, slab_s, slab_d):
        ones = jnp.where(v, 1, 0)
        deg_ref[:] = deg_ref[:].at[s].add(ones).at[d].add(ones)
        slab_s[i, :] = s
        slab_d[i, :] = d

    def _final(deg_ref, lab_ref, cov_ref, sums_ref, slab_s, slab_d):
        sa = slab_s[:].reshape(eb)
        da = slab_d[:].reshape(eb)
        va = sa != sent
        lab = uf.cc_fixpoint(lab_ref[:], sa, da)
        lab_ref[:] = lab
        cov = uf.cc_fixpoint(
            cov_ref[:], jnp.concatenate([sa, sa + (vb + 1)]),
            jnp.concatenate([da + (vb + 1), da]))
        cov_ref[:] = cov
        mdeg, ncomp, odd = _final_summaries(vb, deg_ref[:], lab, cov)
        tri, ovf = _tri_stage(sa, da, va, vb, kb, it, ck)
        sums_ref[:] = _pack_sums(mdeg, ncomp,
                                 jnp.where(odd, 1, 0), tri, ovf)

    def _init(i, deg0, lab0, cov0, deg_ref, lab_ref, cov_ref):
        @pl.when(i == 0)
        def _():
            deg_ref[:] = deg0[:]
            lab_ref[:] = lab0[:]
            cov_ref[:] = cov0[:]

    if compact:
        def kernel(nv_ref, s_ref, d_ref, deg0, lab0, cov0,
                   deg_ref, lab_ref, cov_ref, sums_ref,
                   slab_s, slab_d):
            i = pl.program_id(0)
            _init(i, deg0, lab0, cov0, deg_ref, lab_ref, cov_ref)
            # compact-ingress decode, fused: the window's suffix mask
            # from its valid count + uint16→int32 widening (the
            # widen_stack semantics, per tile in VMEM)
            pos = i * tile_e + jax.lax.broadcasted_iota(
                jnp.int32, (1, tile_e), 1)[0]
            v = pos < nv_ref[0]
            s = jnp.where(v, s_ref[0, :].astype(jnp.int32), sent)
            d = jnp.where(v, d_ref[0, :].astype(jnp.int32), sent)
            _fold_tile(i, s, d, v, deg_ref, slab_s, slab_d)

            @pl.when(i == g - 1)
            def _():
                _final(deg_ref, lab_ref, cov_ref, sums_ref,
                       slab_s, slab_d)

        wire_specs = [
            pl.BlockSpec((1,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, tile_e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ]
    else:
        def kernel(s_ref, d_ref, v_ref, deg0, lab0, cov0,
                   deg_ref, lab_ref, cov_ref, sums_ref,
                   slab_s, slab_d):
            i = pl.program_id(0)
            _init(i, deg0, lab0, cov0, deg_ref, lab_ref, cov_ref)
            v = v_ref[0, :]
            s = jnp.where(v, s_ref[0, :], sent)
            d = jnp.where(v, d_ref[0, :], sent)
            _fold_tile(i, s, d, v, deg_ref, slab_s, slab_d)

            @pl.when(i == g - 1)
            def _():
                _final(deg_ref, lab_ref, cov_ref, sums_ref,
                       slab_s, slab_d)

        wire_specs = [
            pl.BlockSpec((1, tile_e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile_e), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ]

    vb1 = vb + 1
    carry_specs = [
        pl.BlockSpec((vb1,), lambda i: (0,),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((vb1,), lambda i: (0,),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((2 * vb1,), lambda i: (0,),
                     memory_space=pltpu.VMEM),
    ]
    call = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=wire_specs + carry_specs,
        out_specs=carry_specs + [
            pl.BlockSpec((_SUMS,), lambda i: (0,),
                         memory_space=pltpu.SMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((vb1,), jnp.int32),
            jax.ShapeDtypeStruct((vb1,), jnp.int32),
            jax.ShapeDtypeStruct((2 * vb1,), jnp.int32),
            jax.ShapeDtypeStruct((_SUMS,), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((g, tile_e), jnp.int32),
                        pltpu.VMEM((g, tile_e), jnp.int32)],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=window_flops(eb, vb, kb),
            bytes_accessed=window_bytes(eb, vb, compact),
            transcendentals=0),
    )

    def run(deg, lab, cov, *wire):
        return call(*wire, deg, lab, cov)

    _CALLS[key] = run
    return run


def _cohort_call(eb: int, vb: int, kb: int, nb: int, tile_e: int,
                 ck: int, interpret: bool):
    """The tenant-axis megakernel pallas_call closure:
    (deg[nb,vb1], lab[nb,vb1], cov[nb,2vb1], *wire[nb,g,tile_e]) ->
    (deg, lab, cov, sums[nb,8]). The tenant axis is the OUTER grid
    dimension (the edge-tile axis is last, so it iterates innermost:
    each tenant's tiles sweep 0..g-1 before the grid advances to the
    next tenant); the stacked carries are whole-array VMEM blocks
    under constant index maps — the entire cohort stays VMEM-resident
    across the grid, which is exactly the N-row carry term
    cohort_vmem_window_bytes budgets. The slab scratch is reused per
    tenant (tenant n's final stage consumes it at tile g-1, before
    tenant n+1's first tile overwrites it)."""
    key = (eb, vb, kb, nb, tile_e, ck, "n", interpret)
    got = _CALLS.get(key)
    if got is not None:
        return got
    from . import unionfind as uf

    g = eb // tile_e
    sent = vb
    it = min(tile_e, INTERSECT_TILE, eb)
    vb1 = vb + 1

    def _row(ref, n):
        return pl.load(ref, (pl.dslice(n, 1), slice(None)))[0]

    def _set_row(ref, n, val):
        pl.store(ref, (pl.dslice(n, 1), slice(None)), val[None])

    def kernel(s_ref, d_ref, v_ref, deg0, lab0, cov0,
               deg_ref, lab_ref, cov_ref, sums_ref, slab_s, slab_d):
        n = pl.program_id(0)
        i = pl.program_id(1)

        @pl.when(jnp.logical_and(n == 0, i == 0))
        def _():
            # one whole-cohort carry copy at the very first grid step
            deg_ref[:] = deg0[:]
            lab_ref[:] = lab0[:]
            cov_ref[:] = cov0[:]

        v = v_ref[0, 0, :]
        s = jnp.where(v, s_ref[0, 0, :], sent)
        d = jnp.where(v, d_ref[0, 0, :], sent)
        ones = jnp.where(v, 1, 0)
        _set_row(deg_ref, n,
                 _row(deg_ref, n).at[s].add(ones).at[d].add(ones))
        slab_s[i, :] = s
        slab_d[i, :] = d

        @pl.when(i == g - 1)
        def _():
            sa = slab_s[:].reshape(eb)
            da = slab_d[:].reshape(eb)
            va = sa != sent
            lab = uf.cc_fixpoint(_row(lab_ref, n), sa, da)
            _set_row(lab_ref, n, lab)
            cov = uf.cc_fixpoint(
                _row(cov_ref, n), jnp.concatenate([sa, sa + vb1]),
                jnp.concatenate([da + vb1, da]))
            _set_row(cov_ref, n, cov)
            mdeg, ncomp, odd = _final_summaries(
                vb, _row(deg_ref, n), lab, cov)
            tri, ovf = _tri_stage(sa, da, va, vb, kb, it, ck)
            _set_row(sums_ref, n,
                     _pack_sums(mdeg, ncomp, jnp.where(odd, 1, 0),
                                tri, ovf))

    tile_spec = pl.BlockSpec((1, 1, tile_e), lambda n, i: (n, i, 0),
                             memory_space=pltpu.VMEM)
    carry_specs = [
        pl.BlockSpec((nb, vb1), lambda n, i: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((nb, vb1), lambda n, i: (0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec((nb, 2 * vb1), lambda n, i: (0, 0),
                     memory_space=pltpu.VMEM),
    ]
    call = pl.pallas_call(
        kernel,
        grid=(nb, g),
        in_specs=[tile_spec, tile_spec, tile_spec] + carry_specs,
        out_specs=carry_specs + [
            pl.BlockSpec((nb, _SUMS), lambda n, i: (0, 0),
                         memory_space=pltpu.SMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((nb, vb1), jnp.int32),
            jax.ShapeDtypeStruct((nb, vb1), jnp.int32),
            jax.ShapeDtypeStruct((nb, 2 * vb1), jnp.int32),
            jax.ShapeDtypeStruct((nb, _SUMS), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((g, tile_e), jnp.int32),
                        pltpu.VMEM((g, tile_e), jnp.int32)],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=nb * window_flops(eb, vb, kb),
            bytes_accessed=nb * window_bytes(eb, vb, False),
            transcendentals=0),
    )

    def run(deg, lab, cov, *wire):
        return call(*wire, deg, lab, cov)

    _CALLS[key] = run
    return run


def _counter_call(eb: int, vb: int, kb: int, tile_e: int, ck: int,
                  interpret: bool):
    """Triangle-only megakernel (the stream kernel's per-window body
    carries no analytics state): stage the slab tile by tile, run the
    triangle stage at the last tile, emit (count, overflow)."""
    key = (eb, vb, kb, tile_e, ck, "t", interpret)
    got = _CALLS.get(key)
    if got is not None:
        return got
    g = eb // tile_e
    sent = vb
    it = min(tile_e, INTERSECT_TILE, eb)

    def kernel(s_ref, d_ref, v_ref, sums_ref, slab_s, slab_d):
        i = pl.program_id(0)
        v = v_ref[0, :]
        slab_s[i, :] = jnp.where(v, s_ref[0, :], sent)
        slab_d[i, :] = jnp.where(v, d_ref[0, :], sent)

        @pl.when(i == g - 1)
        def _():
            sa = slab_s[:].reshape(eb)
            da = slab_d[:].reshape(eb)
            tri, ovf = _tri_stage(sa, da, sa != sent, vb, kb, it, ck)
            sums_ref[:] = _pack_sums(tri, ovf)

    tile_spec = pl.BlockSpec((1, tile_e), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    call = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[tile_spec, tile_spec, tile_spec],
        out_specs=pl.BlockSpec((_SUMS,), lambda i: (0,),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((_SUMS,), jnp.int32),
        scratch_shapes=[pltpu.VMEM((g, tile_e), jnp.int32),
                        pltpu.VMEM((g, tile_e), jnp.int32)],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=window_flops(eb, vb, kb),
            bytes_accessed=slab_bytes(eb) + 4 * _SUMS,
            transcendentals=0),
    )
    _CALLS[key] = call
    return call


# ----------------------------------------------------------------------
# scan-body builders (the _build_scan-compatible contract)
# ----------------------------------------------------------------------
def build_window_body(eb: int, vb: int, kb: int, tile_e: int = None,
                      chunk_k: int = None, compact: bool = False,
                      interpret: bool = None):
    """The megakernel as a drop-in scan body for
    scan_analytics._build_scan: body(carry, xs) with the identical
    carry layout ((deg[vb+1], labels[vb+1], cover[2(vb+1)])) and
    per-window outputs (max_degree, num_components, odd, triangles,
    K-overflow). `xs` is (src, dst, valid) rows on the standard wire
    or (s16, d16, nvalid-scalar) on the compact wire — the compact
    body consumes the RAW uint16 stacks, decode fused in-kernel."""
    tile_e, ck = resolve_tiles(eb, kb, vb, tile_e, chunk_k)
    if interpret is None:
        interpret = _need_interpret()
    run = _window_call(eb, vb, kb, tile_e, ck, compact, interpret)
    g = eb // tile_e

    if compact:
        def body(carry, xs):
            deg, lab, cov = carry
            s16, d16, nv = xs
            deg, lab, cov, sums = run(
                deg, lab, cov,
                jnp.reshape(nv, (1,)).astype(jnp.int32),
                s16.reshape(g, tile_e), d16.reshape(g, tile_e))
            return (deg, lab, cov), (sums[0], sums[1], sums[2] != 0,
                                     sums[3], sums[4])
    else:
        def body(carry, xs):
            deg, lab, cov = carry
            src, dst, valid = xs
            deg, lab, cov, sums = run(
                deg, lab, cov, src.reshape(g, tile_e),
                dst.reshape(g, tile_e), valid.reshape(g, tile_e))
            return (deg, lab, cov), (sums[0], sums[1], sums[2] != 0,
                                     sums[3], sums[4])

    body.pallas_window = True
    return body


def maybe_window_body(eb: int, vb: int, kb: int,
                      compact: bool = False):
    """The gated, PROBED entry the engines build through: None (use
    the XLA body) unless the selection gate is on, the shape fits the
    chip budget, AND a trace probe of the built body succeeds. A
    pallas_call that raises at trace time — Pallas API drift, a
    lowering gap — degrades here with a durable `selection.fallback`
    event instead of wedging engine construction (the chaos-leg
    contract). On success the analytic cost entry registers with the
    observatory."""
    if not resolve_pallas_window():
        return None
    tile_e, ck = resolve_tiles(eb, kb, vb)
    if not supports(eb, vb, kb, tile_e, ck, compact):
        telemetry.event("selection.fallback", durable=True,
                        component="pallas_window",
                        fallback="xla_scan",
                        error="vmem budget: %d > %d at eb=%d vb=%d "
                              "kb=%d" % (
                                  vmem_window_bytes(eb, vb, kb,
                                                    tile_e, ck),
                                  VMEM_BUDGET, eb, vb, kb))
        return None
    try:
        body = build_window_body(eb, vb, kb, tile_e, ck, compact)
        carry = (jax.ShapeDtypeStruct((vb + 1,), jnp.int32),
                 jax.ShapeDtypeStruct((vb + 1,), jnp.int32),
                 jax.ShapeDtypeStruct((2 * (vb + 1),), jnp.int32))
        if compact:
            xs = (jax.ShapeDtypeStruct((eb,), jnp.uint16),
                  jax.ShapeDtypeStruct((eb,), jnp.uint16),
                  jax.ShapeDtypeStruct((), jnp.int32))
        else:
            xs = (jax.ShapeDtypeStruct((eb,), jnp.int32),
                  jax.ShapeDtypeStruct((eb,), jnp.int32),
                  jax.ShapeDtypeStruct((eb,), jnp.bool_))
        jax.eval_shape(body, carry, xs)
    except Exception as e:
        telemetry.event("selection.fallback", durable=True,
                        component="pallas_window",
                        fallback="xla_scan",
                        error="%s: %s" % (type(e).__name__,
                                          str(e)[:200]))
        return None
    register_cost_model(eb, vb, kb, compact)
    return body


def build_cohort_window_body(eb: int, vb: int, kb: int, nb: int,
                             tile_e: int = None,
                             chunk_k: int = None,
                             interpret: bool = None):
    """The tenant-axis megakernel as a drop-in body for
    scan_analytics.build_cohort_scan's window loop: body(carry, xs)
    with the STACKED carry layout ((deg[nb,vb+1], labels[nb,vb+1],
    cover[nb,2(vb+1)])) and per-window-round outputs of shape [nb]
    each (max_degree, num_components, odd, triangles, K-overflow) —
    the same pytree the vmapped XLA body produces, so the two paths
    are interchangeable under lax.scan. Standard wire only (the
    cohort slab is int32 src/dst + bool valid)."""
    tile_e, ck = resolve_tiles(eb, kb, vb, tile_e, chunk_k)
    if interpret is None:
        interpret = _need_interpret()
    run = _cohort_call(eb, vb, kb, nb, tile_e, ck, interpret)
    g = eb // tile_e

    def body(carry, xs):
        deg, lab, cov = carry
        src, dst, valid = xs
        deg, lab, cov, sums = run(
            deg, lab, cov, src.reshape(nb, g, tile_e),
            dst.reshape(nb, g, tile_e),
            valid.reshape(nb, g, tile_e))
        return (deg, lab, cov), (sums[:, 0], sums[:, 1],
                                 sums[:, 2] != 0, sums[:, 3],
                                 sums[:, 4])

    body.pallas_window = True
    return body


def maybe_cohort_body(eb: int, vb: int, kb: int, nb: int):
    """The gated, PROBED entry build_cohort_scan builds through: None
    (vmap the XLA body over tenants) unless resolve_cohort_pallas()
    is on, the N-row shape fits the chip budget, AND a trace probe of
    the built body succeeds — the same durable `selection.fallback`
    contract as maybe_window_body, under component `cohort_pallas`.
    On success the cohort analytic cost entry registers with the
    observatory."""
    if not resolve_cohort_pallas():
        return None
    tile_e, ck = resolve_tiles(eb, kb, vb)
    if not supports_cohort(eb, vb, kb, nb, tile_e, ck):
        telemetry.event("selection.fallback", durable=True,
                        component="cohort_pallas",
                        fallback="xla_cohort_scan",
                        error="vmem budget: %d > %d at eb=%d vb=%d "
                              "kb=%d nb=%d" % (
                                  cohort_vmem_window_bytes(
                                      eb, vb, kb, nb, tile_e, ck),
                                  VMEM_BUDGET, eb, vb, kb, nb))
        return None
    try:
        body = build_cohort_window_body(eb, vb, kb, nb, tile_e, ck)
        vb1 = vb + 1
        carry = (jax.ShapeDtypeStruct((nb, vb1), jnp.int32),
                 jax.ShapeDtypeStruct((nb, vb1), jnp.int32),
                 jax.ShapeDtypeStruct((nb, 2 * vb1), jnp.int32))
        xs = (jax.ShapeDtypeStruct((nb, eb), jnp.int32),
              jax.ShapeDtypeStruct((nb, eb), jnp.int32),
              jax.ShapeDtypeStruct((nb, eb), jnp.bool_))
        jax.eval_shape(body, carry, xs)
    except Exception as e:
        telemetry.event("selection.fallback", durable=True,
                        component="cohort_pallas",
                        fallback="xla_cohort_scan",
                        error="%s: %s" % (type(e).__name__,
                                          str(e)[:200]))
        return None
    costmodel.record_analytic(
        "cohort_pallas", "eb=%d,vb=%d,kb=%d,nb=%d" % (eb, vb, kb, nb),
        flops=nb * window_flops(eb, vb, kb),
        bytes_accessed=nb * window_bytes(eb, vb, False),
        slab_bytes=nb * slab_bytes(eb, False),
        scan_of_gathers_bytes=nb * scan_of_gathers_bytes(eb, vb),
        model="analytic",
        # PER WINDOW ROUND (one window × nb tenants); a super-batch
        # dispatch folds W of them
        unit="window")
    return body


def maybe_compact_scan_fn(eb: int, vb: int, kb: int, label: str,
                          jit_kwargs: dict = None):
    """The compact-fused scan program BOTH summary engines'
    `_ensure_compact_fn` build when the megakernel is selected —
    decode per tile in-kernel, scanned over the raw uint16 stacks —
    factored here so the scan tier and the (donated) resident tier
    can never diverge on the wiring. None when the compact body's
    gate/probe refuses (callers fall back to the widen_stack twin)."""
    cbody = maybe_window_body(eb, vb, kb, compact=True)
    if cbody is None:
        return None
    from ..utils import metrics

    def run_pc(carry, s16, d16, nvalid):
        return jax.lax.scan(cbody, carry, (s16, d16, nvalid))

    return metrics.wrap_jit(label, jax.jit(run_pc,
                                           **(jit_kwargs or {})))


# ----------------------------------------------------------------------
# the GNN window kernel (ops/gnn_window's fused round)
# ----------------------------------------------------------------------
def gnn_h_bytes(vb: int, F: int) -> int:
    """Bytes of one [vb+1, F] float32 feature-slab copy."""
    return 4 * (vb + 1) * F


def gnn_weight_bytes(F: int) -> int:
    """Bytes of the dense layer's W [F, F] + b [F] (float32)."""
    return 4 * F * (F + 1)


def gnn_window_flops(eb: int, vb: int, F: int) -> int:
    """Stated-model FLOP estimate for ONE GNN round (labeled
    `analytic` in the cost registry): the dense update's matmul
    dominates (2·(vb+1)·F²) — THE term no other program here has —
    plus the aggregation's gather-and-add (2·eb·F) and the clamp/act
    elementwise sweeps (~6·(vb+1)·F)."""
    return (2 * (vb + 1) * F * F + 2 * eb * F
            + 6 * (vb + 1) * F)


def gnn_window_bytes(eb: int, vb: int, F: int) -> int:
    """The fused kernel's HBM traffic per round: ONE standard-wire
    slab read (the features ride the same read as the megakernel's
    analytics — the messages never round-trip HBM), the feature slab
    read+write, the weights, the summary row."""
    return (slab_bytes(eb, False) + 2 * gnn_h_bytes(vb, F)
            + gnn_weight_bytes(F) + 4 * _SUMS)


def gnn_scan_bytes(eb: int, vb: int, F: int) -> int:
    """HBM bytes the XLA gather/segment-sum round moves for the SAME
    window: the slab read, the materialized [eb, F] message matrix's
    write+read (gather out, segment-sum in), the [vb+1, F] aggregate's
    write+read, the feature slab's read+write, and the weights. The
    adoption story is the same subtraction as scan_of_gathers_bytes:
    the fused kernel deletes the message-matrix round-trip."""
    msgs = 4 * eb * F
    return (slab_bytes(eb, False) + 2 * msgs
            + 2 * gnn_h_bytes(vb, F) + 2 * gnn_h_bytes(vb, F)
            + gnn_weight_bytes(F))


def gnn_vmem_window_bytes(eb: int, vb: int, F: int,
                          tile_e: int = None) -> int:
    """The GNN kernel's VMEM high-water estimate (DESIGN.md §23
    mirrors §19's walk): decoded slab scratch, the feature slab in
    and out plus the gathered message matrix and the aggregate /
    pre-activation temporaries (~4 slab-sized blocks), and the
    weights."""
    slab = 2 * 4 * eb
    msgs = 4 * eb * F
    return (slab + 4 * gnn_h_bytes(vb, F) + msgs
            + gnn_weight_bytes(F))


def supports_gnn(eb: int, vb: int, F: int,
                 tile_e: int = None) -> bool:
    """Does a GNN round at (eb, vb, F) fit the chip's VMEM budget?
    Same contract as supports(): enforced on TPU backends only —
    interpret mode has no VMEM, and refusing a CPU parity run over a
    budget the backend doesn't have would gate the oracle out of
    existence."""
    if not _on_tpu():
        return True
    return gnn_vmem_window_bytes(eb, vb, F, tile_e) <= VMEM_BUDGET


def register_gnn_cost_model(eb: int, vb: int, F: int,
                            nb: int = None) -> None:
    """Register the GNN programs' analytic cost models with the
    observatory (armed only) under every wrap_jit label the family
    dispatches as: the XLA scan tiers (`gnn_scan`, `gnn_resident`)
    at the gather/segment-sum byte model, the fused kernel
    (`gnn_pallas`) at the single-slab-read model. With `nb` set,
    registers the vmapped tenant-axis program (`gnn_cohort`) at
    nb-scaled numbers instead. These are the repo's first MXU-class
    rows — the flops term carries a matmul, so the stated arithmetic
    intensity finally has a chance against machine balance."""
    flops = gnn_window_flops(eb, vb, F)
    sig = "eb=%d,vb=%d,F=%d" % (eb, vb, F)
    if nb is not None:
        costmodel.record_analytic(
            "gnn_cohort", sig + (",nb=%d" % nb),
            flops=nb * flops,
            bytes_accessed=nb * gnn_scan_bytes(eb, vb, F),
            slab_bytes=nb * slab_bytes(eb, False),
            model="analytic",
            # PER WINDOW ROUND (one window × nb tenants)
            unit="window")
        return
    for program, nbytes in (
            ("gnn_scan", gnn_scan_bytes(eb, vb, F)),
            ("gnn_resident", gnn_scan_bytes(eb, vb, F)),
            ("gnn_pallas", gnn_window_bytes(eb, vb, F))):
        costmodel.record_analytic(
            program, sig,
            flops=flops,
            bytes_accessed=nbytes,
            slab_bytes=slab_bytes(eb, False),
            model="analytic",
            # the model is PER WINDOW; a chunk dispatch folds W of
            # them, so a reader scaling against per-dispatch span
            # seconds multiplies by the sig's leading window count
            unit="window")


def _gnn_call(eb: int, vb: int, F: int, act: str, tile_e: int,
              interpret: bool):
    """The fused GNN-round pallas_call closure:
    (h[vb+1,F], W[F,F], b[F], s2, d2, v2) -> (h', sums[8]). Stages
    the sentinel-mapped slab tile by tile into VMEM scratch; the last
    tile runs the whole round — gather, scatter-accumulate, clamp,
    the MXU dot at Precision.HIGHEST, activation, re-clip — against
    the VMEM-resident feature slab, then packs the four summary
    scalars. Bit-identical to ops/gnn_window._build_gnn_round by the
    lattice argument (every intermediate an exact float32 integer
    < 2^24, so fold order is free). Memoized per shape."""
    key = (eb, vb, F, act, tile_e, "g", interpret)
    got = _CALLS.get(key)
    if got is not None:
        return got
    from . import gnn_window as gw

    g = eb // tile_e
    sent = vb
    sh = gw.agg_shift(eb)
    sc = np.float32(2.0 ** -sh)
    cap = np.float32(gw.UNIT_CAP)
    actf = gw._ACTS_JNP[act]

    def kernel(s_ref, d_ref, v_ref, h0_ref, w_ref, b_ref,
               h_ref, sums_ref, slab_s, slab_d):
        i = pl.program_id(0)
        v = v_ref[0, :]
        slab_s[i, :] = jnp.where(v, s_ref[0, :], sent)
        slab_d[i, :] = jnp.where(v, d_ref[0, :], sent)

        @pl.when(i == g - 1)
        def _():
            sa = slab_s[:].reshape(eb)
            da = slab_d[:].reshape(eb)
            h = h0_ref[:]
            msgs = h[sa]
            if sh:
                msgs = jnp.floor(msgs * sc)
            m = jnp.zeros((vb + 1, F), jnp.float32).at[da].add(msgs)
            p = jnp.minimum(h + jnp.minimum(m, cap), cap)
            z = jax.lax.dot_general(
                p, w_ref[:], (((1,), (0,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST) + b_ref[:]
            h2 = jnp.clip(actf(z), 0.0, cap)
            h2 = h2.at[sent].set(0.0)
            # the round's empty-window-holds rule (_build_gnn_round):
            # zero valid edges → the slab carries through untouched
            nmsg = jnp.sum(sa != sent, dtype=jnp.int32)
            h2 = jnp.where(nmsg > 0, h2, h)
            h_ref[:] = h2
            maxf = jnp.max(h2[:vb]).astype(jnp.int32)
            active = jnp.sum(jnp.any(h2[:vb] > 0, axis=1),
                             dtype=jnp.int32)
            checksum = jnp.sum(h2.astype(jnp.int32),
                               dtype=jnp.int32)
            sums_ref[:] = _pack_sums(maxf, active, checksum, nmsg)

    tile_spec = pl.BlockSpec((1, tile_e), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    h_spec = pl.BlockSpec((vb + 1, F), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((F, F), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    b_spec = pl.BlockSpec((F,), lambda i: (0,),
                          memory_space=pltpu.VMEM)
    call = pl.pallas_call(
        kernel,
        grid=(g,),
        in_specs=[tile_spec, tile_spec, tile_spec,
                  h_spec, w_spec, b_spec],
        out_specs=[h_spec,
                   pl.BlockSpec((_SUMS,), lambda i: (0,),
                                memory_space=pltpu.SMEM)],
        out_shape=[
            jax.ShapeDtypeStruct((vb + 1, F), jnp.float32),
            jax.ShapeDtypeStruct((_SUMS,), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((g, tile_e), jnp.int32),
                        pltpu.VMEM((g, tile_e), jnp.int32)],
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=gnn_window_flops(eb, vb, F),
            bytes_accessed=gnn_window_bytes(eb, vb, F),
            transcendentals=0),
    )

    def run(h, W, b, *wire):
        return call(*wire, h, W, b)

    _CALLS[key] = run
    return run


def build_gnn_window_body(eb: int, vb: int, F: int, act: str,
                          tile_e: int = None,
                          interpret: bool = None):
    """The fused GNN round as a drop-in body for
    ops/gnn_window._build_gnn_scan: body(h, W, b, xs) with the same
    [vb+1, F] carry and (max_feat, active, checksum, msg_edges)
    outputs as the XLA round — interchangeable under lax.scan by the
    lattice argument. Standard wire only."""
    if tile_e is None:
        tile_e = default_tile(eb)
    if interpret is None:
        interpret = _need_interpret()
    run = _gnn_call(eb, vb, F, act, tile_e, interpret)
    g = eb // tile_e

    def body(h, W, b, xs):
        src, dst, valid = xs
        h, sums = run(h, W, b, src.reshape(g, tile_e),
                      dst.reshape(g, tile_e),
                      valid.reshape(g, tile_e))
        return h, (sums[0], sums[1], sums[2], sums[3])

    body.gnn_pallas = True
    return body


def maybe_gnn_body(eb: int, vb: int, F: int, act: str):
    """The gated, PROBED entry GnnSummaryEngine builds through: None
    (use the XLA gather/segment-sum round) unless
    resolve_gnn_pallas() is on, the [vb+1, F] slab fits the chip
    budget, AND a trace probe of the built body succeeds — the same
    durable `selection.fallback` contract as maybe_window_body, under
    component `gnn_pallas`. On success the GNN analytic cost entries
    register with the observatory."""
    if not resolve_gnn_pallas():
        return None
    tile_e = default_tile(eb)
    if not supports_gnn(eb, vb, F, tile_e):
        telemetry.event("selection.fallback", durable=True,
                        component="gnn_pallas",
                        fallback="xla_gnn_scan",
                        error="vmem budget: %d > %d at eb=%d vb=%d "
                              "F=%d" % (
                                  gnn_vmem_window_bytes(eb, vb, F,
                                                        tile_e),
                                  VMEM_BUDGET, eb, vb, F))
        return None
    try:
        body = build_gnn_window_body(eb, vb, F, act, tile_e)
        h = jax.ShapeDtypeStruct((vb + 1, F), jnp.float32)
        W = jax.ShapeDtypeStruct((F, F), jnp.float32)
        b = jax.ShapeDtypeStruct((F,), jnp.float32)
        xs = (jax.ShapeDtypeStruct((eb,), jnp.int32),
              jax.ShapeDtypeStruct((eb,), jnp.int32),
              jax.ShapeDtypeStruct((eb,), jnp.bool_))
        jax.eval_shape(body, h, W, b, xs)
    except Exception as e:
        telemetry.event("selection.fallback", durable=True,
                        component="gnn_pallas",
                        fallback="xla_gnn_scan",
                        error="%s: %s" % (type(e).__name__,
                                          str(e)[:200]))
        return None
    register_gnn_cost_model(eb, vb, F)
    return body


def maybe_counter(vb: int, kb: int, classic_run):
    """The gated triangle-stream variant for
    triangles.build_window_counter: a selector body that runs the
    triangle-only megakernel where the (trace-static) edge bucket
    fits the budget and the probed kernel built, else `classic_run`.
    The probe runs ONCE per (vb, kb) at a nominal bucket — the same
    durable-fallback contract as maybe_window_body."""
    if not resolve_pallas_window():
        return None
    pkey = (vb, kb, "counter")
    verdict = _PROBES.get(pkey)
    if verdict is None:
        try:
            probe_eb = 128
            tile_e, ck = resolve_tiles(probe_eb, kb, vb)
            call = _counter_call(probe_eb, vb, kb, tile_e, ck,
                                 _need_interpret())
            g = probe_eb // tile_e
            sds = jax.ShapeDtypeStruct((g, tile_e), jnp.int32)
            jax.eval_shape(call, sds, sds,
                           jax.ShapeDtypeStruct((g, tile_e),
                                                jnp.bool_))
            verdict = True
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="pallas_window",
                            fallback="xla_counter",
                            error="%s: %s" % (type(e).__name__,
                                              str(e)[:200]))
            verdict = False
        _PROBES[pkey] = verdict
    if not verdict:
        return None
    # the stream program's stated model: slab in, two scalars out (no
    # carried analytics); joins the pallas_window_stream spans the
    # AOT wrapper tags
    costmodel.record_analytic(
        "pallas_window_stream", "vb=%d,kb=%d" % (vb, kb),
        flops=None, bytes_accessed=None, model="analytic",
        unit="window",
        note="per-window bytes = pallas_window.slab_bytes(eb) + 32; "
             "flops = window_flops(eb, vb, kb) triangle terms")
    pin_tile = knobs.get_int("GS_PALLAS_TILE") or 0
    pin_ck = knobs.get_int("GS_PALLAS_CK") or 0
    interpret = _need_interpret()

    def run(src, dst, valid):
        # tile resolution here is PURE in (eb, the build-time pins):
        # the edge bucket is trace-static, and the knob reads already
        # happened at build — nothing environmental freezes in-trace
        eb = src.shape[0]
        tile_e = max(8, min(pin_tile or default_tile(eb), eb))
        while eb % tile_e:
            tile_e //= 2
        ck = max(8, min(pin_ck or min(128, kb), kb))
        if not supports(eb, vb, kb, tile_e, ck):
            return classic_run(src, dst, valid)
        call = _counter_call(eb, vb, kb, tile_e, ck, interpret)
        g = eb // tile_e
        sums = call(src.reshape(g, tile_e), dst.reshape(g, tile_e),
                    valid.reshape(g, tile_e))
        return sums[0], sums[1]

    run.pallas_window = True
    return run

"""Columnar windowed neighborhood reduce — `reduceOnEdges` /
`foldNeighbors` at stream rate (BASELINE.json config #2).

The record-level runtime executes the generic neighborhood UDFs by
handing per-edge Python `Edge` lists to a kernel per window
(core/runtime.py) — exact reference semantics
(GraphWindowStream.java:101-121), but interpreter-bound. This engine is
the production columnar form: interned COO windows (src, dst, value
arrays) flow straight into the flattened (window, vertex) segment
kernels — the SAME cell trick as the sliding pane path
(ops/neighborhood.py `_make_pane_reduce` with panes = tumbling
windows), so one fixed-shape device dispatch reduces an entire
windows_per_dispatch stack of windows with zero per-edge Python.

Monoid names ('sum'|'min'|'max') run the parallel segment kernels;
a user fn DECLARED associative runs the flagged associative scan
(seg_ops.segmented_reduce_associative). Direction follows the
reference's EdgeDirection: OUT groups by src, IN by dst, ALL by both
(each edge contributes its value to both endpoints' neighborhoods —
SimpleEdgeStream.java slice(ALL) duplicates exactly this way).

Multi-chip: `parallel.sharded.make_sharded_pane_reduce(mesh, vb, pb,
panes_per_window=1, name)` IS this engine's sharded form (a tumbling
window is a sliding window with one pane); ShardedWindowEngine
.sliding_reduce exposes it.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from . import segment as seg_ops
from ..utils import telemetry

_DIRECTIONS = ("out", "in", "all")


_REDUCE_IMPL = {}   # name -> "device" | "host", resolved once per process  # gslint: disable=thread-shared (idempotent memo of committed PERF.json evidence)


def _resolve_reduce_impl(name: str, allow_native: bool = True) -> str:
    """Columnar-reduce tier for monoid `name`: the device segment
    kernels by default; the vectorized host kernel (flattened
    one-bincount-per-chunk for sum, ufunc.at otherwise) or the C++
    fused tier only on committed BACKEND-MATCHED `host_reduce` rows
    showing parity and a ≥5% win for this name at every measured
    bucket — the same measured-default policy as
    `triangles._resolve_stream_impl`. On a CPU backend this is the
    fallback-floor selection (since r3); on a TPU backend the rows are
    the chip window's own host-vs-device measurements
    (tools/profile_kernels.py section_host_reduce runs on the tunnel
    host), so a tunneled chip whose per-dispatch latency loses to the
    host core routes the reduce engine to the measured winner instead
    of shipping a 0.0x chip row (VERDICT r4 item 4 — config #2 must
    actually win somewhere real)."""
    key = (name, allow_native)
    if key in _REDUCE_IMPL:
        return _REDUCE_IMPL[key]
    impl = "device"
    try:
        import jax as _jax

        from .triangles import _load_matching_perf

        if _jax.default_backend() in ("cpu", "tpu"):
            perf = _load_matching_perf()
            rows = [r for r in (perf or {}).get("host_reduce", [])
                    if r.get("name") == name]
            if rows and all(r.get("parity") is True
                            and (r.get("host_edges_per_s") or 0)
                            >= 1.05 * (r.get("device_edges_per_s") or 0)
                            for r in rows):
                impl = "host"
            # the C++ fused tier (native/ingest.cpp
            # gs_windowed_reduce) competes under the same rule: parity
            # + ≥5% over BOTH other tiers at every measured bucket
            if allow_native and rows \
                    and all(r.get("native_parity") is True
                            and (r.get("native_edges_per_s") or 0)
                            >= 1.05 * max(
                                r.get("device_edges_per_s") or 0,
                                r.get("host_edges_per_s") or 0)
                            for r in rows):
                from .. import native as _native

                if _native.windowed_reduce_available():
                    impl = "native"
    except Exception as e:
        telemetry.event("selection.fallback", durable=True,
                        component="windowed_reduce", fallback=impl,
                        error="%s: %s" % (type(e).__name__, e))
    _REDUCE_IMPL[key] = impl
    return impl


def _device_cell_fill(name: str, dtype):
    """The DEVICE segment kernels' empty-segment identity — what an
    untouched cell of the full-egress [wb, vbp] stack holds: the XLA
    reduce init values (segment sum → 0; segment min → +inf /
    iinfo.max; segment max → -inf / iinfo.min). The delta-egress
    decode refills reconstructed rows with it so both egress formats
    are bit-identical cell-for-cell, not just on touched cells (cells
    with count 0 are contractually compared by count, but the bit
    contract keeps the A/B's sha256 assertion meaningful)."""
    dtype = np.dtype(dtype)
    if name == "sum":
        return dtype.type(0)
    if np.issubdtype(dtype, np.floating):
        return np.inf if name == "min" else -np.inf
    info = np.iinfo(dtype)
    return info.max if name == "min" else info.min


def _host_identity(name: str, dtype):
    """Monoid identity for the HOST (numpy) tiers and the reference
    oracle — one definition so the tier, the oracle, and any future
    parity fix cannot drift apart (the device tier's jnp form lives in
    neighborhood._pane_identity, which uses finfo extremes instead of
    ±inf for floats; cells with count 0 are compared by count, never
    by value, so the two conventions never meet in an assertion)."""
    dtype = np.dtype(dtype)
    if name == "sum":
        return 0
    if np.issubdtype(dtype, np.integer):
        return (np.iinfo(dtype).max if name == "min"
                else np.iinfo(dtype).min)
    return np.inf if name == "min" else -np.inf


class WindowedEdgeReduce:
    """Per-window per-vertex reduce over tumbling `edge_bucket`-sized
    windows of a COO value stream.

    `process_stream(src, dst, val)` -> list of (values, counts), one
    pair per window; values[v] is the reduce of the window's edges
    incident to dense vertex v in the given direction, counts[v] the
    number of contributing edges (0 = vertex absent — min/max cells
    hold the fill, mask by counts like the pane path).

    One jitted program per windows-per-dispatch bucket over fixed
    [wb, eb] shapes — steady-state streaming recompiles nothing
    (the same dispatch economics as TriangleWindowKernel). On a CPU
    backend with committed winning measurements the monoid tier routes
    through the vectorized host kernel instead
    (`_resolve_reduce_impl`; same cells/counts, no dispatches).
    """

    MAX_STREAM_WINDOWS = 64

    def __init__(self, vertex_bucket: int, edge_bucket: int,
                 name: str = "sum", direction: str = "out",
                 fn=None, ingress: str = None, egress: str = None,
                 slide: int = None):
        if direction not in _DIRECTIONS:
            raise ValueError(f"direction must be one of {_DIRECTIONS}")
        if egress not in (None, "full", "delta"):
            raise ValueError(f"unknown egress: {egress!r}")
        if fn is not None:
            name = None
        assert name in (None, "sum", "min", "max"), name
        # sliding windows by pane composition: panes are monoid
        # summaries, so each edge folds into its slide-sized pane ONCE
        # and every emission composes the last panes_per_window pane
        # (cells, counts) pairs — O(1) panes per edge instead of the
        # naive twin's O(panes_per_window) refolds of the overlap
        if slide is not None and int(slide) != 0:
            slide = int(slide)
            if name is None:
                raise ValueError(
                    "slide= needs a monoid name (sum/min/max): pane "
                    "composition relies on the named identity fills")
            eb_n = seg_ops.bucket_size(edge_bucket)
            if (slide <= 0 or slide > eb_n or eb_n % slide
                    or slide & (slide - 1)):
                raise ValueError(
                    "slide must be a power of two dividing the "
                    "window size (%d), got %d" % (eb_n, slide))
            self.slide = slide
        else:
            self.slide = None
        self.vb = seg_ops.bucket_size(vertex_bucket)
        self.eb = seg_ops.bucket_size(edge_bucket)
        # compile-size cap on the tunneled chip: its own program class
        # (a segment-reduce stack, unproven on the remote compiler) so
        # a RAISED triangle cap never drags this program past the
        # default (ops/triangles.compile_cap)
        from . import triangles as _tri

        self.MAX_STREAM_WINDOWS = min(
            type(self).MAX_STREAM_WINDOWS,
            _tri.capped_chunk(self.eb, "reduce_stack"))
        self.name = name
        self.fn = fn
        self.direction = direction
        # stream-chunk wire format of the monoid DEVICE tier: uint16
        # ids + per-window valid counts with the (window, vertex) cell
        # ids computed on device (2×u16 + vals vs host-built int64
        # flat ids — fewer h2d bytes AND the id packing moves off the
        # single host core). Same committed-evidence selection and
        # vb gate as TriangleWindowKernel (ops/triangles.
        # resolve_ingress; standard is the fallback whenever
        # compact_ingress.supports(vb) is false).
        if ingress == "compact":
            from . import compact_ingress

            if not compact_ingress.supports(self.vb):
                raise ValueError(
                    "compact ingress is lossy for vertex_bucket %d "
                    "(ids must fit uint16)" % self.vb)
        self.ingress = (ingress if ingress
                        else _tri.resolve_ingress(self.vb))
        # d2h egress of the monoid DEVICE tier: full [wb, vbp]
        # cells+counts stacks, or the touched-cell delta wire
        # (ops/delta_egress — a window touches at most one cell per
        # contribution, so the [cap]-sized wire is exact, no overflow
        # path needed). Same pin/evidence selection as the driver's
        # snapshot egress.
        from . import delta_egress as _de

        self.egress = egress if egress else _de.resolve_egress()
        from . import ingress_pipeline as _ip

        self.stage_timers = _ip.StageTimers()
        self._fns = {}
        # sliding mode: the inner pane engine (this engine at
        # edge_bucket=slide — every tier decision re-resolves at the
        # pane bucket) and the tumbling refold twin, built lazily
        self.panes_per_window = (self.eb // self.slide
                                 if self.slide else 1)
        self._pane_engine = None
        self._full_engine = None

    # ---- sliding windows (pane composition) ---------------------------

    def _monoid_op(self):
        return {"sum": np.add, "min": np.minimum,
                "max": np.maximum}[self.name]

    def _pane_eng(self) -> "WindowedEdgeReduce":
        if self._pane_engine is None:
            self._pane_engine = WindowedEdgeReduce(
                self.vb, self.slide, name=self.name,
                direction=self.direction)
        return self._pane_engine

    def _full_eng(self) -> "WindowedEdgeReduce":
        if self._full_engine is None:
            self._full_engine = WindowedEdgeReduce(
                self.vb, self.eb, name=self.name,
                direction=self.direction)
        return self._full_engine

    def _compose_panes(self, panes: List[Tuple[np.ndarray,
                                               np.ndarray]]
                       ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """One emission per pane: emission i composes panes
        [max(0, i-wp+1), i] — cells under the monoid ufunc (identity
        fills are true identities, so untouched cells stay untouched),
        counts by sum. Head-of-stream emissions compose fewer panes
        (growing windows), the ragged tail pane is just a smaller
        pane: both fall out of the same composition."""
        wp = self.panes_per_window
        op = self._monoid_op()
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        for i in range(len(panes)):
            cells, counts = panes[i]
            cells, counts = cells.copy(), counts.copy()
            for c2, n2 in panes[max(0, i - wp + 1):i]:
                op(cells, c2, out=cells)
                counts += n2
            out.append((cells, counts))
        return out

    def process_stream_naive(self, src, dst, val
                             ) -> List[Tuple[np.ndarray, np.ndarray]]:
        """The refold twin of the sliding path (parity oracle + A/B
        baseline, tools/pump_ab.py): every emission re-reduces its
        FULL window slice through the tumbling engine — each edge is
        folded up to panes_per_window times. Bit-identical emissions
        to the pane path for integer monoids."""
        if self.slide is None:
            return self.process_stream(src, dst, val)
        src = np.asarray(src)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        dst = np.asarray(dst)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        val = np.asarray(val)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        n = len(src)
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        eng, s = self._full_eng(), self.slide
        for i in range(-(-n // s)):
            lo = max(0, (i + 1) * s - self.eb)
            hi = min((i + 1) * s, n)
            out.extend(eng.process_stream(src[lo:hi], dst[lo:hi],
                                          val[lo:hi]))
        return out

    # ---- jitted stack program (monoid tier) ---------------------------

    def _delta_cap(self) -> int:
        """Exact per-window touched-cell bound of the delta egress
        wire: one cell per contribution (two for direction 'all'),
        never more than the row width."""
        per = self.eb * (2 if self.direction == "all" else 1)
        return min(per, self.vb + 1)

    def _delta_tail(self, cap: int):
        """The vmapped per-window encode appended to a stack program
        when egress is delta (ops/delta_egress.compact_touched)."""
        import jax

        from . import delta_egress

        return jax.vmap(
            lambda c, n: delta_egress.compact_touched(c, n, cap))

    def _stack_fn(self, wb: int, delta: bool = False):
        key = (wb, delta)
        fn = self._fns.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            vbp = self.vb + 1
            n_cells = wb * vbp
            name = self.name
            tail = self._delta_tail(self._delta_cap()) if delta else None

            @jax.jit
            def run(ids, vals):
                cells = seg_ops.segment_reduce(
                    vals, ids, n_cells + 1, name)[:-1].reshape(wb, vbp)
                counts = jax.ops.segment_sum(
                    jnp.where(ids < n_cells, 1, 0), ids,
                    n_cells + 1)[:-1].reshape(wb, vbp)
                return tail(cells, counts) if tail else (cells, counts)

            self._fns[key] = fn = run
        return fn

    def _stack_fn_compact(self, wb: int, delta: bool = False):
        """Compact twin of _stack_fn: consumes [wb, eb] uint16 id
        stacks + [wb] valid counts + [wb, eb] values, rebuilds the
        suffix mask and the flattened (window, vertex) cell ids ON
        DEVICE (the widening fused into the same program), then runs
        the identical segment kernels — same cells/counts."""
        key = ("compact", wb, delta)
        fn = self._fns.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            vbp = self.vb + 1
            n_cells = wb * vbp
            eb = self.eb
            name = self.name
            direction = self.direction

            from . import compact_ingress

            tail = self._delta_tail(self._delta_cap()) if delta else None

            @jax.jit
            def run(s16, d16, nvalid, vals):
                # shared compact decode (sentinel 0: the trash-cell
                # `where` below masks padded slots by `valid`)
                s32, d32, valid = compact_ingress.widen_stack(
                    s16, d16, nvalid, eb, 0)
                base = (jnp.arange(wb, dtype=jnp.int32) * vbp)[:, None]

                def ids_of(v32):
                    return jnp.where(valid, base + v32,
                                     n_cells).reshape(-1)

                if direction == "out":
                    ids, v = ids_of(s32), vals.reshape(-1)
                elif direction == "in":
                    ids, v = ids_of(d32), vals.reshape(-1)
                else:
                    ids = jnp.concatenate([ids_of(s32), ids_of(d32)])
                    v = jnp.concatenate([vals.reshape(-1)] * 2)
                cells = seg_ops.segment_reduce(
                    v, ids, n_cells + 1, name)[:-1].reshape(wb, vbp)
                counts = jax.ops.segment_sum(
                    jnp.where(ids < n_cells, 1, 0), ids,
                    n_cells + 1)[:-1].reshape(wb, vbp)
                return tail(cells, counts) if tail else (cells, counts)

            self._fns[key] = fn = run
        return fn

    def _cell_ids(self, src, dst, win, valid, vbp, n_cells):
        """Flattened (window, vertex) cell id per contribution; ALL
        direction doubles the stream (one contribution per endpoint)."""
        if self.direction == "out":
            vtx = [src]
        elif self.direction == "in":
            vtx = [dst]
        else:
            vtx = [src, dst]
        ids, rep = [], len(vtx)
        for v in vtx:
            ids.append(np.where(valid, win * vbp + v, n_cells))
        return np.concatenate(ids), rep

    def process_stream(self, src: np.ndarray, dst: np.ndarray,
                       val: np.ndarray) -> List[Tuple[np.ndarray,
                                                      np.ndarray]]:
        # Original dtypes go to the native tier (int32 streams take
        # the copy-free i32 kernels); the int64 upconversion the other
        # tiers want happens ONLY on their branches — converting
        # eagerly cost the native path two full-stream copies (~30% of
        # its runtime at the bench shape) for arrays it never reads.
        src0, dst0 = np.asarray(src), np.asarray(dst)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        val = np.asarray(val)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        assert len(src0) == len(dst0) == len(val)
        n = len(src0)
        if n == 0:
            return []
        if self.slide is not None:
            # sliding: fold each edge into its pane once (the inner
            # engine at edge_bucket=slide, whatever tier it resolves
            # to), compose panes per emission on the host — one
            # (cells, counts) pair per slide-sized emission
            from ..utils import telemetry as _tm

            with _tm.span("reduce.sliding", monoid=self.name,
                          edges=n, slide=self.slide,
                          panes_per_window=self.panes_per_window):
                panes = self._pane_eng().process_stream(src0, dst0,
                                                        val)
                return self._compose_panes(panes)
        from ..utils import telemetry

        if self.name is not None:
            impl = _resolve_reduce_impl(self.name)
            if impl == "native":
                # the C++ kernel is signed-integer-typed (selection
                # rows are measured on ints; uint64 identities don't
                # fit its int64 slabs) — other dtypes re-resolve as if
                # the native tier didn't exist, so a float stream goes
                # wherever ITS committed rows point, not blindly to
                # the numpy tier
                if np.issubdtype(val.dtype, np.signedinteger):
                    # stopwatch, not span: a declined probe (None —
                    # lib unavailable) must record NOTHING, or the
                    # stream's edges would be double-counted against
                    # the fallback tier's span in trace_report
                    sw = telemetry.stopwatch("reduce.stream",
                                             tier="native",
                                             monoid=self.name,
                                             edges=n)
                    got = self._native_process_stream(src0, dst0, val)
                    if got is not None:
                        sw.stop()
                        return got
                impl = _resolve_reduce_impl(self.name,
                                            allow_native=False)
            if impl == "host":
                with telemetry.span("reduce.stream", tier="host",
                                    monoid=self.name, edges=n):
                    return self._host_process_stream(
                        src0.astype(np.int64, copy=False),
                        dst0.astype(np.int64, copy=False), val)
        # device rounds run through the shared ingress pipeline, whose
        # chunk/stage spans nest under this engine-level span
        with telemetry.span("reduce.stream", tier="device",
                            monoid=self.name or "fn", edges=n):
            return self._device_process_stream(
                src0.astype(np.int64, copy=False),
                dst0.astype(np.int64, copy=False), val)

    def _native_process_stream(self, src, dst, val):
        """The C++ fused tier: one pass produces both cells and counts
        (ingest.cpp gs_windowed_reduce), chunked only to bound the
        dense [num_w, vbp] scratch. Same (cells, counts) per window as
        the other tiers; cells cast back to the value dtype."""
        from .. import native as native_mod

        if not native_mod.windowed_reduce_available():
            return None
        eb, vbp = self.eb, self.vb + 1
        n = len(src)
        num_w = -(-n // eb)
        ident = int(_host_identity(self.name, val.dtype))
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        # chunk by a ~64MB dense-scratch budget (two [chunk_w, vbp]
        # int64 slabs): chunk size only amortizes ctypes call overhead,
        # so a big vertex bucket just takes more, smaller calls instead
        # of multi-GB allocations
        chunk_w = max(1, min(1024, (64 << 20) // (vbp * 16)))
        for at in range(0, num_w, chunk_w):
            lo, hi = at * eb, min((at + chunk_w) * eb, n)
            cells, counts = native_mod.windowed_reduce(
                src[lo:hi], dst[lo:hi], val[lo:hi], eb, vbp,
                self.name, self.direction, ident)
            cells = cells.astype(val.dtype, copy=False)
            out.extend((cells[w], counts[w])
                       for w in range(cells.shape[0]))
        return out

    def _device_process_stream(self, src, dst, val):
        """The device path, selection bypassed (the profiler measures
        both tiers through this split). Monoid chunks route through
        the shared three-stage ingress pipeline
        (ops/ingress_pipeline): cell-id/stack prep on the worker
        pool, h2d + dispatch in chunk order, each chunk's d2h one
        chunk behind — with the compact wire format (uint16 stacks +
        valid counts, widening fused on device) when the kernel's
        resolved ingress is compact. The associative-user-fn tier
        keeps its host argsort inline (its reduce runs through the
        host-sorted flagged scan, not the stack program)."""
        n = len(src)
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        eb, vbp = self.eb, self.vb + 1
        num_w = -(-n // eb)
        chunks = []
        at = 0
        while at < num_w:
            wb = min(self.MAX_STREAM_WINDOWS, num_w - at)
            wb = seg_ops.bucket_size(wb)   # O(log) programs over tails
            chunks.append((at, wb))
            at += wb
        def standard_chunk(at, wb):
            """Flat (cell ids, values) of windows [at, at+wb) in the
            standard wire format — shared by the associative-fn inline
            loop and the pipeline's prep stage."""
            lo, hi = at * eb, min((at + wb) * eb, n)
            s = seg_ops.pad_to(src[lo:hi], wb * eb)
            d = seg_ops.pad_to(dst[lo:hi], wb * eb)
            v = seg_ops.pad_to(val[lo:hi], wb * eb)
            valid = seg_ops.pad_to(np.ones(hi - lo, bool), wb * eb,
                                   fill=False)
            win = np.arange(wb * eb) // eb
            ids, rep = self._cell_ids(s, d, win, valid, vbp, wb * vbp)
            return ids, np.concatenate([v] * rep)

        if self.name is None:
            for at, wb in chunks:
                n_cells = wb * vbp
                ids, vals = standard_chunk(at, wb)
                order = np.argsort(ids, kind="stable")
                res, _has = seg_ops.segmented_reduce_associative(
                    self.fn, ids[order], vals[order], n_cells)
                cells = np.asarray(res).reshape(wb, vbp)  # gslint: disable=host-sync (sanctioned finalize boundary: the associative tier's one materialize per chunk)
                counts = np.bincount(
                    ids[ids < n_cells],
                    minlength=n_cells).reshape(wb, vbp)
                for w in range(min(wb, num_w - at)):
                    out.append((cells[w], counts[w]))
            return out

        from . import ingress_pipeline

        compact = self.ingress == "compact"
        if compact and n:
            from . import compact_ingress

            # the shared main-thread wrap-safety check: bad ids raise
            # the same ValueError every other tier raises (a pooled
            # prep failure would wrap it in PrepError/RuntimeError)
            compact_ingress.validate_ids(src, dst, vbp,
                                         "windowed reduce")

        def prep(item):
            at, wb = item
            lo, hi = at * eb, min((at + wb) * eb, n)
            if compact:
                from . import compact_ingress

                _w, s16, d16, nv = compact_ingress.window_stack(
                    src[lo:hi], dst[lo:hi], eb)
                s16 = seg_ops.pad_to(s16, wb)
                d16 = seg_ops.pad_to(d16, wb)
                nv = seg_ops.pad_to(nv, wb)
                v = seg_ops.pad_to(val[lo:hi],
                                   wb * eb).reshape(wb, eb)
                return at, wb, (s16, d16, nv, v)
            return (at, wb) + (standard_chunk(at, wb),)

        def h2d(payload):
            import jax.numpy as jnp

            at, wb, args = payload
            return at, wb, tuple(jnp.asarray(a) for a in args)

        delta = self.egress == "delta"

        def dispatch(dev_payload):
            at, wb, dev = dev_payload
            fn = (self._stack_fn_compact(wb, delta) if compact
                  else self._stack_fn(wb, delta))
            return (at, wb) + tuple(fn(*dev))

        def finalize(raw):
            if delta:
                # touched-cell wire (ops/delta_egress): d2h one
                # (cnt, idx, cells, counts) [wb, cap] quad instead of
                # two full [wb, vbp] stacks; untouched cells refill
                # with the device kernels' own empty-segment identity,
                # so rows are bit-identical to the full tier's
                at, wb, cnt, idx, cv, cn = raw
                cnt, idx, cv, cn = (np.asarray(x)  # gslint: disable=host-sync (sanctioned finalize boundary: the delta wire's ONE batched d2h per chunk)
                                    for x in (cnt, idx, cv, cn))
                fill = _device_cell_fill(self.name, cv.dtype)
                for w in range(min(wb, num_w - at)):
                    k = int(cnt[w])  # gslint: disable=host-sync (numpy-on-numpy: the materialize above already d2h'd the wire)
                    cells = np.full(vbp, fill, cv.dtype)
                    counts = np.zeros(vbp, cn.dtype)
                    cells[idx[w, :k]] = cv[w, :k]
                    counts[idx[w, :k]] = cn[w, :k]
                    out.append((cells, counts))
                return
            at, wb, cells, counts = raw
            cells, counts = np.asarray(cells), np.asarray(counts)  # gslint: disable=host-sync (sanctioned finalize boundary: the stack program's ONE batched d2h per chunk)
            for w in range(min(wb, num_w - at)):
                out.append((cells[w], counts[w]))

        ingress_pipeline.run_pipeline(chunks, prep, h2d, dispatch,
                                      finalize,
                                      timers=self.stage_timers)
        return out

    def cohort_step(self, rows: List[tuple]) -> List[Tuple[np.ndarray,
                                                           np.ndarray]]:
        """Multi-tenant cohort entry (core/tenancy.py): fold N
        tenants' next windows (each ≤ eb edges) in ONE device
        dispatch — the windowed-reduce leg of the cohort slab. The
        stack program already batches over a leading window axis and
        tumbling windows carry no cross-window state, so a tenant
        cohort is literally MORE WINDOWS IN THE STACK: row r of the
        [nb, vbp] result is tenant r's window, bit-identical to that
        tenant's own single-window device dispatch (the cell ids are
        built by the same standard_chunk recipe, in the same order,
        so even float accumulation folds identically).

        `rows` is a list of (src, dst, val) triples; returns one
        (values, counts) pair per row. Monoid kernels only (a user-fn
        reduce runs its host-sorted flagged scan per tenant); egress
        is the full [nb, vbp] stack — one cohort dispatch's d2h is
        already amortized N ways."""
        if not rows:
            return []
        if self.name is None:
            raise ValueError("cohort_step serves the monoid stack "
                             "kernels; user-fn reduces run per tenant")
        import jax.numpy as jnp

        eb, vbp = self.eb, self.vb + 1
        nb = seg_ops.bucket_size(len(rows))
        n_cells = nb * vbp
        n_rows = len(rows)
        s = np.zeros(nb * eb, np.int64)
        d = np.zeros(nb * eb, np.int64)
        # the shared value buffer takes the PROMOTED dtype across all
        # rows (mixed cohorts fold in np.result_type, never silently
        # truncating a wider row to the first row's dtype); rows that
        # share a dtype — the normal cohort — keep it exactly
        v = np.zeros(nb * eb, np.result_type(
            *(np.asarray(val).dtype for _s, _d, val in rows)))  # gslint: disable=host-sync (host-input dtype probe: cohort rows are numpy/lists, never device values)
        valid = np.zeros(nb * eb, bool)
        for row, (src, dst, val) in enumerate(rows):
            src = np.asarray(src, np.int64)  # gslint: disable=host-sync (host-input normalization: cohort rows are numpy/lists, never device values)
            dst = np.asarray(dst, np.int64)  # gslint: disable=host-sync (host-input normalization: cohort rows are numpy/lists, never device values)
            val = np.asarray(val)  # gslint: disable=host-sync (host-input normalization: cohort rows are numpy/lists, never device values)
            if not len(src) == len(dst) == len(val):
                raise ValueError("row %d: src/dst/val length mismatch"
                                 % row)
            if len(src) > eb:
                raise ValueError(
                    "row %d: %d edges exceed the %d-edge window bucket"
                    % (row, len(src), eb))
            lo = row * eb
            s[lo:lo + len(src)] = src
            d[lo:lo + len(dst)] = dst
            v[lo:lo + len(val)] = val
            valid[lo:lo + len(src)] = True
        win = np.arange(nb * eb) // eb
        ids, rep = self._cell_ids(s, d, win, valid, vbp, n_cells)
        vals = np.concatenate([v] * rep)
        fn = self._stack_fn(nb)
        cells, counts = fn(jnp.asarray(ids), jnp.asarray(vals))
        cells = np.asarray(cells)  # gslint: disable=host-sync (sanctioned finalize boundary: the cohort step's ONE batched d2h)
        counts = np.asarray(counts)  # gslint: disable=host-sync (sanctioned finalize boundary: the cohort step's ONE batched d2h)
        return [(cells[r], counts[r]) for r in range(n_rows)]

    # ---- host (numpy) tier -------------------------------------------

    def _host_process_stream(self, src, dst, val):
        """Vectorized host form of the monoid tiers, selection bypassed
        (the profiler measures both tiers through this split): one
        flattened (window, vertex)-cell bincount per chunk for 'sum'
        (falling back to exact ufunc.at when float64 accumulation
        could round an integer sum), ufunc.at for 'min'/'max'. Same
        cells/counts as the device tier."""
        out: List[Tuple[np.ndarray, np.ndarray]] = []
        eb, vbp = self.eb, self.vb + 1
        n = len(src)
        num_w = -(-n // eb)
        ident = _host_identity(self.name, val.dtype)
        # The bincount fast path accumulates in float64, then casts
        # back. For integer values that is used only when the worst-
        # case cell sum (max|val| × contributions per cell — direction
        # 'all' gives every cell up to 2·eb of them) is exact in
        # float64 AND fits val.dtype: a sum that would overflow the
        # dtype must take the ufunc.at path, whose numpy integer
        # arithmetic wraps modularly exactly like the device
        # segment_sum (out-of-range float→int casts are undefined).
        per_cell = eb * (2 if self.direction == "all" else 1)
        if np.issubdtype(val.dtype, np.integer):
            limit = min(1 << 53, int(np.iinfo(val.dtype).max))
            exact_bincount = (self.name == "sum" and n > 0
                              and int(np.abs(val).max()) * per_cell
                              <= limit)
        else:
            exact_bincount = self.name == "sum"
        if exact_bincount:
            # per-window bincounts: no flattened (window, vertex) cell
            # ids to materialize and no chunk-wide minlength slab —
            # ~3x the flattened form's rate on one core (the cell-id
            # multiply-add and the giant bincount were the cost, not
            # the per-window Python loop)
            for lo in range(0, n, eb):
                s, d, v = src[lo:lo + eb], dst[lo:lo + eb], \
                    val[lo:lo + eb]
                if self.direction == "out":
                    ids, vals = s, v
                elif self.direction == "in":
                    ids, vals = d, v
                else:
                    ids = np.concatenate([s, d])
                    vals = np.concatenate([v, v])
                counts = np.bincount(ids, minlength=vbp)
                if len(counts) > vbp:
                    # the flattened path's reshape raised for ids ≥
                    # vbp; this path must fail as loudly, not emit a
                    # ragged window
                    raise ValueError(
                        "vertex id %d outside [0, %d) in windowed "
                        "reduce input" % (int(ids.max()), vbp))
                cells = np.bincount(
                    ids, weights=vals, minlength=vbp).astype(val.dtype)
                out.append((cells, counts))
            return out
        for at in range(0, num_w, self.MAX_STREAM_WINDOWS):
            hi_w = min(at + self.MAX_STREAM_WINDOWS, num_w)
            lo, hi = at * eb, min(hi_w * eb, n)
            s, d, v = src[lo:hi], dst[lo:hi], val[lo:hi]
            win = np.arange(hi - lo) // eb
            if self.direction == "out":
                vtx = [s]
            elif self.direction == "in":
                vtx = [d]
            else:
                vtx = [s, d]
            ids = np.concatenate([win * vbp + x for x in vtx])
            vals = np.concatenate([v] * len(vtx))
            wb = hi_w - at
            n_cells = wb * vbp
            counts = np.bincount(ids, minlength=n_cells).reshape(
                wb, vbp)
            op = {"sum": np.add, "min": np.minimum,
                  "max": np.maximum}[self.name]
            flat = np.full(n_cells, ident, val.dtype)
            op.at(flat, ids, vals)
            cells = flat.reshape(wb, vbp)
            for w in range(wb):
                out.append((cells[w], counts[w]))
        return out


def numpy_reference(src, dst, val, eb: int, direction: str = "out",
                    name: str = "sum"
                    ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Faithful per-window host port of the reference's windowed
    neighborhood reduce (GraphWindowStream.java:101-121): a per-edge
    fold into a per-vertex slot — the comparison baseline the measured
    leg reports against, and the parity oracle for fuzz tests. Cells
    with count 0 hold the monoid identity (cross-check counts, not
    values, for absence)."""
    op = {"sum": np.add, "min": np.minimum, "max": np.maximum}[name]
    ident = _host_identity(name, np.asarray(val).dtype)  # gslint: disable=host-sync (host oracle: reference inputs are numpy, never device values)
    src = np.asarray(src, np.int64)  # gslint: disable=host-sync (host oracle: reference inputs are numpy, never device values)
    dst = np.asarray(dst, np.int64)  # gslint: disable=host-sync (host oracle: reference inputs are numpy, never device values)
    val = np.asarray(val)  # gslint: disable=host-sync (host oracle: reference inputs are numpy, never device values)
    nv = int(max(src.max(), dst.max())) + 1 if len(src) else 1  # gslint: disable=host-sync (host oracle: numpy-on-numpy bound, no device value in sight)
    out = []
    for lo in range(0, len(src), eb):
        s, d, v = src[lo:lo + eb], dst[lo:lo + eb], val[lo:lo + eb]
        if direction == "out":
            pairs = [(s, v)]
        elif direction == "in":
            pairs = [(d, v)]
        else:
            pairs = [(s, v), (d, v)]
        acc = np.full(nv, ident, val.dtype)
        cnt = np.zeros(nv, np.int64)
        for vtx, vv in pairs:
            op.at(acc, vtx, vv)
            np.add.at(cnt, vtx, 1)
        out.append((acc, cnt))
    return out


def sliding_numpy_reference(src, dst, val, eb: int, slide: int,
                            direction: str = "out", name: str = "sum"
                            ) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Independent sliding oracle: one emission per completed (or
    final ragged) pane, each a FULL per-edge refold of its window
    slice [max(0, (i+1)·slide − eb), (i+1)·slide) through
    numpy_reference — no pane machinery shared with the engine under
    test. Arrays are sized by the slice's max vertex id (compare
    cells under the counts mask, like numpy_reference)."""
    src = np.asarray(src)  # gslint: disable=host-sync (pure-host oracle: numpy on numpy)
    dst = np.asarray(dst)  # gslint: disable=host-sync (pure-host oracle: numpy on numpy)
    val = np.asarray(val)  # gslint: disable=host-sync (pure-host oracle: numpy on numpy)
    n = len(src)
    out: List[Tuple[np.ndarray, np.ndarray]] = []
    for i in range(-(-n // slide)):
        lo = max(0, (i + 1) * slide - eb)
        hi = min((i + 1) * slide, n)
        out.extend(numpy_reference(src[lo:hi], dst[lo:hi],
                                   val[lo:hi], eb, direction, name))
    return out

"""Delta-compacted d2h egress: ship per-window CHANGED slots, not
whole snapshot vectors.

The batched snapshot scan (core/driver._build_snapshot_scan) d2h's a
full [W, vb] int32 stack per analytic per chunk, and the windowed
reduce's monoid device tier a full [W, vb+1] cells+counts pair — even
though the delta masks the scan already computes (emit_deltas) know
how few entries actually changed, and a reduce window touches at most
one cell per contribution. Through a tunneled chip the stream is
transfer-bound (PERF.md "VERIFIED chip rows"), so egress bytes sit on
the critical path exactly like ingress bytes; this module is the
egress twin of ops/compact_ingress.

Wire format, per window: an int32 changed count, an int32 index row
[cap], and a value row [cap] (dtype per analytic), produced ON DEVICE
by `compact_changed` (jnp.nonzero with a static size — the compaction
fuses into the same scan program). The host reconstructs full
read-only snapshots by applying each window's (idx, vals) pairs to its
carried mirrors — bit-identical to the full-vector extraction, because
a changed-mask applied to the previous snapshot IS the next snapshot.

`cap` bounds the per-window changed set. Degrees can change at most
2·eb slots per window (two endpoints per edge), so cap = min(2·eb, vb)
is exact for them; CC/cover labels can cascade past any cap < vb
(a big component relabeling), so a window whose count EXCEEDS the cap
marks its chunk for the host-fold fallback (ops/host_snapshot — the
bit-exact twin the demotion ladder already trusts), keeping results
exact at every cap. GS_EGRESS_CAP shrinks the cap below the exact
bound when the A/B shows a tighter wire wins net of rare refolds.

Adoption is evidence-gated like every other selection
(ops/triangles.resolve_ingress symmetry): full-vector is the default
and the fallback everywhere; `resolve_egress` returns "delta" only
when committed backend-matched `egress_ab` rows (tools/egress_ab.py)
all show exact parity and a ≥5% end-to-end win, or when GS_EGRESS
pins it. The sharded engines keep full-vector egress (their snapshots
ride replicated outputs, and the mesh path has no AOT warm cache).
"""

from __future__ import annotations

import numpy as np

from ..utils import knobs
from ..utils import telemetry

_EGRESS = None   # "full" | "delta", resolved once per process


def _reset_egress() -> None:
    """Test hook: forget the memoized egress selection."""
    global _EGRESS
    _EGRESS = None


def resolve_egress() -> str:
    """The d2h egress format of the batched snapshot/reduce paths:
    GS_EGRESS pins ("full"/"delta"); unset/"auto" = "delta" only on
    committed backend-matched `egress_ab` rows all showing parity and
    a ≥5% win (the repo-wide measured-adoption policy,
    ops/triangles.rows_clear_bar). Memoized per process."""
    global _EGRESS
    pin = knobs.get_str("GS_EGRESS")
    if pin in ("full", "delta"):
        return pin
    if _EGRESS is None:
        impl = "full"
        try:
            from . import triangles as tri_ops

            perf = tri_ops._load_matching_perf()
            if tri_ops.rows_clear_bar((perf or {}).get("egress_ab", []),
                                      "speedup", lambda r: 1.0):
                impl = "delta"
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="egress", fallback=impl,
                            error="%s: %s" % (type(e).__name__, e))
        _EGRESS = impl
    return _EGRESS


def egress_cap(eb: int, vb: int) -> int:
    """Per-window changed-slot capacity of the delta wire:
    min(2·eb, vb) — exact for degrees, a fallback-guarded bound for
    label cascades — unless GS_EGRESS_CAP narrows it (never below 1,
    never above vb)."""
    cap = min(2 * eb, vb)
    pinned = knobs.get_int("GS_EGRESS_CAP")
    if pinned is not None:
        cap = min(pinned, vb)
    return cap


def compact_changed(mask, new_vals, cap: int, pad_idx: int):
    """The ONE device-side encode of the delta wire (jax-traceable):
    (changed count, changed indices [cap] ascending, new values
    [cap]). `count` may EXCEED cap — the host detects truncation from
    it and refolds the chunk; padded index slots carry `pad_idx`
    (callers pass a row that exists, e.g. 0 — slots past `count` are
    never read)."""
    import jax.numpy as jnp

    idx = jnp.nonzero(mask, size=cap, fill_value=pad_idx)[0]
    idx = idx.astype(jnp.int32)
    return (jnp.sum(mask, dtype=jnp.int32), idx, new_vals[idx])


def compact_touched(cells, counts, cap: int):
    """Per-row device encode for PER-WINDOW (non-carried) reduce
    rows: (touched count, touched cell ids [cap] ascending, their
    cell values [cap], their edge counts [cap]). A window touches at
    most one cell per contribution, so `cap` = contributions-per-
    window is an EXACT bound — this wire never overflows. vmap it
    over a [wb, vbp] stack."""
    import jax.numpy as jnp

    m = counts > 0
    idx = jnp.nonzero(m, size=cap, fill_value=0)[0].astype(jnp.int32)
    return (jnp.sum(m, dtype=jnp.int32), idx, cells[idx], counts[idx])


def apply_delta(mirror: np.ndarray, cnt: int, idx: np.ndarray,
                vals: np.ndarray) -> None:
    """Host-side decode: scatter one window's (idx, vals) pairs into
    the carried mirror IN PLACE. The mirror then IS that window's
    snapshot over [:len(mirror)]."""
    k = int(cnt)
    mirror[idx[:k]] = vals[:k]


def scatter_full(vbp: int, cnt: int, idx: np.ndarray,
                 vals: np.ndarray, fill, dtype) -> np.ndarray:
    """Reconstruct one PER-WINDOW (non-carried) full row from its
    delta: `fill`-initialized, changed cells scattered — the windowed
    reduce's decode (its cells reset every window, so there is no
    mirror to carry)."""
    row = np.full(vbp, fill, dtype)
    k = int(cnt)
    row[idx[:k]] = vals[:k]
    return row

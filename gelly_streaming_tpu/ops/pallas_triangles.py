"""Pallas TPU kernel for the dense window triangle count.

The dense path computes 6·T = Σᵢⱼ (A·A)ᵢⱼ ⊙ Aᵢⱼ for the window's V×V
adjacency matrix (ops/triangles.py `triangle_count_dense`, lowering
WindowTriangles.java:61-66). XLA's version materializes the V×V
two-path count matrix `A@A` in HBM before the elementwise mask and
reduce. This kernel fuses the whole contraction: each (i,j) output
tile accumulates its A[i,k]@A[k,j] partials in VMEM scratch across the
k grid dimension and reduces `partial ⊙ A[i,j]` to a single scalar on
the last k step — the only HBM traffic is reading A (three tiled
views) and writing one f32 per tile.

Per-tile counts are ≤ TILE²·V < 2³¹ and every entry of A@A is ≤ V, so
f32 accumulation (exact to 2²⁴) is exact for V ≤ 4096 — twice the
XLA dense limit, at one third the HBM footprint.

On non-TPU backends the kernel runs in interpreter mode (tests use the
virtual CPU mesh), keeping behavior identical everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 128  # MXU-aligned


def _need_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _tri_kernel(a_ik, a_kj, a_ij, out, acc):
    """Grid (i, j, k), k innermost. acc: VMEM (TILE, TILE) scratch.

    The output is the per-column sum of the masked tile (TILE values per
    (i,j) tile), not a single scalar: each column sum is ≤ TILE·V ≤ 2¹⁹
    so it stays exact in f32; the global reduction finishes in int64 on
    the host."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)

    acc[:] += jnp.dot(a_ik[:], a_kj[:], preferred_element_type=jnp.float32)

    @pl.when(k == pl.num_programs(2) - 1)
    def _():
        out[0, :] = jnp.sum(acc[:] * a_ij[:], axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _six_t_partials(a: jax.Array, interpret: bool) -> jax.Array:
    v = a.shape[0]
    g = v // TILE
    return pl.pallas_call(
        _tri_kernel,
        grid=(g, g, g),
        in_specs=[
            pl.BlockSpec((TILE, TILE), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, TILE), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, TILE), lambda i, j, k: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, TILE), lambda i, j, k: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((g, g * TILE), jnp.float32),
        scratch_shapes=[pltpu.VMEM((TILE, TILE), jnp.float32)],
        interpret=interpret,
    )(a, a, a)


@functools.partial(jax.jit, static_argnames=("num_vertices", "interpret"))
def _adjacency_six_t(src: jax.Array, dst: jax.Array, num_vertices: int,
                     interpret: bool) -> jax.Array:
    """Build the simple undirected adjacency (dupes/self-loops dropped by
    the set-to-one scatter) padded to a TILE multiple, then contract."""
    v = num_vertices
    vp = ((v + TILE - 1) // TILE) * TILE
    a = jnp.zeros((vp, vp), jnp.float32)
    # scatter rows at [0, v); padding slots (id == v from the host-side
    # pad fill) are clipped onto row v..vp-1 only when vp > v, otherwise
    # dropped via the drop mode of scatter
    a = a.at[src, dst].set(1.0, mode="drop")
    a = a.at[dst, src].set(1.0, mode="drop")
    diag = jnp.arange(vp)
    a = a.at[diag, diag].set(0.0)
    # zero any rows/cols past v (padding sentinel may have landed there)
    live = (jnp.arange(vp) < v).astype(jnp.float32)
    a = a * live[:, None] * live[None, :]
    return _six_t_partials(a, interpret)


def triangle_count_dense_pallas(src, dst, num_vertices: int) -> int:
    """Drop-in for ops/triangles.triangle_count_dense. src/dst may carry
    padding pointing at index >= num_vertices (masked out here)."""
    import numpy as np

    from . import segment as seg_ops

    vb = seg_ops.bucket_size(num_vertices)
    eb = seg_ops.bucket_size(len(src))
    s = seg_ops.pad_to(np.asarray(src, np.int32), eb, fill=vb)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
    d = seg_ops.pad_to(np.asarray(dst, np.int32), eb, fill=vb)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
    partials = _adjacency_six_t(jnp.asarray(s), jnp.asarray(d), vb,
                                _need_interpret())
    return int(np.asarray(partials).astype(np.int64).sum()) // 6  # gslint: disable=host-sync (sanctioned result boundary: the dense count's ONE d2h)

"""Exact per-window triangle counting kernels.

Device lowering of the reference's WindowTriangles pipeline
(example/WindowTriangles.java:61-66: slice(ALL) → per-vertex candidate
generation (O(d²), :83-116) → keyBy(pair) window count (:119-140) →
global sum). The reference counts each triangle once via its minimum
vertex: a candidate pair (b,c) emitted from vertex a (with b,c > a)
scores iff a real edge b~c is present.

TPU-native replacements (same count, no per-record shuffles):

- `triangle_count_dense` — adjacency matmul on the MXU:
  count = Σ (A@A) ⊙ A / 6 for a simple undirected graph. The window's
  interned vertex set is usually small; a V×V bfloat16/f32 matmul is
  one systolic-array pass. Used when V ≤ `DENSE_LIMIT`.

- `triangle_count_sparse` — edge-iterator adjacency intersection:
  edges are deduplicated and oriented low→high by (degree, id) so
  per-source out-degree is O(√E); for each oriented edge (a,b) the
  deduplicated out-neighbor rows of a and b are intersected with a
  chunked broadcast equality compare (see `intersect_local`). Each
  triangle is counted exactly once, at its min-rank edge.

Both consume a COO batch of dense vertex ids (pre-interned).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from . import ingress_pipeline
from . import segment as seg_ops
from ..utils import costmodel
from ..utils import metrics
from ..utils import telemetry

DENSE_LIMIT = 2048


# ----------------------------------------------------------------------
# dense (MXU) path
# ----------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_vertices",))
def _dense_row_counts(src: jax.Array, dst: jax.Array,
                      num_vertices: int) -> jax.Array:
    """src/dst: directed COO with padding at index num_vertices (dropped).

    Returns per-row Σ_j (A²⊙A)[i,j] — each ≤ V² < 2²⁴, so exact in f32;
    the global sum is finished in int64 on the host to stay exact for
    windows where 6·T would overflow f32/int32.
    """
    v = num_vertices
    a = jnp.zeros((v + 1, v + 1), jnp.float32)
    # symmetrize + drop duplicates/self-loops via set-to-one scatter
    a = a.at[src, dst].set(1.0).at[dst, src].set(1.0)
    a = a.at[jnp.arange(v + 1), jnp.arange(v + 1)].set(0.0)
    a = a[:v, :v]
    paths2 = a @ a  # MXU: paths of length 2
    return jnp.sum(paths2 * a, axis=1)


def triangle_count_dense(src: np.ndarray, dst: np.ndarray,
                         num_vertices: int) -> int:
    vb = seg_ops.bucket_size(num_vertices)
    eb = seg_ops.bucket_size(len(src))
    s = seg_ops.pad_to(np.asarray(src, np.int32), eb, fill=vb)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
    d = seg_ops.pad_to(np.asarray(dst, np.int32), eb, fill=vb)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
    rows = np.asarray(_dense_row_counts(jnp.asarray(s), jnp.asarray(d), vb))  # gslint: disable=host-sync (sanctioned result boundary: the dense count's ONE d2h)
    return int(rows.astype(np.int64).sum() // 6)


# ----------------------------------------------------------------------
# sparse (wedge + binary search) path
# ----------------------------------------------------------------------

def intersect_local(nbr: jax.Array, ea: jax.Array, eb: jax.Array,
                    emask: jax.Array) -> jax.Array:
    """For each oriented edge (a,b), |N_out(a) ∩ N_out(b)| summed over
    the given (possibly per-shard) edge slice.

    nbr:   [V+1, K] per-vertex deduplicated out-neighbor rows, fill = V
           (never a real vertex; row V is the pad row).
    ea/eb: [Ep] oriented edge endpoints (padding → V, masked by emask).

    A triangle {x,y,z} ordered by rank contributes exactly one common
    out-neighbor (z) at exactly one edge (x,y). Shared by the
    single-chip kernel and the sharded engine (which psums the slices).

    Lowering note: per-row binary search (vmap(searchsorted) or
    take_along_axis gathers) is ~40-60x slower on TPU than a chunked
    broadcast equality compare — axis-1 gathers with per-element
    indices defeat the VPU's lane tiling, while the K×K compare is pure
    vectorized elementwise work. Measured at K=256: 438ms → 6.8ms per
    16K-edge batch. The compare is O(Ep·K²) elementwise vs the
    search's O(Ep·K·log K) gathers, but each gathered element costs
    ~2 orders of magnitude more than a compare, so the crossover sits
    beyond any K the streaming kernel produces (k_bucket = 2√edge_bucket
    ≤ 2048 even for 2²⁰-edge windows). Rows are deduplicated, so each
    rows_a entry matches at most one rows_b entry and `any` over the
    compare axis counts it exactly once.
    """
    sentinel = nbr.shape[0] - 1
    return intersect_rows(nbr[ea], nbr[eb], emask, sentinel)


def intersect_rows(rows_a: jax.Array, rows_b: jax.Array,
                   emask: jax.Array, sentinel: int) -> jax.Array:
    """The chunked broadcast equality compare on pre-gathered row
    pairs: rows_a/rows_b are [Ep, K] neighbor rows aligned per edge
    (fill = sentinel). Factored out of intersect_local so the
    owner-local sharded path (which materializes each edge's row pair
    via collectives instead of a replicated table lookup) shares the
    comparator."""
    valid = (rows_a < sentinel) & emask[:, None]
    k = rows_a.shape[1]
    if k == 0:
        return jnp.int32(0)
    chunk = min(k, 128)                          # bound the [Ep,chunk,K] tile

    # static unrolled chunk loop (≤ ⌈k/128⌉ steps): keeps the compare
    # tile bounded and stays shard_map-compatible (no loop-carry vma
    # types); slicing clamps, so a ragged final chunk is handled
    total = jnp.int32(0)
    for c in range(-(-k // chunk)):
        ra = rows_a[:, c * chunk:(c + 1) * chunk]
        va = valid[:, c * chunk:(c + 1) * chunk]
        hit = jnp.any(ra[:, :, None] == rows_b[:, None, :], axis=2)
        total = total + jnp.sum(hit & va, dtype=jnp.int32)
    return total


def intersect_local_bsearch(nbr: jax.Array, ea: jax.Array,
                            eb: jax.Array, emask: jax.Array) -> jax.Array:
    """Same contract as intersect_local, lowered as a per-row binary
    search (vmap(searchsorted) + take_along_axis probe). On TPU this
    loses ~40-60x to the broadcast compare (see intersect_local's
    lowering note), but on CPU the ordering INVERTS — the O(Ep·K·log K)
    search beats the O(Ep·K²) compare ~5x (187ms vs 916ms at Ep=16K,
    K=256, PERF.md `intersect`) — so resolve_intersect_impl selects it
    for CPU backends (tests, the bench's labeled CPU fallback).
    Rows are sorted with the sentinel (= V, larger than any real id)
    as fill, so searchsorted's first->= probe finds the unique match
    when present; sentinel entries of rows_a are masked by `valid`."""
    sentinel = nbr.shape[0] - 1
    rows_a = nbr[ea]                             # [Ep, K]
    rows_b = nbr[eb]                             # [Ep, K]
    if rows_a.shape[1] == 0:
        return jnp.int32(0)
    pos = jax.vmap(jnp.searchsorted)(rows_b, rows_a)
    hit = jnp.take_along_axis(
        rows_b, jnp.clip(pos, 0, nbr.shape[1] - 1), axis=1) == rows_a
    valid = (rows_a < sentinel) & emask[:, None]
    return jnp.sum(hit & valid, dtype=jnp.int32)


_INTERSECT_CHOICE = None   # resolved once per process
_INTERSECT_JIT = None      # jitted form of the choice, built once


def _load_matching_perf(required_backend: str = None):
    """Parsed PERF.json iff its measurements were recorded on THIS
    process's backend (pass required_backend to further restrict, e.g.
    'tpu' for chip-only selections); None otherwise. Shared scaffolding
    of the measurement-driven kernel selections — a selection must
    never be driven by another backend's numbers."""
    import json

    try:
        import jax as _jax

        backend = _jax.default_backend()
        if required_backend is not None and backend != required_backend:
            return None
        perf = None
        # PERF.json carries the most recent profile run; when that run
        # was on ANOTHER backend (e.g. the file is chip-labeled and
        # this is the CPU fallback), the per-backend archive
        # PERF_<backend>.json keeps this backend's committed rows alive
        # — selections must survive the other backend being profiled.
        for path in (_PERF_PATH,
                     _PERF_PATH[:-5] + "_%s.json" % backend):
            try:
                with open(path) as f:
                    cand = json.load(f)
            except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
                continue
            if cand.get("backend") == backend:
                perf = cand
                break
        if perf is None:
            return None
        # drop failed-section stubs ({"error": ...}) and *_error
        # markers the profiler may record: consumers see only real
        # measurement rows
        return {k: v for k, v in perf.items()
                if not (isinstance(v, dict) and "error" in v)}
    except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
        return None


def rows_clear_bar(rows, num_key, den, parity_key="parity",
                   margin=1.05) -> bool:
    """The shared evidence gate of the measurement-driven selections:
    True iff `rows` is a non-empty list whose EVERY row has
    `parity_key` exactly True and `num_key` ≥ margin × denominator
    (`den`: a row key name, or a callable(row) → float for composite
    baselines). One place owns the rule so the tier selections can't
    drift apart on threshold or parity semantics."""
    if not (isinstance(rows, list) and rows):
        return False
    for r in rows:
        if r.get(parity_key) is not True:
            return False
        num = r.get(num_key)
        base = den(r) if callable(den) else r.get(den)
        # a malformed row (missing/zero rate on either side) must fail
        # the gate, not pass it vacuously (0 >= margin*0)
        if not num or not base or num <= 0 or base <= 0:
            return False
        if num < margin * base:
            return False
    return True


def _load_tpu_perf():
    """Chip-only view: PERF.json iff both this process and the file are
    'tpu' (drives the Pallas/dense selections, which only exist on
    chip)."""
    return _load_matching_perf("tpu")


def resolve_intersect_impl():
    """The intersection kernel actually built into the window-counter
    programs: the backend's measured XLA winner by default (chunked
    broadcast compare on chip, binary search on CPU —
    resolve_xla_intersect), upgraded to the Pallas fused-tile variant
    (ops/pallas_intersect.py) only when committed TPU measurements
    (PERF.json `intersect` section) show it at parity and ≥5% faster —
    same selection policy as the dense path."""
    global _INTERSECT_CHOICE
    if _INTERSECT_CHOICE is not None:
        return _INTERSECT_CHOICE
    impl = resolve_xla_intersect()   # compare on chip, bsearch on CPU
    perf = _load_tpu_perf()
    if perf is not None:
        row = perf.get("intersect", {})
        if (row.get("parity_pallas") is True
                and (row.get("pallas_vs_xla_compare") or 0) >= 1.05):
            from .pallas_intersect import intersect_local_pallas

            impl = intersect_local_pallas
    _INTERSECT_CHOICE = impl
    return impl


def resolve_xla_intersect():
    """Intersect choice restricted to plain-XLA lowerings — what
    shard_map bodies must use (pl.pallas_call inside shard_map is
    excluded there, parallel/sharded.py): the broadcast compare on
    chip, the binary search on CPU (same measured inversion as
    resolve_intersect_impl, PERF.md `intersect`)."""
    try:
        import jax as _jax

        if _jax.default_backend() == "cpu":
            return intersect_local_bsearch
    except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
        pass
    return intersect_local


def _intersect_jit():
    """Once-per-process jitted wrapper of the resolved intersect
    kernel (the standalone form triangle_count_sparse dispatches)."""
    global _INTERSECT_JIT
    if _INTERSECT_JIT is None:
        _INTERSECT_JIT = jax.jit(resolve_intersect_impl())
    return _INTERSECT_JIT


def triangle_count_sparse(src: np.ndarray, dst: np.ndarray,
                          num_vertices: int) -> int:
    src = np.asarray(src, np.int64)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
    dst = np.asarray(dst, np.int64)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
    keep = src != dst
    src, dst = src[keep], dst[keep]
    if len(src) == 0:
        return 0
    # undirect + dedupe
    lo = np.minimum(src, dst)
    hi = np.maximum(src, dst)
    und = np.unique(lo * num_vertices + hi)
    lo, hi = und // num_vertices, und % num_vertices
    # orient low-rank → high-rank by (degree, id): bounds out-degree to
    # O(√E) on skewed graphs, the classic edge-iterator trick
    deg = np.bincount(np.concatenate([lo, hi]), minlength=num_vertices)
    rank = np.argsort(np.argsort(deg.astype(np.int64) * num_vertices
                                 + np.arange(num_vertices)))
    a = np.where(rank[lo] < rank[hi], lo, hi).astype(np.int32)
    b = np.where(rank[lo] < rank[hi], hi, lo).astype(np.int32)
    e = len(a)
    order = np.argsort(a.astype(np.int64) * num_vertices + b, kind="stable")
    a, b = a[order], b[order]
    counts = np.bincount(a, minlength=num_vertices)
    starts = np.zeros(num_vertices + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    max_out = seg_ops.bucket_size(int(counts.max()))  # gslint: disable=host-sync (numpy-on-numpy: np.bincount result, no device value)
    # bucket the vertex dimension too, or every distinct per-window
    # vertex count triggers a fresh XLA compile; rows past num_vertices
    # (including sentinel row vb) stay all-sentinel
    vb = seg_ops.bucket_size(num_vertices)
    nbr = np.full((vb + 1, max_out), vb, np.int32)
    nbr[a, np.arange(e) - starts[a]] = b  # ascending within each row
    ep = seg_ops.bucket_size(e)
    count = _intersect_jit()(
        jnp.asarray(nbr),
        jnp.asarray(seg_ops.pad_to(a, ep, fill=vb)),
        jnp.asarray(seg_ops.pad_to(b, ep, fill=vb)),
        jnp.asarray(seg_ops.pad_to(np.ones(e, bool), ep, fill=False)),
    )
    return int(count)


# ----------------------------------------------------------------------
# shared pipeline stages (single-chip kernel + sharded engine)
# ----------------------------------------------------------------------

def orient_by_degree(s: jax.Array, d: jax.Array, deg: jax.Array,
                     sent: int):
    """Orient each edge low(deg, id) → high(deg, id); sentinel maps to
    itself. One source of truth for the tie-break used by both the
    single-chip and the sharded kernel."""
    lo = jnp.minimum(s, d)
    hi = jnp.maximum(s, d)
    swap = (deg[lo] > deg[hi]) | ((deg[lo] == deg[hi]) & (lo > hi))
    return jnp.where(swap, hi, lo), jnp.where(swap, lo, hi)


def dedupe_and_positions(a: jax.Array, b: jax.Array, sent: int, vb: int):
    """Fused dedupe + CSR positions in ONE sort: lexicographic sort of
    (a, b), first-occurrence marking, and each valid edge's column
    rank among the VALID edges of its source via a prefix count —
    duplicates stay in place behind the `evalid` mask instead of being
    re-sorted to the tail. Replaces the round-3 sort→mark→re-sort→
    segment_min sequence in both window counters, deleting the second
    O(E log E) sort from the hot path (the round-3 device trace put the two sorts
    at ~35% of an eb=32768 CPU dispatch; the chip pays them too).

    Returns (a_sorted, b_sorted, evalid, pos); pos is garbage where
    ~evalid — callers mask. Within each source run the valid b's are
    ascending and their positions are 0..deg-1 consecutively, so the
    scattered neighbor rows keep the sorted-row contract the binary-
    search intersect requires."""
    a, b = jax.lax.sort((a, b), num_keys=2)
    n = a.shape[0]
    idx = jnp.arange(n)
    # first-occurrence mark without a materialized [1]-array head:
    # position 0 is unconditionally first, the roll wraparound it
    # masks is irrelevant. (A literal jnp.array([True]) head becomes
    # a captured array constant, which a Pallas kernel body — the
    # fused window megakernel inlines this helper — may not close
    # over; identical booleans either way.)
    first = (idx == 0) | (a != jnp.roll(a, 1)) | (b != jnp.roll(b, 1))
    evalid = first & (a < sent)
    seg_first = jax.ops.segment_min(
        jnp.where(a < sent, idx, n), a, vb + 1)
    ev = evalid.astype(jnp.int32)
    before = jnp.cumsum(ev) - ev     # valid edges strictly before i
    pos = before - before[jnp.clip(seg_first[a], 0, n - 1)]
    return a, b, evalid, pos


def build_window_counter(vb: int, kb: int, pallas_ok: bool = True):
    """Pure (unjitted) one-window exact-count body over fixed buckets:
    run(src[E], dst[E], valid[E]) -> (count, overflow); the edge bucket
    is whatever shape the caller traces with. Shared by
    TriangleWindowKernel (jitted / lax.map-wrapped) and the fused
    analytics scan (ops/scan_analytics.py), which inlines it in a scan
    body.

    When the fused window megakernel is selected
    (ops/pallas_window.resolve_pallas_window) and its probe succeeds,
    the returned body routes through the triangle-only Pallas kernel
    — slab staged into VMEM once, K-bucket intersection via the
    intersect seed's inner loop — falling back to this XLA body
    in-trace for shapes past the chip's VMEM budget. Same counts,
    same K-overflow handoff, by construction."""
    sent = vb  # sentinel vertex id: sorts last, row vb is the pad row
    intersect = resolve_intersect_impl()  # measured choice, build time

    def run(src, dst, valid):
        # ---- clean: drop self-loops and padding
        valid = valid & (src != dst)
        src = jnp.where(valid, src, sent)
        dst = jnp.where(valid, dst, sent)

        # ---- degrees over the undirected multigraph (for orientation)
        ones = jnp.where(valid, 1, 0)
        deg = jax.ops.segment_sum(ones, src, vb + 1)
        deg = deg + jax.ops.segment_sum(ones, dst, vb + 1)

        # ---- orient low(deg, id) -> high(deg, id)
        a, b = orient_by_degree(src, dst, deg, sent)

        # ---- fused sort/dedupe + CSR column positions (one sort;
        # duplicates stay masked in place)
        a, b, evalid, pos = dedupe_and_positions(a, b, sent, vb)
        overflow = jnp.sum((pos >= kb) & evalid)
        ok = evalid & (pos < kb)
        rows = jnp.where(ok, a, vb)
        cols = jnp.clip(pos, 0, kb - 1)
        nbr = jnp.full((vb + 1, kb), sent, jnp.int32)
        nbr = nbr.at[rows, cols].set(
            jnp.where(ok, b, sent).astype(jnp.int32))

        # ---- neighbor-row intersection at each oriented edge
        # (duplicate slots carry real ids now; evalid masks them out)
        # (an optimization_barrier before the intersect wins ~20% on a
        # single-window CPU microbenchmark at K=32 but measures FLAT
        # through the lax.map streaming form the bench actually runs —
        # tried and reverted in round 3; re-evaluate on chip)
        count = intersect(nbr, a.astype(jnp.int32),
                          b.astype(jnp.int32), evalid)
        return count, overflow

    if pallas_ok:
        from . import pallas_window

        sel = pallas_window.maybe_counter(vb, kb, run)
        if sel is not None:
            return sel
    return run


# ----------------------------------------------------------------------
# streaming fixed-shape engine: the whole window pipeline on device
# ----------------------------------------------------------------------

_STREAM_IMPL = None    # cpu-backend tier, resolved once per process
_STREAM_IMPL_EB = {}   # chip per-bucket tier (eb -> impl)  # gslint: disable=thread-shared (idempotent memo: same key always computes the same value; a racing double-compute is last-write-wins)


def _pick_host_tier(rows) -> str:
    """Shared tier scoring over committed `host_stream` rows: "host"
    when the numpy kernel clears the device path at parity on every
    row, upgraded to "native" when the C++ tier also clears both and
    the library loads. "device" otherwise."""
    impl = "device"
    if rows_clear_bar(rows, "host_edges_per_s",
                      "device_edges_per_s"):
        impl = "host"
    if rows_clear_bar(rows, "native_edges_per_s",
                      lambda r: max(
                          r.get("device_edges_per_s") or 0,
                          r.get("host_edges_per_s") or 0),
                      parity_key="native_parity"):
        from .. import native as _native

        if _native.triangles_available():
            impl = "native"
    return impl


def _native_count_stream_parallel(src: np.ndarray, dst: np.ndarray,
                                  eb: int):
    """The native (C++) stream tier across the ingress prep pool:
    windows are independent, so the stream splits into window-ALIGNED
    slices, one gs_triangle_count_stream call per slice, run
    concurrently (the ctypes call drops the GIL for the C++ pass).
    Slice results concatenate in order — counts are identical to the
    single-call form at every pool size. None when the library (or
    symbol) is unavailable, same as the underlying binding."""
    from .. import native as native_mod

    if not native_mod.triangles_available():
        return None
    if not ingress_pipeline.pipeline_enabled():
        # the TRUE sync form is the single whole-stream C++ call (no
        # slice copies, one ctypes crossing) — forced_sync /
        # GS_STREAM_PREFETCH=0 must measure exactly the pre-pipeline
        # shape, or the bench A/B inflates pipeline_speedup
        counts = native_mod.triangle_count_stream(src, dst, eb)
        return None if counts is None else [int(x) for x in counts]
    num_w = -(-len(src) // eb)
    # ~4 slices per worker amortizes call overhead while keeping the
    # pool busy through windows of uneven triangle cost
    groups = max(1, min(num_w,
                        4 * max(1, ingress_pipeline.worker_count())))
    per = -(-num_w // groups)

    def one(at):
        return native_mod.triangle_count_stream(
            src[at * eb:(at + per) * eb], dst[at * eb:(at + per) * eb],
            eb)

    parts = ingress_pipeline.map_ordered(one, range(0, num_w, per))
    if any(p is None for p in parts):
        return None
    return [int(x) for p in parts for x in p]


def _resolve_stream_impl(eb: int = None) -> str:
    """Streaming-counter tier: the device (XLA) kernel by default; a
    HOST tier only on committed backend-matched measurements
    (PERF.json `host_stream` section, tools/profile_kernels.py)
    showing that form at parity and ≥5% faster. Two host tiers
    compete under the same rule: "native" (the C++ compact-forward
    counter, native/ingest.cpp — needs `native_parity`/
    `native_edges_per_s` rows AND a loadable library) beats "host"
    (the vectorized numpy kernel, ops/host_triangles.py) when its
    committed rows also clear the numpy tier by ≥5%.

    Backend scope differs deliberately:
      - CPU backend: ONE process-wide tier from ALL committed cpu
        rows (the fallback floor; unchanged since r3).
      - TPU backend: per-EDGE-BUCKET routing from that bucket's own
        chip-labeled rows (VERDICT r4 item 5 — the tunneled chip
        loses outright at 8192-edge windows, 0.44× the numpy port,
        because per-dispatch latency dominates small windows; a
        measured sub-crossover bucket routes to the faster host tier
        while other buckets keep the device path). `eb=None` on chip
        always means "device" (no evidence consulted).
    Same measured-default policy as the dense/Pallas/intersect
    selections."""
    global _STREAM_IMPL
    try:
        import jax as _jax

        backend = _jax.default_backend()
    except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
        return "device"
    if backend == "cpu":
        if _STREAM_IMPL is not None:
            return _STREAM_IMPL
        impl = "device"
        try:
            perf = _load_matching_perf("cpu")
            impl = _pick_host_tier((perf or {}).get("host_stream", []))
        except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
            pass
        _STREAM_IMPL = impl
        return impl
    if eb is None:
        return "device"
    if eb in _STREAM_IMPL_EB:
        return _STREAM_IMPL_EB[eb]
    impl = "device"
    try:
        perf = _load_matching_perf()
        rows = [r for r in (perf or {}).get("host_stream", [])
                if r.get("edge_bucket") == eb]
        if rows:
            impl = _pick_host_tier(rows)
    except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
        pass
    _STREAM_IMPL_EB[eb] = impl
    return impl


_INGRESS = None   # "standard" | "compact", resolved once per process


def _reset_ingress() -> None:
    """Test hook: forget the memoized ingress selection."""
    global _INGRESS
    _INGRESS = None


def resolve_ingress(vb: int) -> str:
    """Stream-chunk wire format: "standard" (int32 ids + bool mask,
    9 bytes/slot) or "compact" (uint16 ids + per-window valid counts,
    4 bytes/slot; ops/compact_ingress.py). The chip's end-to-end
    stream rate is h2d-transfer bound (PERF.md "VERIFIED chip rows"),
    so the format is a measured selection like the kernels: compact
    only when (a) ids fit uint16 for THIS vertex bucket and (b) the
    committed backend-matched `ingress_ab` rows (tools/ingress_ab.py
    via tools/profile_kernels.py) all show parity and a ≥5%
    end-to-end win. Memoized per process (reset: _reset_ingress);
    the vb gate applies per kernel instance."""
    global _INGRESS
    if _INGRESS is None:
        impl = "standard"
        try:
            perf = _load_matching_perf()
            if rows_clear_bar((perf or {}).get("ingress_ab", []),
                              "speedup", lambda r: 1.0):
                impl = "compact"
        except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
            pass
        _INGRESS = impl
    if _INGRESS == "compact":
        from . import compact_ingress

        if not compact_ingress.supports(vb):
            return "standard"
    return _INGRESS


_TUNED_KB = {}  # eb -> measured starting K (resolved once per process)  # gslint: disable=thread-shared (idempotent memo of committed PERF.json evidence)


def _tuned_kb(eb: int) -> int:
    """Initial K bucket for an edge-bucket size. The K×K intersection
    compare dominates per-window cost and shrinks quadratically with
    K, so the default comes from the committed k-sweep measurements
    (PERF.json `window` section, tools/profile_kernels.py) when they
    exist for this bucket on this hardware: the fastest measured row
    wins OUTRIGHT — each row's per_window_ms was measured on a run
    that already paid that K's overflow recounts, so a small K that
    overflows occasionally but wins net (CPU sweep at eb=32768: K=32
    with 1 recount/64 windows runs 1.76× faster than the clean K=64)
    is taken at its measured value, not excluded. The escalation
    ladder guarantees exactness regardless; a stream with a heavier
    degree tail than the profile stream just pays more of the
    recounts the measurement priced in. Fallback: the analytic O(√E)
    heuristic."""
    if eb in _TUNED_KB:
        return _TUNED_KB[eb]
    # K tuning applies per BACKEND: the committed k-sweep for whatever
    # backend this process runs.
    _TUNED_KB[eb] = _fastest_sweep_row(
        eb, "k_sweep", "k_bucket", default=min(128, 2 * int(np.sqrt(eb))))  # gslint: disable=host-sync (python-int bucket math, no device value in sight)
    return _TUNED_KB[eb]


def _fastest_sweep_row(eb: int, sweep_key: str, value_key: str,
                       default: int) -> int:
    """Shared selection core of _tuned_kb/_tuned_chunk: the fastest
    measured row (min per_window_ms, recount cost included in the
    measurement) of this bucket's backend-matched committed sweep;
    `default` when unmeasured. Sweep rows missing the value key (a
    malformed or hand-edited PERF.json) are skipped, and the selected
    value is clamped to a positive int — a zero/None K or chunk would
    break the kernel's range stepping (ADVICE r3)."""
    perf = _load_matching_perf()
    if perf is not None:
        # chunk_deep rows (tools/profile_kernels.section_chunk_deep)
        # extend the window section's sweep past the pre-probe compile
        # cap in the same chip window; they carry the same
        # {edge_bucket, chunk_sweep: [...]} shape and no k_sweep, so
        # merging them here is a no-op for the K selection.
        rows = (list(perf.get("window", []) or [])
                + list(perf.get("chunk_deep", []) or []))
        measured = [s for row in rows
                    if row.get("edge_bucket") == eb
                    for s in row.get(sweep_key, []) or []
                    if s.get("per_window_ms") and s.get(value_key)]
        if measured:
            default = max(1, int(min(  # gslint: disable=host-sync (committed-evidence JSON ints, no device value in sight)
                measured,
                key=lambda s: s["per_window_ms"])[value_key]))
    return default

_TUNED_CHUNK = {}  # eb -> measured windows-per-dispatch  # gslint: disable=thread-shared (idempotent memo of committed PERF.json evidence)


_COMPILE_CAPS = {}           # program -> slots, resolved once per process  # gslint: disable=thread-shared (idempotent memo: probe result is deterministic per program)
_COMPILE_CAP_DEFAULT = 1 << 19
# sizes proven clean OUTSIDE the probe (the round-4 chip window's
# bench compiles): a probed failure above these never lowers the cap
# beneath them. The scan programs have no proven size — they wedged
# at/below the default.
_PROVEN_CLEAN = {"triangle_stream": 1 << 19}


def _reset_compile_caps() -> None:
    """Test hook: forget the memoized per-program compile caps."""
    _COMPILE_CAPS.clear()


def compile_cap(program: str = "triangle_stream") -> int:
    """Largest stream-program size (window-slots per dispatch) trusted
    to COMPILE for `program` on this backend.

    Default 2^19: both triangle stream shapes at that size compiled
    cleanly in the round-4 chip window (64×8192, 16×32768) while the
    2^21 one wedged the tunnel's remote compiler >25 min twice
    (logs/bench_r04_stage1.err) — and the multi-analytic scan programs
    (fused engine, driver snapshot) wedged even at the default, which
    is why the cap is per-PROGRAM. Committed backend-matched
    `compile_probe`/`compile_probe_scan` rows
    (tools/profile_kernels.py, each candidate compiled in its own
    hard-timeout subprocess) move it: a clean row RAISES the cap to
    its size; a probed failure at/below the current cap LOWERS it to
    the largest clean size beneath the failure (or a quarter of the
    failing size when none is measured)."""
    if program in _COMPILE_CAPS:
        return _COMPILE_CAPS[program]
    cap = _COMPILE_CAP_DEFAULT
    try:
        perf = _load_matching_perf()
        rows = []
        for key in ("compile_probe", "compile_probe_scan"):
            sec = (perf or {}).get(key, [])
            if isinstance(sec, list):
                rows += [r for r in sec
                         if r.get("program") == program]
        clean = sorted(int(r["slots"]) for r in rows  # gslint: disable=host-sync (committed-evidence JSON ints, no device value in sight)
                       if r.get("ok") is True and r.get("slots"))
        failed = sorted(int(r["slots"]) for r in rows  # gslint: disable=host-sync (committed-evidence JSON ints, no device value in sight)
                        if r.get("ok") is False and r.get("slots"))
        if clean:
            cap = max(cap, clean[-1])
        if failed and failed[0] <= cap and not (
                clean and clean[-1] >= failed[0]):
            # Lower only when no clean row exists at/above the failing
            # size: a successful compile is direct evidence of the
            # shape, while a probe timeout can be a tunnel flake — on
            # contradictory rows the measured success wins (ADVICE r4).
            floor = [s for s in clean if s < failed[0]]
            proven = _PROVEN_CLEAN.get(program)
            if proven is not None and proven < failed[0]:
                floor.append(proven)
            cap = max(floor) if floor else max(1, failed[0] // 4)
    except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
        pass
    _COMPILE_CAPS[program] = cap
    return cap


def capped_chunk(eb: int, program: str) -> int:
    """Windows-per-dispatch limit for `program` at this edge bucket:
    the probed compile cap on a TPU backend, the class maximum
    off-chip (dispatch is ~free there and the host compiler does not
    wedge)."""
    try:
        import jax as _jax

        if _jax.default_backend() == "tpu":
            return max(1, compile_cap(program) // max(eb, 1))
    except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
        pass
    return TriangleWindowKernel.MAX_STREAM_WINDOWS


def _default_chunk(eb: int) -> int:
    """Unmeasured windows-per-dispatch default for the triangle stream
    program (compile-size-capped on TPU backends; compile_cap)."""
    return max(1, min(TriangleWindowKernel.MAX_STREAM_WINDOWS,
                      capped_chunk(eb, "triangle_stream")))


def _tuned_chunk(eb: int) -> int:
    """Windows per count_stream dispatch: the fastest measured
    chunk_sweep row for this bucket on this backend (committed
    PERF.json `window` rows; the sweep runs at the same fastest-row K
    that _tuned_kb selects, so the chunk is tuned for the K production
    actually runs). Fallback: _default_chunk (compile-size-capped on
    the tunneled chip). On CPU the committed sweep is flat within a
    few percent at every bucket — dispatch is ~free off-chip, so the
    pick there is load-noise-driven and harmless; the selector exists
    for the tunneled chip, where each dispatch costs ~0.2s and the
    chunk size sets how that latency amortizes."""
    if eb in _TUNED_CHUNK:
        return _TUNED_CHUNK[eb]
    val = _fastest_sweep_row(
        eb, "chunk_sweep", "windows_per_dispatch",
        default=_default_chunk(eb))
    # On chip, a measured depth never overrides the CURRENT compile
    # cap: a chunk_deep row persisted under a since-lowered cap would
    # otherwise re-compile the exact oversized program the cap exists
    # to prevent (the >25-min remote-compiler wedge). Off-chip the
    # host compiler has no wedge and sweeps legitimately measure past
    # the class default, so no clamp there.
    try:
        import jax as _jax

        if _jax.default_backend() == "tpu":
            val = min(val, max(1, compile_cap("triangle_stream")
                               // max(eb, 1)))
    except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
        pass
    _TUNED_CHUNK[eb] = val
    return _TUNED_CHUNK[eb]


class TriangleWindowKernel:
    """One compiled program for an unbounded stream of windows.

    The per-window host work of `triangle_count_sparse` (dedupe, degree
    orientation, CSR build) re-runs numpy sorts and ships an O(V·K)
    neighbor table to the device for EVERY window — and every window
    with a new max-degree bucket recompiles. This engine moves the whole
    pipeline into a single jitted program over fixed buckets
    (edge_bucket, vertex_bucket, k_bucket): the host sends only the raw
    COO arrays (~1MB/window), the device does dedupe (lexicographic
    sort), (degree, id) orientation, CSR scatter, and sorted-row
    intersection, and returns (count, overflow). Steady-state streaming
    pays zero recompiles and minimal PCIe/tunnel traffic.

    `overflow` > 0 means some vertex's oriented out-degree exceeded
    k_bucket; the kernel then escalates to a lazily-built 4·K program
    (and ultimately the dynamic-shape host path), so exactness is never
    sacrificed. With (degree, id) orientation the out-degree is O(√E)
    worst-case but far smaller on real skewed streams (tens, not
    hundreds), so the default K starts small — the K×K intersection
    compare is the dominant per-window cost and shrinks quadratically
    with K.

    `count()` runs one window per dispatch; `count_stream()` ships the
    whole stream to HBM once and folds every window inside a single
    `lax.map` program, which amortizes host↔device transfer and
    dispatch latency (dominant through a tunneled chip: ~0.2s/window)
    across the entire stream.

    Replaces the three shuffles of WindowTriangles.java:61-66 with one
    device program; cites SURVEY.md §3.3.
    """

    MAX_STREAM_WINDOWS = 64  # windows per dispatch in count_stream

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 k_bucket: int = 0, ingress: str = None):
        self.eb = seg_ops.bucket_size(edge_bucket)
        self.vb = seg_ops.bucket_size(vertex_bucket)
        self.kb = seg_ops.bucket_size(
            k_bucket if k_bucket else _tuned_kb(self.eb))
        self.kb_max = seg_ops.bucket_size(2 * int(np.sqrt(self.eb)))  # gslint: disable=host-sync (numpy scalar math on a python int bucket, no device value in sight)
        # instance attribute shadows the class default when a committed
        # chunk sweep exists for this bucket on this backend
        self.MAX_STREAM_WINDOWS = _tuned_chunk(self.eb)
        # wire format of stream-chunk dispatches; explicit `ingress`
        # pins a format (the A/B tool measures both), None resolves
        # from committed evidence
        if ingress == "compact":
            from . import compact_ingress

            if not compact_ingress.supports(self.vb):
                raise ValueError(
                    "compact ingress is lossy for vertex_bucket %d "
                    "(ids must fit uint16)" % self.vb)
        self.ingress = ingress if ingress else resolve_ingress(self.vb)
        # explicit constructor pins freeze those knobs for the online
        # tuner too: an A/B tool or profiler sweep that pinned a K or
        # a wire format must measure exactly that configuration
        # (ops/autotune arms then vary only the unpinned knobs)
        self._pinned_kb = bool(k_bucket)
        self._pinned_ingress = ingress is not None
        # per-stage wall-time counters of every pipelined stream run
        # through this kernel (ops/ingress_pipeline.StageTimers);
        # tools/profile_kernels.py commits their snapshot to PERF.json
        self.stage_timers = ingress_pipeline.StageTimers()
        self._fns = {self.kb: self._build(self.kb)}
        self._stream_fns = {}
        self._stream_execs = {}

    def _build(self, kb):
        fn = build_window_counter(self.vb, kb)
        # remember the selection for the AOT stream-program label: the
        # cost observatory must attribute the megakernel-backed stream
        # distinctly from the XLA one it replaces
        self._pallas_counter = bool(getattr(fn, "pallas_window",
                                            False))
        return jax.jit(fn)

    def _escalation_ladder(self):
        """K values to try in order: kb, 4·kb, ... up to kb_max."""
        ks, k = [], self.kb
        while k < self.kb_max:
            ks.append(k)
            k *= 4
        ks.append(max(self.kb, self.kb_max))
        return ks

    def count(self, src: np.ndarray, dst: np.ndarray,
              min_k: int = 0) -> int:
        """Exact triangle count of one window batch (dense ids < vb).

        `min_k` skips ladder rungs already known to overflow (used by
        count_stream's recount so an overflowing window isn't re-tried
        at the K that just failed)."""
        n = len(src)
        if n == 0:
            return 0
        if n > self.eb:
            raise ValueError(f"window of {n} edges exceeds edge bucket "
                             f"{self.eb}")
        s = seg_ops.pad_to(np.asarray(src, np.int32), self.eb, fill=self.vb)  # gslint: disable=host-sync (host-input normalization: count() takes numpy/lists, never device values)
        d = seg_ops.pad_to(np.asarray(dst, np.int32), self.eb, fill=self.vb)  # gslint: disable=host-sync (host-input normalization: count() takes numpy/lists, never device values)
        valid = seg_ops.pad_to(np.ones(n, bool), self.eb, fill=False)
        s, d, valid = jnp.asarray(s), jnp.asarray(d), jnp.asarray(valid)
        for kb in self._escalation_ladder():  # widen K only when a hub
            if kb <= min_k:                   # outruns the current table
                continue
            if kb not in self._fns:
                self._fns[kb] = self._build(kb)
            count, overflow = self._fns[kb](s, d, valid)
            if not int(overflow):
                return int(count)
        return triangle_count_sparse(src, dst, self.vb)  # exact last resort

    def _build_stream(self, kb):
        window = self._fns[kb]

        @jax.jit
        def run_stream(src, dst, valid):  # [W, eb] each
            return jax.lax.map(lambda t: window(*t), (src, dst, valid))

        return run_stream

    def _stream_exec(self, wb: int, kb: int = None,
                     ingress: str = None):
        """AOT-compiled stream program for a [wb, eb] chunk at K `kb`
        and wire format `ingress` (both default to the kernel's static
        selection; the autotuner passes its arm's values), in the
        kernel's OWN cache: warming via .lower().compile() never
        executes anything (jit's internal shape cache is not populated
        by AOT compilation, so the dispatch path must share this cache
        for compile-only warming to stick)."""
        kb = self.kb if kb is None else kb
        ingress = self.ingress if ingress is None else ingress
        key = (kb, wb, ingress)
        ex = self._stream_execs.get(key)
        if ex is None:
            if kb not in self._fns:
                self._fns[kb] = self._build(kb)
            fkey = (kb, ingress)
            if fkey not in self._stream_fns:
                if ingress == "compact":
                    from . import compact_ingress

                    self._stream_fns[fkey] = jax.jit(
                        compact_ingress.build_stream_fn(
                            self._fns[kb], self.vb, self.eb))
                else:
                    self._stream_fns[fkey] = self._build_stream(kb)
            if ingress == "compact":
                sds_u = jax.ShapeDtypeStruct((wb, self.eb), jnp.uint16)
                sds_n = jax.ShapeDtypeStruct((wb,), jnp.int32)
                sds = (sds_u, sds_u, sds_n)
            else:
                sds_i = jax.ShapeDtypeStruct((wb, self.eb), jnp.int32)
                sds_b = jax.ShapeDtypeStruct((wb, self.eb), jnp.bool_)
                sds = (sds_i, sds_i, sds_b)
            ex = self._stream_fns[fkey].lower(*sds).compile()
            # cost observatory (utils/costmodel): the AOT executable
            # carries its own cost_analysis — registration is free,
            # and armed dispatches tag their ledger spans program/sig
            program = ("pallas_window_stream"
                       if getattr(self, "_pallas_counter", False)
                       else "triangle_stream")
            ex = costmodel.wrap_exec(
                program, ex, metrics.abstract_sig(sds))
            self._stream_execs[key] = ex
        return ex


    def _run_stack_loop(self, num_w: int, make_chunk, recount) -> list:
        """The ONE pipelined chunk loop both wire formats run, routed
        through the shared three-stage ingress pipeline
        (ops/ingress_pipeline.run_pipeline — VERDICT r4 item 2: the
        chip rate was pinned ~600K edges/s by serialized host work):

        - PREP runs on the worker POOL: `make_chunk(at, hi)` returns
          (args_tuple, n) — the padded host stacks for windows
          [at:hi] plus the real window count (a ragged final chunk
          pads its window axis to a power-of-two bucket, so varying
          stream lengths reuse O(log MAX_STREAM_WINDOWS) programs).
          Several chunks prep concurrently; results are consumed in
          chunk order, so counts never depend on the pool size.
        - H2D converts the stacks on the SAME worker right after that
          chunk's prep (through a tunneled chip a device_put is
          effectively synchronous network time, so the transfer
          overlaps device execute and the previous chunk's d2h wait;
          the stage timer decomposes it) — the h2d closure must stay
          thread-safe, i.e. jnp.asarray of worker-local arrays only.
        - DISPATCH stays pipelined depth 2: chunk i's [W]-scalar
          outputs materialize only after chunk i+1 is enqueued, so
          the d2h round trip of one chunk hides behind the next.
          `recount(w)` exactly recounts window w when its hubs
          overflow K.

        `GS_STREAM_PREFETCH=0` (or ingress_pipeline.forced_sync)
        forces the single-threaded inline-prep form — same counts.
        """
        counts: list = []

        def prep(at):
            hi = min(at + self.MAX_STREAM_WINDOWS, num_w)
            args, n = make_chunk(at, hi)
            return at, n, args

        def h2d(payload):
            at, n, args = payload
            return at, n, [jnp.asarray(a) for a in args]

        def dispatch(dev_payload):
            at, n, dev = dev_payload
            c, o = self._stream_exec(dev[0].shape[0])(*dev)
            return at, n, c, o

        def finalize(raw):
            at, n, c_dev, o_dev = raw
            # np.array (not asarray): device outputs can be read-only
            c, o = np.array(c_dev)[:n], np.array(o_dev)[:n]  # gslint: disable=host-sync (sanctioned finalize boundary: the chunk's ONE batched [W]-scalar d2h, pipelined one chunk behind dispatch)
            for w in np.nonzero(o)[0]:  # rare hub overflow: exact redo
                c[w] = recount(at + int(w))
            counts.extend(int(x) for x in c)

        ingress_pipeline.run_pipeline(
            range(0, num_w, self.MAX_STREAM_WINDOWS),
            prep, h2d, dispatch, finalize, timers=self.stage_timers)
        return counts

    def _run_stack(self, s, d, valid, get_window) -> list:
        """Standard-format window stack through _run_stack_loop."""

        def make_chunk(at, hi):
            sc, dc, vc, n = seg_ops.pad_window_chunk(
                s, d, valid, at, hi, self.MAX_STREAM_WINDOWS, self.eb,
                self.vb)
            return (sc, dc, vc), n

        def recount(w):
            ws, wd = get_window(w)
            return self.count(ws, wd, min_k=self.kb)

        return self._run_stack_loop(s.shape[0], make_chunk, recount)

    def _run_stack_compact(self, num_w, s16, d16, nvalid,
                           recount) -> list:
        """Compact-format stacks (ops/compact_ingress prep) through
        the SAME _run_stack_loop."""
        from . import compact_ingress

        def make_chunk(at, hi):
            sc, dc, nv, n = compact_ingress.pad_chunk(
                s16, d16, nvalid, at, hi, self.MAX_STREAM_WINDOWS,
                self.eb)
            return (sc, dc, nv), n

        return self._run_stack_loop(num_w, make_chunk, recount)

    # ---- online autotuning (ops/autotune.py) -------------------------

    def _tuner_space(self) -> dict:
        """The kernel's arm space: windows-per-dispatch rungs under the
        (compile-capped) chunk limit, the first K rungs of the existing
        escalation ladder (exactness guaranteed by the overflow recount
        at ANY K), and the two parity-proven wire formats (compact only
        when ids fit uint16 for this vertex bucket)."""
        wb_max = self.MAX_STREAM_WINDOWS
        wbs = sorted({max(1, wb_max // 4), max(1, wb_max // 2), wb_max})
        if self._pinned_kb:
            kbs = [self.kb]
        else:
            kbs = self._escalation_ladder()[:3]
        ing = [self.ingress]
        if not self._pinned_ingress:
            from . import compact_ingress

            ing = ["standard"]
            if compact_ingress.supports(self.vb):
                ing.append("compact")
        return {"wb": wbs, "kb": sorted(set(kbs)), "ingress": ing}

    def _ensure_tuner(self):
        from . import autotune

        if getattr(self, "tuner", None) is None:
            self.tuner = autotune.DispatchTuner(
                "triangle_stream:eb=%d:vb=%d" % (self.eb, self.vb),
                self._tuner_space(),
                {"wb": self.MAX_STREAM_WINDOWS, "kb": self.kb,
                 "ingress": self.ingress})
        return self.tuner

    def _warm_arm(self, arm: dict) -> None:
        """AOT-compile an arm's full-chunk stream program BEFORE its
        first timed round (ragged tail buckets compile on first use at
        the stream end, exactly like the legacy path's tail)."""
        self._stream_exec(arm["wb"], kb=arm["kb"],
                          ingress=arm["ingress"])

    def _run_stack_tuned(self, src: np.ndarray,
                         dst: np.ndarray) -> list:
        """The autotuned twin of the _run_stack* paths: the stream is
        folded in measurement ROUNDS of `autotune.round_chunks()`
        dispatch chunks each; the tuner picks each round's
        (windows-per-dispatch, K, ingress) arm, the round runs through
        the SAME shared ingress pipeline, and its measured edges/s
        feeds the tuner. Counts are identical to every static
        configuration (same kernels, same overflow recounts); only
        dispatch economics change. Under forced_sync (the bench's A/B
        lever) the tuner FREEZES — the incumbent runs and nothing is
        recorded."""
        from . import autotune
        from . import compact_ingress

        eb = self.eb
        n = len(src)
        num_w = -(-n // eb)
        tuner = self._ensure_tuner()
        freeze = ingress_pipeline.forced_sync_active()

        def recount(w: int, min_k: int) -> int:
            return self.count(src[w * eb:(w + 1) * eb],
                              dst[w * eb:(w + 1) * eb], min_k=min_k)

        # chunk stacks build FROM THE RAW COO inside the (pooled) prep
        # stage — no whole-stream per-format stacks: exploring the
        # other wire format must not double the resident ingress
        # memory of a long stream
        def make_chunk(a, hi, wb, ingress):
            lo, hi_e = a * eb, min(hi * eb, n)
            if ingress == "compact":
                m, s16, d16, nv = compact_ingress.window_stack(
                    src[lo:hi_e], dst[lo:hi_e], eb)
                sc, dc, nvc, m = compact_ingress.pad_chunk(
                    s16, d16, nv, 0, m, wb, eb)
                return (sc, dc, nvc), m
            m, s, d, valid = seg_ops.window_stack(
                src[lo:hi_e], dst[lo:hi_e], eb, sentinel=self.vb)
            sc, dc, vc, m = seg_ops.pad_window_chunk(
                s, d, valid, 0, m, wb, eb, self.vb)
            return (sc, dc, vc), m

        counts: list = []
        round_len = autotune.round_chunks()
        at = 0
        while at < num_w:
            arm = tuner.best() if freeze else tuner.next_round()
            self._warm_arm(arm)
            wb, kb, ingress = arm["wb"], arm["kb"], arm["ingress"]
            take = min(num_w - at, round_len * wb)
            # the telemetry span is the round's stopwatch (identical
            # perf_counter measurement with the recorder disarmed)
            with telemetry.span("triangles.round", window=at,
                                wb=wb, kb=kb, ingress=ingress,
                                edges=take * eb) as sp:
                self._run_window_range(at, at + take, wb, kb, ingress,
                                       make_chunk, recount, counts)
            # record full rounds (or a whole call smaller than one):
            # a long stream's ragged tail has different per-edge
            # amortization and would drag the arm's EMA (and the
            # persisted cache) with tail economics
            if not freeze and take == min(round_len * wb, num_w):
                tuner.record(arm, take * eb, sp.elapsed)
            at += take
        if not freeze:
            tuner.save()
        return counts

    def _run_window_range(self, at0: int, hi_w: int, wb: int, kb: int,
                          ingress: str, make_chunk, recount,
                          counts: list) -> None:
        """One round's windows [at0, hi_w) through the shared
        three-stage ingress pipeline at an explicit arm — the
        arm-parameterized core of _run_stack_loop."""

        def prep(at):
            hi = min(at + wb, hi_w)
            args, m = make_chunk(at, hi, wb, ingress)
            return at, m, args

        def h2d(payload):
            at, m, args = payload
            return at, m, [jnp.asarray(a) for a in args]

        def dispatch(dev_payload):
            at, m, dev = dev_payload
            c, o = self._stream_exec(dev[0].shape[0], kb=kb,
                                     ingress=ingress)(*dev)
            return at, m, c, o

        def finalize(raw):
            at, m, c_dev, o_dev = raw
            c, o = np.array(c_dev)[:m], np.array(o_dev)[:m]  # gslint: disable=host-sync (sanctioned finalize boundary: the tuned round's ONE batched [W]-scalar d2h)
            for w in np.nonzero(o)[0]:  # rare hub overflow: exact redo
                c[w] = recount(at + int(w), kb)
            counts.extend(int(x) for x in c)

        ingress_pipeline.run_pipeline(
            range(at0, hi_w, wb), prep, h2d, dispatch, finalize,
            timers=self.stage_timers)

    def warm_chunks(self) -> None:
        """Compile every stream-chunk program _run_stack can dispatch
        at the current K, so a streaming consumer (the driver) pays
        stream-program COMPILES at (re)build time, never mid-stream:
        the steady-state compile discipline tools/scale_run.py
        asserts. Compile-only — no dispatches, no compute (the first
        execute-based version cost ~16% of the 10M driver leg running
        full-size zero streams). seg_ops.warm_stream_buckets is the
        shared body. A no-op when the numpy tier is selected — there
        is nothing to compile."""
        if _resolve_stream_impl(self.eb) in ("host", "native"):
            return
        seg_ops.warm_stream_buckets(self)

    def count_stream(self, src: np.ndarray, dst: np.ndarray) -> list:
        """Exact counts of every tumbling `edge_bucket`-sized window of
        the stream, batched into one device program per
        MAX_STREAM_WINDOWS windows: one h2d of the COO chunk, a
        `lax.map` over its windows, one d2h of the counts. Windows whose
        hubs overflow K are recounted individually (escalating count()),
        so results are always exact. On a CPU backend with committed
        winning measurements the vectorized numpy tier takes over
        (`_resolve_stream_impl`; same counts, no dispatches)."""
        src = np.asarray(src, np.int32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/python COO, never device arrays)
        dst = np.asarray(dst, np.int32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy/python COO, never device arrays)
        if len(src) == 0:
            return []
        impl = _resolve_stream_impl(self.eb)
        if impl == "native":
            counts = _native_count_stream_parallel(src, dst, self.eb)
            if counts is not None:
                metrics.mark_window(len(counts), len(src),
                                    engine="triangle_stream",
                                    tier="native")
                return counts
            impl = "host"  # stale library: numpy tier stands in
        if impl == "host":
            from . import host_triangles

            counts = host_triangles.count_stream(src, dst, self.eb)
            metrics.mark_window(len(counts), len(src),
                                engine="triangle_stream", tier="host")
            return counts
        # health-plane marks live ONLY at this top-level entry (all
        # tiers, once per stream): the chunk loops underneath are
        # shared with count_windows — the driver's flush path — whose
        # windows the driver already marks at its own chunk boundary
        counts = self._count_stream_device(src, dst)
        metrics.mark_window(len(counts), len(src),
                            engine="triangle_stream", tier="device")
        return counts

    def _count_stream_device(self, src: np.ndarray,
                             dst: np.ndarray) -> list:
        """The device path of count_stream, selection bypassed (the
        profiler measures both tiers through this split). Streams
        longer than one maximal dispatch chunk route through the
        online autotuner (GS_AUTOTUNE, ops/autotune.py) — identical
        counts, live-measured dispatch knobs; GS_AUTOTUNE=0 (or a
        short stream) runs the static-gate path below bit-identically."""
        eb = self.eb
        from . import autotune

        if autotune.enabled() \
                and -(-len(src) // eb) > self.MAX_STREAM_WINDOWS:
            return self._run_stack_tuned(src, dst)
        if self.ingress == "compact":
            from . import compact_ingress

            num_w, s16, d16, nv = compact_ingress.window_stack(
                src, dst, eb)
            return self._run_stack_compact(
                num_w, s16, d16, nv,
                lambda w: self.count(src[w * eb:(w + 1) * eb],
                                     dst[w * eb:(w + 1) * eb],
                                     min_k=self.kb))
        num_w, s, d, valid = seg_ops.window_stack(src, dst, self.eb,
                                                  sentinel=self.vb)
        return self._run_stack(
            s, d, valid,
            lambda w: (src[w * eb:(w + 1) * eb], dst[w * eb:(w + 1) * eb]))

    def count_windows(self, windows) -> list:
        """Exact counts of a list of (src, dst) window batches of
        varying lengths (each ≤ edge_bucket), padded into one stack and
        dispatched in chunks — the batched form of calling count() per
        window (used by the driver's event-time windows). Routes to the
        numpy tier under the same selection as count_stream."""
        if not windows:
            return []
        impl = _resolve_stream_impl(self.eb)
        if impl == "native":
            from .. import native as native_mod

            def one(win):
                s, d = win
                c = native_mod.triangle_count_stream(
                    np.asarray(s), np.asarray(d), max(len(s), 1))  # gslint: disable=host-sync (host-input normalization: window lists are numpy/python, never device values)
                if c is None:
                    return None
                return int(c[0]) if len(c) else 0  # gslint: disable=host-sync (native-tier ctypes result: host numpy, no device value)

            # per-window ctypes calls across the prep pool (the C++
            # kernel drops the GIL); window order is preserved
            out = ingress_pipeline.map_ordered(one, windows)
            if all(c is not None for c in out):
                return out
            impl = "host"  # stale library: numpy tier stands in
        if impl == "host":
            from . import host_triangles

            return host_triangles.count_windows(windows)
        if self.ingress == "compact":
            from . import compact_ingress

            s16, d16, nv = compact_ingress.stack_window_list(
                windows, self.eb)
            return self._run_stack_compact(
                len(windows), s16, d16, nv,
                lambda w: self.count(*windows[w], min_k=self.kb))
        s, d, valid = seg_ops.stack_window_list(windows, self.eb,
                                                self.vb)
        return self._run_stack(s, d, valid, lambda w: windows[w])


_DENSE_CHOICE = None  # resolved once per process: ("xla"|"pallas", limit)
_PERF_PATH = os.path.join(
    os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "PERF.json")


def _resolve_dense_choice():
    """Pick the dense path from COMMITTED on-chip measurements
    (PERF.json, written by tools/profile_kernels.py), not an env var
    (VERDICT r1: 'make Pallas earn its place'). The Pallas fused
    contraction wins only if (a) this process runs a TPU backend — the
    interpret mode times nothing real — (b) the measurements were
    themselves taken on a TPU backend (PERF.json records it), and
    (c) the parity-checked rows show ≥5% speedup at EVERY measured V.
    Otherwise the measured-default XLA path stands. (No chip-generation
    or freshness tag is recorded: re-run tools/profile_kernels.py when
    the hardware or the kernels change.) The Pallas path also doubles
    the exact dense limit (f32 argument in ops/pallas_triangles.py)."""
    global _DENSE_CHOICE
    if _DENSE_CHOICE is not None:
        return _DENSE_CHOICE
    choice = ("xla", DENSE_LIMIT)
    perf = _load_tpu_perf()
    if perf is not None:
        rows = perf.get("dense", [])
        if (isinstance(rows, list) and rows
                and all(r.get("pallas_speedup", 0) >= 1.05
                        for r in rows)):
            choice = ("pallas", 2 * DENSE_LIMIT)
    _DENSE_CHOICE = choice
    return choice


def triangle_count(src: np.ndarray, dst: np.ndarray, num_vertices: int) -> int:
    """Pick the MXU dense path for small windows, wedge path otherwise.
    The dense implementation (XLA matmul vs Pallas fused contraction)
    is selected by `_resolve_dense_choice` from committed on-chip
    measurements; on CPU backends the measured host tier takes the
    whole window (same `_resolve_stream_impl` evidence as
    count_stream — identical counts, no dispatch)."""
    tier = _resolve_stream_impl()
    if tier == "native":
        from .. import native as native_mod

        counts = native_mod.triangle_count_stream(
            np.asarray(src), np.asarray(dst), max(len(src), 1))  # gslint: disable=host-sync (host-input normalization: callers pass numpy/lists, never device values)
        if counts is not None:
            return int(counts[0]) if len(counts) else 0  # gslint: disable=host-sync (native-tier ctypes result: host numpy, no device value)
        tier = "host"
    if tier == "host":
        from . import host_triangles

        return host_triangles.window_count(src, dst)
    impl, limit = _resolve_dense_choice()
    if num_vertices <= limit:
        if impl == "pallas":
            from . import pallas_triangles

            return pallas_triangles.triangle_count_dense_pallas(
                src, dst, num_vertices)
        return triangle_count_dense(src, dst, num_vertices)
    return triangle_count_sparse(src, dst, num_vertices)

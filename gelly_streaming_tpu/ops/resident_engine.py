"""Resident-state window megakernel: device-resident summaries +
double-buffered ingest, ONE dispatch per many windows.

Every committed ladder (BENCH_r01→r05) shows the same shape: device
compute is cheap and the per-window host↔device round trip is the wall
— the device path sits ~1M edges/s while the native CPU tier does
8.8-10.3M on the 524K/32768 rows. The IO-aware GNN papers (PAPERS.md)
say the fix is restructuring for the memory hierarchy, not faster
math. The three ingredients already landed — compact ingress
(ops/compact_ingress), delta egress (ops/delta_egress), and the fused
scans (ops/scan_analytics, core/driver._build_snapshot_scan) — and
this module is the refactor that joins them:

- **ResidentState** — the summary carry (degree slab, DisjointSet
  label slab, double-cover slab) as a named pytree pinned on device.
  The fused program takes it with explicit donation
  (`jax.jit(..., donate_argnums=(0,))` where the backend honors
  donation), so each super-batch UPDATES the slabs in place instead of
  re-allocating + copying them per dispatch. On backends that ignore
  donation (CPU) the same program runs undonated — bit-identical, just
  without the aliasing win.
- **IngestRing** — a small (default two-slot, `GS_RESIDENT_SLOTS`)
  device-side ingest ring built on the existing ingress-pipeline
  worker pool (ops/ingress_pipeline.submit_prep): while super-batch N
  computes, slot N+1's prep AND h2d run on a worker, so the host's
  only steady-state jobs are topping up edge slabs and draining
  compacted deltas. The ring depth feeds the health plane's
  `gs_inflight_chunks` backlog gauge.
- **ResidentSummaryEngine** — the resident tier of the fused summary
  engine: the same scan body and checkpoint layout as
  StreamSummaryEngine, with compact-ingress decode fused into the
  donated program and `GS_RESIDENT_SPB` windows folded per dispatch
  (the autotuner's windows-per-superbatch arm explores rungs under
  it). Checkpoints stay engine-interchangeable: the resident carry is
  gathered at super-batch boundaries only, so kill→resume lands on
  the scan tier or the numpy host twin bit-exactly.
- The **driver integration** lives in core/driver.py: `resident` is a
  snapshot tier ABOVE `scan` in the demotion ladder
  (resident → scan → native → host), selected by `resolve_resident()`
  below — GS_RESIDENT pin or committed backend-matched `resident_ab`
  rows (tools/resident_ab.py) clearing parity + the 1.05 bar over the
  best committed alternative tier, the same measured-adoption policy
  as compact ingress and delta egress.

Exactness: the resident program is the SAME scan body as the tiers
below it, so window-by-window results are bit-identical by
construction and asserted by tools/resident_ab.py, the chaos resident
leg (tools/chaos_run.py), and tests/operations/test_resident.py.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import compact_ingress
from . import scan_analytics
from . import segment as seg_ops
from . import triangles as tri_ops
from ..utils import knobs
from ..utils import metrics
from ..utils import telemetry


# ----------------------------------------------------------------------
# knobs / selection
# ----------------------------------------------------------------------
def resident_spb(eb: int) -> int:
    """Windows per super-batch of the resident megakernel: the
    GS_RESIDENT_SPB bucket, compile-size-capped per PROGRAM on the
    tunneled chip (the multi-analytic scan programs wedge the remote
    compiler at sizes the triangle program compiles —
    ops/triangles.compile_cap, program key "resident_scan"). Off-chip
    the host compiler does not wedge, so the knob stands as asked."""
    spb = seg_ops.bucket_size(knobs.get_int("GS_RESIDENT_SPB"))
    try:
        if jax.default_backend() == "tpu":
            cap = max(1, tri_ops.compile_cap("resident_scan")
                      // max(eb, 1))
            spb = min(spb, seg_ops.bucket_size(cap))
    except Exception as e:
        telemetry.event("selection.fallback", durable=True,
                        component="resident_spb", fallback=spb,
                        error="%s: %s" % (type(e).__name__, e))
    return spb


def ring_slots() -> int:
    """Ingest-ring depth (GS_RESIDENT_SLOTS, default 2): super-batches
    prepped+transferred ahead of the dispatch cursor. 2 is the
    double-buffered form the tentpole names; 1 degenerates to the
    scan tier's single-lookahead prefetch."""
    return knobs.get_int("GS_RESIDENT_SLOTS")


def donation_supported() -> bool:
    """True when this backend honors buffer donation. CPU ignores
    donate_argnums (with a per-compile warning), so the resident
    programs only request donation where it actually aliases —
    results are bit-identical either way."""
    try:
        return jax.default_backend() in ("tpu", "gpu", "cuda", "rocm")
    except Exception:  # gslint: disable=except-hygiene (availability probe: selects the no-donation form, never correctness)
        return False


def donate_kw() -> dict:
    """jit kwargs of the resident programs: explicit donation of the
    carry argument where the backend honors it (the ResidentState
    slabs then update in place), empty elsewhere — CPU would warn per
    compile and ignore it."""
    return {"donate_argnums": (0,)} if donation_supported() else {}


_RESIDENT = None  # "resident" | "scan", resolved once per process


def _reset_resident() -> None:
    """Test hook: forget the memoized resident-tier selection."""
    global _RESIDENT
    _RESIDENT = None


def resolve_resident() -> bool:
    """Should the driver's batched snapshot path run the RESIDENT tier
    instead of `scan`? GS_RESIDENT pins (`on`/`off`); unset/`auto`
    adopts resident only when committed backend-matched `resident_ab`
    driver rows (tools/resident_ab.py via tools/profile_kernels.py)
    ALL show exact parity and ≥1.05× over the best committed
    alternative tier in the row — scan AND, where measured, native —
    so adopting resident can never regress a stream that native
    already serves faster (the repo-wide measured-adoption policy,
    ops/triangles.rows_clear_bar). Memoized per process."""
    global _RESIDENT
    pin = knobs.get_str("GS_RESIDENT")
    if pin == "on":
        return True
    if pin == "off":
        return False
    if _RESIDENT is None:
        impl = "scan"
        try:
            perf = tri_ops._load_matching_perf()
            rows = [r for r in (perf or {}).get("resident_ab", [])
                    if r.get("probe") == "driver_resident"]

            def best_alternative(r):
                return max(r.get("scan_edges_per_s") or 0,
                           r.get("native_edges_per_s") or 0)

            if tri_ops.rows_clear_bar(rows, "resident_edges_per_s",
                                      best_alternative):
                impl = "resident"
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="resident", fallback=impl,
                            error="%s: %s" % (type(e).__name__, e))
        _RESIDENT = impl
    return _RESIDENT == "resident"


_RESIDENT_COHORT = None  # "resident" | "scan", resolved once per process


def _reset_resident_cohort() -> None:
    """Test hook: forget the memoized resident-cohort selection."""
    global _RESIDENT_COHORT
    _RESIDENT_COHORT = None


def resolve_resident_cohort() -> bool:
    """Should TenantCohort keep its carries stacked on DEVICE between
    rounds (the resident cohort tier: one donated `[N, ...]` carry
    pytree updated by one super-batch program, restacked only when
    membership changes) instead of restacking per-tenant host-visible
    carries every dispatch? GS_COHORT_RESIDENT pins (`on`/`off`);
    unset/`auto` adopts only when committed backend-matched
    `tenancy_ab` rows with probe `cohort_resident` ALL show exact
    per-tenant parity and ≥1.05× over per-tenant resident dispatch
    (the repo-wide measured-adoption policy,
    ops/triangles.rows_clear_bar). Memoized per process."""
    global _RESIDENT_COHORT
    pin = knobs.get_str("GS_COHORT_RESIDENT")
    if pin == "on":
        return True
    if pin == "off":
        return False
    if _RESIDENT_COHORT is None:
        impl = "scan"
        try:
            perf = tri_ops._load_matching_perf()
            rows = [r for r in (perf or {}).get("tenancy_ab", [])
                    if r.get("probe") == "cohort_resident"]
            if tri_ops.rows_clear_bar(
                    rows, "tenant_edges_per_s",
                    lambda r: r.get("sequential_edges_per_s") or 0):
                impl = "resident"
        except Exception as e:
            telemetry.event("selection.fallback", durable=True,
                            component="resident_cohort", fallback=impl,
                            error="%s: %s" % (type(e).__name__, e))
        _RESIDENT_COHORT = impl
    return _RESIDENT_COHORT == "resident"


# ----------------------------------------------------------------------
# ResidentState
# ----------------------------------------------------------------------
class ResidentState(NamedTuple):
    """The device-resident summary carry, as a named pytree (NamedTuple
    registers with jax automatically): the same three slabs — and the
    same layouts — every fused scan carries (degrees [vb+1] with the
    sentinel slot vb, min-label DisjointSet slab [vb+1], double-cover
    slab [2(vb+1)]), so a resident checkpoint is interchangeable with
    the scan/sharded/host-twin engines at equal buckets. Kept a
    DISTINCT type (not a bare tuple) so donation sites and tests can
    name exactly what is pinned on device."""

    degrees: object   # [vb+1]  int32, sentinel slot vb
    labels: object    # [vb+1]  int32, min-label union-find slab
    cover: object     # [2(vb+1)] int32, double-cover slab

    @classmethod
    def fresh(cls, vb: int, xp=np) -> "ResidentState":
        """Zero-stream state in the shared layout (host numpy by
        default; pass jax.numpy to build on device)."""
        return cls(xp.zeros(vb + 1, xp.int32),
                   xp.arange(vb + 1, dtype=xp.int32),
                   xp.arange(2 * (vb + 1), dtype=xp.int32))

    def to_host(self) -> "ResidentState":
        """Gather the slabs to host numpy (the super-batch-boundary
        d2h checkpoints and demotions re-enter from)."""
        return ResidentState(*(np.asarray(a) for a in self))  # gslint: disable=host-sync (sanctioned gather boundary: the resident state's ONE d2h at super-batch/checkpoint edges)

    @classmethod
    def grow(cls, old: "ResidentState", old_vb: int,
             new_vb: int) -> "ResidentState":
        """Re-lay the carried slabs out over a wider vertex bucket
        (host-side; the caller re-uploads). Degrees copy (the sentinel
        slot always holds 0 — masked padding never folds); labels keep
        their values (real min-labels are < old_vb, new slots are
        identity); cover labels pointing at/past the (+)-sentinel
        old_vb shift with the sentinel to new_vb (the (−) half and
        both sentinels live above it), mirroring
        core/driver._grow_cover for the vb+1-offset resident layout."""
        if new_vb < old_vb:
            raise ValueError("vertex bucket cannot shrink: %d -> %d"
                             % (old_vb, new_vb))
        old = old.to_host()
        shift = new_vb - old_vb
        deg = np.zeros(new_vb + 1, np.int32)
        deg[:old_vb] = old.degrees[:old_vb]
        lab = np.arange(new_vb + 1, dtype=np.int32)
        lab[:old_vb] = old.labels[:old_vb]
        cov = np.arange(2 * (new_vb + 1), dtype=np.int32)
        shifted = np.where(old.cover >= old_vb, old.cover + shift,
                           old.cover).astype(np.int32)
        cov[:old_vb] = shifted[:old_vb]
        cov[new_vb + 1:new_vb + 1 + old_vb] = shifted[
            old_vb + 1:old_vb + 1 + old_vb]
        # sentinel slots stay identity: they only ever union with each
        # other (invalid edges map to the (sent+, sent−) pair), so
        # their labels never reach a real slot
        return cls(deg, lab, cov)


# ----------------------------------------------------------------------
# IngestRing
# ----------------------------------------------------------------------
class IngestRing:
    """The resident tier's bounded ingest ring over the shared
    ingress-pipeline worker pool: `submit(fn, key, item)` schedules
    one super-batch's prep+h2d (fn runs WHOLLY on a worker and returns
    the device payload), `pop(key)` hands the payload back in
    submission order. While super-batch N computes on device, slot N+1
    fills — the double-buffered h2d stage of the tentpole. Depth is
    `GS_RESIDENT_SLOTS` (2 = classic double buffering); with the
    pipeline disabled (forced_sync / GS_STREAM_PREFETCH=0 / zero
    workers) submit() declines and the caller builds inline — same
    payloads, the worker-pool determinism contract.

    The filled-slot count feeds the health plane's
    `gs_inflight_chunks` gauge (utils/metrics): the ring IS the
    resident tier's in-flight backlog."""

    def __init__(self, slots: Optional[int] = None):
        from collections import deque

        self.slots = max(1, slots if slots is not None
                         else ring_slots())
        self._q = deque()

    def __len__(self) -> int:
        return len(self._q)

    @property
    def full(self) -> bool:
        return len(self._q) >= self.slots

    def _gauge(self) -> None:
        metrics.gauge_set("gs_inflight_chunks", len(self._q))

    def submit(self, fn, key, item) -> bool:
        """Schedule fn(item) on the pool under `key`; False when the
        ring is full or pipelining is disabled (caller runs inline)."""
        from . import ingress_pipeline

        if self.full:
            return False
        fut = ingress_pipeline.submit_prep(fn, item)
        if fut is None:
            return False
        self._q.append((key, fut, item))
        self._gauge()
        return True

    def pop(self, key):
        """(future, item) of the ring head iff it is `key`, else None
        (out-of-order pops are a caller bug — the ring is FIFO by the
        scan carry's sequential-dispatch contract)."""
        if self._q and self._q[0][0] == key:
            _k, fut, item = self._q.popleft()
            self._gauge()
            return fut, item
        return None

    def drain(self) -> None:
        """Cancel everything still queued (error paths); workers
        already running simply complete into dropped futures."""
        while self._q:
            _k, fut, _item = self._q.popleft()
            fut.cancel()
        self._gauge()


# ----------------------------------------------------------------------
# Mailbox
# ----------------------------------------------------------------------
class Mailbox:
    """Small bounded thread-safe mailbox — the hand-off primitive of
    the async serving pump (core/serve.py). Two uses there: the
    ingest→pump wake channel (feed() posts, the pump thread blocks in
    `get`) and the per-connection `subscribe` delivery queues (the
    pump posts WindowResult rows, the connection thread drains; a
    full queue returns False from put() so the slow subscriber is
    SHED instead of wedging the pump — the same never-block-the-pump
    contract as GS_SERVE_IDLE_S).

    `close()` wakes every blocked `get` permanently (they return
    None); items already queued still drain first. All methods are
    safe from any thread."""

    def __init__(self, capacity: int = 256):
        import threading
        from collections import deque

        self.capacity = max(1, int(capacity))
        self._q = deque()
        self._cv = threading.Condition()
        self._closed = False
        self.dropped = 0  # put() refusals (the shed counter's feed)

    def __len__(self) -> int:
        with self._cv:
            return len(self._q)

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed

    def put(self, item) -> bool:
        """Enqueue without ever blocking: False when the mailbox is
        full or closed (the caller owns the shed)."""
        with self._cv:
            if self._closed or len(self._q) >= self.capacity:
                self.dropped += 1
                return False
            self._q.append(item)
            self._cv.notify()
            return True

    def get(self, timeout: Optional[float] = None):
        """Dequeue one item, blocking up to `timeout` seconds (forever
        when None). Returns None on timeout or when the mailbox was
        closed and drained."""
        with self._cv:
            while not self._q:
                if self._closed:
                    return None
                if not self._cv.wait(timeout):
                    return None
            return self._q.popleft()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()


# ----------------------------------------------------------------------
# ResidentSummaryEngine
# ----------------------------------------------------------------------
class ResidentSummaryEngine(scan_analytics.StreamSummaryEngine):
    """The resident tier of the fused summary engine: the
    StreamSummaryEngine scan body + chunk loop with (a) the carry held
    as a donated device-resident ResidentState across super-batches,
    (b) `GS_RESIDENT_SPB` windows per dispatch (the tuner's
    windows-per-superbatch arm explores rungs under it), (c)
    compact-ingress decode fused into the donated program whenever the
    vertex bucket fits uint16, and (d) the ingest ring bounded at
    GS_RESIDENT_SLOTS (INGEST_SLOTS → ops/ingress_pipeline
    .run_pipeline) so slot N+1's prep+h2d fills while super-batch N
    computes. Summaries, window cuts, and the checkpoint layout are
    bit-identical to every other summary engine — kill→resume lands on
    the scan tier or the numpy host twin exactly
    (tests/test_checkpoint_roundtrip.py)."""

    METRICS_TIER = "resident"
    TUNER_FAMILY = "resident"
    AUTOTUNE = True
    TUNABLE_INGRESS = False  # the wire format is fused at build

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 k_bucket: int = 0, ingress: str = None,
                 superbatch: int = None):
        self._superbatch = superbatch
        if ingress is None:
            # the fused decode is the point: compact whenever the
            # bucket fits uint16, standard only as the fallback
            vb = seg_ops.bucket_size(vertex_bucket)
            ingress = ("compact" if compact_ingress.supports(vb)
                       else "standard")
        super().__init__(edge_bucket, vertex_bucket,
                         k_bucket=k_bucket, ingress=ingress)
        # super-batch depth replaces the scan tier's 64-window chunk;
        # programs rebuilt donated (and re-capped) on vb growth
        self.MAX_WINDOWS = seg_ops.bucket_size(
            superbatch if superbatch else resident_spb(self.eb))
        self._rebuild_programs()

    @property
    def INGEST_SLOTS(self):
        # live read: tests and tools flip the knob mid-process
        return ring_slots()

    def _rebuild_programs(self) -> None:
        """(Re)wrap the scan body as the donated resident programs —
        one standard-wire, one compact-wire twin with the device-side
        decode fused in front of the scan."""
        body = self._body

        def run(carry, src_w, dst_w, valid_w):
            return jax.lax.scan(body, carry, (src_w, dst_w, valid_w))

        # wrap_jit also feeds the cost observatory (utils/costmodel):
        # the resident super-batch program's FLOPs/bytes land in the
        # cost registry per signature, and armed dispatches tag their
        # spans program="resident_fused"/sig for the attribution join
        # — "resident_pallas" when the selected body is the fused
        # window megakernel (ops/pallas_window), so the observatory
        # attributes the new program distinctly on this tier too
        self._pallas = bool(getattr(body, "pallas_window", False))
        self._run = metrics.wrap_jit(
            "resident_pallas" if self._pallas else "resident_fused",
            jax.jit(run, **donate_kw()))
        self._run_c = None
        if self.ingress == "compact":
            self._ensure_compact_fn()

    def _ensure_compact_fn(self):
        """Compact twin of the donated program: widen uint16 ids +
        rebuild the suffix mask ON DEVICE (the one shared decode,
        compact_ingress.widen_stack) fused into the same donated
        scan — or, when the Pallas megakernel is selected, fused one
        level deeper (the compact body decodes per tile INSIDE the
        kernel, ops/pallas_window), still under the same donation."""
        if self._run_c is None:
            eb_, vb_, body = self.eb, self.vb, self._body

            if getattr(body, "pallas_window", False):
                from . import pallas_window

                run_pc = pallas_window.maybe_compact_scan_fn(
                    eb_, vb_, self.kb, "resident_pallas_compact",
                    jit_kwargs=donate_kw())
                if run_pc is not None:
                    self._run_c = run_pc
                    return self._run_c

            def run_c(carry, s16, d16, nvalid):
                s_w, d_w, valid_w = compact_ingress.widen_stack(
                    s16, d16, nvalid, eb_, vb_)
                return jax.lax.scan(body, carry, (s_w, d_w, valid_w))

            self._run_c = metrics.wrap_jit(
                "resident_fused_compact",
                jax.jit(run_c, **donate_kw()))
        return self._run_c

    def resident_state(self) -> ResidentState:
        """The live carry as a named ResidentState (device arrays;
        `.to_host()` gathers)."""
        return ResidentState(*self._carry)

    def grow_vertex_bucket(self, vertex_bucket: int) -> None:
        """Adopt a wider vertex bucket MID-STREAM: the carried slabs
        re-lay out (ResidentState.grow), the donated programs rebuild
        at the new shapes, and the live tuner RE-KEYS instead of being
        discarded (ops/autotune.DispatchTuner.rekey) — the incumbent
        windows-per-superbatch survives as the prior and the persisted
        cache re-seeds the new key, so O(log V) bucket doublings never
        reset the learned dispatch configuration (the ISSUE-9
        arm-freezing fix, pinned by
        tests/operations/test_resident.py)."""
        new_vb = seg_ops.bucket_size(vertex_bucket)
        if new_vb <= self.vb:
            return
        grown = ResidentState.grow(self.resident_state(), self.vb,
                                   new_vb)
        old_eb, old_kb = self.eb, self.kb
        cursor = self.windows_done
        closed = self._closed_partial
        tuner = getattr(self, "_tuner", None)
        timers = self.stage_timers
        ck_path, ck_policy = self._ckpt_path, self._ckpt_policy
        # an explicit construction-time pin survives the rebuild (the
        # A/B tools must keep measuring the wire they pinned) — unless
        # the pinned compact wire turned lossy at the new bucket, in
        # which case the pin degrades to standard rather than raising
        pin = self.ingress if getattr(self, "_pinned_ingress",
                                      False) else None
        if pin == "compact" and not compact_ingress.supports(new_vb):
            pin = "standard"
        self.__init__(edge_bucket=old_eb, vertex_bucket=new_vb,
                      k_bucket=old_kb, ingress=pin,
                      superbatch=self._superbatch)
        self._carry = tuple(jnp.asarray(a) for a in grown)
        self.windows_done = cursor
        self._closed_partial = closed
        self.stage_timers = timers
        self._ckpt_path, self._ckpt_policy = ck_path, ck_policy
        if tuner is not None:
            # re-key-instead-of-discard (the driver's _ensure_buckets
            # discipline): learned state carries into the new identity.
            # The ingress arm re-pins to the REBUILT engine's wire
            # format — growing past the uint16 ceiling switches the
            # fused decode to standard, and a surviving compact arm
            # would be lossy at the new bucket.
            wbm = self.MAX_WINDOWS
            wbs = sorted({max(1, wbm // 4), max(1, wbm // 2), wbm})
            inc = dict(tuner.incumbent)
            if inc.get("wb") not in wbs:
                inc["wb"] = wbm
            inc["ingress"] = self.ingress
            tuner.rekey(
                "%s:eb=%d:vb=%d" % (self.TUNER_FAMILY, self.eb,
                                    self.vb),
                space={"wb": wbs, "ingress": [self.ingress]},
                initial=inc)
            self._tuner = tuner

"""Pallas prototype of the dominant sparse kernel: per-edge sorted-row
intersection counting (the `intersect_local` compare of
ops/triangles.py:77-121, lowering WindowTriangles.java:61-140).

The XLA lowering is a chunked broadcast equality compare whose
[Ep, chunk, K] hit tensor is fused into its `any`-reduce by XLA. This
kernel makes the fusion explicit: each grid step owns a TILE_E-edge
slice, keeps the pre-gathered neighbor rows in VMEM, runs the K×K
compare chunk loop entirely in registers/VMEM, and writes ONE partial
count per tile — no intermediate ever exists in HBM.

The row gather (nbr[ea], nbr[eb]) stays in XLA outside the kernel:
dynamic row gathers from HBM inside a Pallas kernel would serialize
into per-edge DMAs, and XLA's gather is already bandwidth-optimal.
What the kernel can win is the compare loop's scheduling; what it can
lose is XLA's fusion of the gather INTO the compare (which skips the
[Ep, K] rows_a/rows_b round trip to HBM entirely). tools/
profile_kernels.py measures both on-chip; ops/triangles.py keeps
whichever the committed PERF.json says wins (see PERF.md).

On non-TPU backends the kernel runs in interpreter mode (virtual CPU
mesh tests), keeping behavior identical everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_triangles import _need_interpret

TILE_E = 64      # edges per grid step: the [T, CHUNK_K, K] broadcast
                 # compare materializes in VMEM, so T=64/Ck=128/K<=256
                 # stays under the 16M scoped-vmem limit (T=256 OOMs)
CHUNK_K = 128    # compare-chunk width (lane-aligned)
MAX_TILES = 2048 # grid steps per pallas_call: the [g] partial vector
                 # lives wholly in SMEM (scarce scalar memory), so cap
                 # it at 8KB and slab larger edge buckets over several
                 # calls (each slab shape is identical -> one compile)


def _intersect_kernel(ra, rb, va, out):
    """ra/rb: [TILE_E, K] int32 neighbor rows; va: [TILE_E, K] bool
    validity of ra entries (sentinel/padding pre-masked). out: [g]
    int32 partial counts in SMEM — the whole array is the block (a
    size-1 block per step is not lowerable on TPU), each grid step
    writes its own slot."""
    k = ra.shape[1]
    rb_val = rb[:]                                # [T, K] in VMEM
    total = jnp.int32(0)
    for c in range(-(-k // CHUNK_K)):
        ck = min(CHUNK_K, k - c * CHUNK_K)
        a_chunk = ra[:, pl.ds(c * CHUNK_K, ck)]   # [T, Ck]
        v_chunk = va[:, pl.ds(c * CHUNK_K, ck)]
        hit = jnp.any(
            a_chunk[:, :, None] == rb_val[:, None, :], axis=2)  # [T, Ck]
        total += jnp.sum(jnp.where(hit & v_chunk, 1, 0),
                         dtype=jnp.int32)
    out[pl.program_id(0)] = total


@functools.partial(jax.jit, static_argnames=("interpret",))
def _intersect_tiles(rows_a: jax.Array, rows_b: jax.Array,
                     valid: jax.Array, interpret: bool) -> jax.Array:
    ep, k = rows_a.shape
    assert ep % TILE_E == 0, (ep, TILE_E)
    g = ep // TILE_E
    return pl.pallas_call(
        _intersect_kernel,
        grid=(g,),
        in_specs=[
            pl.BlockSpec((TILE_E, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_E, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE_E, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        # One scalar per grid step. A PER-STEP size-1 output block
        # ((1,) block over a (g,) array, g>1) is not lowerable on TPU
        # in any memory space; a block whose size EQUALS the array dim
        # is always legal (this also covers g==1). So expose the whole
        # [g] vector as one SMEM block and index by program_id.
        out_specs=pl.BlockSpec((g,), lambda i: (0,),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((g,), jnp.int32),
        interpret=interpret,
    )(rows_a, rows_b, valid)


def intersect_local_pallas(nbr: jax.Array, ea: jax.Array, eb: jax.Array,
                           emask: jax.Array) -> jax.Array:
    """Drop-in for ops/triangles.intersect_local (same contract: count
    of |N_out(a) ∩ N_out(b)| over all valid oriented edges)."""
    sentinel = nbr.shape[0] - 1
    ep = ea.shape[0]
    slab_e = MAX_TILES * TILE_E
    pad = (-ep) % (TILE_E if ep <= slab_e else slab_e)
    if pad:
        ea = jnp.concatenate([ea, jnp.full(pad, sentinel, ea.dtype)])
        eb = jnp.concatenate([eb, jnp.full(pad, sentinel, eb.dtype)])
        emask = jnp.concatenate([emask, jnp.zeros(pad, emask.dtype)])
    interpret = _need_interpret()
    total = jnp.int32(0)
    for s in range(0, ea.shape[0], slab_e):
        rows_a = nbr[ea[s:s + slab_e]]
        rows_b = nbr[eb[s:s + slab_e]]
        valid = (rows_a < sentinel) & emask[s:s + slab_e, None]
        partials = _intersect_tiles(rows_a, rows_b, valid, interpret)
        total = total + jnp.sum(partials, dtype=jnp.int32)
    return total

"""Pallas prototype of the dominant sparse kernel: per-edge sorted-row
intersection counting (the `intersect_local` compare of
ops/triangles.py:77-121, lowering WindowTriangles.java:61-140).

The XLA lowering is a chunked broadcast equality compare whose
[Ep, chunk, K] hit tensor is fused into its `any`-reduce by XLA. This
kernel makes the fusion explicit: each grid step owns a TILE_E-edge
slice, keeps the pre-gathered neighbor rows in VMEM, runs the K×K
compare chunk loop entirely in registers/VMEM, and writes ONE partial
count per tile — no intermediate ever exists in HBM.

The row gather (nbr[ea], nbr[eb]) stays in XLA outside the kernel:
dynamic row gathers from HBM inside a Pallas kernel would serialize
into per-edge DMAs, and XLA's gather is already bandwidth-optimal.
What the kernel can win is the compare loop's scheduling; what it can
lose is XLA's fusion of the gather INTO the compare (which skips the
[Ep, K] rows_a/rows_b round trip to HBM entirely). tools/
profile_kernels.py measures both on-chip; ops/triangles.py keeps
whichever the committed PERF.json says wins (see PERF.md).

On non-TPU backends the kernel runs in interpreter mode (virtual CPU
mesh tests), keeping behavior identical everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_triangles import _need_interpret

TILE_E = 64      # default edges per grid step: the [T, CHUNK_K, K]
                 # broadcast compare materializes in VMEM, so
                 # T=64/Ck=128/K<=256 stays under the 16M scoped-vmem
                 # limit (T=256 OOMs). The SHIPPED shape is a measured
                 # selection — see _resolve_tile.
CHUNK_K = 128    # default compare-chunk width (lane-aligned)
MAX_TILES = 2048 # grid steps per pallas_call: the [g] partial vector
                 # lives wholly in SMEM (scarce scalar memory), so cap
                 # it at 8KB and slab larger edge buckets over several
                 # calls (each slab shape is identical -> one compile)

_TILE_CHOICE = None  # (tile_e, chunk_k), resolved once per process


def _resolve_tile():
    """The (TILE_E, CHUNK_K) shape intersect_local_pallas ships:
    the best parity-true row of the committed chip tile sweep
    (PERF.json `intersect.pallas_sweep`, tools/profile_kernels.py
    section_intersect) when one exists, else the module defaults —
    the same committed-evidence policy as every other kernel
    selection."""
    global _TILE_CHOICE
    if _TILE_CHOICE is not None:
        return _TILE_CHOICE
    choice = (TILE_E, CHUNK_K)
    try:
        from .triangles import _load_tpu_perf

        perf = _load_tpu_perf()
        rows = [r for r in ((perf or {}).get("intersect", {})
                            .get("pallas_sweep", []) or [])
                if r.get("parity") is True and r.get("ms")
                and r.get("tile_e") and r.get("chunk_k")]
        if rows:
            best = min(rows, key=lambda r: r["ms"])
            choice = (int(best["tile_e"]), int(best["chunk_k"]))  # gslint: disable=host-sync (committed-evidence JSON ints, no device value in sight)
    except Exception:  # gslint: disable=except-hygiene (committed-evidence probe: absence/corruption selects the proven default)
        pass
    _TILE_CHOICE = choice
    return choice


def tile_intersect_count(ra, rb, va, chunk_k: int):
    """The seed kernel's inner compare loop on one pre-gathered tile
    pair — ra/rb: [T, K] int32 neighbor rows, va: [T, K] bool validity
    of ra entries (sentinel/padding pre-masked) — as a plain traceable
    function, so the fused window megakernel (ops/pallas_window.py)
    runs the IDENTICAL K-bucket intersection inside its own
    pallas_call: one [T, Ck, K] broadcast-equality chunk at a time,
    never materializing more than a chunk in VMEM. Rows are
    deduplicated, so each ra entry matches at most one rb entry and
    the `any` over the compare axis counts it exactly once. Returns
    the int32 tile total."""
    k = ra.shape[1]
    total = jnp.int32(0)
    for c in range(-(-k // chunk_k)):
        ck = min(chunk_k, k - c * chunk_k)
        a_chunk = ra[:, c * chunk_k:c * chunk_k + ck]   # [T, Ck]
        v_chunk = va[:, c * chunk_k:c * chunk_k + ck]
        hit = jnp.any(
            a_chunk[:, :, None] == rb[:, None, :], axis=2)
        total += jnp.sum(jnp.where(hit & v_chunk, 1, 0),
                         dtype=jnp.int32)
    return total


def _make_kernel(chunk_k: int):
    def _intersect_kernel(ra, rb, va, out):
        """ra/rb: [T, K] int32 neighbor rows; va: [T, K] bool validity
        of ra entries (sentinel/padding pre-masked). out: [g] int32
        partial counts in SMEM — the whole array is the block (a
        size-1 block per step is not lowerable on TPU), each grid step
        writes its own slot. The compare math lives in
        tile_intersect_count, shared with the window megakernel."""
        out[pl.program_id(0)] = tile_intersect_count(
            ra[:], rb[:], va[:], chunk_k)

    return _intersect_kernel


@functools.partial(jax.jit,
                   static_argnames=("interpret", "tile_e", "chunk_k"))
def _intersect_tiles(rows_a: jax.Array, rows_b: jax.Array,
                     valid: jax.Array, interpret: bool,
                     tile_e: int = TILE_E,
                     chunk_k: int = CHUNK_K) -> jax.Array:
    ep, k = rows_a.shape
    assert ep % tile_e == 0, (ep, tile_e)
    g = ep // tile_e
    return pl.pallas_call(
        _make_kernel(chunk_k),
        grid=(g,),
        in_specs=[
            pl.BlockSpec((tile_e, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_e, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_e, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        # One scalar per grid step. A PER-STEP size-1 output block
        # ((1,) block over a (g,) array, g>1) is not lowerable on TPU
        # in any memory space; a block whose size EQUALS the array dim
        # is always legal (this also covers g==1). So expose the whole
        # [g] vector as one SMEM block and index by program_id.
        out_specs=pl.BlockSpec((g,), lambda i: (0,),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((g,), jnp.int32),
        interpret=interpret,
    )(rows_a, rows_b, valid)


def intersect_local_pallas(nbr: jax.Array, ea: jax.Array, eb: jax.Array,
                           emask: jax.Array, tile_e: int = None,
                           chunk_k: int = None) -> jax.Array:
    """Drop-in for ops/triangles.intersect_local (same contract: count
    of |N_out(a) ∩ N_out(b)| over all valid oriented edges). The tile
    shape defaults to the committed chip sweep's winner
    (_resolve_tile); the profiler passes explicit shapes to sweep."""
    if tile_e is None or chunk_k is None:
        rt, rc = _resolve_tile()
        tile_e = rt if tile_e is None else tile_e
        chunk_k = rc if chunk_k is None else chunk_k
    sentinel = nbr.shape[0] - 1
    ep = ea.shape[0]
    slab_e = MAX_TILES * tile_e
    pad = (-ep) % (tile_e if ep <= slab_e else slab_e)
    if pad:
        ea = jnp.concatenate([ea, jnp.full(pad, sentinel, ea.dtype)])
        eb = jnp.concatenate([eb, jnp.full(pad, sentinel, eb.dtype)])
        emask = jnp.concatenate([emask, jnp.zeros(pad, emask.dtype)])
    interpret = _need_interpret()
    total = jnp.int32(0)
    for s in range(0, ea.shape[0], slab_e):
        rows_a = nbr[ea[s:s + slab_e]]
        rows_b = nbr[eb[s:s + slab_e]]
        valid = (rows_a < sentinel) & emask[s:s + slab_e, None]
        partials = _intersect_tiles(rows_a, rows_b, valid, interpret,
                                    tile_e, chunk_k)
        total = total + jnp.sum(partials, dtype=jnp.int32)
    return total

"""Online dispatch autotuner: find the fast (windows-per-dispatch,
K bucket, ingress format) configuration ON the stream actually running.

Every dispatch knob used to be a STATIC committed-evidence gate read
from PERF.json at import (ops/triangles._tuned_chunk/_tuned_kb/
resolve_ingress): right for reproducibility, wrong for a stream whose
load, skew, or tunnel latency differs from the profile stream — the
chip rows pin end-to-end rate at ~500-770K edges/s while the chunk
sweep was still climbing at the compile cap (PERF.md "Hot-kernel
profile"), i.e. the static pick amortizes dispatch latency worse than
the best live pick would. This module is the runtime's measured
selection loop:

- The search space is SMALL and SAFE by construction: every arm is a
  configuration today's kernels already run correctly (wb rungs within
  the compile cap, K rungs on the existing escalation ladder —
  exactness preserved by the overflow recount regardless of K — and
  the two parity-proven wire formats), so an arm change can alter
  TIMING only, never counts.
- Exploration is DETERMINISTIC epsilon-greedy: every
  `explore_period`-th measurement round tries the next single-knob
  move away from the incumbent (coordinate moves, round-robin); all
  other rounds exploit the incumbent. No randomness anywhere — reruns
  take identical decisions on identical timings.
- Promotion has HYSTERESIS: a challenger replaces the incumbent only
  when its smoothed (EMA) edges/s clears `margin` (default the
  repo-wide 1.05 adoption bar) over the incumbent's, so load noise
  cannot flap the configuration.
- The winner PERSISTS to a per-backend tuning cache
  (`~/.cache/gelly_streaming_tpu/tuning_<backend>.json`, override dir
  with GS_TUNE_CACHE) so the second run starts at the first run's
  optimum; the cache is advisory (corrupt/missing files are ignored)
  and seeds only arms inside the current space.

`GS_AUTOTUNE=0` disables everything: callers take their exact legacy
static-gate path, bit-identically (asserted by
tests/operations/test_autotune.py and the chaos autotune leg).

Pre-warm discipline: callers compile an arm's programs (AOT,
`_stream_exec`-style caches) BEFORE its first timed round, so
steady-state streaming still never compiles mid-measurement; arms
never exceed the per-program compile cap (ops/triangles.compile_cap).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from ..utils import knobs
from ..utils import telemetry

_DEF_MARGIN = 1.05        # the repo-wide measured-adoption bar
_EMA_ALPHA = 0.5          # smoothing of per-arm measured rates
_TIMELINE_CAP = 256       # bound per-tuner event history

_CACHE_LOCK = threading.Lock()


# ----------------------------------------------------------------------
# env knobs (read per call through the utils/knobs registry)
# ----------------------------------------------------------------------
def enabled() -> bool:
    """GS_AUTOTUNE=0 disables the online tuner process-wide; callers
    then run their legacy static-gate path bit-identically."""
    return knobs.get_bool("GS_AUTOTUNE")


def round_chunks() -> int:
    """Dispatch chunks per measurement round (GS_AUTOTUNE_ROUND,
    default 4). The engines run each round as ONE
    ingress_pipeline.run_pipeline call, whose worker pool and depth-2
    overlap only engage past a single item — a 1-chunk round would
    silently measure (and run) the synchronous form, so the default
    keeps several chunks in flight per round; lower it only for
    diagnosis."""
    return knobs.get_int("GS_AUTOTUNE_ROUND")


def explore_period() -> int:
    """Every Nth measurement round is an exploration round
    (GS_AUTOTUNE_EXPLORE, default 3); the rest exploit the
    incumbent."""
    return knobs.get_int("GS_AUTOTUNE_EXPLORE")


def cache_path(backend: str) -> str:
    """Per-backend tuning cache file. GS_TUNE_CACHE overrides the
    DIRECTORY (set it to "0" to disable persistence entirely)."""
    root = knobs.get_path("GS_TUNE_CACHE")
    if root == "0":
        return ""
    if not root:
        root = os.path.join(os.path.expanduser("~"), ".cache",
                            "gelly_streaming_tpu")
    return os.path.join(root, "tuning_%s.json" % backend)


def _backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # gslint: disable=except-hygiene (availability probe: cache filename only, never correctness)
        return "unknown"


def load_cached_best(key: str, backend: str = None) -> Optional[dict]:
    """The persisted best entry {"arm": {...}, "edges_per_s": float}
    for `key`, or None (missing/disabled/corrupt cache — all
    advisory)."""
    path = cache_path(backend or _backend())
    if not path:
        return None
    try:
        with open(path) as f:
            data = json.load(f)
        entry = data.get(key)
        if isinstance(entry, dict) and isinstance(entry.get("arm"),
                                                  dict):
            return entry
    except (OSError, ValueError):
        pass
    return None


def store_best(key: str, arm: dict, edges_per_s: float,
               backend: str = None) -> None:
    """Merge one key's best arm into the cache (atomic replace;
    best-effort — a read-only home never breaks a stream)."""
    path = cache_path(backend or _backend())
    if not path:
        return
    with _CACHE_LOCK:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                with open(path) as f:
                    data = json.load(f)
                if not isinstance(data, dict):
                    data = {}
            except (OSError, ValueError):
                data = {}
            data[key] = {"arm": dict(arm),
                         "edges_per_s": round(float(edges_per_s))}
            tmp = "%s.tmp.%d" % (path, os.getpid())
            with open(tmp, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass


# ----------------------------------------------------------------------
# the tuner
# ----------------------------------------------------------------------
def _akey(arm: dict) -> str:
    """Canonical JSON-able identity of an arm (dict key for EMAs and
    checkpoint state)."""
    return json.dumps(arm, sort_keys=True)


class DispatchTuner:
    """Deterministic epsilon-greedy coordinate search over a small knob
    space, with hysteresis and persistence (module docstring).

    space:   {knob: [ordered values]} — e.g.
             {"wb": [16, 32, 64], "ingress": ["standard", "compact"]}
    initial: the static-gate configuration (one value per knob; values
             must be in the space — the incumbent before any
             measurement, and the arm `GS_AUTOTUNE=0` would run).

    Protocol per measurement round:
        arm = tuner.next_round()      # caller pre-warms arm's programs
        ... run round_chunks dispatch chunks at `arm`, timed ...
        tuner.record(arm, edges, seconds)
    and once per stream: tuner.save().
    """

    def __init__(self, key: str, space: Dict[str, list], initial: dict,
                 margin: float = _DEF_MARGIN, backend: str = None):
        for k, v in initial.items():
            if k not in space or v not in space[k]:
                raise ValueError(
                    "initial %s=%r outside tuning space %r"
                    % (k, v, space.get(k)))
        self.key = key
        self.space = {k: list(vs) for k, vs in space.items()}
        self.margin = float(margin)
        self.backend = backend or _backend()
        self.incumbent = dict(initial)
        self._ema: Dict[str, float] = {}
        self._round = 0
        self._promotions = 0
        self._explore_cursor = 0
        self.timeline: List[dict] = []
        cached = load_cached_best(key, self.backend)
        if cached and self._in_space(cached["arm"]) \
                and cached["arm"] != self.incumbent:
            # the previous run's optimum: start there (the whole point
            # of persistence); it stays on probation like any incumbent
            self.incumbent = {k: cached["arm"][k] for k in self.space}
            self._event("cache_seed", self.incumbent, None)

    # -- helpers -------------------------------------------------------
    def _in_space(self, arm: dict) -> bool:
        return all(k in arm and arm[k] in vs
                   for k, vs in self.space.items())

    def _candidates(self) -> List[dict]:
        """Single-knob moves away from the incumbent, in deterministic
        knob-name order, nearest values first (down then up)."""
        out = []
        for k in sorted(self.space):
            vs = self.space[k]
            i = vs.index(self.incumbent[k])
            for j in (i - 1, i + 1):
                if 0 <= j < len(vs):
                    cand = dict(self.incumbent)
                    cand[k] = vs[j]
                    out.append(cand)
        return out

    def _event(self, action: str, arm: dict, rate) -> None:
        self.timeline.append({
            "round": self._round, "action": action, "arm": dict(arm),
            "edges_per_s": None if rate is None else round(rate)})
        if len(self.timeline) > _TIMELINE_CAP:
            del self.timeline[:len(self.timeline) - _TIMELINE_CAP]
        # every scheduler decision is a structured flight-recorder
        # event (promotions durably: a mid-stream configuration change
        # is exactly what a post-mortem must be able to date)
        telemetry.event("autotune." + action,
                        durable=action == "promote",
                        key=self.key, round=self._round,
                        arm=json.dumps(arm, sort_keys=True),
                        edges_per_s=None if rate is None
                        else round(rate))

    # -- protocol ------------------------------------------------------
    def next_round(self) -> dict:
        """The arm the next measurement round runs: the incumbent,
        except on every `explore_period()`-th round, where the next
        coordinate move is probed (round-robin over the candidate
        list). Deterministic in the round counter."""
        cands = self._candidates()
        if not cands or (self._round + 1) % explore_period():
            return dict(self.incumbent)
        arm = cands[self._explore_cursor % len(cands)]
        self._explore_cursor += 1
        return arm

    def record(self, arm: dict, edges: int, seconds: float) -> None:
        """Fold one round's measured rate into the arm's EMA; promote
        the arm over the incumbent only when its EMA clears the
        hysteresis margin (never on the first observation of a
        challenger — one lucky draw must not flip the config)."""
        if seconds <= 0 or edges <= 0:
            return
        rate = edges / seconds
        self._round += 1
        k = _akey(arm)
        seen = k in self._ema
        self._ema[k] = (rate if not seen
                        else (1 - _EMA_ALPHA) * self._ema[k]
                        + _EMA_ALPHA * rate)
        explored = arm != self.incumbent
        promoted = False
        inc_ema = self._ema.get(_akey(self.incumbent))
        if explored and seen and inc_ema is not None \
                and self._ema[k] >= self.margin * inc_ema:
            self.incumbent = dict(arm)
            self._promotions += 1
            self._explore_cursor = 0
            promoted = True
        self._event("promote" if promoted
                    else ("explore" if explored else "exploit"),
                    arm, rate)

    def rekey(self, key: str, space: Dict[str, list] = None,
              initial: dict = None) -> None:
        """Adopt a new cache identity mid-stream (bucket growth changed
        the shapes the rates were measured at): EMAs reset — they
        described the old shapes — while the incumbent survives as the
        prior (clamped to `initial` if the new `space` dropped it), and
        the new key's persisted best, if any, re-seeds it. Keeps the
        tuner object (and its checkpointed continuity) alive across
        O(log V) bucket doublings instead of discarding learned state."""
        self.key = key
        if space is not None:
            self.space = {k: list(vs) for k, vs in space.items()}
        if not self._in_space(self.incumbent):
            if initial is None or not self._in_space(initial):
                raise ValueError(
                    "rekey needs an in-space initial when the "
                    "incumbent %r left the space" % (self.incumbent,))
            self.incumbent = dict(initial)
        self._ema = {}
        self._explore_cursor = 0
        cached = load_cached_best(key, self.backend)
        if cached and self._in_space(cached["arm"]):
            self.incumbent = {k: cached["arm"][k] for k in self.space}
        self._event("rekey", self.incumbent, None)

    def best(self) -> dict:
        return dict(self.incumbent)

    def best_rate(self) -> Optional[float]:
        return self._ema.get(_akey(self.incumbent))

    def save(self) -> None:
        """Persist the incumbent (with its smoothed rate) so the next
        process seeds from it."""
        rate = self.best_rate()
        if rate:
            store_best(self.key, self.incumbent, rate, self.backend)

    # -- observability / checkpointing --------------------------------
    def summary(self) -> dict:
        """Provenance row for bench/profiler output: the chosen knobs
        plus the decision timeline (bounded)."""
        return {
            "key": self.key,
            "chosen": dict(self.incumbent),
            "rounds": self._round,
            "promotions": self._promotions,
            "edges_per_s_ema": (None if self.best_rate() is None
                                else round(self.best_rate())),
            "timeline": [dict(e) for e in self.timeline[-32:]],
        }

    def state_dict(self) -> dict:
        """JSON/npz-able tuning state (rides engine/driver checkpoints
        so a resumed stream keeps its learned configuration). The
        cache `key` is deliberately NOT state: it is the tuner's
        identity at its current buckets, and a resume after bucket
        growth restores the learned values into the new identity."""
        return {
            "incumbent": dict(self.incumbent),
            "ema": [[k, float(v)] for k, v in sorted(self._ema.items())],
            "round": int(self._round),
            "promotions": int(self._promotions),
            "explore_cursor": int(self._explore_cursor),
        }

    def load_state_dict(self, state: dict) -> None:
        """Adopt checkpointed tuning state; entries outside the current
        space are dropped (a resume across a code change must not pin
        an arm that no longer exists)."""
        inc = state.get("incumbent")
        if isinstance(inc, dict) and self._in_space(inc):
            self.incumbent = {k: inc[k] for k in self.space}
        self._ema = {str(k): float(v)
                     for k, v in state.get("ema", [])}
        self._round = int(state.get("round", 0))  # gslint: disable=host-sync (checkpoint payloads are host scalars, never device values)
        self._promotions = int(state.get("promotions", 0))  # gslint: disable=host-sync (checkpoint payloads are host scalars, never device values)
        self._explore_cursor = int(state.get("explore_cursor", 0))  # gslint: disable=host-sync (checkpoint payloads are host scalars, never device values)

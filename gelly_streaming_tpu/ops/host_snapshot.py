"""Pure-numpy host tier of the driver's batched snapshot analytics —
the LAST rung of the tier-demotion ladder (device scan → native C++ →
host numpy, core/driver._maybe_demote).

Same contract as native.snapshot_windows (which is itself shaped like
the device scan's `outs`): window w is the [offsets[w], offsets[w+1])
slice of the flat COO arrays; the caller-owned carried arrays
(`deg`/`cc`/`cov`, the driver's host-mirror layouts) are updated in
place; per-window int32 snapshot stacks come back as
{"deg": [W, vb], "labels": [W, vb], "cover": [W, 2·vb]}.

Bit-exactness across tiers is by CONSTRUCTION, not coincidence: the
carried min-label semantics (ops/unionfind.cc_fixpoint with
carried=True) converge to the canonical labeling — every vertex maps
to the smallest vertex index reachable through this window's edges
plus the carried forest links (v, labels0[v]) — which is unique
whatever the iteration schedule. `_fixpoint` below replays the same
scatter-min + root-hook + pointer-jump rounds in numpy, so checkpoints
and mid-stream demotions carry state across tiers without any
translation (the tier-interchangeability the checkpoint round-trip
suite pins).

This tier exists for availability, not speed: it needs no compiler, no
device, no libgsnative.so — only numpy. A stream that lands here is
degraded and LABELED as such (resilience.record_demotion →
PERF.json's `degradations` section); it is never a measurement tier.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


def _fixpoint(labels: np.ndarray, s: np.ndarray,
              d: np.ndarray) -> np.ndarray:
    """Carried min-label fixpoint (numpy twin of
    unionfind.cc_fixpoint(carried=True)): scatter-min each edge's
    smaller label to both endpoints and both endpoints' roots, then
    pointer-jump, until stable. The carried forest's parent links
    participate as edges (see cc_fixpoint's docstring for why dropping
    them can split a component)."""
    n = len(labels)
    src = np.concatenate([s, np.arange(n, dtype=np.int64)])
    dst = np.concatenate([d, labels.astype(np.int64)])
    while True:
        ls = labels[src]
        ld = labels[dst]
        m = np.minimum(ls, ld)
        new = labels.copy()
        np.minimum.at(new, src, m)
        np.minimum.at(new, dst, m)
        np.minimum.at(new, ls, m)
        np.minimum.at(new, ld, m)
        new = new[new]
        if np.array_equal(new, labels):
            return new
        labels = new


def snapshot_windows(src: np.ndarray, dst: np.ndarray,
                     offsets: np.ndarray, vb: int,
                     deg: Optional[np.ndarray] = None,
                     cc: Optional[np.ndarray] = None,
                     cov: Optional[np.ndarray] = None
                     ) -> Dict[str, np.ndarray]:
    """Host-tier carried-state windowed snapshots; see module
    docstring for the contract (identical to native.snapshot_windows).
    """
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    offsets = np.ascontiguousarray(offsets, np.int64)
    num_w = len(offsets) - 1
    if num_w < 0 or int(offsets[-1]) != len(src):
        raise ValueError("offsets must span the flat edge arrays")
    for name, a, ln in (("deg", deg, vb), ("cc", cc, vb),
                        ("cov", cov, 2 * vb)):
        if a is not None and (a.dtype != np.int32 or len(a) != ln):
            raise ValueError("carried %s must be int32[%d]"
                             % (name, ln))
    out: Dict[str, np.ndarray] = {}
    od = np.empty((num_w, vb), np.int32) if deg is not None else None
    oc = np.empty((num_w, vb), np.int32) if cc is not None else None
    ov = (np.empty((num_w, 2 * vb), np.int32)
          if cov is not None else None)
    for w in range(num_w):
        lo, hi = int(offsets[w]), int(offsets[w + 1])
        s, d = src[lo:hi], dst[lo:hi]
        if deg is not None:
            np.add.at(deg, s, 1)
            np.add.at(deg, d, 1)
            od[w] = deg
        if cc is not None:
            cc[:] = _fixpoint(cc, s, d)
            oc[w] = cc
        if cov is not None:
            cov[:] = _fixpoint(cov, np.concatenate([s, s + vb]),
                               np.concatenate([d + vb, d]))
            ov[w] = cov
    if od is not None:
        out["deg"] = od
    if oc is not None:
        out["labels"] = oc
    if ov is not None:
        out["cover"] = ov
    return out

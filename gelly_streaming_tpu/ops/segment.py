"""Segment (per-vertex) reduction kernels — the TPU lowering of the
reference's per-(vertex, window) incremental folds and reduces
(GraphWindowStream.java:62-121: `WindowedStream.fold/reduce` panes).

A window's edges arrive as COO arrays; grouping a vertex's neighborhood
is a segment reduction over edges sorted by vertex. Three tiers:

- named monoids (sum/min/max/count) → `jax.ops.segment_*`, fully parallel;
- arbitrary associative reduce fns → segmented scan over sorted edges;
- arbitrary (non-associative) fold fns → sequential-in-arrival-order
  segmented `lax.scan`, which reproduces the reference's per-pane fold
  semantics exactly (fold order = arrival order within each key).

All kernels take padded, power-of-two-bucketed shapes so XLA compiles a
small number of programs regardless of per-window edge counts
(SURVEY.md §7 "Hard parts: dynamic shapes").
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_MIN_BUCKET = 8


def bucket_size(n: int) -> int:
    """Next power-of-two ≥ n (min 8) — bounds XLA recompilation."""
    b = _MIN_BUCKET
    while b < n:
        b *= 2
    return b


def pad_to(arr: np.ndarray, size: int, fill=0) -> np.ndarray:
    if arr.shape[0] == size:
        return arr
    pad = np.full((size - arr.shape[0],) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


# ----------------------------------------------------------------------
# named-monoid fast path
# ----------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("num_segments", "kind"))
def segment_reduce(values: jax.Array, segment_ids: jax.Array, num_segments: int,
                   kind: str = "sum") -> jax.Array:
    """Parallel segment reduction. Padded elements must carry
    segment_id == num_segments - 1 reserved padding row, or a neutral value."""
    if kind == "sum":
        return jax.ops.segment_sum(values, segment_ids, num_segments)
    if kind == "min":
        return jax.ops.segment_min(values, segment_ids, num_segments)
    if kind == "max":
        return jax.ops.segment_max(values, segment_ids, num_segments)
    if kind == "count":
        return jax.ops.segment_sum(jnp.ones_like(values), segment_ids, num_segments)
    raise ValueError(kind)


@jax.jit
def degree_update(state: jax.Array, src: jax.Array,
                  dst: jax.Array) -> jax.Array:
    """Fold one window batch into a running degree vector on device
    (continuous-degree semantics of SimpleEdgeStream.java:465-482,
    emitted per window). src/dst are padded with sentinel id
    len(state)-1, whose slot absorbs the padding contributions."""
    return state.at[src].add(1).at[dst].add(1)


# ----------------------------------------------------------------------
# generic segmented fold (sequential within segment, parallel-free scan)
# ----------------------------------------------------------------------

def _fold_kernel(fold_fn: Callable, init_tree: Any, seg: jax.Array,
                 mask: jax.Array, fields: Tuple[jax.Array, ...],
                 num_segments: int):
    """Scan edges (sorted by segment, stable) carrying the accumulator;
    reset at segment starts; per-segment result = accumulator at the
    segment's last element."""
    n = seg.shape[0]
    is_start = jnp.concatenate(
        [jnp.array([True]), seg[1:] != seg[:-1]]
    )

    def body(acc, x):
        start, m, s_fields = x
        acc = jax.tree_util.tree_map(
            lambda i, a: jnp.where(start, i, a), init_tree, acc
        )
        new_acc = fold_fn(acc, *s_fields)
        new_acc = jax.tree_util.tree_map(
            lambda nv, a: jnp.where(m, nv, a), new_acc, acc
        )
        return new_acc, new_acc

    _, accs = jax.lax.scan(body, init_tree, (is_start, mask, fields))
    # index of last (masked-valid) element in each segment
    idx = jnp.arange(n)
    last_idx = jax.ops.segment_max(
        jnp.where(mask, idx, -1), seg, num_segments + 1
    )[:num_segments]
    has_any = last_idx >= 0
    safe_idx = jnp.maximum(last_idx, 0)
    result = jax.tree_util.tree_map(lambda a: a[safe_idx], accs)
    return result, has_any


@functools.lru_cache(maxsize=256)
def _jit_fold(fold_fn, num_segments_bucket):
    @jax.jit
    def run(init_tree, seg, mask, fields):
        return _fold_kernel(fold_fn, init_tree, seg, mask, fields,
                            num_segments_bucket)
    return run


def segmented_fold(fold_fn: Callable, init_tree: Any, segment_ids: np.ndarray,
                   fields: Tuple[np.ndarray, ...], num_segments: int):
    """Host wrapper: pads, buckets, runs the jitted scan fold.

    fold_fn(acc_tree, *field_scalars) -> acc_tree, jax-traceable.
    Returns (result_tree_stacked[num_segments], has_any[num_segments]).
    """
    n = segment_ids.shape[0]
    nb = bucket_size(n)
    sb = bucket_size(num_segments)
    seg = pad_to(np.asarray(segment_ids, np.int32), nb, fill=sb)
    mask = pad_to(np.ones(n, bool), nb, fill=False)
    fpad = tuple(pad_to(np.asarray(f), nb) for f in fields)
    init = jax.tree_util.tree_map(jnp.asarray, init_tree)
    result, has_any = _jit_fold(fold_fn, sb)(init, seg, mask, fpad)
    return (
        jax.tree_util.tree_map(lambda a: np.asarray(a[:num_segments]), result),
        np.asarray(has_any[:num_segments]),
    )


@functools.lru_cache(maxsize=256)
def _jit_assoc_reduce(reduce_fn, num_segments_bucket):
    @jax.jit
    def run(seg, mask, vals):
        n = seg.shape[0]
        flags = jnp.concatenate(
            [jnp.array([True]), seg[1:] != seg[:-1]])

        def comb(a, b):
            fa, va = a
            fb, vb = b
            # segment-start flag resets the running combine: classic
            # flagged associative scan (requires reduce_fn associative)
            return fa | fb, jnp.where(fb, vb, reduce_fn(va, vb))

        _, scanned = jax.lax.associative_scan(comb, (flags, vals))
        idx = jnp.arange(n)
        last_idx = jax.ops.segment_max(
            jnp.where(mask, idx, -1), seg, num_segments_bucket + 1
        )[:num_segments_bucket]
        has_any = last_idx >= 0
        return scanned[jnp.maximum(last_idx, 0)], has_any

    return run


def segmented_reduce_associative(reduce_fn: Callable,
                                 segment_ids: np.ndarray,
                                 values: np.ndarray, num_segments: int):
    """Per-segment reduce for a user fn DECLARED associative: a flagged
    `lax.associative_scan` runs in O(log E) parallel steps instead of
    the O(E) sequential pane scan of `segmented_reduce` — the fast tier
    between named monoids and arbitrary fns (the combine tree reorders
    the applications, which is exactly what associativity licenses).
    Same contract as segmented_reduce: segment_ids sorted (stable),
    returns (results[num_segments], has_any[num_segments])."""
    values = np.asarray(values)
    n = segment_ids.shape[0]
    nb = bucket_size(n)
    sb = bucket_size(num_segments)
    seg = pad_to(np.asarray(segment_ids, np.int32), nb, fill=sb)
    mask = pad_to(np.ones(n, bool), nb, fill=False)
    vals = pad_to(values, nb)
    res, has_any = _jit_assoc_reduce(reduce_fn, sb)(
        jnp.asarray(seg), jnp.asarray(mask), jnp.asarray(vals))
    return (np.asarray(res[:num_segments]),
            np.asarray(has_any[:num_segments]))


def segmented_reduce(reduce_fn: Callable, segment_ids: np.ndarray,
                     values: np.ndarray, num_segments: int):
    """Generic per-segment reduce of edge values in arrival order
    (reference: EdgesReduceFunction, GraphWindowStream.java:107-121).

    Implemented as a segmented fold seeded from the first element:
    acc = (value, seen); fold(acc, v) = reduce(acc, v) if seen else v.
    """
    values = np.asarray(values)
    init = (jnp.zeros((), jnp.asarray(values).dtype), jnp.zeros((), jnp.bool_))

    def fold(acc, v):
        val, seen = acc
        return (jnp.where(seen, reduce_fn(val, v), v), jnp.ones((), jnp.bool_))

    (res, _seen), has_any = segmented_fold(
        fold, init, segment_ids, (values,), num_segments
    )
    return res, has_any


def warm_stream_buckets(kernel) -> None:
    """AOT-compile every stream-chunk program a window kernel's
    _run_stack can dispatch at its current configuration — the full
    MAX_STREAM_WINDOWS chunk and each power-of-two ragged window
    bucket — via the kernel's own `_stream_exec(wb)` executable cache.
    Compile-only: nothing executes, so warming costs compile time, not
    a full-size dispatch per bucket. Shared by
    TriangleWindowKernel.warm_chunks and
    ShardedTriangleWindowKernel.warm_chunks so both kernels always
    warm the same program set."""
    sizes = {kernel.MAX_STREAM_WINDOWS}
    w = bucket_size(1)
    while w < kernel.MAX_STREAM_WINDOWS:
        sizes.add(w)
        w *= 2
    for w in sorted(sizes):
        kernel._stream_exec(w)


def window_stack(src: np.ndarray, dst: np.ndarray, eb: int,
                 sentinel: int):
    """Pad a COO stream to whole `eb`-sized windows and reshape to
    [W, eb] stacks plus the validity mask — the shared layout of every
    batched window dispatch (triangles.count_stream, sharded
    count_stream, scan_analytics.process)."""
    src = np.asarray(src, np.int32)  # gslint: disable=host-sync (host-input normalization: stream payloads are numpy, never device values)
    dst = np.asarray(dst, np.int32)  # gslint: disable=host-sync (host-input normalization: stream payloads are numpy, never device values)
    n = len(src)
    num_w = -(-n // eb)
    s = pad_to(src, num_w * eb, fill=sentinel).reshape(num_w, eb)
    d = pad_to(dst, num_w * eb, fill=sentinel).reshape(num_w, eb)
    valid = pad_to(np.ones(n, bool), num_w * eb,
                   fill=False).reshape(num_w, eb)
    return num_w, s, d, valid


def stack_window_list(windows, eb: int, sentinel: int):
    """Pad a list of (src, dst) window batches of varying lengths
    (each ≤ eb) into [W, eb] stacks + validity mask — shared by the
    single-chip and sharded count_windows batched dispatches."""
    num_w = len(windows)
    s = np.full((num_w, eb), sentinel, np.int32)
    d = np.full((num_w, eb), sentinel, np.int32)
    valid = np.zeros((num_w, eb), bool)
    for w, (ws, wd) in enumerate(windows):
        n = len(ws)
        if n > eb:
            raise ValueError(f"window of {n} edges exceeds edge "
                             f"bucket {eb}")
        s[w, :n] = ws
        d[w, :n] = wd
        valid[w, :n] = True
    return s, d, valid


def stack_window_rows(pairs, wb: int, eb: int, sentinel: int):
    """Pack a chunk's dense (src, dst) window arrays into [wb, eb]
    stacks + validity mask (rows past len(pairs) stay all-sentinel) —
    the driver's snapshot-scan chunk prep, factored here so the
    ingress pipeline can run it on the prep worker pool
    (ops/ingress_pipeline) while the previous chunk executes on
    device."""
    s_w = np.full((wb, eb), sentinel, np.int32)
    d_w = np.full((wb, eb), sentinel, np.int32)
    valid = np.zeros((wb, eb), bool)
    for i, (s, d) in enumerate(pairs):
        s_w[i, :len(s)] = s
        d_w[i, :len(d)] = d
        valid[i, :len(s)] = True
    return s_w, d_w, valid


def pad_window_chunk(s, d, valid, at: int, hi: int, max_w: int,
                     eb: int, sentinel: int):
    """Slice [at:hi] of a [W, eb] stack and pad the window axis to a
    power-of-two bucket (≤ max_w) with all-invalid rows, so ragged
    final chunks reuse O(log max_w) compiled programs. Returns
    (s, d, valid, n) with n = the real window count."""
    n = hi - at
    wb = min(bucket_size(n), max_w)
    if n == wb:  # full chunk (the steady state): zero-copy views
        return s[at:hi], d[at:hi], valid[at:hi], n
    sc = np.full((wb, eb), sentinel, np.int32)
    dc = np.full((wb, eb), sentinel, np.int32)
    vc = np.zeros((wb, eb), bool)
    sc[:n], dc[:n], vc[:n] = s[at:hi], d[at:hi], valid[at:hi]
    return sc, dc, vc, n


# ----------------------------------------------------------------------
# vertex interning (dense ids for device kernels)
# ----------------------------------------------------------------------

def intern(*id_arrays: np.ndarray):
    """Map arbitrary numeric vertex ids in the given arrays to dense
    0..V-1 ints (SURVEY.md §7 'vertex-id interning'). Returns
    (unique_ids, [dense_arrays...])."""
    stacked = np.concatenate([np.asarray(a) for a in id_arrays])
    uniq, inv = np.unique(stacked, return_inverse=True)
    out = []
    off = 0
    for a in id_arrays:
        n = np.asarray(a).shape[0]
        out.append(inv[off:off + n].astype(np.int32))
        off += n
    return uniq, out

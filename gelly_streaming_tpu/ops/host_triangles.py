"""Vectorized HOST (numpy) window triangle kernel — the CPU-backend
tier of the streaming window counter.

Same exact algorithm as the device kernel (ops/triangles.py
build_window_counter): drop self-loops, undirect + dedupe, orient
low(deg, id) → high(deg, id), then count each triangle once at its
min-rank edge via out-neighbor intersection. The intersection here is
wedge enumeration + one vectorized searchsorted into the sorted edge
keys instead of the device's K-bucketed row compare: on a CPU backend
every XLA dispatch runs the same single core numpy uses, but pays two
O(E log E) lax.sorts, segment scatters and fixed-shape padding per
window — the numpy form does one argsort and touches only real edges,
so it wins the CPU-fallback regime outright (committed PERF.json
`host_stream` section; selection is backend-matched and measured, like
every kernel choice in this package — ops/triangles.py
`_resolve_stream_impl`).

The chip path is untouched: on a TPU backend the device kernel always
stands (dispatch cost amortizes over the stream, and the MXU/VPU do
the intersection orders of magnitude faster than the host).

Counts match the reference pipeline (WindowTriangles.java:61-66,
:83-140) exactly — asserted against the device kernel and the golden
ITCase totals in tests/library/test_triangles.py.
"""

from __future__ import annotations

import numpy as np

# wedge-enumeration slice cap: bounds peak memory of the repeat/searchsorted
# arrays (~5 int64 arrays of this length) regardless of window skew
_WEDGE_CHUNK = 4 << 20


def window_count(src: np.ndarray, dst: np.ndarray) -> int:
    """Exact triangle count of one window (any integer vertex ids:
    dense non-negative ids index directly; negative or huge ids — where
    v² would overflow the packed int64 keys — are first compressed to
    dense slots, the same split the native tier makes)."""
    s = np.asarray(src, np.int64)
    d = np.asarray(dst, np.int64)
    keep = s != d
    s, d = s[keep], d[keep]
    if len(s) == 0:
        return 0
    if int(s.min()) < 0 or int(d.min()) < 0 \
            or int(max(s.max(), d.max())) >= (1 << 31):
        uniq, inv = np.unique(np.concatenate([s, d]),
                              return_inverse=True)
        s, d = inv[:len(s)].astype(np.int64), inv[len(s):].astype(
            np.int64)
    v = int(max(s.max(), d.max())) + 1

    # undirect + dedupe on packed keys
    lo = np.minimum(s, d)
    hi = np.maximum(s, d)
    und = np.unique(lo * v + hi)
    lo, hi = und // v, und % v

    # (degree, id) orientation — identical tie-break to
    # triangles.orient_by_degree, so per-source out-degree is O(sqrt E)
    deg = np.bincount(lo, minlength=v) + np.bincount(hi, minlength=v)
    swap = (deg[lo] > deg[hi]) | ((deg[lo] == deg[hi]) & (lo > hi))
    a = np.where(swap, hi, lo)
    b = np.where(swap, lo, hi)

    # sort by (a, b): one argsort of packed keys; CSR starts by cumsum
    keys = a * v + b
    order = np.argsort(keys, kind="stable")
    a, b, keys = a[order], b[order], keys[order]
    e = len(a)
    cnt = np.bincount(a, minlength=v)
    starts = np.zeros(v + 1, np.int64)
    np.cumsum(cnt, out=starts[1:])

    # wedge enumeration: for each oriented edge (a,b) and each
    # x in N_out(a), the triangle {a,b,x} exists iff the oriented edge
    # (b,x) is present — one searchsorted probe into the sorted keys.
    # (A triangle is counted exactly once: at its min-rank edge, by its
    # third vertex — the same invariant as the device kernel.)
    wedge_cnt = cnt[a]                       # out_deg(a) per edge
    wedge_starts = np.zeros(e + 1, np.int64)
    np.cumsum(wedge_cnt, out=wedge_starts[1:])
    total = int(wedge_starts[-1])
    count = 0
    # slice by EDGE ranges so each slice's wedges stay contiguous
    lo_e = 0
    while lo_e < e:
        hi_e = int(np.searchsorted(wedge_starts,
                                   wedge_starts[lo_e] + _WEDGE_CHUNK,
                                   side="left"))
        hi_e = max(hi_e - 1, lo_e + 1)
        hi_e = min(hi_e, e)
        n_w = int(wedge_starts[hi_e] - wedge_starts[lo_e])
        if n_w:
            eidx = np.repeat(np.arange(lo_e, hi_e),
                             wedge_cnt[lo_e:hi_e])
            off = (np.arange(n_w) + wedge_starts[lo_e]
                   - wedge_starts[eidx])
            x = b[starts[a[eidx]] + off]
            q = b[eidx] * v + x
            pos = np.searchsorted(keys, q)
            hit = keys[np.minimum(pos, e - 1)] == q
            count += int(hit.sum())
        lo_e = hi_e
    return count


def count_stream(src: np.ndarray, dst: np.ndarray, eb: int) -> list:
    """Exact counts of every tumbling eb-sized window of the stream —
    the host form of TriangleWindowKernel.count_stream (same window
    boundaries, same counts). Windows are independent, so they count
    in parallel across the ingress prep pool
    (ops/ingress_pipeline.map_ordered — numpy's argsort/searchsorted
    cores drop the GIL); results return in window order, identical at
    every pool size."""
    from . import ingress_pipeline

    src = np.asarray(src)
    dst = np.asarray(dst)
    return ingress_pipeline.map_ordered(
        lambda at: window_count(src[at:at + eb], dst[at:at + eb]),
        range(0, len(src), eb))


def count_windows(windows) -> list:
    """Exact counts of explicit (src, dst) window batches — the host
    form of TriangleWindowKernel.count_windows (same per-window pool
    parallelism as count_stream)."""
    from . import ingress_pipeline

    return ingress_pipeline.map_ordered(
        lambda w: window_count(w[0], w[1]), windows)

"""Compact h2d ingress for batched window dispatches.

The standard stream-chunk format (seg_ops.window_stack →
TriangleWindowKernel._run_stack) ships 9 bytes per edge-slot to the
device: src int32 + dst int32 + valid bool. Two structural facts make
a 4-bytes/slot form lossless:

  1. vertex ids fit uint16 whenever the vertex bucket ≤ 65536 (every
     bench scale, and any interned window of ≤64K distinct vertices);
  2. padding is always a per-window SUFFIX (window_stack /
     stack_window_list fill tails), so the [wb, eb] bool mask is
     reconstructible on device from ONE int32 valid-count per window.

The device side widens uint16 → int32 and rebuilds (valid, sentinel)
with a VPU-cheap `where` before the unchanged window program — same
counts, 2.25× fewer h2d bytes. On the tunneled chip the end-to-end
stream rate is transfer/dispatch bound (PERF.md "VERIFIED chip rows"),
so ingress bytes are directly on the critical path; on real
deployments this is the PCIe/DCN ingest-bandwidth lever.

Adoption is evidence-gated like every other selection
(ops/triangles.py `_resolve_*` family): the kernel only switches to
compact ingress when a committed backend-matched `ingress_ab` row
(tools/ingress_ab.py) shows parity and a ≥5% end-to-end win.

Design provenance: the reference streams edges as (int,int) tuples
through Flink's network stack (SimpleEdgeStream.java:60-90); the
columnar re-design makes the wire format an explicit, measurable
choice.
"""

import numpy as np

MAX_U16_VB = 65536  # ids ≤ 65535 fit; the sentinel is rebuilt on device


def supports(vb: int) -> bool:
    """Compact ingress is lossless iff every REAL id < 65536; padded
    slots carry zeros and are masked by the rebuilt valid mask."""
    return vb <= MAX_U16_VB


def validate_ids(src: np.ndarray, dst: np.ndarray, bound: int,
                 what: str = "compact ingress") -> None:
    """Raise ValueError for any id the uint16 cast would WRAP
    (negatives, and ids ≥ min(bound, 65536)) — the one wrap-safety
    check every compact consumer runs on the MAIN thread before its
    pipeline (so callers see the same ValueError the other tiers
    raise, never a pooled-prep RuntimeError). `bound` is the caller's
    own id range (e.g. the reduce engine's vbp); the uint16 ceiling is
    applied on top, so a vb=65536 consumer whose nominal range reaches
    65536 still rejects the one unrepresentable id loudly."""
    if len(src) == 0 and len(dst) == 0:
        return
    top = int(max(src.max(), dst.max()))  # gslint: disable=host-sync (host-input wrap-safety check: callers pass numpy, never device values)
    bot = int(min(src.min(), dst.min()))  # gslint: disable=host-sync (host-input wrap-safety check: callers pass numpy, never device values)
    limit = min(bound, MAX_U16_VB)
    if bot < 0 or top >= limit:
        raise ValueError(
            "vertex id %d outside [0, %d) in %s input"
            % (bot if bot < 0 else top, limit, what))


def widen_stack(src16, dst16, nvalid, eb: int, sentinel: int):
    """The ONE device-side decode of the compact wire format
    (jax-traceable): rebuild the per-window suffix mask from the valid
    counts and widen uint16 ids to int32 with `sentinel` in the padded
    slots. Returns (s, d, valid), each [wb, eb]. Every compact
    consumer (the triangle stream program, the fused scan, the
    windowed-reduce stack program) decodes through here, so a format
    change cannot silently diverge between them."""
    import jax.numpy as jnp

    pos = jnp.arange(eb, dtype=jnp.int32)[None, :]
    valid = pos < nvalid[:, None]
    s = jnp.where(valid, src16.astype(jnp.int32), sentinel)
    d = jnp.where(valid, dst16.astype(jnp.int32), sentinel)
    return s, d, valid


def build_stream_fn(window_fn, vb: int, eb: int):
    """The compact twin of TriangleWindowKernel._build_stream: widen
    uint16 ids, rebuild the suffix mask from per-window counts
    (widen_stack), then lax.map the SAME per-window program. Returns
    an un-jitted callable (callers jit/AOT-compile it alongside the
    standard form)."""
    import jax

    def run_stream(src16, dst16, nvalid):  # [wb, eb] u16, [wb] i32
        s, d, valid = widen_stack(src16, dst16, nvalid, eb, vb)
        return jax.lax.map(lambda t: window_fn(*t), (s, d, valid))

    return run_stream


def window_stack(src: np.ndarray, dst: np.ndarray, eb: int):
    """Compact form of seg_ops.window_stack: [W, eb] uint16 stacks +
    [W] int32 valid counts (padding implied as each window's suffix)."""
    n = len(src)
    num_w = -(-n // eb)
    s16 = np.zeros(num_w * eb, np.uint16)
    d16 = np.zeros(num_w * eb, np.uint16)
    s16[:n] = src.astype(np.uint16)
    d16[:n] = dst.astype(np.uint16)
    nvalid = np.full(num_w, eb, np.int32)
    if n % eb:
        nvalid[-1] = n % eb
    return num_w, s16.reshape(num_w, eb), d16.reshape(num_w, eb), nvalid


def stack_window_list(windows, eb: int):
    """Compact form of seg_ops.stack_window_list (driver event-time
    windows): per-window uint16 rows + valid counts."""
    num_w = len(windows)
    s16 = np.zeros((num_w, eb), np.uint16)
    d16 = np.zeros((num_w, eb), np.uint16)
    nvalid = np.zeros(num_w, np.int32)
    for w, (ws, wd) in enumerate(windows):
        k = len(ws)
        if k > eb:
            raise ValueError(f"window of {k} edges exceeds edge "
                             f"bucket {eb}")
        s16[w, :k] = np.asarray(ws, np.uint16)  # gslint: disable=host-sync (host-side wire-format pack: inputs are host window lists)
        d16[w, :k] = np.asarray(wd, np.uint16)  # gslint: disable=host-sync (host-side wire-format pack: inputs are host window lists)
        nvalid[w] = k
    return s16, d16, nvalid


def pad_chunk(s16, d16, nvalid, at: int, hi: int, max_w: int, eb: int):
    """Compact form of seg_ops.pad_window_chunk: slice [at:hi] and pad
    the window axis to a power-of-two bucket with empty (count-0)
    rows. Returns (s16, d16, nvalid, n)."""
    from . import segment as seg_ops

    n = hi - at
    wb = min(seg_ops.bucket_size(n), max_w)
    if n == wb:  # steady state: zero-copy views
        return s16[at:hi], d16[at:hi], nvalid[at:hi], n
    sc = np.zeros((wb, eb), np.uint16)
    dc = np.zeros((wb, eb), np.uint16)
    nv = np.zeros(wb, np.int32)
    sc[:n], dc[:n], nv[:n] = s16[at:hi], d16[at:hi], nvalid[at:hi]
    return sc, dc, nv, n

"""Three-stage host-ingress pipeline: parallel window prep ‖ h2d
transfer ‖ device compute.

PERF.md's verified chip ladder pins the end-to-end stream rate at
~500-770K edges/s across every scale — flat while the baseline falls
10× — i.e. the wall is serialized single-core host prep plus
transfer/dispatch, not the K×K device compare. The reference delegates
exactly this ingest/shuffle layer to Flink's network stack
(SimpleEdgeStream.java:60-90); this module is its TPU-native
replacement, generalizing the depth-2 producer thread that used to
live inline in TriangleWindowKernel._run_stack_loop into ONE reusable
pipeline every streaming kernel routes through (triangles,
windowed_reduce, the fused scan engines, and the sharded kernels —
which keep their own table contract but share this loop).

Stages, per chunk of windows:

  1. PREP     — build the padded host stacks (seg_ops.window_stack /
                compact_ingress slicing / cell-id packing). Runs on a
                process-wide worker POOL (`prep_pool`), so several
                chunks prep concurrently — numpy copies and the native
                parser drop the GIL, so the parallelism is real. Prep
                results are consumed strictly in chunk order; worker
                scheduling can never reorder (or change) results, so
                counts are identical at every pool size.
  2. H2D      — convert/enqueue the host stacks to device arrays on
                the SAME worker, immediately after that chunk's prep
                (timed as its own stage). Through a tunneled chip a
                device_put is effectively synchronous network time,
                so running it on the worker is what lets chunk i+1's
                transfer overlap both device execution and the main
                thread's blocking d2h wait on chunk i-1 — the overlap
                the round-5 producer thread provided.
  3. DISPATCH — enqueue the chunk's device program (async, main
                thread, chunk order) and, one chunk later,
                MATERIALIZE the previous chunk's outputs (d2h +
                overflow recounts), so the d2h round trip of chunk i
                hides behind chunk i+1's execution — the same depth-2
                discipline as before, now with a parallel front end.

Per-stage wall time accumulates in a `StageTimers` (prep/h2d/compute
ms per chunk) that tools/profile_kernels.py commits to PERF.json, so
the next tunnel window can decompose the chip-side wall without new
instrumentation.

Env knobs:
  GS_STREAM_PREFETCH=0  — force the fully synchronous single-threaded
                          form (no pool, prep inline; dispatch keeps
                          its depth-2 overlap). Same counts.
  GS_PIPELINE_WORKERS=N — prep pool size (default min(4, cpus-1);
                          0 behaves like GS_STREAM_PREFETCH=0 for the
                          pool while keeping the API).
  GS_PIPELINE_INFLIGHT=N — max prepped+TRANSFERRED chunks in flight
                          ahead of dispatch in run_pipeline (default
                          3): the host+HBM footprint bound the old
                          depth-2 producer queue provided, kept
                          independent of the pool width so capping
                          device memory never requires shrinking prep
                          parallelism for the host-tier map_ordered
                          users.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Callable, Iterable, List, Optional

_MAX_DEFAULT_WORKERS = 4


class StageTimers:
    """Per-stage wall-time accumulators of one pipelined run (or a
    kernel's lifetime): milliseconds spent in prep (summed across
    workers — CPU time, not critical-path time, when prep runs
    parallel), h2d conversion/enqueue, and compute (the blocking
    materialize wait: device execute + d2h as observed by the host).
    `snapshot()` renders the per-chunk means PERF.json commits."""

    __slots__ = ("chunks", "prep_ms", "h2d_ms", "compute_ms", "_lock")

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        self.chunks = 0
        self.prep_ms = 0.0
        self.h2d_ms = 0.0
        self.compute_ms = 0.0

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:  # prep accumulates from several workers
            setattr(self, stage + "_ms",
                    getattr(self, stage + "_ms") + seconds * 1e3)

    def snapshot(self) -> dict:
        n = max(self.chunks, 1)
        return {
            "chunks": self.chunks,
            "prep_ms_per_chunk": round(self.prep_ms / n, 3),
            "h2d_ms_per_chunk": round(self.h2d_ms / n, 3),
            "compute_ms_per_chunk": round(self.compute_ms / n, 3),
        }


class PrepError(RuntimeError):
    """A prep-stage worker failed. The message carries the worker's
    FORMATTED traceback (the raw re-raise used to surface only the
    consumer-side frames, losing where in make_chunk the producer
    actually died); the original exception rides as __cause__."""


_POOL = None
_POOL_WORKERS = None
_POOL_LOCK = threading.Lock()
_FORCE_SYNC = 0  # nesting depth of forced_sync() contexts


def worker_count() -> int:
    """Prep pool width: GS_PIPELINE_WORKERS, defaulting to
    min(4, cpus-1) — one core stays with the main thread's
    h2d/dispatch stage."""
    env = os.environ.get("GS_PIPELINE_WORKERS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return max(1, min(_MAX_DEFAULT_WORKERS, (os.cpu_count() or 2) - 1))


def inflight_limit() -> int:
    """Max prepped+transferred chunks run_pipeline keeps in flight
    ahead of dispatch (GS_PIPELINE_INFLIGHT, default 3) — the bounded-
    footprint contract of the old depth-2 queue, decoupled from the
    pool width."""
    env = os.environ.get("GS_PIPELINE_INFLIGHT")
    if env is not None:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return 3


def pipeline_enabled() -> bool:
    """False when the caller (or env) pinned the synchronous form."""
    if _FORCE_SYNC:
        return False
    if os.environ.get("GS_STREAM_PREFETCH", "1") == "0":
        return False
    return worker_count() > 0


class forced_sync:
    """Context manager pinning the synchronous single-threaded form —
    the A/B lever bench.py and the profiler use to measure the
    pipeline against its own sync baseline without env juggling.
    Process-global (and lock-guarded, so nested/concurrent contexts
    can't corrupt the depth): while any context is active, EVERY
    pipelined call in the process runs sync — measurement harnesses
    must not run unrelated pipelined work concurrently."""

    def __enter__(self):
        global _FORCE_SYNC
        with _POOL_LOCK:
            _FORCE_SYNC += 1
        return self

    def __exit__(self, *exc):
        global _FORCE_SYNC
        with _POOL_LOCK:
            _FORCE_SYNC -= 1
        return False


def prep_pool():
    """The process-wide prep ThreadPoolExecutor (lazily built, rebuilt
    when GS_PIPELINE_WORKERS changes); None when pipelining is off."""
    global _POOL, _POOL_WORKERS
    if not pipeline_enabled():
        return None
    w = worker_count()
    with _POOL_LOCK:
        if _POOL is None or _POOL_WORKERS != w:
            from concurrent.futures import ThreadPoolExecutor

            # a superseded pool is ABANDONED, never shut down: a
            # concurrent run still holding it must be able to finish
            # submitting (ThreadPoolExecutor's workers exit on their
            # own once the dropped executor is garbage collected)
            _POOL = ThreadPoolExecutor(
                max_workers=w, thread_name_prefix="gs-ingress-prep")
            _POOL_WORKERS = w
        return _POOL


def reset_pool() -> None:
    """Test hook: drop the memoized pool (e.g. after changing
    GS_PIPELINE_WORKERS mid-process). The old pool is abandoned, not
    shut down — see prep_pool."""
    global _POOL, _POOL_WORKERS
    with _POOL_LOCK:
        _POOL = None
        _POOL_WORKERS = None


def _timed_prep(prep: Callable, item, timers: Optional[StageTimers]):
    """Worker-side prep wrapper: times the call and converts a failure
    into a PrepError carrying the formatted worker traceback."""
    t0 = time.perf_counter()
    try:
        out = prep(item)
    except Exception as e:
        # Exception only: KeyboardInterrupt/SystemExit must abort the
        # run unwrapped (a broad caller-side `except RuntimeError`
        # fallback must never eat an interrupt as a prep failure);
        # pool futures re-raise those at .result() regardless
        raise PrepError(
            "ingress prep stage failed for chunk %r:\n%s"
            % (item, traceback.format_exc())) from e
    if timers is not None:
        timers.add("prep", time.perf_counter() - t0)
    return out


def _prep_then_h2d(prep: Callable, h2d: Callable, item,
                   timers: Optional[StageTimers]):
    """One worker task = prep + h2d of one chunk, each stage timed
    separately; h2d failures carry the worker traceback too."""
    payload = _timed_prep(prep, item, timers)
    t0 = time.perf_counter()
    try:
        dev = h2d(payload)
    except Exception as e:  # see _timed_prep: interrupts pass through
        raise PrepError(
            "ingress h2d stage failed for chunk %r:\n%s"
            % (item, traceback.format_exc())) from e
    if timers is not None:
        timers.add("h2d", time.perf_counter() - t0)
    return dev


def run_pipeline(items: Iterable, prep: Callable, h2d: Callable,
                 dispatch: Callable, finalize: Callable,
                 timers: Optional[StageTimers] = None) -> None:
    """Run `items` (ordered chunk descriptors) through the three
    stages. Contracts:

      prep(item)     -> host payload (pure; runs on the pool, any
                        worker, but results are consumed in item
                        order — parallelism never reorders effects)
      h2d(payload)   -> device payload (runs on the SAME worker right
                        after that chunk's prep, so a tunneled chip's
                        synchronous transfer overlaps device execute
                        and the previous chunk's d2h wait; must be
                        thread-safe — jnp.asarray/device_put are)
      dispatch(dev)  -> raw outputs (main thread, item order; must be
                        ASYNC — do not block on device results here)
      finalize(raw)  -> None (materializes d2h + any recount; called
                        one item BEHIND dispatch so the round trip of
                        chunk i hides behind chunk i+1, then once more
                        at the end)

    A prep/h2d failure surfaces in the caller as PrepError
    (RuntimeError) carrying the worker traceback; pending futures are
    cancelled. With pipelining disabled (`forced_sync`,
    GS_STREAM_PREFETCH=0, or zero workers) both stages run inline —
    identical results either way.
    """
    items = list(items)
    pool = prep_pool() if len(items) > 1 else None
    pending_raw = None

    def _finalize(raw):
        t0 = time.perf_counter()
        finalize(raw)
        if timers is not None:
            timers.add("compute", time.perf_counter() - t0)
            timers.chunks += 1

    def _consume(dev):
        nonlocal pending_raw
        raw = dispatch(dev)
        if pending_raw is not None:
            _finalize(pending_raw)
        pending_raw = raw

    if pool is None:
        for item in items:
            _consume(_prep_then_h2d(prep, h2d, item, timers))
    else:
        from collections import deque

        # bounded look-ahead caps host memory AND in-flight device
        # buffers at inflight_limit() prepped+transferred chunks
        # (default 3) — the footprint bound of the old depth-2 queue,
        # independent of the pool width
        lookahead = min(len(items), worker_count() + 1,
                        inflight_limit())
        futures = deque(
            pool.submit(_prep_then_h2d, prep, h2d, it, timers)
            for it in items[:lookahead])
        nxt = lookahead
        try:
            while futures:
                dev = futures.popleft().result()
                if nxt < len(items):
                    futures.append(pool.submit(
                        _prep_then_h2d, prep, h2d, items[nxt], timers))
                    nxt += 1
                _consume(dev)
        finally:
            for f in futures:
                f.cancel()
    if pending_raw is not None:
        _finalize(pending_raw)


def submit_prep(fn: Callable, item, timers: Optional[StageTimers] = None):
    """Submit ONE prep task to the pool, or None when pipelining is
    disabled (caller then preps inline) — the single-lookahead form
    for consumers whose dispatches carry sequential state the full
    run_pipeline loop doesn't model (the driver's snapshot scan). The
    future's result() raises PrepError with the worker traceback on
    failure, same as run_pipeline."""
    pool = prep_pool()
    if pool is None:
        return None
    return pool.submit(_timed_prep, fn, item, timers)


def map_ordered(fn: Callable, items: Iterable) -> List:
    """Ordered parallel map over the prep pool — the host-tier form of
    the prep stage (per-window numpy/native counting, per-window
    first-occurrence uniques for interning). Results are returned in
    item order regardless of worker scheduling, and the sequential
    form runs when pipelining is disabled, so outputs are identical at
    every pool size (the worker-pool determinism contract)."""
    items = list(items)
    pool = prep_pool() if len(items) > 1 else None
    if pool is None:
        return [_timed_prep(fn, it, None) for it in items]
    futures = [pool.submit(_timed_prep, fn, it, None) for it in items]
    try:
        return [f.result() for f in futures]
    finally:
        for f in futures:
            f.cancel()

"""Three-stage host-ingress pipeline: parallel window prep ‖ h2d
transfer ‖ device compute.

PERF.md's verified chip ladder pins the end-to-end stream rate at
~500-770K edges/s across every scale — flat while the baseline falls
10× — i.e. the wall is serialized single-core host prep plus
transfer/dispatch, not the K×K device compare. The reference delegates
exactly this ingest/shuffle layer to Flink's network stack
(SimpleEdgeStream.java:60-90); this module is its TPU-native
replacement, generalizing the depth-2 producer thread that used to
live inline in TriangleWindowKernel._run_stack_loop into ONE reusable
pipeline every streaming kernel routes through (triangles,
windowed_reduce, the fused scan engines, and the sharded kernels —
which keep their own table contract but share this loop).

Stages, per chunk of windows:

  1. PREP     — build the padded host stacks (seg_ops.window_stack /
                compact_ingress slicing / cell-id packing). Runs on a
                process-wide worker POOL (`prep_pool`), so several
                chunks prep concurrently — numpy copies and the native
                parser drop the GIL, so the parallelism is real. Prep
                results are consumed strictly in chunk order; worker
                scheduling can never reorder (or change) results, so
                counts are identical at every pool size.
  2. H2D      — convert/enqueue the host stacks to device arrays on
                the SAME worker, immediately after that chunk's prep
                (timed as its own stage). Through a tunneled chip a
                device_put is effectively synchronous network time,
                so running it on the worker is what lets chunk i+1's
                transfer overlap both device execution and the main
                thread's blocking d2h wait on chunk i-1 — the overlap
                the round-5 producer thread provided.
  3. DISPATCH — enqueue the chunk's device program (async, main
                thread, chunk order) and, one chunk later,
                MATERIALIZE the previous chunk's outputs (d2h +
                overflow recounts), so the d2h round trip of chunk i
                hides behind chunk i+1's execution — the same depth-2
                discipline as before, now with a parallel front end.

Per-stage wall time accumulates in a `StageTimers` (prep/h2d/compute
ms per chunk) that tools/profile_kernels.py commits to PERF.json, so
the next tunnel window can decompose the chip-side wall without new
instrumentation. With the flight recorder armed (utils/telemetry,
GS_TELEMETRY=1) each chunk additionally records a correlated span
tree — an `ingress.chunk` span with prep/h2d/dispatch/finalize child
spans, worker-side stages included — into the run ledger.

Env knobs:
  GS_STREAM_PREFETCH=0  — force the fully synchronous single-threaded
                          form (no pool, prep inline; dispatch keeps
                          its depth-2 overlap). Same counts.
  GS_PIPELINE_WORKERS=N — prep pool size (default min(4, cpus-1);
                          0 behaves like GS_STREAM_PREFETCH=0 for the
                          pool while keeping the API).
  GS_PIPELINE_INFLIGHT=N — max prepped+TRANSFERRED chunks in flight
                          ahead of dispatch in run_pipeline (default
                          3): the host+HBM footprint bound the old
                          depth-2 producer queue provided, kept
                          independent of the pool width so capping
                          device memory never requires shrinking prep
                          parallelism for the host-tier map_ordered
                          users.
  GS_STAGE_TIMEOUT_S=T  — per-STAGE watchdog deadline (utils/
                          resilience): a prep/h2d/dispatch/finalize
                          call that exceeds T surfaces as a typed
                          StageTimeout naming the chunk instead of
                          stalling the stream forever (the round-5
                          hung-tunnel shape). 0 (default) disables.
  GS_STAGE_RETRIES=N    — bounded retry for the re-runnable stages
                          (prep and h2d are pure/idempotent) with
                          deterministic jitterless exponential
                          backoff (GS_STAGE_BACKOFF_S base, default
                          0.05 s). Side-effecting stages (dispatch,
                          finalize) never retry — a deadline/failure
                          there is typed and raised at once. Default
                          0; with both knobs unset the guard is inert
                          and the legacy inline path (and its exact
                          exception types) runs.

On ANY failure escaping the loop, in-flight device work is DRAINED:
the already-dispatched previous chunk's finalize runs best-effort
before the error re-raises, so its device buffers and d2h are never
silently abandoned mid-stream.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Iterable, List, Optional

from ..utils import faults
from ..utils import knobs
from ..utils import metrics
from ..utils import resilience
from ..utils import telemetry
from ..utils.resilience import StageFailed, StageTimeout

_MAX_DEFAULT_WORKERS = 4
_POLL_S = 0.02  # watchdog poll tick while awaiting a guarded stage


class StageTimers:
    """Per-stage wall-time accumulators of one pipelined run (or a
    kernel's lifetime): milliseconds spent in prep (summed across
    workers — CPU time, not critical-path time, when prep runs
    parallel), h2d conversion/enqueue, and compute (the blocking
    materialize wait: device execute + d2h as observed by the host).
    `snapshot()` renders the per-chunk means PERF.json commits."""

    __slots__ = ("chunks", "prep_ms", "h2d_ms", "compute_ms", "_lock")

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        # under the lock: a concurrent worker's add() between the
        # field writes would otherwise be partially erased
        with self._lock:
            self.chunks = 0
            self.prep_ms = 0.0
            self.h2d_ms = 0.0
            self.compute_ms = 0.0

    def add(self, stage: str, seconds: float) -> None:
        with self._lock:  # prep accumulates from several workers
            setattr(self, stage + "_ms",
                    getattr(self, stage + "_ms") + seconds * 1e3)

    def snapshot(self) -> dict:
        n = max(self.chunks, 1)
        return {
            "chunks": self.chunks,
            "prep_ms_per_chunk": round(self.prep_ms / n, 3),
            "h2d_ms_per_chunk": round(self.h2d_ms / n, 3),
            "compute_ms_per_chunk": round(self.compute_ms / n, 3),
        }


class PrepError(RuntimeError):
    """A prep-stage worker failed. The message carries the worker's
    FORMATTED traceback (the raw re-raise used to surface only the
    consumer-side frames, losing where in make_chunk the producer
    actually died); the original exception rides as __cause__."""


_POOL = None
_POOL_WORKERS = None
_POOL_LOCK = threading.Lock()
_FORCE_SYNC = 0  # nesting depth of forced_sync() contexts


def worker_count() -> int:
    """Prep pool width: GS_PIPELINE_WORKERS, defaulting to
    min(4, cpus-1) — one core stays with the main thread's
    h2d/dispatch stage."""
    env = knobs.get_int("GS_PIPELINE_WORKERS")
    if env is not None:
        return env
    return max(1, min(_MAX_DEFAULT_WORKERS, (os.cpu_count() or 2) - 1))


def inflight_limit() -> int:
    """Max prepped+transferred chunks run_pipeline keeps in flight
    ahead of dispatch (GS_PIPELINE_INFLIGHT, default 3) — the bounded-
    footprint contract of the old depth-2 queue, decoupled from the
    pool width."""
    return knobs.get_int("GS_PIPELINE_INFLIGHT")


def pipeline_enabled() -> bool:
    """False when the caller (or env) pinned the synchronous form."""
    if _FORCE_SYNC:
        return False
    if not knobs.get_bool("GS_STREAM_PREFETCH"):
        return False
    return worker_count() > 0


def forced_sync_active() -> bool:
    """True while any forced_sync() context is live — the explicit A/B
    measurement lever. The dispatch autotuner FREEZES under it (exploit
    only, no recording), so a sync-baseline rep can neither pollute the
    tuner's rate estimates nor be measured at a different configuration
    than the pipelined rep it is compared against."""
    return _FORCE_SYNC > 0


class forced_sync:
    """Context manager pinning the synchronous single-threaded form —
    the A/B lever bench.py and the profiler use to measure the
    pipeline against its own sync baseline without env juggling.
    Process-global (and lock-guarded, so nested/concurrent contexts
    can't corrupt the depth): while any context is active, EVERY
    pipelined call in the process runs sync — measurement harnesses
    must not run unrelated pipelined work concurrently."""

    def __enter__(self):
        global _FORCE_SYNC
        with _POOL_LOCK:
            _FORCE_SYNC += 1
        return self

    def __exit__(self, *exc):
        global _FORCE_SYNC
        with _POOL_LOCK:
            _FORCE_SYNC -= 1
        return False


def prep_pool():
    """The process-wide prep ThreadPoolExecutor (lazily built, rebuilt
    when GS_PIPELINE_WORKERS changes); None when pipelining is off."""
    global _POOL, _POOL_WORKERS
    if not pipeline_enabled():
        return None
    w = worker_count()
    with _POOL_LOCK:
        if _POOL is None or _POOL_WORKERS != w:
            from concurrent.futures import ThreadPoolExecutor

            # a superseded pool is ABANDONED, never shut down: a
            # concurrent run still holding it must be able to finish
            # submitting (ThreadPoolExecutor's workers exit on their
            # own once the dropped executor is garbage collected)
            _POOL = ThreadPoolExecutor(
                max_workers=w, thread_name_prefix="gs-ingress-prep")
            _POOL_WORKERS = w
        return _POOL


def reset_pool() -> None:
    """Test hook: drop the memoized pool (e.g. after changing
    GS_PIPELINE_WORKERS mid-process). The old pool is abandoned, not
    shut down — see prep_pool."""
    global _POOL, _POOL_WORKERS
    with _POOL_LOCK:
        _POOL = None
        _POOL_WORKERS = None


def _mark(cell: Optional[dict], stage: str) -> None:
    """Record which stage a worker task is in (and since when) so the
    consumer-side watchdog can enforce a per-STAGE deadline and name
    the actual hung stage. Plain dict writes: each key is written by
    one thread and read by one other — torn reads are impossible for
    the float/str values involved."""
    if cell is not None:
        cell["since"] = time.perf_counter()
        cell["stage"] = stage


def _span_cell(cell: Optional[dict], item):
    """(parent span id, chunk correlation id) of a worker stage: the
    chunk ctx rides the worker cell (thread-local span nesting cannot
    cross the pool); without one, the item's own key still correlates
    the stage records of one chunk."""
    ctx = cell.get("tctx") if cell else None
    if ctx is not None:
        return ctx["sid"], ctx["chunk"]
    return None, telemetry.chunk_key(item)


def _timed_prep(prep: Callable, item, timers: Optional[StageTimers],
                cell: Optional[dict] = None):
    """Worker-side prep wrapper: times the call and converts a failure
    into a PrepError carrying the formatted worker traceback."""
    _mark(cell, "prep")
    t0 = time.perf_counter()
    try:
        faults.fire("prep")
        out = prep(item)
    except Exception as e:
        # Exception only: KeyboardInterrupt/SystemExit must abort the
        # run unwrapped (a broad caller-side `except RuntimeError`
        # fallback must never eat an interrupt as a prep failure);
        # pool futures re-raise those at .result() regardless
        raise PrepError(
            "ingress prep stage failed for chunk %r:\n%s"
            % (item, traceback.format_exc())) from e
    dt = time.perf_counter() - t0
    if timers is not None:
        timers.add("prep", dt)
    par, ck = _span_cell(cell, item)
    telemetry.record_span("ingress.prep", t0, dt, parent=par,
                          chunk=ck)
    return out


def _prep_then_h2d(prep: Callable, h2d: Callable, item,
                   timers: Optional[StageTimers],
                   cell: Optional[dict] = None):
    """One worker task = prep + h2d of one chunk, each stage timed
    separately; h2d failures carry the worker traceback too."""
    payload = _timed_prep(prep, item, timers, cell)
    _mark(cell, "h2d")
    t0 = time.perf_counter()
    try:
        faults.fire("h2d")
        dev = h2d(payload)
    except Exception as e:  # see _timed_prep: interrupts pass through
        raise PrepError(
            "ingress h2d stage failed for chunk %r:\n%s"
            % (item, traceback.format_exc())) from e
    dt = time.perf_counter() - t0
    if timers is not None:
        timers.add("h2d", dt)
    par, ck = _span_cell(cell, item)
    telemetry.record_span("ingress.h2d", t0, dt, parent=par, chunk=ck)
    _mark(cell, "done")
    return dev


def _is_fatal(exc: BaseException) -> bool:
    """True for the chaos harness's simulated hard kill
    (faults.InjectedFault(fatal=True)), possibly wrapped in PrepError
    by the worker: never retried, re-raised as-is."""
    cause = exc.__cause__ if isinstance(exc, PrepError) else exc
    return isinstance(cause, faults.InjectedFault) and cause.fatal


def _await_attempt(wait_tick: Callable, outcome: Callable,
                   cell: dict, timeout: float, queued_since: float):
    """Shared wait loop of one guarded prep+h2d attempt. `wait_tick(t)`
    blocks up to t seconds and returns True once the attempt finished;
    `outcome()` then returns its value or raises. Enforces `timeout`
    per STAGE via the worker-updated cell; a task no worker has picked
    up yet counts its QUEUE wait (since `queued_since`) against the
    same deadline — with every pool worker wedged on abandoned hangs,
    the queue itself is the hung stage, and the retry's dedicated
    thread is what routes around the dead pool. Returns
    (True, value, None) | (False, exception, stage) |
    (False, None, stage) — the last meaning a stage deadline expired
    (the attempt's thread is abandoned)."""
    while True:
        if wait_tick(_POLL_S if timeout > 0 else None):
            try:
                return True, outcome(), None
            except BaseException as e:  # gslint: disable=except-hygiene (captured: caller raises or retries it)
                return False, e, cell.get("stage")
        stage = cell.get("stage", "queued")
        since = cell.get("since", queued_since)
        if (timeout > 0 and stage in ("queued", "prep", "h2d")
                and time.perf_counter() - since > timeout):
            return False, None, stage


def _guarded_prep_h2d(prep: Callable, h2d: Callable, item,
                      timers: Optional[StageTimers],
                      first_future=None, first_cell=None):
    """Resolve one chunk's prep+h2d under the stage guard
    (GS_STAGE_TIMEOUT_S / GS_STAGE_RETRIES): a per-stage deadline with
    bounded deterministic-backoff retry. Attempt 1 consumes
    `first_future` (already submitted to the pool) when given; retry
    attempts run on DEDICATED daemon threads so a hung pool worker is
    abandoned rather than re-poisoned. Prep and h2d are safe to re-run
    by contract (prep is pure, h2d an idempotent transfer).

    This is the cell-aware twin of resilience.call_guarded (which
    deadlines a whole call): the per-STAGE deadline and the
    pooled-first-attempt handoff need the worker-updated cell, which
    the generic guard has no notion of. A change to retry semantics
    (fatal pass-through, attempt accounting, backoff) must land in
    BOTH."""
    retries = resilience.stage_retries()
    timeout = resilience.stage_timeout_s()
    backoff = resilience.stage_backoff_s()
    attempts: List[dict] = []
    last_stage = "prep"
    for attempt in range(retries + 1):
        t0 = time.perf_counter()
        if attempt == 0 and first_future is not None:
            cell = first_cell if first_cell is not None else {}
            ok, res, stage = _await_attempt(
                lambda t: _future_wait(first_future, t),
                first_future.result, cell, timeout,
                cell.get("submitted", t0))
        elif timeout > 0:
            # retry attempts keep the chunk-span correlation of the
            # pooled first attempt (telemetry): same parent, so the
            # ledger shows the retries under one chunk
            cell = {"tctx": (first_cell or {}).get("tctx")}
            box, done = {}, threading.Event()

            def _runner(cell=cell, box=box, done=done):
                try:
                    box["value"] = _prep_then_h2d(prep, h2d, item,
                                                  timers, cell)
                except BaseException as e:  # gslint: disable=except-hygiene (captured: _outcome re-raises on the waiter)
                    box["error"] = e
                finally:
                    done.set()

            threading.Thread(target=_runner, daemon=True,
                             name="gs-ingress-retry").start()

            def _outcome(box=box):
                if "error" in box:
                    raise box["error"]
                return box["value"]

            ok, res, stage = _await_attempt(done.wait, _outcome, cell,
                                            timeout, t0)
        else:  # retries without a deadline: run inline
            cell = {"tctx": (first_cell or {}).get("tctx")}
            try:
                return _prep_then_h2d(prep, h2d, item, timers, cell)
            except Exception as e:  # gslint: disable=except-hygiene (captured: the retry loop re-raises as StageFailed)
                ok, res, stage = False, e, cell.get("stage")
        if ok:
            return res
        if res is not None and (not isinstance(res, Exception)
                                or _is_fatal(res)):
            raise res  # interrupts and the simulated kill: unretried
        last_stage = stage or last_stage
        attempts.append({
            "stage": last_stage,
            "outcome": "timeout" if res is None else type(res).__name__,
            "elapsed_s": round(time.perf_counter() - t0, 6)})
        if attempt >= retries:
            if res is None:
                raise StageTimeout(
                    "%s stage of chunk %r exceeded its %.3gs deadline "
                    "(GS_STAGE_TIMEOUT_S) on %d attempt(s)"
                    % (last_stage, item, timeout, len(attempts)),
                    last_stage, item, attempts)
            raise StageFailed(
                "%s stage of chunk %r failed after %d attempt(s): %s"
                % (last_stage, item, len(attempts), res),
                last_stage, item, attempts) from res
        telemetry.event("stage_retry", stage=last_stage,
                        chunk=telemetry.chunk_key(item),
                        attempt=attempt + 1,
                        outcome=attempts[-1]["outcome"])
        time.sleep(backoff * (2 ** attempt))


def _future_wait(fut, t: Optional[float]) -> bool:
    """Event.wait-shaped adapter over Future: True once done."""
    if t is None:
        try:
            fut.exception()  # blocks to completion; outcome re-raises
        except BaseException:  # gslint: disable=except-hygiene (wait only: outcome() re-raises the real error)
            pass
        return True
    try:
        fut.exception(timeout=t)
    except _FutureTimeout:
        return fut.done()
    except BaseException:  # gslint: disable=except-hygiene (wait only: outcome() re-raises the real error)
        pass
    return True


def run_pipeline(items: Iterable, prep: Callable, h2d: Callable,
                 dispatch: Callable, finalize: Callable,
                 timers: Optional[StageTimers] = None,
                 inflight: Optional[int] = None) -> None:
    """Run `items` (ordered chunk descriptors) through the three
    stages. Contracts:

      prep(item)     -> host payload (pure; runs on the pool, any
                        worker, but results are consumed in item
                        order — parallelism never reorders effects)
      h2d(payload)   -> device payload (runs on the SAME worker right
                        after that chunk's prep, so a tunneled chip's
                        synchronous transfer overlaps device execute
                        and the previous chunk's d2h wait; must be
                        thread-safe — jnp.asarray/device_put are)
      dispatch(dev)  -> raw outputs (main thread, item order; must be
                        ASYNC — do not block on device results here)
      finalize(raw)  -> None (materializes d2h + any recount; called
                        one item BEHIND dispatch so the round trip of
                        chunk i hides behind chunk i+1, then once more
                        at the end)

    A prep/h2d failure surfaces in the caller as PrepError
    (RuntimeError) carrying the worker traceback — or, with the stage
    guard armed (GS_STAGE_TIMEOUT_S / GS_STAGE_RETRIES), as a typed
    StageTimeout/StageFailed naming the chunk and stage once the
    attempt budget is exhausted. Pending futures are cancelled, and the
    already-dispatched previous chunk is DRAINED (its finalize runs
    best-effort) before any error re-raises, so device buffers and the
    d2h in flight are never silently abandoned. With pipelining
    disabled (`forced_sync`, GS_STREAM_PREFETCH=0, or zero workers)
    both stages run inline — identical results either way.

    `inflight` narrows the prepped+transferred look-ahead below the
    global GS_PIPELINE_INFLIGHT for callers with their own ring
    contract (the resident tier's GS_RESIDENT_SLOTS ingest ring);
    None keeps the global bound.
    """
    items = list(items)
    pool = prep_pool() if len(items) > 1 else None
    pending = None  # (item, raw, chunk ctx) one chunk behind dispatch
    guard = resilience.guard_active()
    futures = ()

    def _ctx(it):
        # chunk span handle (telemetry): stage spans of this chunk —
        # including the pool worker's prep/h2d — parent to it; closed
        # when the chunk's finalize lands. None when disarmed.
        return telemetry.chunk_ctx(telemetry.chunk_key(it))

    def _finalize(item, raw, tctx=None):
        t0 = time.perf_counter()

        def _call():
            faults.fire("finalize")
            finalize(raw)

        if guard:
            # deadline only, NEVER retried: finalize mutates consumer
            # state (appends results, advances carried mirrors), so a
            # re-run would double-apply; a hang still surfaces as a
            # typed StageTimeout instead of stalling the stream
            resilience.call_guarded("finalize", item, _call, retries=0)
        else:
            _call()
        dt = time.perf_counter() - t0
        if timers is not None:
            timers.add("compute", dt)
            timers.chunks += 1
        par, ck = _span_cell({"tctx": tctx}, item)
        telemetry.record_span("ingress.finalize", t0, dt, parent=par,
                              chunk=ck)
        telemetry.close_chunk(tctx)

    def _consume(item, dev, tctx=None):
        nonlocal pending

        # the wrapped program called inside dispatch() binds its
        # program/signature tags (utils/costmodel) in the TLS of
        # WHICHEVER thread runs it — under an armed stage watchdog
        # that is the gs-stage-watchdog helper, not this thread — so
        # the tags are captured inside the callable and carried back
        # through the closure for the span record below
        disp_tags = {}

        def _call():
            faults.fire("dispatch")
            out = dispatch(dev)
            disp_tags.update(telemetry.pop_dispatch_tags())
            return out

        t0 = time.perf_counter()
        telemetry.pop_dispatch_tags()  # drop any stale pre-dispatch tag
        # dispatch is retries=0 too: engines fold the chunk into a
        # device-resident carry inside it, so re-running would
        # double-fold the chunk
        raw = (resilience.call_guarded("dispatch", item, _call,
                                       retries=0)
               if guard else _call())
        par, ck = _span_cell({"tctx": tctx}, item)
        telemetry.record_span("ingress.dispatch", t0,
                              time.perf_counter() - t0, parent=par,
                              chunk=ck, **disp_tags)
        if pending is not None:
            done_chunk, pending = pending, None
            _finalize(*done_chunk)
        pending = (item, raw, tctx)

    def _submit(it):
        # `submitted` anchors the queue-wait deadline: a task no
        # wedged-pool worker ever picks up must still time out
        cell = {"submitted": time.perf_counter(), "tctx": _ctx(it)}
        return (it, cell,
                pool.submit(_prep_then_h2d, prep, h2d, it, timers,
                            cell))

    try:
        if pool is None:
            for item in items:
                tctx = _ctx(item)
                cell = {"tctx": tctx}
                dev = (_guarded_prep_h2d(prep, h2d, item, timers,
                                         first_cell=cell)
                       if guard
                       else _prep_then_h2d(prep, h2d, item, timers,
                                           cell))
                _consume(item, dev, tctx)
        else:
            from collections import deque

            # bounded look-ahead caps host memory AND in-flight device
            # buffers at inflight_limit() prepped+transferred chunks
            # (default 3) — the footprint bound of the old depth-2
            # queue, independent of the pool width
            lookahead = min(len(items), worker_count() + 1,
                            min(inflight, inflight_limit())
                            if inflight else inflight_limit())
            futures = deque(_submit(it) for it in items[:lookahead])
            nxt = lookahead
            while futures:
                item, cell, fut = futures.popleft()
                dev = (_guarded_prep_h2d(prep, h2d, item, timers,
                                         first_future=fut,
                                         first_cell=cell)
                       if guard else fut.result())
                if nxt < len(items):
                    futures.append(_submit(items[nxt]))
                    nxt += 1
                # backlog gauges for the health plane (no-op
                # disarmed): prepped+transferred chunks waiting on
                # dispatch, plus the AGE of the oldest one — depth
                # says how much is queued, age says how long the head
                # of the line has already waited (the pipeline-level
                # twin of the per-tenant queue-age gauge)
                metrics.gauge_set("gs_inflight_chunks", len(futures))
                if metrics.enabled():
                    oldest = (futures[0][1].get("submitted")
                              if futures else None)
                    # 0.0 when drained: a scrape after the stream
                    # finishes must not show age for work that no
                    # longer exists
                    metrics.gauge_set(
                        "gs_inflight_oldest_s",
                        time.perf_counter() - oldest
                        if oldest is not None else 0.0)
                _consume(item, dev, cell.get("tctx"))
    except Exception:
        # drain in-flight device work before surfacing the failure:
        # the previous chunk was already dispatched, so its outputs
        # (device buffers + the pending d2h) are materialized
        # best-effort instead of abandoned (a hung drain is bounded by
        # the same finalize deadline when the guard is armed)
        if pending is not None:
            done_chunk, pending = pending, None
            try:
                _finalize(*done_chunk)
            except Exception as drain_err:
                try:
                    telemetry.event(
                        "drain_failed", durable=True,
                        component="ingress_pipeline",
                        error="%s: %s" % (type(drain_err).__name__,
                                          drain_err))
                except Exception:  # gslint: disable=except-hygiene (a failing ledger write must not replace the typed StageError the demotion ladder keys on)
                    pass
        raise
    finally:
        for _it, _cell, f in futures:
            f.cancel()
    if pending is not None:
        _finalize(*pending)


def submit_prep(fn: Callable, item, timers: Optional[StageTimers] = None):
    """Submit ONE prep task to the pool, or None when pipelining is
    disabled (caller then preps inline) — the single-lookahead form
    for consumers whose dispatches carry sequential state the full
    run_pipeline loop doesn't model (the driver's snapshot scan). The
    future's result() raises PrepError with the worker traceback on
    failure, same as run_pipeline."""
    pool = prep_pool()
    if pool is None:
        return None
    return pool.submit(_timed_prep, fn, item, timers)


def map_ordered(fn: Callable, items: Iterable) -> List:
    """Ordered parallel map over the prep pool — the host-tier form of
    the prep stage (per-window numpy/native counting, per-window
    first-occurrence uniques for interning). Results are returned in
    item order regardless of worker scheduling, and the sequential
    form runs when pipelining is disabled, so outputs are identical at
    every pool size (the worker-pool determinism contract).

    Honors the stage guard like every other prep consumer: with
    GS_STAGE_RETRIES/GS_STAGE_TIMEOUT_S armed, a failed or hung pooled
    item is re-run under resilience.call_guarded (fn is pure by the
    prep contract); inert knobs keep the legacy zero-overhead path."""
    items = list(items)
    pool = prep_pool() if len(items) > 1 else None
    guard = resilience.guard_active()

    def _rerun(i, it):
        return resilience.call_guarded(
            "prep", i, lambda: _timed_prep(fn, it, None))

    if pool is None:
        if not guard:
            return [_timed_prep(fn, it, None) for it in items]
        return [_rerun(i, it) for i, it in enumerate(items)]
    futures = [pool.submit(_timed_prep, fn, it, None) for it in items]
    try:
        if not guard:
            return [f.result() for f in futures]
        out = []
        timeout = resilience.stage_timeout_s()
        for i, (it, fut) in enumerate(zip(items, futures)):
            try:
                out.append(fut.result(
                    timeout=2 * timeout if timeout > 0 else None))
            except BaseException as e:
                if not isinstance(e, Exception) or _is_fatal(e):
                    raise  # interrupts / the simulated kill
                # pooled attempt failed (or its 2×deadline wait
                # expired — the worker is abandoned): re-run under the
                # guard's own watchdog/retry budget
                out.append(_rerun(i, it))
        return out
    finally:
        for f in futures:
            f.cancel()

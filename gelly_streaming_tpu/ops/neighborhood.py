"""Windowed neighborhood kernels: per-window columnar execution of
foldNeighbors / reduceOnEdges / applyOnNeighbors
(reference: GraphWindowStream.java:62-182).

Each kernel consumes one tumbling window's edges (already
direction-transformed so key = source, neighbor = target, matching the
reference's slice(): SimpleEdgeStream.java:153-171), converts them to
COO arrays, interns vertex ids, and runs a device segment kernel
(ops/segment.py). Host fallbacks reproduce the reference's per-record
semantics for arbitrary Python UDFs.
"""

from __future__ import annotations

import copy
import functools
from collections import OrderedDict
from typing import Any, Callable, List, Tuple

import numpy as np

from ..core.functions import (EdgesApply, EdgesFold, EdgesReduce,
                              JaxEdgesApply, JaxEdgesFold, JaxEdgesReduce)
from . import segment as seg_ops

Record = Tuple[Any, int]


def _window_arrays(edges) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """COO arrays (key=source, neighbor=target, value) for a window batch."""
    src = np.asarray([e.source for e in edges])
    dst = np.asarray([e.target for e in edges])
    values = [e.value for e in edges]
    if all(isinstance(v, int) and not isinstance(v, bool) for v in values):
        val = np.asarray(values, dtype=np.int64)
    else:
        try:
            val = np.asarray(values, dtype=np.float64)
        except (TypeError, ValueError):
            val = np.zeros(len(edges), np.int32)  # NullValue edges
    return src, dst, val


def _group_by_key(edges) -> "OrderedDict[Any, List[Tuple[Any, Any]]]":
    """Arrival-order neighborhood grouping (host path)."""
    groups: "OrderedDict[Any, List]" = OrderedDict()
    for e in edges:
        groups.setdefault(e.source, []).append((e.target, e.value))
    return groups


def _py(x):
    """numpy scalar → python scalar for sink formatting."""
    if isinstance(x, np.generic):
        return x.item()
    return x


# ----------------------------------------------------------------------
# fold
# ----------------------------------------------------------------------

def make_fold_kernel(fold) -> Callable[[List[Any], int], List[Record]]:
    if isinstance(fold, JaxEdgesFold):
        return _device_fold_kernel(fold)
    if isinstance(fold, tuple):  # (initial, EdgesFold)
        initial, fold_udf = fold
        return _host_fold_kernel(initial, fold_udf)
    raise TypeError(type(fold))


def _host_fold_kernel(initial, fold_udf: EdgesFold):
    def kernel(edges, wmax) -> List[Record]:
        out: List[Record] = []
        accs: "OrderedDict[Any, Any]" = OrderedDict()
        for e in edges:
            acc = accs.get(e.source)
            if acc is None:
                acc = copy.deepcopy(initial)
            accs[e.source] = fold_udf.fold_edges(acc, e.source, e.target, e.value)
        for _key, acc in accs.items():
            out.append((acc, wmax))
        return out

    return kernel


def _device_fold_kernel(fold: JaxEdgesFold):
    """No pane path exists for folds (unlike reduces,
    _make_pane_reduce): a fold visits a vertex's edges in arrival order
    across the WHOLE window, so pre-collapsing each pane to a partial
    and combining partials is only valid when the combine is
    associative — which a general fold is not (its accumulator type
    need not even match its element type). Sliding folds therefore pay
    the size/slide per-window duplication by design."""
    fold_fn = fold.fn  # bind once: stable identity keys the jit cache

    def kernel(edges, wmax) -> List[Record]:
        src, dst, val = _window_arrays(edges)
        uniq, (s_dense,) = seg_ops.intern(src)
        order = np.argsort(s_dense, kind="stable")
        fields = (src[order], dst[order], val[order])
        result, has_any = seg_ops.segmented_fold(
            fold_fn, fold.init, s_dense[order], fields, len(uniq)
        )
        out: List[Record] = []
        leaves = [np.asarray(l) for l in _tree_leaves(result)]
        for i, vid in enumerate(uniq):
            if not has_any[i]:
                continue
            row = tuple(_py(l[i]) for l in leaves)
            value = fold.emit(_py(vid), row) if fold.emit else row
            out.append((value, wmax))
        return out

    return kernel


def _tree_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


# ----------------------------------------------------------------------
# reduce
# ----------------------------------------------------------------------

def make_reduce_kernel(reduce_udf) -> Callable[[List[Any], int], List[Record]]:
    if isinstance(reduce_udf, JaxEdgesReduce):
        return _device_reduce_kernel(reduce_udf)
    if isinstance(reduce_udf, EdgesReduce):
        return _host_reduce_kernel(reduce_udf)
    raise TypeError(type(reduce_udf))


def _host_reduce_kernel(reduce_udf: EdgesReduce):
    def kernel(edges, wmax) -> List[Record]:
        groups = _group_by_key(edges)
        out: List[Record] = []
        for key, nbrs in groups.items():
            acc = nbrs[0][1]
            for _n, v in nbrs[1:]:
                acc = reduce_udf.reduce_edges(acc, v)
            # result projected to (vertexId, value) — reference
            # GraphWindowStream.java:103 `.project(0,2)`
            out.append(((key, acc), wmax))
        return out

    return kernel


def _device_reduce_kernel(reduce_udf: JaxEdgesReduce):
    name = reduce_udf.name
    fn = reduce_udf.fn

    def kernel(edges, wmax) -> List[Record]:
        src, _dst, val = _window_arrays(edges)
        uniq, (s_dense,) = seg_ops.intern(src)
        n_seg = len(uniq)
        if name in ("sum", "min", "max"):
            import jax.numpy as jnp

            nb = seg_ops.bucket_size(len(val))
            sb = seg_ops.bucket_size(n_seg)
            vpad = seg_ops.pad_to(val, nb)
            spad = seg_ops.pad_to(s_dense.astype(np.int32), nb, fill=sb)
            res = np.asarray(
                seg_ops.segment_reduce(jnp.asarray(vpad), jnp.asarray(spad),
                                       sb + 1, name)
            )[:n_seg]
            has_any = np.ones(n_seg, bool)
        else:
            order = np.argsort(s_dense, kind="stable")
            reduce = (seg_ops.segmented_reduce_associative
                      if getattr(reduce_udf, "associative", False)
                      else seg_ops.segmented_reduce)
            res, has_any = reduce(fn, s_dense[order], val[order], n_seg)
            res = np.asarray(res)
        return [
            ((_py(uniq[i]), _py(res[i])), wmax)
            for i in range(n_seg) if has_any[i]
        ]

    if name in ("sum", "min", "max"):
        kernel.pane_kernel = _make_pane_reduce(kernel, name=name)
    elif getattr(reduce_udf, "associative", False):
        # associativity is exactly the license pane decomposition
        # needs: combine per-pane partials instead of re-reducing each
        # edge size/slide times (VERDICT r2 weak-7). Arbitrary
        # non-associative reduces (and folds, see _device_fold_kernel)
        # stay on the duplicating per-window path.
        kernel.pane_kernel = _make_pane_reduce(kernel, fn=fn)
    return kernel


_PANE_CELL_LIMIT = 1 << 22  # (panes x vertex-bucket) cells per dispatch


def _pane_identity(name: str, dtype):
    import jax.numpy as jnp

    if name == "sum":
        return dtype.type(0)
    big = (jnp.finfo(dtype).max if jnp.issubdtype(dtype, jnp.floating)
           else jnp.iinfo(dtype).max)
    return big if name == "min" else -big


def _combine_shifted(pv, pc, wp: int, step):
    """Combine the wp shifted slices of both-end-padded (pane, vertex)
    stacks: window w covers padded pane rows [w, w+wp-1], so
    W = P + wp - 1. The single home of that indexing — both pane tiers
    (monoid and associative-fn) and the shared emit block depend on
    it. step(acc_v, acc_c, next_v, next_c) -> (acc_v, acc_c)."""
    n_w = pv.shape[0] - (wp - 1)
    accv, accc = pv[:n_w], pc[:n_w]
    for k in range(1, wp):
        accv, accc = step(accv, accc, pv[k:k + n_w], pc[k:k + n_w])
    return accv, accc


def window_stack_combine(cells, counts, wp: int, name: str):
    """Sliding-window combine from pane partials: normalize empty
    (pane, vertex) cells to the monoid identity (segment_min/max fill
    them with dtype extremes — coincides with the identity for ints,
    NOT for floats), pad wp-1 identity rows on BOTH ends (window w
    covers padded pane rows [w, w+wp-1], w = 0 .. P+wp-2), then
    elementwise-combine the wp shifted slices. Shared by the
    single-chip pane path and parallel/sharded.make_sharded_pane_reduce
    — returns ([W, V] values, [W, V] counts), W = P + wp - 1."""
    import jax.numpy as jnp

    ident = _pane_identity(name, cells.dtype)
    if name != "sum":
        cells = jnp.where(counts > 0, cells, ident)
    comb = {"sum": jnp.add, "min": jnp.minimum,
            "max": jnp.maximum}[name]
    cols = cells.shape[1]
    pad_v = jnp.full((wp - 1, cols), ident, cells.dtype)
    pad_c = jnp.zeros((wp - 1, cols), counts.dtype)
    pv = jnp.concatenate([pad_v, cells, pad_v])
    pc = jnp.concatenate([pad_c, counts, pad_c])
    return _combine_shifted(
        pv, pc, wp, lambda av, ac, nv, nc: (comb(av, nv), ac + nc))


def masked_combine(fn, av, ap, nv, npn):
    """One presence-masked combine step for a general associative fn
    (which has no identity element to pad with): fn(a, next) where
    both cells are present, the present side where only one is, `a`
    unchanged otherwise. The single home of this selection logic — the
    sliding window combine below and the sharded cross-shard fold
    (parallel/sharded.py assoc tier) must stay semantically
    identical."""
    import jax.numpy as jnp

    return (jnp.where(ap & npn, fn(av, nv), jnp.where(npn, nv, av)),
            ap | npn)


@functools.lru_cache(maxsize=256)
def _jit_assoc_combine(fn, wp: int):
    """Jitted masked window combine for a generic associative fn: no
    identity element exists in general for the VALUES, so empty
    (pane, vertex) cells are carried as a presence mask and the combine
    selects fn(acc, next) / next / acc per cell. COUNTS do have an
    identity (0) even when values don't, so this tier returns real
    per-cell edge counts like the monoid tier — `run(cells, counts)`
    -> (win_vals, win_counts), win_counts the summed pane counts
    (ADVICE r3: a caller switching name='min' to fn=jnp.minimum must
    not silently lose count information). Cached per (fn, wp) like the
    segment kernels."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(cells, counts):
        cols = cells.shape[1]
        present = counts > 0
        pad_v = jnp.zeros((wp - 1, cols), cells.dtype)
        pad_p = jnp.zeros((wp - 1, cols), jnp.bool_)
        pad_c = jnp.zeros((wp - 1, cols), counts.dtype)
        pv = jnp.concatenate([pad_v, cells, pad_v])
        pp = jnp.concatenate([pad_p, present, pad_p])
        pc = jnp.concatenate([pad_c, counts, pad_c])

        # fn runs elementwise on every cell (garbage in absent slots);
        # masked_combine keeps only the licensed results
        accv, _ = _combine_shifted(
            pv, pp, wp,
            lambda av, ap, nv, npn: masked_combine(fn, av, ap, nv, npn))
        accc, _ = _combine_shifted(
            pc, pc, wp, lambda av, ac, nv, nc: (av + nv, ac))
        return accv, accc

    return run


def _make_pane_reduce(per_window_kernel, name: str = None, fn=None):
    """Sliding-window reduce from slide-sized PANE partials: one device
    dispatch computes every window instead of re-reducing each edge
    size/slide times. partial[p, v] = reduce over pane p's edges at
    vertex v (a flattened (pane, vertex) segment reduce); window w =
    combine over its size/slide consecutive panes — a static stack of
    shifted slices, elementwise-combined (the TPU-native form of
    Flink-style pane aggregation; the reference never materializes
    sliding windows at all).

    Two tiers share this scaffolding: named monoids (`name`) use the
    parallel segment kernels + identity-padded combine; user fns
    DECLARED associative (`fn`) use the flagged associative scan +
    masked combine — associativity is precisely what licenses
    regrouping a window's edges into pane partials. Non-associative
    reduces and ALL folds stay on the duplicating per-window path: a
    fold must visit a vertex's edges in arrival order across the whole
    window, and pane pre-collapse destroys that order.

    Falls back to per-window calls of the plain kernel when the dense
    pane axis would be degenerate (sparse stream spanning a huge time
    range)."""
    assert (name is None) != (fn is None)

    def pane_kernel(panes, size: int, slide: int) -> List[Record]:
        import jax
        import jax.numpy as jnp

        if not panes:
            return []
        starts = sorted(panes)
        p0 = starts[0]
        wp = size // slide
        n_panes = (starts[-1] - p0) // slide + 1

        srcs, vals, pids = [], [], []
        for st in starts:
            s, _d, v = _window_arrays(panes[st])
            srcs.append(s)
            vals.append(v)
            pids.append(np.full(len(s), (st - p0) // slide, np.int64))
        src = np.concatenate(srcs)
        val = np.concatenate(vals)
        pid = np.concatenate(pids)
        uniq, (s_dense,) = seg_ops.intern(src)
        n_seg = len(uniq)
        sb = seg_ops.bucket_size(n_seg)
        pb = seg_ops.bucket_size(n_panes)

        if pb * (sb + 1) > _PANE_CELL_LIMIT:
            # dense pane axis too sparse to pay for: per-window calls,
            # iterating only windows that CONTAIN an occupied pane (a
            # dense range(n_panes) sweep would hang on a sparse stream
            # spanning a huge time range — the exact input this
            # fallback exists for)
            out: List[Record] = []
            occupied = set(starts)
            wstarts = sorted({st - k * slide for st in starts
                              for k in range(wp)})
            for wstart in wstarts:
                values = [v_ for j in range(wp)
                          if (wstart + j * slide) in occupied
                          for v_ in panes[wstart + j * slide]]
                if values:
                    out.extend(per_window_kernel(values,
                                                 wstart + size - 1))
            return out

        n_cells = pb * (sb + 1)
        seg = pid * (sb + 1) + s_dense
        if name is not None:
            nb = seg_ops.bucket_size(len(val))
            vpad = seg_ops.pad_to(val, nb)
            segpad = seg_ops.pad_to(seg, nb, fill=n_cells)

            vj = jnp.asarray(vpad)
            sj = jnp.asarray(segpad)
            counts = jax.ops.segment_sum(
                (sj < n_cells).astype(jnp.int32), sj,
                n_cells + 1)[:-1].reshape(pb, sb + 1)
            part = seg_ops.segment_reduce(vj, sj, n_cells + 1,
                                          name)[:-1].reshape(pb, sb + 1)
            accv, accc = window_stack_combine(part, counts, wp, name)
        else:
            order = np.argsort(seg, kind="stable")
            res, _has_any = seg_ops.segmented_reduce_associative(
                fn, seg[order], val[order], n_cells)
            part = jnp.asarray(res).reshape(pb, sb + 1)
            # real per-cell edge counts (identity 0 exists for counts
            # even when fn has none) — same win_counts semantics as
            # the monoid tier
            cnt = np.bincount(seg, minlength=n_cells).astype(
                np.int32).reshape(pb, sb + 1)
            accv, accc = _jit_assoc_combine(fn, wp)(part,
                                                    jnp.asarray(cnt))
        accv, accc = np.asarray(accv), np.asarray(accc)

        # emit only occupied (window, vertex) cells, vectorized — a
        # dense Python scan of the (windows x vertex-bucket) grid would
        # cost more host time than the device dispatch saved
        ws, vs = np.nonzero(accc[:n_panes + wp - 1, :n_seg])
        return [
            ((_py(uniq[v]), _py(accv[w, v])),
             p0 + (w - (wp - 1)) * slide + size - 1)
            for w, v in zip(ws.tolist(), vs.tolist())
        ]

    return pane_kernel


# ----------------------------------------------------------------------
# apply
# ----------------------------------------------------------------------

def make_apply_kernel(apply_udf) -> Callable[[List[Any], int], List[Record]]:
    if isinstance(apply_udf, JaxEdgesApply):
        return _device_apply_kernel(apply_udf)
    if isinstance(apply_udf, EdgesApply):
        return _host_apply_kernel(apply_udf)
    raise TypeError(type(apply_udf))


def _host_apply_kernel(apply_udf: EdgesApply):
    """Buffered whole-neighborhood apply, 0..n outputs per vertex
    (reference: EdgesWindowFunction, GraphWindowStream.java:135-182)."""

    def kernel(edges, wmax) -> List[Record]:
        groups = _group_by_key(edges)
        out: List[Record] = []
        for key, nbrs in groups.items():
            apply_udf.apply_on_edges(key, nbrs, lambda v: out.append((v, wmax)))
        return out

    return kernel


def _device_apply_kernel(apply_udf: JaxEdgesApply):
    """Padded-CSR neighborhood view vmapped over vertices."""
    import jax
    import jax.numpy as jnp

    fn = apply_udf.fn
    vmapped = jax.jit(jax.vmap(fn))

    def kernel(edges, wmax) -> List[Record]:
        src, dst, val = _window_arrays(edges)
        uniq, (s_dense,) = seg_ops.intern(src)
        order = np.argsort(s_dense, kind="stable")
        s_sorted = s_dense[order]
        n_seg = len(uniq)
        counts = np.bincount(s_sorted, minlength=n_seg)
        max_deg = seg_ops.bucket_size(int(counts.max()) if n_seg else 1)
        nbr = np.zeros((n_seg, max_deg), dtype=np.int64)
        vals = np.zeros((n_seg, max_deg), dtype=val.dtype)
        mask = np.zeros((n_seg, max_deg), dtype=bool)
        starts = np.zeros(n_seg + 1, np.int64)
        np.cumsum(counts, out=starts[1:])
        dst_sorted = np.asarray(dst)[order]
        val_sorted = val[order]
        # vectorized CSR fill: each sorted edge's slot is its rank
        # within its segment (one O(E) scatter, no per-vertex loop —
        # same idiom as the sharded neighbor-table build,
        # parallel/sharded.py)
        rank = np.arange(len(s_sorted)) - starts[s_sorted]
        nbr[s_sorted, rank] = dst_sorted
        vals[s_sorted, rank] = val_sorted
        mask[s_sorted, rank] = True
        res = vmapped(jnp.asarray(np.asarray(uniq)), jnp.asarray(nbr),
                      jnp.asarray(vals), jnp.asarray(mask))
        leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(res)]
        out: List[Record] = []
        for i in range(n_seg):
            row = tuple(_py(l[i]) for l in leaves)
            value = apply_udf.emit(_py(uniq[i]), row) if apply_udf.emit else row
            out.append((value, wmax))
        return out

    return kernel

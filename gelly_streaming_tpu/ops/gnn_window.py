"""Windowed GNN message passing as a first-class engine workload.

One GNN message-passing round per tumbling window — exactly the frame
the SNIPPETS brief puts on this repo's operator, and the first program
here whose arithmetic intensity can clear machine balance (every hot
program the §16 observatory has measured is bytes-bound gathers at
0.25–0.28 FLOPs/byte with the MXU idle). Per window:

  1. aggregation — `segment_sum` of the per-vertex feature slab over
     the window's COO slab (the same gather/scatter machinery the
     degree fold and `windowed_reduce` ride), sentinel-mapped padding
     folding as no-ops;
  2. dense update — a GCN-style layer `H' = act(P · W + b)` where
     `P = min(H + min(M, cap), cap)` is the self-loop-included
     aggregate, on the MXU.

The carry is the `[vb+1, F]` float32 feature slab (sentinel row `vb`
absorbs padded edges and is re-zeroed every round); the weights ride
each dispatch as explicit arguments so a `set_weights` never
recompiles. A window with ZERO valid edges holds the slab untouched —
unlike the analytics monoids a GNN round is not a no-op on empty
input (the dense layer would tick on the carry), and the chunk loop
and cohort both right-pad dispatches with all-invalid windows, so
padding inertness REQUIRES the hold rule.

Exactness policy (the reason the numpy twin is a BIT-exactness oracle
and not a tolerance check): features and weights live on a dyadic
lattice — storage grid 2^-5, feature values in [0, 16) (≤ UNIT_CAP
integer lattice units), weights snapped at set-time to the same grid
with |W| ≤ 16. Every intermediate of the round is then an INTEGER
(in float32) of magnitude < 2^24:

  - aggregation: ≤ eb messages of ≤ UNIT_CAP units each; for
    eb ≤ 2^15 every partial sum (any order) is an exact float32
    integer, so XLA's segment_sum ≡ numpy's add.at ≡ the Pallas
    scatter bit-for-bit. Larger eb pre-shifts messages by the
    deterministic `agg_shift(eb)` (same floor on every tier).
  - dense update: |P·W| ≤ F · UNIT_CAP · WEIGHT_CAP < 2^24 for
    F ≤ 64, so the matmul is exact under ANY accumulation order —
    including the MXU's, forced to float32 accumulation via
    Precision.HIGHEST. Larger F snaps weights to a coarser grid
    (`weight_shift(F)`), preserving the bound.
  - activations are restricted to exact elementwise ops
    (relu/abs/identity — GS_GNN_ACT), and the slab re-clips to
    [0, UNIT_CAP] before carrying.

Per-window summary scalars are exact integers by the same argument:
`max_feat` (lattice units), `active_vertices`, `feat_checksum` (a
wrapping-int32 modular sum of the slab — associative and commutative
mod 2^32, hence order-free), `msg_edges`. Arbitrary float weights
would break all of this; `set_weights` therefore SNAPS its inputs and
DESIGN.md §23 carries the caveat.

Tiers, house style: `GnnSummaryEngine` (fused `lax.scan`, one
dispatch per MAX_WINDOWS windows, optional Pallas body behind
`ops/pallas_window.resolve_gnn_pallas`), `GnnHostEngine` (numpy
parity twin and demotion floor), `GnnResidentEngine` (donated-carry
super-batch rung), and `build_gnn_cohort_scan` (the vmapped
tenant-axis program `core/tenancy.GnnTenantCohort` dispatches).
Checkpoint/WAL/resume ride `SummaryEngineBase` unchanged — the
state_dict carries the feature slab as the carry plus a `gnn` section
(feature width, activation, snapped weights) so gnn→gnn and
gnn→host-twin hand-offs are exact.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import segment as seg_ops
from ..utils import knobs
from ..utils import latency
from ..utils import metrics
from ..utils import provenance
from .scan_analytics import SummaryEngineBase

Q_BITS = 5                    # storage grid 2^-Q_BITS (units of 1/32)
UNIT_CAP = 511                # max lattice units per slot (< 2^9)
AGG_EXACT_LOG2 = 15           # eb ≤ 2^15 sums exactly at full width
MATMUL_EXACT_F = 64           # F ≤ 64 dots exactly at full width

_ACTS_JNP = {
    "relu": lambda z: jnp.maximum(z, 0.0),
    "abs": jnp.abs,
    "identity": lambda z: z,
}
_ACTS_NP = {
    "relu": lambda z: np.maximum(z, 0.0),
    "abs": np.abs,
    "identity": lambda z: z,
}


def agg_shift(eb: int) -> int:
    """Pre-aggregation message shift: messages floor-divide by
    2^shift so a full eb-edge window's segment sum stays under 2^24
    lattice units (exact float32 integers in any fold order).
    Deterministic from eb alone, so every tier shifts identically."""
    return max(0, int(eb).bit_length() - 1 - AGG_EXACT_LOG2)


def weight_shift(F: int) -> int:
    """Weight-grid coarsening for wide feature dims: F ≤ 64 keeps the
    full ±512-unit weight range exact; each doubling beyond halves
    the weight cap so |P·W| stays under 2^24."""
    return max(0, (int(F) - 1).bit_length() - 6)


def weight_cap(F: int) -> int:
    return max(1, (UNIT_CAP + 1) >> weight_shift(F))


def snap_weights(W, b, F: int):
    """Snap real-valued weights onto the dyadic lattice the exactness
    argument needs: round to the 2^-5 grid, clip to the F-derived cap.
    Returns (W_units, b_units) as integer-valued float32 arrays —
    the representation every tier folds with."""
    cap = float(weight_cap(F))  # gslint: disable=host-sync (pure-python cap, no device value in sight)
    wu = np.clip(np.rint(np.asarray(W, np.float64) * (1 << Q_BITS)),  # gslint: disable=host-sync (host-input normalization: callers pass numpy, never device values)
                 -cap, cap).astype(np.float32)
    bu = np.clip(np.rint(np.asarray(b, np.float64) * (1 << Q_BITS)),  # gslint: disable=host-sync (host-input normalization: callers pass numpy, never device values)
                 -cap, cap).astype(np.float32)
    if wu.shape != (F, F) or bu.shape != (F,):
        raise ValueError(
            "GNN weights must be W [F, F] and b [F] at F=%d; got %s "
            "and %s" % (F, wu.shape, bu.shape))
    return wu, bu


def snap_features(feats, vb: int, F: int) -> np.ndarray:
    """Snap real-valued per-vertex features onto the storage lattice:
    2^-5 grid, clipped to [0, UNIT_CAP] units ([0, ~16) values).
    Accepts [n, F] for n ≤ vb; missing rows stay zero."""
    f = np.asarray(feats, np.float64)  # gslint: disable=host-sync (host-input normalization: callers pass numpy, never device values)
    if f.ndim != 2 or f.shape[1] != F or f.shape[0] > vb:
        raise ValueError(
            "features must be [n ≤ vb=%d, F=%d]; got %s"
            % (vb, F, f.shape))
    units = np.clip(np.rint(f * (1 << Q_BITS)), 0,
                    UNIT_CAP).astype(np.float32)
    slab = np.zeros((vb + 1, F), np.float32)
    slab[:units.shape[0]] = units
    return slab


def default_features(vb: int, F: int, seed: int = 0) -> np.ndarray:
    """Deterministic small-integer feature slab for benches/tests:
    units in [0, 8) so a few rounds of aggregation stay informative
    before the cap saturates."""
    rng = np.random.RandomState(seed)
    slab = np.zeros((vb + 1, F), np.float32)
    slab[:vb] = rng.randint(0, 8, size=(vb, F)).astype(np.float32)
    return slab


def default_weights(F: int):
    """Identity layer at value 1.0 (32 lattice units) with zero bias —
    the out-of-the-box round is pure clipped message accumulation."""
    return np.eye(F, dtype=np.float32), np.zeros(F, np.float32)


def _wrap_i32(total) -> np.ndarray:
    """Two's-complement int32 wrap of an exact int64 sum — the host
    twin's form of the device's native wrapping int32 accumulation
    (modular addition is order-free, which is the whole point of the
    checksum)."""
    return np.asarray(total, np.int64).astype(np.int32)  # gslint: disable=host-sync (host twin arithmetic: numpy-on-numpy, no device value in sight)


def _build_gnn_round(eb: int, vb: int, F: int, act: str):
    """One window's XLA round: (h, W, b, s, d, v) ->
    (h', (max_feat, active, checksum, msg_edges))."""
    sent = vb
    sh = agg_shift(eb)
    sc = np.float32(2.0 ** -sh)
    cap = np.float32(UNIT_CAP)
    actf = _ACTS_JNP[act]

    def round_(h, W, b, s, d, v):
        s = jnp.where(v, s, sent)
        d = jnp.where(v, d, sent)
        msgs = h[s]                      # [eb, F]; sentinel row is 0
        if sh:
            msgs = jnp.floor(msgs * sc)
        m = jax.ops.segment_sum(msgs, d, num_segments=vb + 1)
        p = jnp.minimum(h + jnp.minimum(m, cap), cap)
        z = jax.lax.dot_general(
            p, W, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST) + b
        h2 = jnp.clip(actf(z), 0.0, cap)
        h2 = h2.at[sent].set(0.0)
        # EMPTY windows hold the slab: no messages, no layer tick.
        # This is what makes window-axis padding inert — the chunk
        # loop and the cohort both right-pad dispatches with
        # all-invalid windows, and unlike the analytics monoids a GNN
        # round is NOT a no-op on empty input (the dense layer would
        # still fire on the carry). A select between two exact slabs
        # keeps bit-exactness.
        h2 = jnp.where(jnp.any(v), h2, h)
        maxf = jnp.max(h2[:vb]).astype(jnp.int32)
        active = jnp.sum(jnp.any(h2[:vb] > 0, axis=1),
                         dtype=jnp.int32)
        checksum = jnp.sum(h2.astype(jnp.int32), dtype=jnp.int32)
        nmsg = jnp.sum(v, dtype=jnp.int32)
        return h2, (maxf, active, checksum, nmsg)

    return round_


def _build_gnn_scan(eb: int, vb: int, F: int, act: str,
                    pallas_ok: bool = True):
    """The per-window body the scan engines fold:
    body(h, W, b, (s, d, v)) -> (h', ys). When the fused Pallas GNN
    kernel is selected (ops/pallas_window.resolve_gnn_pallas —
    GS_GNN_PALLAS pin or committed parity+≥1.05× `gnn_ab` chip rows)
    AND its build/trace probe succeeds, the returned body is the
    kernel instead: one pallas_call per window streaming the edge
    slab through VMEM with the feature slab resident — the features
    ride the same single HBM read as the megakernel's analytics.
    `pallas_ok=False` keeps the cohort's vmapped composition pure-XLA
    (same opt-out as scan_analytics.build_cohort_scan)."""
    if pallas_ok:
        from . import pallas_window

        got = pallas_window.maybe_gnn_body(eb, vb, F, act)
        if got is not None:
            return got

    round_ = _build_gnn_round(eb, vb, F, act)

    def body(h, W, b, xs):
        s, d, v = xs
        return round_(h, W, b, s, d, v)

    return body


def build_gnn_cohort_scan(eb: int, vb: int, F: int, act: str):
    """N tenants' GNN windows in ONE vmapped dispatch: carries stack
    [N, vb+1, F], slabs [N, W, eb], the (shared) weights broadcast.
    Both padding axes are inert by the round's empty-window-holds
    rule (all-invalid windows leave the slab untouched — see
    _build_gnn_round), so ragged cohorts right-pad to power-of-two
    (tenants, windows) buckets and reuse O(log N × log W) programs;
    the padded rows' summary outputs are dropped by the dispatcher.
    The single-tenant body builds with pallas_ok=False — a vmapped
    fallback must never smuggle a pallas_call through the XLA path
    (the cohort-Pallas rung is its own future kernel)."""
    body = _build_gnn_scan(eb, vb, F, act, pallas_ok=False)

    def one_tenant(carry, W, b, src_w, dst_w, valid_w):
        def step(h, xs):
            return body(h, W, b, xs)

        return jax.lax.scan(step, carry, (src_w, dst_w, valid_w))

    def run(carries, W, b, src, dst, valid):
        return jax.vmap(
            one_tenant,
            in_axes=(0, None, None, 0, 0, 0))(carries, W, b,
                                              src, dst, valid)

    return run


class GnnEngineBase(SummaryEngineBase):
    """Shared GNN engine scaffolding over SummaryEngineBase: the
    [vb+1, F] feature-slab carry, snapped-weight management, the GNN
    summary assembly, and the checkpoint layout (carry + `gnn`
    section). The chunk loop, WAL/replay, auto-checkpoint and the
    ingress pipeline are the base's, unchanged — a GNN stream gets
    the same durability contracts as the analytics engines."""

    AUTOTUNE = False
    TUNABLE_INGRESS = False
    ingress = "standard"
    METRICS_TIER = "gnn_scan"

    def _configure(self, edge_bucket: int, vertex_bucket: int,
                   feature_dim, activation) -> None:
        self.eb = seg_ops.bucket_size(edge_bucket)
        self.vb = seg_ops.bucket_size(vertex_bucket)
        self.F = int(feature_dim if feature_dim
                     else knobs.get_int("GS_GNN_F"))
        self.act = str(activation if activation
                       else (knobs.get_str("GS_GNN_ACT") or "relu"))
        if self.act not in _ACTS_JNP:
            raise ValueError(
                "unknown GNN activation %r (exact-parity choices: "
                "%s)" % (self.act, sorted(_ACTS_JNP)))
        if not (1 <= self.F <= 256):
            raise ValueError("feature_dim %d out of range [1, 256]"
                             % self.F)
        self._w_units, self._b_units = snap_weights(
            *default_weights(self.F), self.F)

    # -- weights / features -------------------------------------------
    def set_weights(self, W, b=None) -> None:
        """Adopt a dense-update layer, SNAPPED onto the lattice (see
        module docstring — arbitrary float weights would void the
        bit-exactness contract). Never recompiles: weights are
        dispatch arguments, not trace constants."""
        if b is None:
            b = np.zeros(self.F, np.float32)
        self._w_units, self._b_units = snap_weights(W, b, self.F)
        self._weights_changed()

    def _weights_changed(self) -> None:
        """Device engines refresh their on-device weight copies."""

    def weights(self):
        """(W_units, b_units) — the snapped lattice representation."""
        return self._w_units.copy(), self._b_units.copy()

    def load_features(self, feats) -> None:
        """Seed the per-vertex feature slab (real values, snapped).
        Only legal at a window boundary — mid-window the carry covers
        dispatched-but-undelivered state."""
        slab = snap_features(feats, self.vb, self.F)
        self._carry = (self._to_carry(slab),)

    def load_feature_units(self, slab: np.ndarray) -> None:
        """Adopt a prebuilt [vb+1, F] unit slab (e.g.
        default_features) without re-snapping."""
        slab = np.asarray(slab, np.float32)  # gslint: disable=host-sync (host-input normalization: callers pass numpy, never device values)
        if slab.shape != (self.vb + 1, self.F):
            raise ValueError("unit slab must be [vb+1=%d, F=%d]; got "
                             "%s" % (self.vb + 1, self.F, slab.shape))
        self._carry = (self._to_carry(slab),)

    # -- carry / checkpoint -------------------------------------------
    def _init_carry(self):
        return (jnp.zeros((self.vb + 1, self.F), jnp.float32),)

    def state(self) -> np.ndarray:
        """[vb, F] feature snapshot in lattice units."""
        (h,) = self._carry
        return np.asarray(h)[: self.vb].copy()  # gslint: disable=host-sync (sanctioned snapshot boundary: the engine's state() d2h)

    def state_dict(self) -> dict:
        state = super().state_dict()
        state["gnn"] = {
            "feat_dim": self.F,
            "act": self.act,
            "weights": self._w_units.copy(),
            "bias": self._b_units.copy(),
        }
        return state

    def load_state_dict(self, state: dict) -> None:
        g = state.get("gnn") or {}
        if int(g.get("feat_dim", self.F)) != self.F:  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
            raise ValueError(
                "feature-width mismatch: checkpoint carries F=%s, "
                "engine runs F=%d — the [vb+1, F] slab layout would "
                "shift" % (g.get("feat_dim"), self.F))
        act = g.get("act")
        if act is not None and act != self.act:
            raise ValueError(
                "activation mismatch: checkpoint was folded with "
                "act=%r, engine runs act=%r — replayed windows would "
                "diverge from the journal" % (act, self.act))
        super().load_state_dict(state)
        if g.get("weights") is not None:
            self._w_units = np.asarray(g["weights"],  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
                                       np.float32).copy()
            self._b_units = np.asarray(g["bias"], np.float32).copy()  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
            self._weights_changed()

    # -- summary assembly ---------------------------------------------
    def _finalize_summaries(self, item, src, dst, out: list) -> None:
        f_at, f_real, raw = item
        maxf, active, csum, nmsg = (
            x[:f_real] for x in self._materialize(raw))
        for w in range(f_real):
            out.append({
                "max_feat": int(maxf[w]),  # gslint: disable=host-sync (numpy-on-numpy after _materialize)
                "active_vertices": int(active[w]),  # gslint: disable=host-sync (numpy-on-numpy after _materialize)
                "feat_checksum": int(csum[w]),  # gslint: disable=host-sync (numpy-on-numpy after _materialize)
                "msg_edges": int(nmsg[w]),  # gslint: disable=host-sync (numpy-on-numpy after _materialize)
            })
        if latency.enabled():
            st = self._lat_stamps.pop(f_at, None)
            lane = self._lat_lane or self._wal_tenant
            for w in range(f_real):
                lo_w = (f_at + w) * self.eb
                latency.on_window(
                    lane,
                    edges=min(lo_w + self.eb, len(src)) - lo_w,
                    st=st, ordinal=self.windows_done + w,
                    defer=self._lat_defer)
        if provenance.armed():
            # same cursor arithmetic as the scan-family emitter: the
            # recorded span is what replay must stream to re-derive
            # exactly this summary (windows_done × eb contract)
            tenant = self._lat_lane or self._wal_tenant
            for w in range(f_real):
                lo = (self.windows_done + w) * self.eb
                lo_c = (f_at + w) * self.eb
                n_w = min(lo_c + self.eb, len(src)) - lo_c
                provenance.emit(
                    tenant=tenant, window=self.windows_done + w,
                    wal_lo=lo, wal_hi=lo + n_w,
                    tier=self.METRICS_TIER, program="gnn_round",
                    summary=out[len(out) - f_real + w])
        self.windows_done += f_real
        lo_e = f_at * self.eb
        metrics.mark_window(
            f_real, min((f_at + f_real) * self.eb, len(src)) - lo_e,
            engine=type(self).__name__, tier=self.METRICS_TIER)

    def _redo(self, src, dst, b_ovf: int, k_ovf: int) -> int:
        return 0  # no overflow concept: the GNN fold is always exact

    def warm_fallback(self) -> None:
        """No escalation path to warm — the GNN round has no overflow
        recount."""


class GnnSummaryEngine(GnnEngineBase):
    """Single-chip windowed GNN rounds, one dispatch per MAX_WINDOWS
    windows (a `lax.scan` over the chunk's [W, eb] slabs against the
    device-resident feature slab). The body is the XLA
    gather/segment-sum round, or the fused Pallas GNN kernel when
    `ops/pallas_window.resolve_gnn_pallas` selects it — bit-identical
    by the lattice argument either way."""

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 feature_dim: int = None, activation: str = None):
        self._configure(edge_bucket, vertex_bucket, feature_dim,
                        activation)
        body = _build_gnn_scan(self.eb, self.vb, self.F, self.act)
        self._pallas = bool(getattr(body, "gnn_pallas", False))

        @jax.jit
        def run(carry, W, b, src_w, dst_w, valid_w):
            def step(h, xs):
                return body(h, W, b, xs)

            return jax.lax.scan(step, carry, (src_w, dst_w, valid_w))

        # compile-watch + cost-observatory label: dispatches tag
        # their ledger spans program="gnn_scan" (or "gnn_pallas"),
        # joining the analytic slab model pallas_window registers
        self._run = metrics.wrap_jit(
            "gnn_pallas" if self._pallas else "gnn_scan", run)
        from . import pallas_window

        pallas_window.register_gnn_cost_model(self.eb, self.vb,
                                              self.F)
        self._wdev = None
        self._bdev = None
        self.reset()

    def _weights_changed(self) -> None:
        self._wdev = jnp.asarray(self._w_units)
        self._bdev = jnp.asarray(self._b_units)

    def _dispatch_async(self, s, d, valid):
        if self._wdev is None:
            self._weights_changed()
        (h,) = self._carry
        h, outs = self._run(h, self._wdev, self._bdev,
                            jnp.asarray(s), jnp.asarray(d),
                            jnp.asarray(valid))
        self._carry = (h,)
        return outs

    def _materialize(self, raw):
        return tuple(np.array(x) for x in raw)  # gslint: disable=host-sync (sanctioned finalize boundary: the engine's ONE batched d2h per chunk)


class GnnResidentEngine(GnnSummaryEngine):
    """Resident-tier rung of the GNN workload: the same scan program
    re-jitted with the feature-slab carry DONATED
    (ops/resident_engine.donate_kw — in-place slab updates where the
    backend honors donation, bit-identical undonated elsewhere) and a
    super-batch chunk size (GS_RESIDENT_SPB buckets), so a deep queue
    of windows costs one donated dispatch instead of many."""

    METRICS_TIER = "gnn_resident"

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 feature_dim: int = None, activation: str = None,
                 superbatch: int = None):
        super().__init__(edge_bucket, vertex_bucket, feature_dim,
                         activation)
        from . import resident_engine

        self.MAX_WINDOWS = seg_ops.bucket_size(
            superbatch if superbatch
            else resident_engine.resident_spb(self.eb))
        body = _build_gnn_scan(self.eb, self.vb, self.F, self.act)
        self._pallas = bool(getattr(body, "gnn_pallas", False))

        def run(carry, W, b, src_w, dst_w, valid_w):
            def step(h, xs):
                return body(h, W, b, xs)

            return jax.lax.scan(step, carry, (src_w, dst_w, valid_w))

        self._run = metrics.wrap_jit(
            "gnn_resident",
            jax.jit(run, **resident_engine.donate_kw()))
        self.reset()

    def _dispatch_async(self, s, d, valid):
        if self._wdev is None:
            self._weights_changed()
        (h,) = self._carry
        # the donated carry is CONSUMED by the dispatch; the returned
        # slab replaces it (same discipline as ResidentSummaryEngine)
        h, outs = self._run(h, self._wdev, self._bdev,
                            jnp.asarray(s), jnp.asarray(d),
                            jnp.asarray(valid))
        self._carry = (h,)
        return outs

    def state_dict(self) -> dict:
        # materializing the donated carry for a checkpoint must not
        # invalidate it: np.array copies d2h, the device slab stays
        # live for the next dispatch
        return super().state_dict()


class GnnHostEngine(GnnEngineBase):
    """Numpy twin of the GNN engines — the bit-exactness oracle and
    demotion floor: the same SummaryEngineBase chunk loop, window
    cuts, checkpoint layout and summary dicts, with the device round
    replayed per window in numpy (`np.add.at` aggregation, BLAS
    float32 matmul — exact by the lattice argument), no compiler and
    no device. Loadable straight from a GnnSummaryEngine (or
    resident) checkpoint of equal buckets and feature width."""

    METRICS_TIER = "host"

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 feature_dim: int = None, activation: str = None):
        self._configure(edge_bucket, vertex_bucket, feature_dim,
                        activation)
        self.reset()

    @classmethod
    def from_state(cls, state: dict) -> "GnnHostEngine":
        """Build a twin directly from a GNN engine checkpoint and
        adopt it — the gnn→host demotion hand-off."""
        g = state.get("gnn") or {}
        twin = cls(edge_bucket=int(state["edge_bucket"]),  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
                   vertex_bucket=int(state["vertex_bucket"]),  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
                   feature_dim=int(g.get("feat_dim") or 0) or None,
                   activation=g.get("act"))
        twin.load_state_dict(state)
        return twin

    def _init_carry(self):
        return (np.zeros((self.vb + 1, self.F), np.float32),)

    def _to_carry(self, a):
        return np.asarray(a, np.float32).copy()  # gslint: disable=host-sync (host twin: checkpoint carries are host numpy by construction)

    def _h2d(self, args):
        return args

    def _dispatch_async(self, s, d, valid):
        vb, F = self.vb, self.F
        sh = agg_shift(self.eb)
        sc = np.float32(2.0 ** -sh)
        cap = np.float32(UNIT_CAP)
        actf = _ACTS_NP[self.act]
        (h,) = self._carry
        h = h.copy()
        s = np.asarray(s)  # gslint: disable=host-sync (host twin: pipeline payloads are numpy by _h2d identity)
        d = np.asarray(d)  # gslint: disable=host-sync (host twin: pipeline payloads are numpy by _h2d identity)
        valid = np.asarray(valid)  # gslint: disable=host-sync (host twin: pipeline payloads are numpy by _h2d identity)
        num_w = s.shape[0]
        maxf = np.zeros(num_w, np.int32)
        active = np.zeros(num_w, np.int32)
        csum = np.zeros(num_w, np.int32)
        nmsg = np.zeros(num_w, np.int32)
        for i in range(num_w):
            v = valid[i]
            if v.any():
                si = np.where(v, s[i], vb).astype(np.int64)
                di = np.where(v, d[i], vb).astype(np.int64)
                msgs = h[si]
                if sh:
                    msgs = np.floor(msgs * sc)
                m = np.zeros((vb + 1, F), np.float32)
                np.add.at(m, di, msgs)
                p = np.minimum(h + np.minimum(m, cap), cap)
                z = p @ self._w_units + self._b_units
                h = np.clip(actf(z), 0.0, cap).astype(np.float32)
                h[vb] = 0.0
            # else: EMPTY window holds the slab (the device round's
            # rule — padding inertness and parity depend on it)
            maxf[i] = np.int32(h[:vb].max())
            active[i] = np.int32(np.sum(np.any(h[:vb] > 0, axis=1)))
            # exact int64 total, wrapped to the device's native
            # wrapping-int32 accumulation (order-free mod 2^32)
            csum[i] = _wrap_i32(h.astype(np.int64).sum())
            nmsg[i] = np.int32(np.sum(v))
        self._carry = (h,)
        return maxf, active, csum, nmsg

    def _materialize(self, raw):
        return tuple(np.asarray(x) for x in raw)  # gslint: disable=host-sync (host twin: raw outputs are already numpy)

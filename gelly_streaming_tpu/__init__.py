"""gelly_streaming_tpu — a TPU-native streaming graph analytics framework.

A ground-up re-design of the capabilities of `gelly-streaming`
(single-pass graph streaming on Flink) for TPU hardware: a thin
host-side stream driver feeds tumbling-window COO edge batches to
XLA-compiled JAX/Pallas kernels; summaries merge across chips with
collectives over a `jax.sharding.Mesh`.

Layers (SURVEY.md §1):
- core/     stream driver: env, DataStream slice, GraphStream API, time
- ops/      device kernels: segment folds, neighborhoods, triangles, union-find
- models/   algorithm library + workloads (CC, bipartiteness, triangles, …)
- parallel/ multi-chip: mesh, shard_map merge-tree, collectives
- utils/    aggregate state types (DisjointSet, Candidates, events)
- io/       sources/sinks
"""

from .core.datastream import DataStream
from .core.driver import StreamingAnalyticsDriver, WindowResult
from .core.env import StreamEnvironment
from .core.functions import (EdgesApply, EdgesFold, EdgesReduce,
                             JaxEdgesApply, JaxEdgesFold, JaxEdgesReduce)
from .core.graphstream import GraphStream, GraphWindowStream, SimpleEdgeStream
from .core.gtime import (AscendingTimestampExtractor, ManualClock, SystemClock,
                         Time, TimeCharacteristic)
from .core.types import NULL, Edge, EdgeDirection, NullValue, Vertex
from .core.tenancy import GnnTenantCohort
from .ops.gnn_window import (GnnHostEngine, GnnResidentEngine,
                             GnnSummaryEngine)

__version__ = "0.1.0"

__all__ = [
    "DataStream", "StreamEnvironment", "EdgesApply", "EdgesFold",
    "EdgesReduce", "JaxEdgesApply", "JaxEdgesFold", "JaxEdgesReduce",
    "GraphStream", "GraphWindowStream", "SimpleEdgeStream",
    "AscendingTimestampExtractor", "ManualClock", "SystemClock", "Time",
    "TimeCharacteristic", "NULL", "Edge", "EdgeDirection", "NullValue",
    "Vertex", "StreamingAnalyticsDriver", "WindowResult",
    "GnnSummaryEngine", "GnnResidentEngine", "GnnHostEngine",
    "GnnTenantCohort",
]

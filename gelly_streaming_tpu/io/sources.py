"""Edge sources.

The columnar fast path (`load_edge_arrays`) parses edge files with the
native C++ parser (native/ingest.cpp) straight into int64 COO arrays —
the host-ingest stage that feeds device pipelines without building
per-record Python objects. `read_edge_file` exposes the same data as a
record-level DataStream for the reference-shaped API
(reference sources: readTextFile + edge MapFunction,
WindowTriangles.java:175-185).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .. import native
from ..utils import faults
from ..core.datastream import DataStream
from ..core.graphstream import SimpleEdgeStream
from ..core.gtime import AscendingTimestampExtractor
from ..core.plan import OpNode
from ..core.types import NULL, Edge


def load_edge_arrays(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(src, dst, ts) int64 arrays; ts = -1 where the file has no third
    column. Native parser when available."""
    return native.parse_edge_file(path)


def _iter_edge_chunks_sync(path: str, chunk_bytes: int):
    remainder = b""
    with open(path, "rb") as f:
        while True:
            buf = f.read(chunk_bytes)
            if not buf:
                break
            data = remainder + buf
            cut = data.rfind(b"\n")
            if cut < 0:
                remainder = data
                continue
            remainder = data[cut + 1:]
            # fault-injection point (utils/faults site "parse"): a
            # corrupt_bytes plan garbles an edge line here, pinning
            # that the parser DROPS a torn line without misaligning
            # the arrays (tests/operations/test_faults.py)
            arrays = native.parse_edge_bytes(
                faults.fire("parse", data[:cut + 1]))
            if len(arrays[0]):
                yield arrays
    if remainder:
        arrays = native.parse_edge_bytes(faults.fire("parse", remainder))
        if len(arrays[0]):
            yield arrays


def tail_edge_file(path: str, stop, chunk_bytes: int = 1 << 20,
                   poll_s: float = 0.2):
    """Follow a growing 'src dst [ts]' file (the serving front-end's
    file-tail source, core/serve.StreamServer.attach_file_tail):
    yield parsed COO chunks for every complete appended line, polling
    every `poll_s` seconds. `stop` is a threading.Event ending the
    tail; on stop the final (possibly newline-less) record is flushed
    through the parser — the same EOF contract as
    `_iter_edge_chunks_sync`, so a producer that doesn't terminate
    its last line still loses nothing."""
    remainder = b""
    with open(path, "rb") as f:
        while not stop.is_set():
            buf = f.read(chunk_bytes)
            if not buf:
                stop.wait(poll_s)
                continue
            data = remainder + buf
            cut = data.rfind(b"\n")
            if cut < 0:
                remainder = data
                continue
            remainder = data[cut + 1:]
            arrays = native.parse_edge_bytes(
                faults.fire("parse", data[:cut + 1]))
            if len(arrays[0]):
                yield arrays
        # drain: whatever landed between the last poll and stop, plus
        # a final line with no trailing newline
        remainder += f.read()
    if remainder:
        arrays = native.parse_edge_bytes(faults.fire("parse", remainder))
        if len(arrays[0]):
            yield arrays


def iter_edge_chunks(path: str, chunk_bytes: int = 1 << 24,
                     prefetch: int = 2):
    """Stream a 'src dst [ts]' file as bounded-memory COO chunks: read
    `chunk_bytes` at a time, cut at the last newline, parse with the
    native parser. The unbounded-file ingestion the reference gets from
    Flink's streaming file source — no full-file materialization.

    `prefetch` > 0 reads and parses up to that many chunks AHEAD in a
    producer thread, overlapping host IO/parse with whatever the
    consumer does with the previous chunk (the driver's device
    dispatches). The native parser is a ctypes call, which drops the
    GIL for the C parse, so the overlap is real. prefetch=0 parses
    inline (the producer thread is skipped entirely)."""
    if chunk_bytes < 1:
        raise ValueError("chunk_bytes must be >= 1")
    if prefetch < 1:
        yield from _iter_edge_chunks_sync(path, chunk_bytes)
        return

    import queue
    import threading

    q: "queue.Queue" = queue.Queue(maxsize=prefetch)
    DONE, ERROR = object(), object()
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                pass
        return False

    def produce():
        try:
            for arrays in _iter_edge_chunks_sync(path, chunk_bytes):
                if stop.is_set() or not _put(arrays):
                    return  # consumer gone: stop reading the file
            _put(DONE)
        except BaseException as e:  # surface in the consumer  # gslint: disable=except-hygiene (forwarded: consumer re-raises it)
            _put((ERROR, e))

    t = threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is DONE:
                break
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] is ERROR:
                raise item[1]
            yield item
    finally:
        # consumer abandoned (or finished): cancel the producer — it
        # checks `stop` between chunks, so at most one in-flight chunk
        # is parsed, NOT the rest of the file
        stop.set()
        while t.is_alive():
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.05)


def read_edge_file(env, path: str,
                   event_time: bool = False) -> SimpleEdgeStream:
    """A SimpleEdgeStream over a 'src dst [ts]' file. With event_time,
    the third column drives event-time windows (and edge values become
    NullValue, matching the reference's RemoveEdgeValue pattern)."""
    src, dst, ts = load_edge_arrays(path)

    def _records():
        if event_time:
            for s, d, t in zip(src.tolist(), dst.tolist(), ts.tolist()):
                yield Edge(s, d, t)
        else:
            for s, d in zip(src.tolist(), dst.tolist()):
                yield Edge(s, d, NULL)

    edges = DataStream(env, OpNode("source", (), items_fn=_records))
    if event_time:
        stream = SimpleEdgeStream(
            edges, env,
            timestamp_extractor=AscendingTimestampExtractor(lambda e: e.value),
        )
        return stream.map_edges(lambda e: NULL)
    return SimpleEdgeStream(edges, env)

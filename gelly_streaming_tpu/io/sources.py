"""Edge sources.

The columnar fast path (`load_edge_arrays`) parses edge files with the
native C++ parser (native/ingest.cpp) straight into int64 COO arrays —
the host-ingest stage that feeds device pipelines without building
per-record Python objects. `read_edge_file` exposes the same data as a
record-level DataStream for the reference-shaped API
(reference sources: readTextFile + edge MapFunction,
WindowTriangles.java:175-185).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .. import native
from ..core.datastream import DataStream
from ..core.graphstream import SimpleEdgeStream
from ..core.gtime import AscendingTimestampExtractor
from ..core.plan import OpNode
from ..core.types import NULL, Edge


def load_edge_arrays(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(src, dst, ts) int64 arrays; ts = -1 where the file has no third
    column. Native parser when available."""
    return native.parse_edge_file(path)


def read_edge_file(env, path: str,
                   event_time: bool = False) -> SimpleEdgeStream:
    """A SimpleEdgeStream over a 'src dst [ts]' file. With event_time,
    the third column drives event-time windows (and edge values become
    NullValue, matching the reference's RemoveEdgeValue pattern)."""
    src, dst, ts = load_edge_arrays(path)

    def _records():
        if event_time:
            for s, d, t in zip(src.tolist(), dst.tolist(), ts.tolist()):
                yield Edge(s, d, t)
        else:
            for s, d in zip(src.tolist(), dst.tolist()):
                yield Edge(s, d, NULL)

    edges = DataStream(env, OpNode("source", (), items_fn=_records))
    if event_time:
        stream = SimpleEdgeStream(
            edges, env,
            timestamp_extractor=AscendingTimestampExtractor(lambda e: e.value),
        )
        return stream.map_edges(lambda e: NULL)
    return SimpleEdgeStream(edges, env)

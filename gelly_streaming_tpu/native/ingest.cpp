// Native host-runtime kernels: edge-file parsing, tumbling-window
// assignment, and incremental vertex interning.
//
// The reference delegates its host runtime to Flink's JVM (SURVEY.md §1
// L1); our host driver's hot loops — the parts that feed the TPU —
// are implemented here and exposed over a C ABI consumed via ctypes
// (gelly_streaming_tpu/native/__init__.py). Python fallbacks exist for
// every entry point.
//
// Build: make -C gelly_streaming_tpu/native   (produces libgsnative.so)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------
// Edge text parsing: whitespace-separated "src dst [ts]" lines.
// Returns the number of edges parsed; fills src/dst/ts (ts = -1 when a
// line has only two fields). Stops at max_edges.
// ---------------------------------------------------------------------
int64_t gs_parse_edges(const char* buf, int64_t len, int64_t max_edges,
                       int64_t* src, int64_t* dst, int64_t* ts) {
    int64_t count = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end && count < max_edges) {
        // skip blank space / newlines
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
            ++p;
        if (p >= end) break;
        int64_t fields[3] = {0, 0, -1};
        int nfields = 0;
        // Only the first three tokens are parsed; anything after them on
        // the line (labels, extra columns) is ignored — same semantics as
        // the Python fallback (native/__init__.py), so results cannot
        // depend on whether the native library is available.
        while (p < end && *p != '\n' && nfields < 3) {
            while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
            if (p >= end || *p == '\n') break;
            bool neg = false;
            if (*p == '-') { neg = true; ++p; }
            else if (*p == '+') { ++p; }
            int64_t v = 0;
            bool digits = false;
            while (p < end && *p >= '0' && *p <= '9') {
                v = v * 10 + (*p - '0');
                ++p;
                digits = true;
            }
            if (!digits || (p < end && *p != ' ' && *p != '\t' &&
                            *p != '\n' && *p != '\r')) {
                // malformed token among the first three: drop the line
                nfields = -1;
                break;
            }
            fields[nfields] = neg ? -v : v;
            ++nfields;
        }
        while (p < end && *p != '\n') ++p;  // discard the rest of the line
        if (nfields >= 2) {
            src[count] = fields[0];
            dst[count] = fields[1];
            ts[count] = fields[2];
            ++count;
        }
    }
    return count;
}

// ---------------------------------------------------------------------
// Tumbling-window assignment: wstart[i] = ts[i] - ts[i] % size
// (Flink TimeWindow semantics; SimpleEdgeStream.java:159-167).
// ---------------------------------------------------------------------
void gs_assign_windows(const int64_t* ts, int64_t n, int64_t size,
                       int64_t* wstart) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t t = ts[i];
        int64_t w = t % size;
        if (w < 0) w += size;  // floor semantics for negative timestamps
        wstart[i] = t - w;
    }
}

// ---------------------------------------------------------------------
// Incremental interner: stable dense slots for int64 vertex ids
// (SURVEY.md §7 "vertex-id interning at stream rate").
// ---------------------------------------------------------------------
struct GsInterner {
    std::unordered_map<int64_t, int32_t> to_dense;
    std::vector<int64_t> to_id;
};

void* gs_interner_new() { return new GsInterner(); }

void gs_interner_free(void* h) { delete static_cast<GsInterner*>(h); }

int64_t gs_interner_size(void* h) {
    return static_cast<int64_t>(static_cast<GsInterner*>(h)->to_id.size());
}

void gs_interner_intern(void* h, const int64_t* ids, int64_t n,
                        int32_t* out) {
    auto* interner = static_cast<GsInterner*>(h);
    for (int64_t i = 0; i < n; ++i) {
        auto it = interner->to_dense.find(ids[i]);
        if (it == interner->to_dense.end()) {
            int32_t slot = static_cast<int32_t>(interner->to_id.size());
            interner->to_dense.emplace(ids[i], slot);
            interner->to_id.push_back(ids[i]);
            out[i] = slot;
        } else {
            out[i] = it->second;
        }
    }
}

// dense slot -> original id (bulk)
void gs_interner_lookup(void* h, const int32_t* dense, int64_t n,
                        int64_t* out) {
    auto* interner = static_cast<GsInterner*>(h);
    for (int64_t i = 0; i < n; ++i) out[i] = interner->to_id[dense[i]];
}

}  // extern "C"

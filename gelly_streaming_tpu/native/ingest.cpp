// Native host-runtime kernels: edge-file parsing, tumbling-window
// assignment, and incremental vertex interning.
//
// The reference delegates its host runtime to Flink's JVM (SURVEY.md §1
// L1); our host driver's hot loops — the parts that feed the TPU —
// are implemented here and exposed over a C ABI consumed via ctypes
// (gelly_streaming_tpu/native/__init__.py). Python fallbacks exist for
// every entry point.
//
// Build: make -C gelly_streaming_tpu/native   (produces libgsnative.so)

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------
// Edge text parsing: whitespace-separated "src dst [ts]" lines.
// Returns the number of edges parsed; fills src/dst/ts (ts = -1 when a
// line has only two fields). Stops at max_edges.
// ---------------------------------------------------------------------
int64_t gs_parse_edges(const char* buf, int64_t len, int64_t max_edges,
                       int64_t* src, int64_t* dst, int64_t* ts) {
    int64_t count = 0;
    const char* p = buf;
    const char* end = buf + len;
    while (p < end && count < max_edges) {
        // skip blank space / newlines
        while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
            ++p;
        if (p >= end) break;
        int64_t fields[3] = {0, 0, -1};
        int nfields = 0;
        // Only the first three tokens are parsed; anything after them on
        // the line (labels, extra columns) is ignored — same semantics as
        // the Python fallback (native/__init__.py), so results cannot
        // depend on whether the native library is available.
        while (p < end && *p != '\n' && nfields < 3) {
            while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
            if (p >= end || *p == '\n') break;
            bool neg = false;
            if (*p == '-') { neg = true; ++p; }
            else if (*p == '+') { ++p; }
            int64_t v = 0;
            bool digits = false;
            while (p < end && *p >= '0' && *p <= '9') {
                v = v * 10 + (*p - '0');
                ++p;
                digits = true;
            }
            if (!digits || (p < end && *p != ' ' && *p != '\t' &&
                            *p != '\n' && *p != '\r')) {
                // malformed token among the first three: drop the line
                nfields = -1;
                break;
            }
            fields[nfields] = neg ? -v : v;
            ++nfields;
        }
        while (p < end && *p != '\n') ++p;  // discard the rest of the line
        if (nfields >= 2) {
            src[count] = fields[0];
            dst[count] = fields[1];
            ts[count] = fields[2];
            ++count;
        }
    }
    return count;
}

// ---------------------------------------------------------------------
// Tumbling-window assignment: wstart[i] = ts[i] - ts[i] % size
// (Flink TimeWindow semantics; SimpleEdgeStream.java:159-167).
// ---------------------------------------------------------------------
void gs_assign_windows(const int64_t* ts, int64_t n, int64_t size,
                       int64_t* wstart) {
    for (int64_t i = 0; i < n; ++i) {
        int64_t t = ts[i];
        int64_t w = t % size;
        if (w < 0) w += size;  // floor semantics for negative timestamps
        wstart[i] = t - w;
    }
}

// ---------------------------------------------------------------------
// Incremental interner: stable dense slots for int64 vertex ids
// (SURVEY.md §7 "vertex-id interning at stream rate").
// ---------------------------------------------------------------------
struct GsInterner {
    std::unordered_map<int64_t, int32_t> to_dense;
    std::vector<int64_t> to_id;
};

void* gs_interner_new() { return new GsInterner(); }

void gs_interner_free(void* h) { delete static_cast<GsInterner*>(h); }

int64_t gs_interner_size(void* h) {
    return static_cast<int64_t>(static_cast<GsInterner*>(h)->to_id.size());
}

void gs_interner_intern(void* h, const int64_t* ids, int64_t n,
                        int32_t* out) {
    auto* interner = static_cast<GsInterner*>(h);
    for (int64_t i = 0; i < n; ++i) {
        auto it = interner->to_dense.find(ids[i]);
        if (it == interner->to_dense.end()) {
            int32_t slot = static_cast<int32_t>(interner->to_id.size());
            interner->to_dense.emplace(ids[i], slot);
            interner->to_id.push_back(ids[i]);
            out[i] = slot;
        } else {
            out[i] = it->second;
        }
    }
}

// dense slot -> original id (bulk)
void gs_interner_lookup(void* h, const int32_t* dense, int64_t n,
                        int64_t* out) {
    auto* interner = static_cast<GsInterner*>(h);
    for (int64_t i = 0; i < n; ++i) out[i] = interner->to_id[dense[i]];
}

// ---------------------------------------------------------------------
// Exact window triangle count — the native tier of the streaming
// counter (ops/triangles._resolve_stream_impl "native").
//
// Same counting invariant as the device kernel (ops/triangles.py
// build_window_counter) and the numpy tier (ops/host_triangles.py):
// drop self-loops, undirect + dedupe, orient each edge
// low(deg, id) -> high(deg, id), count every triangle once — at its
// min-rank edge, by two-pointer intersection of the endpoints' sorted
// out-neighbor lists ("compact forward": per-source out-degree is
// O(sqrt E) after orientation, so the scan is O(E^1.5) worst case with
// cache-friendly constant factors a single-core numpy pipeline cannot
// reach (no temporary wedge materialization, no log-factor probes).
// ---------------------------------------------------------------------
namespace {

// LSD radix sort for the window's packed (a*v + b) edge keys: the two
// key sorts dominate count_one_window at bench window sizes, and a
// counting radix over 11-bit digits beats std::sort's branchy
// comparisons ~3-4x on 32K random uint64s. Pass count adapts to the
// actual key range (v^2), so small id spaces pay 2-3 passes.
static void radix_sort_keys(std::vector<uint64_t>& a,
                            std::vector<uint64_t>& tmp,
                            uint64_t max_key) {
    constexpr int B = 11, R = 1 << B;
    if (a.size() < 2048) {  // small windows: std::sort wins
        std::sort(a.begin(), a.end());
        return;
    }
    int passes = 1;
    while (passes * B < 64 && (max_key >> (uint64_t(passes) * B)))
        ++passes;
    tmp.resize(a.size());
    int64_t cnt[R];
    uint64_t shift = 0;
    for (int p = 0; p < passes; ++p, shift += B) {
        std::fill(cnt, cnt + R, 0);
        for (uint64_t x : a) ++cnt[(x >> shift) & (R - 1)];
        int64_t run = 0;
        for (int i = 0; i < R; ++i) {
            int64_t c = cnt[i];
            cnt[i] = run;
            run += c;
        }
        for (uint64_t x : a) tmp[cnt[(x >> shift) & (R - 1)]++] = x;
        a.swap(tmp);
    }
}

int64_t count_one_window(const int64_t* src, const int64_t* dst,
                         int64_t n, std::vector<int64_t>& scratch_ids,
                         std::vector<uint64_t>& keys,
                         std::vector<int32_t>& deg,
                         std::vector<int64_t>& starts,
                         std::vector<uint64_t>& radix_tmp) {
    if (n <= 2) return 0;
    // id space: ids that are already small non-negative ints (every
    // interned stream; the bench's generated streams) index arrays
    // directly — no compression pass. Arbitrary/huge ids fall back to
    // sort-unique + binary-search compression.
    int64_t max_id = -1;
    bool direct = true;
    for (int64_t i = 0; i < n; ++i) {
        if (src[i] < 0 || dst[i] < 0) { direct = false; break; }
        if (src[i] > max_id) max_id = src[i];
        if (dst[i] > max_id) max_id = dst[i];
    }
    // direct indexing allocates O(max_id) scratch per call: only worth
    // it when the id space is within a small factor of the edge count
    if (max_id >= (int64_t(1) << 22) || max_id > 16 * n) direct = false;
    uint64_t v;
    keys.clear();
    keys.reserve(n);
    if (direct) {
        v = static_cast<uint64_t>(max_id) + 1;
        for (int64_t i = 0; i < n; ++i) {
            if (src[i] == dst[i]) continue;  // self-loop
            uint64_t a = static_cast<uint64_t>(src[i]);
            uint64_t b = static_cast<uint64_t>(dst[i]);
            if (a > b) std::swap(a, b);
            keys.push_back(a * v + b);
        }
        if (keys.empty()) return 0;
    } else {
        // local dense ids: sort-unique of all endpoints
        scratch_ids.clear();
        scratch_ids.reserve(2 * n);
        for (int64_t i = 0; i < n; ++i) {
            if (src[i] == dst[i]) continue;  // self-loop
            scratch_ids.push_back(src[i]);
            scratch_ids.push_back(dst[i]);
        }
        if (scratch_ids.empty()) return 0;
        std::sort(scratch_ids.begin(), scratch_ids.end());
        scratch_ids.erase(
            std::unique(scratch_ids.begin(), scratch_ids.end()),
            scratch_ids.end());
        v = scratch_ids.size();
        auto dense = [&](int64_t id) -> uint64_t {
            return static_cast<uint64_t>(
                std::lower_bound(scratch_ids.begin(), scratch_ids.end(),
                                 id)
                - scratch_ids.begin());
        };
        for (int64_t i = 0; i < n; ++i) {
            if (src[i] == dst[i]) continue;
            uint64_t a = dense(src[i]), b = dense(dst[i]);
            if (a > b) std::swap(a, b);
            keys.push_back(a * v + b);
        }
    }
    radix_sort_keys(keys, radix_tmp, v * v - 1);
    keys.erase(std::unique(keys.begin(), keys.end()), keys.end());
    const int64_t e = static_cast<int64_t>(keys.size());

    // degrees over the deduped undirected edges
    deg.assign(v, 0);
    for (int64_t i = 0; i < e; ++i) {
        ++deg[keys[i] / v];
        ++deg[keys[i] % v];
    }

    // orient by (degree, id) and re-sort by (a, b): out-adjacency
    // lists come out sorted, ready for two-pointer intersection
    for (int64_t i = 0; i < e; ++i) {
        uint64_t lo = keys[i] / v, hi = keys[i] % v;
        if (deg[lo] > deg[hi] || (deg[lo] == deg[hi] && lo > hi))
            std::swap(lo, hi);
        keys[i] = lo * v + hi;
    }
    radix_sort_keys(keys, radix_tmp, v * v - 1);

    // CSR starts of the oriented lists
    starts.assign(v + 1, 0);
    for (int64_t i = 0; i < e; ++i) ++starts[keys[i] / v + 1];
    for (uint64_t u = 0; u < v; ++u) starts[u + 1] += starts[u];

    // for each oriented edge (a, b): |N_out(a) ∩ N_out(b)|
    int64_t count = 0;
    for (int64_t i = 0; i < e; ++i) {
        const uint64_t a = keys[i] / v, b = keys[i] % v;
        int64_t pa = starts[a], ea = starts[a + 1];
        int64_t pb = starts[b], eb2 = starts[b + 1];
        while (pa < ea && pb < eb2) {
            const uint64_t xa = keys[pa] % v, xb = keys[pb] % v;
            if (xa == xb) { ++count; ++pa; ++pb; }
            else if (xa < xb) ++pa;
            else ++pb;
        }
    }
    return count;
}

}  // namespace

// ---------------------------------------------------------------------
// Windowed edge reduce — the native tier of ops/windowed_reduce.py
// (BASELINE config #2: reduceOnEdges over tumbling count windows,
// reference hot loop GraphWindowStream.java:101-121).
//
// One fused pass: for every edge, fold its value into the (window,
// vertex) cell of the chosen endpoint(s) and bump the cell's count —
// the two outputs the engine's contract requires, produced in a single
// cache-resident loop (the numpy tier pays two full bincount passes
// plus flattened cell-id materialization).
//
// op: 0 = sum, 1 = min, 2 = max.  direction: 0 = out (src), 1 = in
// (dst), 2 = all (both endpoints).  cells/counts are [num_w, vbp]
// row-major, cells pre-filled by the CALLER with the monoid identity
// (counts with 0); vertex ids must lie in [0, vbp).
// ---------------------------------------------------------------------
}  // extern "C" (templates need C++ linkage; reopened below)

namespace {

template <int OP, bool SRC, bool DST, typename ID, typename VAL,
          typename OUT>
int64_t reduce_loop(const ID* src, const ID* dst, const VAL* val,
                    int64_t n, int64_t eb, int64_t vbp, OUT* cells,
                    OUT* counts) {
    int64_t oob = 0;   // out-of-range ids: counted, never written
    for (int64_t lo = 0, w = 0; lo < n; lo += eb, ++w) {
        const int64_t hi = (n - lo < eb) ? n : lo + eb;
        OUT* wc = cells + w * vbp;
        OUT* wn = counts + w * vbp;
        for (int64_t i = lo; i < hi; ++i) {
            const OUT v = static_cast<OUT>(val[i]);
            if (SRC) {
                // unsigned compare rejects negatives too
                if (static_cast<uint64_t>(src[i])
                        >= static_cast<uint64_t>(vbp)) { ++oob; }
                else {
                    OUT* c = wc + src[i];
                    if (OP == 0) *c += v;
                    else if (OP == 1) { if (v < *c) *c = v; }
                    else { if (v > *c) *c = v; }
                    ++wn[src[i]];
                }
            }
            if (DST) {
                if (static_cast<uint64_t>(dst[i])
                        >= static_cast<uint64_t>(vbp)) { ++oob; }
                else {
                    OUT* c = wc + dst[i];
                    if (OP == 0) *c += v;
                    else if (OP == 1) { if (v < *c) *c = v; }
                    else { if (v > *c) *c = v; }
                    ++wn[dst[i]];
                }
            }
        }
    }
    return oob;
}

template <typename ID, typename VAL, typename OUT>
int64_t reduce_dispatch(const ID* src, const ID* dst, const VAL* val,
                        int64_t n, int64_t eb, int64_t vbp, int32_t op,
                        int32_t direction, OUT* cells, OUT* counts) {
    using Fn = int64_t (*)(const ID*, const ID*, const VAL*, int64_t,
                           int64_t, int64_t, OUT*, OUT*);
    static const Fn table[3][3] = {
        {reduce_loop<0, true, false, ID, VAL, OUT>,
         reduce_loop<0, false, true, ID, VAL, OUT>,
         reduce_loop<0, true, true, ID, VAL, OUT>},
        {reduce_loop<1, true, false, ID, VAL, OUT>,
         reduce_loop<1, false, true, ID, VAL, OUT>,
         reduce_loop<1, true, true, ID, VAL, OUT>},
        {reduce_loop<2, true, false, ID, VAL, OUT>,
         reduce_loop<2, false, true, ID, VAL, OUT>,
         reduce_loop<2, true, true, ID, VAL, OUT>},
    };
    return table[op][direction](src, dst, val, n, eb, vbp, cells,
                                counts);
}

}  // namespace

extern "C" {

// Returns the number of out-of-range vertex ids encountered (the
// Python wrapper raises when nonzero — other tiers fail loudly on bad
// ids, this one must never scribble outside its slabs). op and
// endpoint selection are hoisted into compile-time specializations
// (the generic form's per-edge division + branches halved throughput).
int64_t gs_windowed_reduce(const int64_t* src, const int64_t* dst,
                           const int64_t* val, int64_t n, int64_t eb,
                           int64_t vbp, int32_t op, int32_t direction,
                           int64_t* cells, int64_t* counts) {
    return reduce_dispatch(src, dst, val, n, eb, vbp, op, direction,
                           cells, counts);
}

// int32 ids + values form: no up-conversion copies on the Python side
// (interned slots and typical weights are int32; accumulation is
// int64 either way)
int64_t gs_windowed_reduce_i32(const int32_t* src, const int32_t* dst,
                               const int32_t* val, int64_t n,
                               int64_t eb, int64_t vbp, int32_t op,
                               int32_t direction, int64_t* cells,
                               int64_t* counts) {
    return reduce_dispatch(src, dst, val, n, eb, vbp, op, direction,
                           cells, counts);
}

// All-int32 form: int32 output slabs halve the faulted/written output
// bytes and drop the Python-side astype copy entirely. Only safe when
// the caller proves the worst-case cell sum fits int32 (the wrapper's
// overflow bound, mirroring the numpy tier's exact_bincount guard) —
// min/max outputs are input values, always safe for int32 inputs.
int64_t gs_windowed_reduce_i32o(const int32_t* src, const int32_t* dst,
                                const int32_t* val, int64_t n,
                                int64_t eb, int64_t vbp, int32_t op,
                                int32_t direction, int32_t* cells,
                                int32_t* counts) {
    return reduce_dispatch(src, dst, val, n, eb, vbp, op, direction,
                           cells, counts);
}

// int64 ids + int32 values/outputs: the common bench/driver shape when
// ids arrive un-interned (int64) — avoids both the caller-side id
// downcast (two full min/max scans + casts) and the int64 output
// slabs. Out-of-range ids (including any beyond int32) hit the
// unsigned bound check and are reported, never wrapped.
int64_t gs_windowed_reduce_i64i32o(const int64_t* src,
                                   const int64_t* dst,
                                   const int32_t* val, int64_t n,
                                   int64_t eb, int64_t vbp, int32_t op,
                                   int32_t direction, int32_t* cells,
                                   int32_t* counts) {
    return reduce_dispatch(src, dst, val, n, eb, vbp, op, direction,
                           cells, counts);
}

// ---------------------------------------------------------------------
// Carried-state windowed snapshot analytics: degrees + connected-
// component labels + bipartite double cover over tumbling eb-sized
// windows, per-window snapshot rows written into caller buffers.
//
// Host tier of the driver's batched snapshot scan (core/driver.py
// _run_batched): the reference computes these in Flink operators
// (SURVEY.md §2.2-2.3); on a CPU fallback a carried union-find beats
// re-running the XLA fixpoint scan. Semantics parity with the device
// path: labels converge to the component's MINIMUM member id (union
// attaches the larger root beneath the smaller), the double cover
// joins (u,+)-(w,-) and (u,-)-(w,+) at offset `vb`, and degree state
// is int32 like the device carry. Carried arrays use the SAME layout
// as the driver's host mirrors, so checkpoints stay interchangeable
// between tiers.
// ---------------------------------------------------------------------
static inline int32_t snap_find(int32_t* p, int32_t x) {
    int32_t r = x;
    while (p[r] != r) r = p[r];
    while (p[x] != r) {  // path compression
        int32_t nxt = p[x];
        p[x] = r;
        x = nxt;
    }
    return r;
}

static inline void snap_union(int32_t* p, int32_t a, int32_t b) {
    int32_t ra = snap_find(p, a), rb = snap_find(p, b);
    if (ra == rb) return;
    if (ra < rb) p[rb] = ra; else p[ra] = rb;  // min-id root
}

// flags: bit0 degrees, bit1 cc, bit2 bipartite. Buffers for disabled
// analytics may be null. Windows are [offsets[w], offsets[w+1])
// slices of the flat COO arrays (varying lengths — the driver's
// event-time windows). Snapshot rows: out_deg/out_cc [num_w, vb],
// out_cov [num_w, 2*vb]. Returns the number of windows written.
int64_t gs_snapshot_windows(const int32_t* src, const int32_t* dst,
                            const int64_t* offsets, int64_t num_w,
                            int64_t vb, int32_t flags,
                            int32_t* deg, int32_t* cc, int32_t* cov,
                            int32_t* out_deg, int32_t* out_cc,
                            int32_t* out_cov) {
    const bool want_deg = flags & 1, want_cc = flags & 2,
               want_cov = flags & 4;
    int64_t w = 0;
    for (; w < num_w; ++w) {
        for (int64_t i = offsets[w]; i < offsets[w + 1]; ++i) {
            const int32_t s = src[i], d = dst[i];
            if (want_deg) { ++deg[s]; ++deg[d]; }
            if (want_cc) snap_union(cc, s, d);
            if (want_cov) {
                snap_union(cov, s, (int32_t)(d + vb));
                snap_union(cov, (int32_t)(s + vb), d);
            }
        }
        if (want_deg)
            std::memcpy(out_deg + w * vb, deg, vb * sizeof(int32_t));
        if (want_cc) {
            for (int64_t v = 0; v < vb; ++v)
                cc[v] = snap_find(cc, (int32_t)v);  // flatten = snapshot
            std::memcpy(out_cc + w * vb, cc, vb * sizeof(int32_t));
        }
        if (want_cov) {
            for (int64_t v = 0; v < 2 * vb; ++v)
                cov[v] = snap_find(cov, (int32_t)v);
            std::memcpy(out_cov + w * 2 * vb, cov,
                        2 * vb * sizeof(int32_t));
        }
    }
    return w;
}

// counts[w] = exact triangle count of the w-th tumbling eb-sized
// window of the stream (the trailing window may be shorter); returns
// the number of windows written.
int64_t gs_triangle_count_stream(const int64_t* src, const int64_t* dst,
                                 int64_t n, int64_t eb,
                                 int64_t* counts) {
    std::vector<int64_t> ids;
    std::vector<uint64_t> keys;
    std::vector<int32_t> deg;
    std::vector<int64_t> starts;
    std::vector<uint64_t> radix_tmp;
    int64_t w = 0;
    for (int64_t at = 0; at < n; at += eb, ++w) {
        const int64_t len = (n - at < eb) ? (n - at) : eb;
        counts[w] = count_one_window(src + at, dst + at, len, ids, keys,
                                     deg, starts, radix_tmp);
    }
    return w;
}

}  // extern "C"

"""ctypes bindings for the native host-runtime kernels (ingest.cpp).

Loads (building on first use if the toolchain is available)
libgsnative.so; every entry point has a numpy/python fallback so the
framework works without a compiler. `available()` reports which path is
active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from ..utils import telemetry

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libgsnative.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        except Exception as e:
            telemetry.event("native.build_failed", durable=True,
                            error="%s: %s" % (type(e).__name__, e))
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.gs_parse_edges.restype = ctypes.c_int64
    lib.gs_parse_edges.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.gs_assign_windows.restype = None
    lib.gs_assign_windows.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.gs_interner_new.restype = ctypes.c_void_p
    lib.gs_interner_free.argtypes = [ctypes.c_void_p]
    lib.gs_interner_size.restype = ctypes.c_int64
    lib.gs_interner_size.argtypes = [ctypes.c_void_p]
    lib.gs_interner_intern.restype = None
    lib.gs_interner_intern.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.gs_interner_lookup.restype = None
    lib.gs_interner_lookup.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    try:
        lib.gs_triangle_count_stream.restype = ctypes.c_int64
        lib.gs_triangle_count_stream.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.gs_windowed_reduce.restype = ctypes.c_int64
        lib.gs_windowed_reduce.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.gs_windowed_reduce_i32.restype = ctypes.c_int64
        lib.gs_windowed_reduce_i32.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
    except AttributeError:
        # a stale libgsnative.so missing newer symbols: everything else
        # still works; the affected helpers report unavailable
        pass
    try:
        lib.gs_windowed_reduce_i32o.restype = ctypes.c_int64
        lib.gs_windowed_reduce_i32o.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.gs_windowed_reduce_i64i32o.restype = ctypes.c_int64
        lib.gs_windowed_reduce_i64i32o.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
    except AttributeError:
        pass
    try:
        lib.gs_snapshot_windows.restype = ctypes.c_int64
        lib.gs_snapshot_windows.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
    except AttributeError:
        pass
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _i64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


# ----------------------------------------------------------------------
def parse_edge_bytes(data: bytes) -> Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
    """Parse a byte buffer of 'src dst [ts]' lines into int64 COO
    arrays (ts = -1 when missing). Native fast path with a behavior-
    identical Python fallback."""
    lib = _load()
    if lib is not None:
        max_edges = data.count(b"\n") + 1
        src = np.empty(max_edges, np.int64)
        dst = np.empty(max_edges, np.int64)
        ts = np.empty(max_edges, np.int64)
        n = lib.gs_parse_edges(data, len(data), max_edges,
                               _i64ptr(src), _i64ptr(dst), _i64ptr(ts))
        return src[:n].copy(), dst[:n].copy(), ts[:n].copy()
    return _parse_edge_bytes_py(data)


def parse_edge_file(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Parse 'src dst [ts]' lines into int64 COO arrays (ts = -1 when
    missing). Native fast path; numpy loadtxt-style fallback."""
    with open(path, "rb") as f:
        return parse_edge_bytes(f.read())


def _parse_edge_bytes_py(data: bytes):
    """Pure-Python parser; must stay behaviorally identical to
    gs_parse_edges (ingest.cpp) so results never depend on whether the
    native library is available."""
    src_l, dst_l, ts_l = [], [], []
    for line in data.decode().splitlines():
        fields = line.split()
        if len(fields) >= 2:
            try:  # parse the whole line before appending anything, so a
                # malformed field can't leave the arrays misaligned
                row = (int(fields[0]), int(fields[1]),
                       int(fields[2]) if len(fields) > 2 else -1)
            except ValueError:
                continue
            src_l.append(row[0])
            dst_l.append(row[1])
            ts_l.append(row[2])
    return (np.array(src_l, np.int64), np.array(dst_l, np.int64),
            np.array(ts_l, np.int64))


def assign_windows(ts: np.ndarray, size_ms: int) -> np.ndarray:
    """Tumbling window starts per timestamp (Flink TimeWindow floor)."""
    ts = np.ascontiguousarray(ts, np.int64)
    lib = _load()
    if lib is not None:
        out = np.empty(len(ts), np.int64)
        lib.gs_assign_windows(_i64ptr(ts), len(ts), size_ms, _i64ptr(out))
        return out
    return ts - np.mod(ts, size_ms)


def triangles_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "gs_triangle_count_stream")


def triangle_count_stream(src: np.ndarray, dst: np.ndarray,
                          eb: int) -> Optional[np.ndarray]:
    """Exact triangle counts of every tumbling `eb`-sized window of the
    stream via the C++ compact-forward counter (ingest.cpp
    gs_triangle_count_stream) — the native tier of
    ops/triangles.count_stream. Returns None when the library (or the
    symbol, for a stale build) is unavailable; callers fall back to the
    numpy tier. Counting invariant and results are identical to the
    numpy and device tiers (asserted in tests/library/test_triangles.py)."""
    if not triangles_available():
        return None
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    num_w = (len(src) + eb - 1) // eb
    counts = np.empty(max(num_w, 1), np.int64)
    w = _lib.gs_triangle_count_stream(_i64ptr(src), _i64ptr(dst),
                                      len(src), eb, _i64ptr(counts))
    return counts[:w]


_REDUCE_OPS = {"sum": 0, "min": 1, "max": 2}
_REDUCE_DIRS = {"out": 0, "in": 1, "all": 2}


def windowed_reduce_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "gs_windowed_reduce")


def windowed_reduce(src: np.ndarray, dst: np.ndarray, val: np.ndarray,
                    eb: int, vbp: int, name: str, direction: str,
                    ident: int):
    """Fused (cells, counts) windowed reduce via the C++ kernel
    (ingest.cpp gs_windowed_reduce*) — the native tier of
    ops/windowed_reduce.WindowedEdgeReduce for integer values. Returns
    (cells [num_w, vbp], counts [num_w, vbp]); cells pre-filled with
    `ident`. The slab dtype is int32 when the fast forms apply (int32
    values whose worst-case cell sum provably fits int32 — evaluated
    PER CALL, so a chunked stream can legitimately return int32 rows
    for one chunk and int64 for another; every consumer of the
    (cells, counts) contract is dtype-agnostic, and the engine casts
    cells back to the value dtype) and int64 otherwise. None when the
    library/symbol is unavailable (callers fall back to the numpy
    tier)."""
    if not windowed_reduce_available():
        return None
    n = len(src)
    num_w = -(-n // eb) if n else 0
    src, dst, val = (np.asarray(a) for a in (src, dst, val))
    ids_i32 = src.dtype == np.int32 and dst.dtype == np.int32
    ids_i64 = src.dtype == np.int64 and dst.dtype == np.int64
    # int32-output fast forms (gs_windowed_reduce_i32o /
    # _i64i32o): int32 output slabs halve the faulted/written output
    # bytes and make the engine's astype-back a no-op. Gated on the
    # same worst-case-sum bound as the numpy tier's exact_bincount
    # guard: max|val| × the most contributions one cell can receive
    # must fit int32 (min/max outputs are input values — always
    # safe). ident fits int32 by construction for int32 values. Ids
    # stay their own width — the i64-id form keeps the unsigned bound
    # check exact for ids beyond int32 (reported, never wrapped).
    out_i32 = (val.dtype == np.int32 and (ids_i32 or ids_i64)
               and getattr(_lib, "gs_windowed_reduce_i64i32o", None)
               is not None)
    per_cell = eb * (2 if direction == "all" else 1)
    # the COUNTS slab shares the output dtype, and one cell can
    # receive up to per_cell (= 2·eb for direction 'all')
    # contributions regardless of the reduce op — so the int32 form
    # needs 2*eb <= INT32_MAX for min/max and for the all-zero-sum
    # case too, where the old value-only gate (0 × per_cell) passed
    # vacuously while the counts could still wrap
    if out_i32:
        out_i32 = per_cell <= np.iinfo(np.int32).max
    if out_i32 and name == "sum" and n:
        # exact max|val| via two scans in Python ints (np.abs wraps on
        # INT32_MIN and would pass the gate with a negative bound)
        maxabs = max(int(val.max()), -int(val.min()))
        out_i32 = maxabs * per_cell <= np.iinfo(np.int32).max
    out_dt = np.int32 if out_i32 else np.int64
    if ident == 0:
        # calloc-backed zeros: the kernel touches only real cells, so
        # the identity fill is free (np.full writes the whole slab)
        cells = np.zeros((max(num_w, 1), vbp), out_dt)
    else:
        cells = np.full((max(num_w, 1), vbp), ident, out_dt)
    counts = np.zeros((max(num_w, 1), vbp), out_dt)
    if out_i32 and ids_i32:
        s32, d32, v32 = (np.ascontiguousarray(a)
                         for a in (src, dst, val))
        oob = _lib.gs_windowed_reduce_i32o(
            _i32ptr(s32), _i32ptr(d32), _i32ptr(v32), n, eb,
            vbp, _REDUCE_OPS[name], _REDUCE_DIRS[direction],
            _i32ptr(cells), _i32ptr(counts))
    elif out_i32:
        s64, d64 = np.ascontiguousarray(src), np.ascontiguousarray(dst)
        v32 = np.ascontiguousarray(val)
        oob = _lib.gs_windowed_reduce_i64i32o(
            _i64ptr(s64), _i64ptr(d64), _i32ptr(v32), n, eb, vbp,
            _REDUCE_OPS[name], _REDUCE_DIRS[direction],
            _i32ptr(cells), _i32ptr(counts))
    elif ids_i32 and val.dtype == np.int32:
        s32, d32, v32 = (np.ascontiguousarray(a)
                         for a in (src, dst, val))
        oob = _lib.gs_windowed_reduce_i32(
            _i32ptr(s32), _i32ptr(d32), _i32ptr(v32), n, eb,
            vbp, _REDUCE_OPS[name], _REDUCE_DIRS[direction],
            _i64ptr(cells), _i64ptr(counts))
    else:
        src = np.ascontiguousarray(src, np.int64)
        dst = np.ascontiguousarray(dst, np.int64)
        val = np.ascontiguousarray(val, np.int64)
        oob = _lib.gs_windowed_reduce(
            _i64ptr(src), _i64ptr(dst), _i64ptr(val), n, eb, vbp,
            _REDUCE_OPS[name], _REDUCE_DIRS[direction],
            _i64ptr(cells), _i64ptr(counts))
    if oob:
        # other tiers fail loudly on bad ids (bincount raises); the
        # C++ kernel skips them and reports — surface it identically
        raise ValueError(
            "%d vertex id(s) outside [0, %d) in windowed_reduce input"
            % (oob, vbp))
    return cells[:num_w], counts[:num_w]


def snapshot_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "gs_snapshot_windows")


def snapshot_windows(src: np.ndarray, dst: np.ndarray,
                     offsets: np.ndarray, vb: int,
                     deg: np.ndarray = None, cc: np.ndarray = None,
                     cov: np.ndarray = None):
    """Carried-state windowed snapshot analytics via the C++ kernel
    (ingest.cpp gs_snapshot_windows) — the host tier of the driver's
    batched snapshot scan. Window w is the [offsets[w], offsets[w+1])
    slice of the flat COO arrays (varying lengths — event-time
    windows). `deg`/`cc`/`cov` are the caller-owned carried arrays
    (int32 [vb], [vb], [2·vb] — the driver's host mirror layouts, so
    checkpoints stay tier-interchangeable), updated in place; pass
    None to skip an analytic. Returns {"deg": [W, vb], "labels":
    [W, vb], "cover": [W, 2·vb]} int32 snapshot stacks for the
    enabled analytics (the scan tier's `outs` shape contract), or
    None when the library/symbol is unavailable."""
    if not snapshot_available():
        return None
    src = np.ascontiguousarray(src, np.int32)
    dst = np.ascontiguousarray(dst, np.int32)
    offsets = np.ascontiguousarray(offsets, np.int64)
    num_w = len(offsets) - 1
    if num_w < 0 or int(offsets[-1]) != len(src):
        raise ValueError("offsets must span the flat edge arrays")
    nullp = ctypes.POINTER(ctypes.c_int32)()

    def ptr(a):
        return _i32ptr(a) if a is not None else nullp

    for name, a, ln in (("deg", deg, vb), ("cc", cc, vb),
                        ("cov", cov, 2 * vb)):
        if a is not None and (a.dtype != np.int32 or len(a) != ln
                              or not a.flags["C_CONTIGUOUS"]):
            raise ValueError("carried %s must be contiguous int32[%d]"
                             % (name, ln))
    flags = ((1 if deg is not None else 0)
             | (2 if cc is not None else 0)
             | (4 if cov is not None else 0))
    od = np.empty((num_w, vb), np.int32) if deg is not None else None
    oc = np.empty((num_w, vb), np.int32) if cc is not None else None
    ov = (np.empty((num_w, 2 * vb), np.int32)
          if cov is not None else None)
    w = _lib.gs_snapshot_windows(
        _i32ptr(src), _i32ptr(dst), _i64ptr(offsets), num_w, vb, flags,
        ptr(deg), ptr(cc), ptr(cov), ptr(od), ptr(oc), ptr(ov))
    if w != num_w:
        # not an assert: a short write must fail under `python -O` too
        raise RuntimeError("native snapshot_windows wrote %d of %d "
                           "windows" % (w, num_w))
    out = {}
    if od is not None:
        out["deg"] = od
    if oc is not None:
        out["labels"] = oc
    if ov is not None:
        out["cover"] = ov
    return out


class NativeInterner:
    """Incremental int64-id interner backed by the C++ hash map, with
    the same contract as utils.interning.IncrementalInterner."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.gs_interner_new()

    def __len__(self) -> int:
        return int(self._lib.gs_interner_size(self._handle))

    def intern_array(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64)
        out = np.empty(len(ids), np.int32)
        self._lib.gs_interner_intern(self._handle, _i64ptr(ids), len(ids),
                                     _i32ptr(out))
        return out

    def ids_of(self, dense: np.ndarray) -> np.ndarray:
        dense = np.ascontiguousarray(dense, np.int32)
        out = np.empty(len(dense), np.int64)
        self._lib.gs_interner_lookup(self._handle, _i32ptr(dense),
                                     len(dense), _i64ptr(out))
        return out

    def id_of(self, dense: int) -> int:
        return int(self.ids_of(np.array([dense], np.int32))[0])

    def __del__(self):
        try:
            self._lib.gs_interner_free(self._handle)
        except Exception:  # gslint: disable=except-hygiene (interpreter teardown: lib/handle may already be unloaded)
            pass

"""ctypes bindings for the native host-runtime kernels (ingest.cpp).

Loads (building on first use if the toolchain is available)
libgsnative.so; every entry point has a numpy/python fallback so the
framework works without a compiler. `available()` reports which path is
active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libgsnative.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.gs_parse_edges.restype = ctypes.c_int64
    lib.gs_parse_edges.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.gs_assign_windows.restype = None
    lib.gs_assign_windows.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.gs_interner_new.restype = ctypes.c_void_p
    lib.gs_interner_free.argtypes = [ctypes.c_void_p]
    lib.gs_interner_size.restype = ctypes.c_int64
    lib.gs_interner_size.argtypes = [ctypes.c_void_p]
    lib.gs_interner_intern.restype = None
    lib.gs_interner_intern.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.gs_interner_lookup.restype = None
    lib.gs_interner_lookup.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    try:
        lib.gs_triangle_count_stream.restype = ctypes.c_int64
        lib.gs_triangle_count_stream.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ]
    except AttributeError:
        # a stale pre-triangle libgsnative.so: everything else still
        # works; triangle_count_stream() reports unavailable
        pass
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _i64ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


# ----------------------------------------------------------------------
def parse_edge_bytes(data: bytes) -> Tuple[np.ndarray, np.ndarray,
                                           np.ndarray]:
    """Parse a byte buffer of 'src dst [ts]' lines into int64 COO
    arrays (ts = -1 when missing). Native fast path with a behavior-
    identical Python fallback."""
    lib = _load()
    if lib is not None:
        max_edges = data.count(b"\n") + 1
        src = np.empty(max_edges, np.int64)
        dst = np.empty(max_edges, np.int64)
        ts = np.empty(max_edges, np.int64)
        n = lib.gs_parse_edges(data, len(data), max_edges,
                               _i64ptr(src), _i64ptr(dst), _i64ptr(ts))
        return src[:n].copy(), dst[:n].copy(), ts[:n].copy()
    return _parse_edge_bytes_py(data)


def parse_edge_file(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Parse 'src dst [ts]' lines into int64 COO arrays (ts = -1 when
    missing). Native fast path; numpy loadtxt-style fallback."""
    with open(path, "rb") as f:
        return parse_edge_bytes(f.read())


def _parse_edge_bytes_py(data: bytes):
    """Pure-Python parser; must stay behaviorally identical to
    gs_parse_edges (ingest.cpp) so results never depend on whether the
    native library is available."""
    src_l, dst_l, ts_l = [], [], []
    for line in data.decode().splitlines():
        fields = line.split()
        if len(fields) >= 2:
            try:  # parse the whole line before appending anything, so a
                # malformed field can't leave the arrays misaligned
                row = (int(fields[0]), int(fields[1]),
                       int(fields[2]) if len(fields) > 2 else -1)
            except ValueError:
                continue
            src_l.append(row[0])
            dst_l.append(row[1])
            ts_l.append(row[2])
    return (np.array(src_l, np.int64), np.array(dst_l, np.int64),
            np.array(ts_l, np.int64))


def assign_windows(ts: np.ndarray, size_ms: int) -> np.ndarray:
    """Tumbling window starts per timestamp (Flink TimeWindow floor)."""
    ts = np.ascontiguousarray(ts, np.int64)
    lib = _load()
    if lib is not None:
        out = np.empty(len(ts), np.int64)
        lib.gs_assign_windows(_i64ptr(ts), len(ts), size_ms, _i64ptr(out))
        return out
    return ts - np.mod(ts, size_ms)


def triangles_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "gs_triangle_count_stream")


def triangle_count_stream(src: np.ndarray, dst: np.ndarray,
                          eb: int) -> Optional[np.ndarray]:
    """Exact triangle counts of every tumbling `eb`-sized window of the
    stream via the C++ compact-forward counter (ingest.cpp
    gs_triangle_count_stream) — the native tier of
    ops/triangles.count_stream. Returns None when the library (or the
    symbol, for a stale build) is unavailable; callers fall back to the
    numpy tier. Counting invariant and results are identical to the
    numpy and device tiers (asserted in tests/library/test_triangles.py)."""
    if not triangles_available():
        return None
    src = np.ascontiguousarray(src, np.int64)
    dst = np.ascontiguousarray(dst, np.int64)
    num_w = (len(src) + eb - 1) // eb
    counts = np.empty(max(num_w, 1), np.int64)
    w = _lib.gs_triangle_count_stream(_i64ptr(src), _i64ptr(dst),
                                      len(src), eb, _i64ptr(counts))
    return counts[:w]


class NativeInterner:
    """Incremental int64-id interner backed by the C++ hash map, with
    the same contract as utils.interning.IncrementalInterner."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._handle = lib.gs_interner_new()

    def __len__(self) -> int:
        return int(self._lib.gs_interner_size(self._handle))

    def intern_array(self, ids: np.ndarray) -> np.ndarray:
        ids = np.ascontiguousarray(ids, np.int64)
        out = np.empty(len(ids), np.int32)
        self._lib.gs_interner_intern(self._handle, _i64ptr(ids), len(ids),
                                     _i32ptr(out))
        return out

    def ids_of(self, dense: np.ndarray) -> np.ndarray:
        dense = np.ascontiguousarray(dense, np.int32)
        out = np.empty(len(dense), np.int64)
        self._lib.gs_interner_lookup(self._handle, _i32ptr(dense),
                                     len(dense), _i64ptr(out))
        return out

    def id_of(self, dense: int) -> int:
        return int(self.ids_of(np.array([dense], np.int32))[0])

    def __del__(self):
        try:
            self._lib.gs_interner_free(self._handle)
        except Exception:
            pass

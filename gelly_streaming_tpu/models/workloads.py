"""Runnable workload pipelines — the reference's example programs
(SURVEY.md §2.3) as composable functions used by both the CLI examples
and the tests.
"""

from __future__ import annotations

from ..core.datastream import DataStream
from ..core.functions import EdgesApply
from ..core.gtime import AscendingTimestampExtractor, Time
from ..core.graphstream import SimpleEdgeStream
from ..core.types import NULL, Edge, EdgeDirection
from .triangles import count_triangles, generate_candidate_edges


def parse_edge_line(line: str) -> Edge:
    """'src trg timestamp' whitespace-separated line → Edge with the
    timestamp as value (reference: WindowTriangles.java:176-185)."""
    fields = line.split()
    return Edge(int(fields[0]), int(fields[1]), int(fields[2]))


def timestamped_graph(env, path: str) -> SimpleEdgeStream:
    """Event-time graph stream from a 'src trg ts' file; edge values are
    replaced by NullValue after timestamp extraction
    (reference: WindowTriangles.java:172-186)."""
    edges = env.read_text_file(path).map(parse_edge_line)
    stream = SimpleEdgeStream(
        edges, env,
        timestamp_extractor=AscendingTimestampExtractor(lambda e: e.value),
    )
    return stream.map_edges(lambda e: NULL)


def window_triangles_pipeline(graph: SimpleEdgeStream,
                              window_time: Time) -> DataStream:
    """The reference WindowTriangles dataflow, stage for stage
    (WindowTriangles.java:61-66): slice(ALL) → candidate generation →
    keyBy(pair) window count → all-window sum."""
    return (
        graph.slice(window_time, EdgeDirection.ALL)
        .apply_on_neighbors(EdgesApply(generate_candidate_edges))
        .key_by(0, 1)
        .time_window(window_time)
        .apply(count_triangles)
        .time_window_all(window_time)
        .sum(0)
    )

"""Streaming bipartiteness check via summary aggregation.

Counterpart of the reference's `BipartitenessCheck`
(library/BipartitenessCheck.java:40-136): per-window fold merges each
edge as a two-vertex signed component (edgeToCandidate, :57-64) into a
`Candidates` summary; the combiner merges window summaries, collapsing
to (false,{}) on any odd cycle.

Two execution modes:
- `BipartitenessCheck` — host fold, exact reference string parity.
- `TpuBipartitenessCheck` — the window fold runs on device as a signed
  (parity) union-find via the bipartite double cover
  (ops/unionfind.bipartite_labels): one cc-label program over 2·V
  cover vertices replaces the reference's O(C²·V) Candidates merge
  hot loop (Candidates.java:75 TODO). The per-window summary is
  converted to a canonical `Candidates` (component key = min vertex,
  root sign positive).

Deliberate divergences from the host/reference path (both are cases
where the reference's merge is buggy and the device result is the
mathematically correct one):
- self-loops: the device kernel reports the odd cycle (false,{});
  the reference's edgeToCandidate silently drops the sign conflict
  (Candidates.java:110 ignores add's return) and stays bipartite.
- components the reference's merge leaves split/duplicated (a min
  vertex arriving via a later bridging edge, Candidates.java:107-133)
  are emitted fully canonicalized here — same bipartiteness verdicts,
  possibly different component grouping in the printed state.
"""

from __future__ import annotations

import numpy as np

from ..core.aggregation import WindowGraphAggregation
from ..ops import segment as seg_ops
from ..ops import unionfind
from ..utils.candidates import Candidates, SignedVertex, edge_to_candidate


def _update(cand: Candidates, v1, v2, _value) -> Candidates:
    return cand.merge(edge_to_candidate(v1, v2))


def _combine(c1: Candidates, c2: Candidates) -> Candidates:
    return c1.merge(c2)


class BipartitenessCheck(WindowGraphAggregation):
    def __init__(self, merge_window_millis: int):
        super().__init__(
            update_fun=_update,
            combine_fun=_combine,
            initial_value=Candidates(True),
            time_millis=merge_window_millis,
            transient_state=False,
        )


class TpuBipartitenessCheck(WindowGraphAggregation):
    def __init__(self, merge_window_millis: int):
        super().__init__(
            update_fun=_update,  # unused: fold_kernel takes the window
            combine_fun=_combine,
            initial_value=Candidates(True),
            time_millis=merge_window_millis,
            transient_state=False,
            fold_kernel=self._window_candidates,
        )

    @staticmethod
    def _window_candidates(edges, _wmax) -> Candidates:
        src = np.asarray([e.source for e in edges])
        dst = np.asarray([e.target for e in edges])
        uniq, (s_dense, d_dense) = seg_ops.intern(src, dst)
        labels, signs, odd = unionfind.bipartite_labels(
            s_dense, d_dense, len(uniq)
        )
        if bool(odd.any()):
            return Candidates(False)
        cand = Candidates(True)
        roots = uniq[labels]
        for v, root, sign in zip(uniq.tolist(), roots.tolist(), signs.tolist()):
            cand.add(int(root), SignedVertex(int(v), bool(sign)))
        return cand

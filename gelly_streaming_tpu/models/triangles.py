"""Window triangle count — the north-star workload.

Fused TPU pipeline for the reference's WindowTriangles
(example/WindowTriangles.java:61-66): instead of materializing O(d²)
candidate-pair records and shuffling them twice (slice → candidates →
keyBy(pair) → window count → global sum), each tumbling window's COO
batch runs ONE device program (ops/triangles.py) that produces the
exact per-window triangle count directly. Output records are
(count, window_max_timestamp) tuples, matching the reference's
`timeWindowAll(...).sum(0)` emission (:66, TimeWindow.maxTimestamp).

`generate_candidate_edges` / `count_triangles` reproduce the
intermediate-record semantics of the reference's two window UDFs
(:83-116, :119-140) for the API-parity example path
(examples/window_triangles.py).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from ..core.datastream import DataStream
from ..core.gtime import Time
from ..core.plan import OpNode
from ..ops import segment as seg_ops
from ..ops import triangles as tri_ops


class WindowTriangleCount:
    """Exact sliced triangle count, fused per-window device kernel."""

    def __init__(self, window_time: Time):
        self.window_time = window_time

    def run(self, graph) -> DataStream:
        """graph: a SimpleEdgeStream. Returns a stream of
        (triangle_count, window_max_ts)."""
        edges = graph.get_edges()

        def kernel(window_edges, wmax) -> List[Tuple[tuple, int]]:
            src = np.asarray([e.source for e in window_edges])
            dst = np.asarray([e.target for e in window_edges])
            _uniq, (s, d) = seg_ops.intern(src, dst)
            n = tri_ops.triangle_count(s, d, len(_uniq))
            return [((n, wmax), wmax)]

        node = OpNode("window_batch", [edges.node],
                      size_ms=self.window_time.milliseconds, kernel=kernel)
        return DataStream(graph.env, node)


# ----------------------------------------------------------------------
# API-parity UDFs (the reference's two-stage candidate pipeline)
# ----------------------------------------------------------------------

def generate_candidate_edges(vertex_id, neighbors, collect):
    """Per-vertex window apply: emit each (vertex, neighbor) as a real-edge
    record (flag False) and every distinct neighbor pair with both ids
    greater than the vertex as a candidate record (flag True)
    (reference: GenerateCandidateEdges, WindowTriangles.java:83-116,
    including the j=i self-pair quirk)."""
    seen = []
    seen_set = set()
    for nbr, _val in neighbors:
        collect((vertex_id, nbr, False))
        if nbr not in seen_set:
            seen_set.add(nbr)
            seen.append(nbr)
    for i in range(len(seen) - 1):
        for j in range(i, len(seen)):
            if seen[i] > vertex_id and seen[j] > vertex_id:
                collect((seen[i], seen[j], True))


def count_triangles(_key, window, values, collect):
    """Per-(pair, window) count: number of candidate records if at least
    one real-edge record shares the group
    (reference: CountTriangles, WindowTriangles.java:119-140)."""
    candidates = sum(1 for v in values if v[2])
    edges = sum(1 for v in values if not v[2])
    if edges > 0:
        collect((candidates, window.max_timestamp()))

"""Algorithm library + workloads (the reference's L4 `library/` plus the
aggregate state types' algorithmic backends)."""

from .bipartiteness import BipartitenessCheck, TpuBipartitenessCheck
from .connected_components import ConnectedComponents, TpuConnectedComponents

__all__ = [
    "BipartitenessCheck", "TpuBipartitenessCheck",
    "ConnectedComponents", "TpuConnectedComponents",
]

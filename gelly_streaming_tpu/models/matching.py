"""Centralized preemptive greedy weighted matching.

Counterpart of the reference's CentralizedWeightedMatching
(example/CentralizedWeightedMatching.java:56-108): a parallelism-1
sequential stage (parallelism strategy P4, SURVEY.md §2.4) that keeps a
local matching; an arriving edge replaces its colliding matched edges
iff its weight exceeds TWICE their summed weight, emitting ADD/REMOVE
events. This 2x-threshold preemptive greedy (Feigenbaum et al.'s
streaming matching) guarantees a 1/6-approximation in the worst case —
NOT the folklore 1/2 of offline greedy: a kept edge flanked by two
just-under-threshold rivals shows the gap (pinned with an executed
counterexample: tests/library/test_workloads.py::
test_weighted_matching_counterexample_to_half). Inherently sequential —
this stays a host stage by design; the endpoint-collision lookup uses
a dict index instead of the reference's full-set scan.
"""

from __future__ import annotations

from typing import Dict, Set

from ..core.datastream import DataStream
from ..core.types import Edge
from ..utils.events import MatchingEvent, MatchingEventType


class WeightedMatchingMapper:
    """Stateful flat-mapper holding the current matching."""

    def __init__(self):
        self._matching: Set[Edge] = set()
        self._by_vertex: Dict[object, Set[Edge]] = {}

    def __call__(self, edge: Edge, collect) -> None:
        collisions = set()
        for endpoint in (edge.source, edge.target):
            collisions |= self._by_vertex.get(endpoint, set())
        total = sum(e.value for e in collisions)
        if edge.value > 2 * total:
            for colliding in collisions:
                self._remove(colliding)
                collect(MatchingEvent(MatchingEventType.REMOVE, colliding))
            self._add(edge)
            collect(MatchingEvent(MatchingEventType.ADD, edge))

    def _add(self, edge: Edge) -> None:
        self._matching.add(edge)
        for endpoint in (edge.source, edge.target):
            self._by_vertex.setdefault(endpoint, set()).add(edge)

    def _remove(self, edge: Edge) -> None:
        self._matching.discard(edge)
        for endpoint in (edge.source, edge.target):
            self._by_vertex.get(endpoint, set()).discard(edge)


def centralized_weighted_matching(edges: DataStream) -> DataStream:
    """edges: DataStream of weighted Edge records → MatchingEvent stream."""
    return edges.flat_map(WeightedMatchingMapper()).set_parallelism(1)

"""Iterative connected components.

Two designs for the reference's feedback-loop CC
(example/IterativeConnectedComponents.java:52-168):

- `iterative_connected_components` — the reference's shape: a stream
  iteration whose body (`AssignComponents`) keeps per-key component
  sets, relabels on merges, and re-emits relabeled (vertex, component)
  records into the feedback edge until quiescence.

- `TpuIterativeConnectedComponents` — the TPU-native replacement
  (SURVEY.md §7 "streaming iteration"): no feedback queue; each batch
  runs min-label propagation to the fixpoint *inside* one device
  program (`lax.while_loop`, ops/unionfind.cc_labels) with labels
  carried across batches as device-resident state. Same fixpoint,
  compiler-friendly schedule.
"""

from __future__ import annotations

from typing import Dict, Set

import numpy as np

from ..core.datastream import DataStream
from ..ops import segment as seg_ops
from ..ops import unionfind
from ..utils.interning import make_interner


class AssignComponents:
    """Stateful component assigner (reference:
    IterativeConnectedComponents.java:67-168). Input records are
    (vertex, vertex) edges — including fed-back (vertex, component)
    relabels; emits (vertex, component) updates."""

    def __init__(self):
        self._components: Dict[int, Set[int]] = {}
        self._comp_of: Dict[int, int] = {}

    def __call__(self, edge, collect) -> None:
        source, target = edge[0], edge[1]
        source_comp = self._comp_of.get(source, -1)
        target_comp = self._comp_of.get(target, -1)

        if source_comp != -1 and target_comp != -1:
            if source_comp != target_comp:
                self._merge(source_comp, target_comp, collect)
        elif source_comp != -1:
            self._add_to_existing(source_comp, target, collect)
        elif target_comp != -1:
            self._add_to_existing(target_comp, source, collect)
        else:
            self._create(source, target, collect)

    def _set_comp(self, comp: int, vertices: Set[int]) -> None:
        self._components[comp] = vertices
        for v in vertices:
            self._comp_of[v] = comp

    def _create(self, source: int, target: int, collect) -> None:
        comp = min(source, target)
        self._set_comp(comp, {source, target})
        collect((source, comp))
        collect((target, comp))

    def _add_to_existing(self, comp: int, to_add: int, collect) -> None:
        vertices = self._components.pop(comp)
        if comp >= to_add:
            # the new vertex id becomes the component id: relabel everyone
            for v in vertices:
                collect((v, to_add))
            vertices.add(to_add)
            self._set_comp(to_add, vertices)
        else:
            vertices.add(to_add)
            self._set_comp(comp, vertices)
            collect((to_add, comp))

    def _merge(self, source_comp: int, target_comp: int, collect) -> None:
        src_set = self._components.pop(source_comp)
        trg_set = self._components.pop(target_comp)
        comp = min(source_comp, target_comp)
        relabeled = trg_set if comp == source_comp else src_set
        for v in relabeled:
            collect((v, comp))
        src_set |= trg_set
        self._set_comp(comp, src_set)


def iterative_connected_components(edges: DataStream,
                                   max_iterations: int = 1000) -> DataStream:
    """Feedback-loop CC (reference: IterativeConnectedComponents.java:56-58):
    relabel records re-enter the loop until no more updates."""
    iteration = edges.iterate(max_iterations=max_iterations)
    result = iteration.key_by(0).flat_map(AssignComponents())
    iteration.close_with(result)
    return result


class TpuIterativeConnectedComponents:
    """In-step while_loop label propagation over carried device state.

    Vertex ids get stable dense slots (IncrementalInterner); the label
    vector is device-resident and grows by bucket doubling, so a batch
    costs O(E_batch + V_bucket) vectorized work, not a rebuild of the
    whole history.
    """

    def __init__(self):
        self._interner = None  # chosen (native vs python) on first batch
        self._labels = np.arange(0, dtype=np.int32)  # dense slot -> dense root

    def process_batch(self, src: np.ndarray, dst: np.ndarray):
        """Union a batch of edges into the carried labeling; returns the
        (vertex, component) pairs whose component changed, component =
        the smallest-slot vertex's id (first-seen vertex of the
        component, matching min-label semantics in arrival order)."""
        if self._interner is None:
            self._interner = make_interner(np.asarray(src))
        s = self._interner.intern_array(np.asarray(src))
        d = self._interner.intern_array(np.asarray(dst))
        v = len(self._interner)
        vb = seg_ops.bucket_size(v)
        old = self._labels
        labels = np.arange(vb, dtype=np.int32)
        labels[: len(old)] = old
        new = unionfind.connected_components_with_labels(s, d, labels, vb)
        changed_slots = np.nonzero(new[:v] != labels[:v])[0]
        # also report fresh vertices (slots beyond the previous state)
        fresh = np.arange(len(old), v)[new[len(old):v]
                                       == np.arange(len(old), v)]
        self._labels = new[:v]
        out = []
        for slot in np.concatenate([changed_slots, fresh]).tolist():
            out.append((self._interner.id_of(slot),
                        self._interner.id_of(int(new[slot]))))
        return out

    # ------------------------------------------------------------------
    # checkpoint / resume (utils/checkpoint.py)
    # ------------------------------------------------------------------
    def state_dict(self) -> dict:
        ids = (list(self._interner.ids_of(
                   np.arange(len(self._interner), dtype=np.int32)))
               if self._interner is not None else [])
        if all(isinstance(i, (int, np.integer)) for i in ids):
            ids = np.asarray(ids, np.int64)  # compact array form
        return {"labels": self._labels, "ids": ids}

    def load_state_dict(self, state: dict) -> None:
        ids = state["ids"]
        ids = np.asarray(ids) if len(ids) else np.asarray([], np.int64)
        self._interner = make_interner(ids)
        if len(ids):
            self._interner.intern_array(ids)
        self._labels = np.asarray(state["labels"], np.int32)

"""Streaming connected components via summary aggregation.

Counterpart of the reference's `ConnectedComponents`
(library/ConnectedComponents.java:43-139): a WindowGraphAggregation
whose per-window fold unions each edge into a DisjointSet
(UpdateCC, :87-90) and whose combiner merges the smaller summary into
the larger (CombineCC, :121-130).

Two execution modes:
- `ConnectedComponents` — host fold, exact reference semantics.
- `TpuConnectedComponents` — the window fold runs on device as array
  min-label propagation (ops/unionfind.py); the per-window summary is
  the (vertex → component-min) labeling, unioned into the global
  DisjointSet by the merger. Same results, O(E) device work per window
  and only O(V_window) host merge work.
"""

from __future__ import annotations

import numpy as np

from ..core.aggregation import WindowGraphAggregation
from ..ops import segment as seg_ops
from ..ops import unionfind
from ..utils.disjoint_set import DisjointSet


def _update_cc(ds: DisjointSet, src, trg, _value) -> DisjointSet:
    ds.union(src, trg)
    return ds


def _combine_cc(s1: DisjointSet, s2: DisjointSet) -> DisjointSet:
    if s1.size() <= s2.size():
        s2.merge(s1)
        return s2
    s1.merge(s2)
    return s1


class ConnectedComponents(WindowGraphAggregation):
    def __init__(self, merge_window_millis: int):
        super().__init__(
            update_fun=_update_cc,
            combine_fun=_combine_cc,
            initial_value=DisjointSet(),
            time_millis=merge_window_millis,
            transient_state=False,
        )


class TpuConnectedComponents(WindowGraphAggregation):
    def __init__(self, merge_window_millis: int):
        super().__init__(
            update_fun=_update_cc,  # unused: fold_kernel takes the window
            combine_fun=_combine_cc,
            initial_value=DisjointSet(),
            time_millis=merge_window_millis,
            transient_state=False,
            fold_kernel=self._window_labels,
        )

    @staticmethod
    def _window_labels(edges, _wmax) -> DisjointSet:
        """Device window fold: one cc-label program over the window's COO
        batch; summary = DisjointSet of (vertex, component-min) pairs."""
        src = np.asarray([e.source for e in edges])
        dst = np.asarray([e.target for e in edges])
        uniq, (s_dense, d_dense) = seg_ops.intern(src, dst)
        labels = unionfind.connected_components(s_dense, d_dense, len(uniq))
        summary = DisjointSet()
        for v, root in zip(uniq.tolist(), uniq[labels].tolist()):
            # root first: union-by-rank ties keep the component minimum
            # as representative, so printed summaries match the host
            # variant's typical output
            summary.union(root, v)
        return summary

"""Sampling-based triangle count estimators.

Counterparts of the reference's two estimators, re-designed so the
per-sample-instance state updates are vectorized across all S instances
(state-of-arrays instead of the reference's List<SampleTriangleState>
object loop — the replicated-sampling strategy P3, SURVEY.md §2.4):

- Broadcast estimator (example/BroadcastTriangleCount.java:62-174):
  every instance sees every edge; instance i resamples its wedge
  candidate with probability 1/edge_count, draws a uniform third
  vertex, sets beta=1 when both closing edges have been seen; the
  summer scales Σbeta by maxEdges·(V-2)/samples.

- Incidence sampler (example/IncidenceSamplingTriangleCount.java:61-242):
  a parallelism-1 router flips the same coins (seeded 0xDEADBEEF,
  :78) and forwards only sampled/incident edges to per-instance
  processors — same estimate, less traffic.

Randomness is deterministic per instance (seeded numpy Generators), so
estimates are reproducible — unlike the reference's Math.random() third
-vertex draw.
"""

from __future__ import annotations


import numpy as np

from ..core.datastream import DataStream
from ..core.types import Edge
from ..utils.events import SampledEdge, TriangleEstimate


class VectorTriangleSampler:
    """S wedge-sampling instances updated as arrays per edge.

    Emits a TriangleEstimate whenever the local beta sum changes
    (reference: TriangleSampler.flatMap, BroadcastTriangleCount.java:80-134).
    """

    def __init__(self, samples: int, vertex_count: int, seed: int = 0xDEADBEEF,
                 subtask: int = 0):
        self.s = samples
        self.v = vertex_count
        self.seed = seed
        self.subtask = subtask
        self.rng = np.random.default_rng(seed + subtask)
        self.edge_count = 0
        self.src = np.full(samples, -1, np.int64)
        self.trg = np.full(samples, -1, np.int64)
        self.third = np.full(samples, -1, np.int64)
        self.src_found = np.zeros(samples, bool)
        self.trg_found = np.zeros(samples, bool)
        self.beta = np.zeros(samples, np.int64)
        self.previous = 0

    def open(self, ctx) -> None:
        # factories build instances before the subtask is known — reseed
        # here so parallel instances are independent estimators
        self.subtask = ctx.get_index_of_this_subtask()
        self.rng = np.random.default_rng(self.seed + self.subtask)

    def _resample(self, mask: np.ndarray, edge: Edge) -> None:
        n = int(mask.sum())
        if n == 0:
            return
        self.src[mask] = edge.source
        self.trg[mask] = edge.target
        third = self.rng.integers(0, self.v, n)
        # redraw any collision with the edge's endpoints
        for _ in range(64):
            bad = (third == edge.source) | (third == edge.target)
            if not bad.any():
                break
            third[bad] = self.rng.integers(0, self.v, int(bad.sum()))
        self.third[mask] = third
        self.src_found[mask] = False
        self.trg_found[mask] = False
        self.beta[mask] = 0

    def _update(self, edge: Edge) -> None:
        open_ = self.beta == 0
        s, t = edge.source, edge.target
        self.src_found |= open_ & (
            ((self.src == s) & (self.third == t))
            | ((self.src == t) & (self.third == s))
        )
        self.trg_found |= open_ & (
            ((self.trg == s) & (self.third == t))
            | ((self.trg == t) & (self.third == s))
        )
        self.beta = np.where(open_ & self.src_found & self.trg_found,
                             1, self.beta)

    def __call__(self, edge: Edge, collect) -> None:
        self.edge_count += 1
        resample = self.rng.random(self.s) < (1.0 / self.edge_count)
        self._resample(resample, edge)
        self._update(edge)
        beta_sum = int(self.beta.sum())
        if beta_sum != self.previous:
            self.previous = beta_sum
            collect(TriangleEstimate(self.subtask, self.edge_count, beta_sum))


class TriangleSummer:
    """Combine per-subtask estimates into the global running estimate
    (reference: TriangleSummer, BroadcastTriangleCount.java:138-174):
    estimate = Σbeta · maxEdges · (V-2) / samples, emitted on change."""

    def __init__(self, samples: int, vertex_count: int):
        self.samples = samples
        self.v = vertex_count
        self.results = {}
        self.max_edges = 0
        self.previous = 0

    def __call__(self, estimate: TriangleEstimate, collect) -> None:
        self.results[estimate.source_subtask] = estimate
        self.max_edges = max(self.max_edges, estimate.edge_count)
        beta_sum = sum(e.beta for e in self.results.values())
        result = int((1.0 / self.samples) * beta_sum * self.max_edges
                     * (self.v - 2))
        if result != self.previous:
            self.previous = result
            collect((self.max_edges, result))


def broadcast_triangle_count(edges: DataStream, samples: int,
                             vertex_count: int,
                             parallelism: int = 1) -> DataStream:
    """Broadcast estimator pipeline
    (reference: BroadcastTriangleCount.java:41-45): replicate the edge
    stream to `parallelism` sampler instances, funnel estimates through
    one summer."""
    local = max(1, samples // parallelism)
    sampled = edges.broadcast().parallel_flat_map(
        lambda: VectorTriangleSampler(local, vertex_count), parallelism
    )
    # normalize by the number of instances actually running (rounding /
    # the >=1-per-subtask floor can make local*parallelism != samples)
    return sampled.flat_map(
        TriangleSummer(local * parallelism, vertex_count)
    ).set_parallelism(1)


# ----------------------------------------------------------------------
# incidence sampling
# ----------------------------------------------------------------------

class EdgeSampleRouter:
    """Parallelism-1 router: flips every instance's coin, forwards
    resampled edges and edges incident to an instance's current sample
    as SampledEdge records keyed by subtask
    (reference: EdgeSampleMapper, IncidenceSamplingTriangleCount.java:61-122)."""

    def __init__(self, instance_size: int, parallelism: int,
                 seed: int = 0xDEADBEEF):
        self.n = instance_size * parallelism
        self.p = parallelism
        self.rng = np.random.default_rng(seed)
        self.sample_src = np.full(self.n, -1, np.int64)
        self.sample_trg = np.full(self.n, -1, np.int64)
        self.has_sample = np.zeros(self.n, bool)
        self.edge_count = 0

    def __call__(self, edge: Edge, collect) -> None:
        self.edge_count += 1
        flips = self.rng.random(self.n) < (1.0 / self.edge_count)
        s, t = edge.source, edge.target
        incident = self.has_sample & (
            (self.sample_src == s) | (self.sample_src == t)
            | (self.sample_trg == s) | (self.sample_trg == t)
        )
        for i in np.nonzero(flips)[0]:
            collect(SampledEdge(int(i) % self.p, int(i) // self.p, edge,
                                self.edge_count, True))
        for i in np.nonzero(~flips & incident)[0]:
            collect(SampledEdge(int(i) % self.p, int(i) // self.p, edge,
                                self.edge_count, False))
        self.sample_src[flips] = s
        self.sample_trg[flips] = t
        self.has_sample |= flips


class RoutedTriangleSampler:
    """Per-(subtask, instance) wedge state driven by routed SampledEdge
    records (reference: TriangleSampleMapper,
    IncidenceSamplingTriangleCount.java:125-203).

    The runtime executes a keyed flat-map as a single stateful instance,
    so this holds a [parallelism, instances] state matrix — each
    (subtask, instance) slot is an independent sampler, and estimates
    carry the record's subtask, matching the reference's p parallel
    mapper instances.
    """

    def __init__(self, instances: int, vertex_count: int,
                 parallelism: int = 1, seed: int = 17):
        self.v = vertex_count
        self.rng = np.random.default_rng(seed)
        shape = (parallelism, instances)
        self.src = np.full(shape, -1, np.int64)
        self.trg = np.full(shape, -1, np.int64)
        self.third = np.full(shape, -1, np.int64)
        self.src_found = np.zeros(shape, bool)
        self.trg_found = np.zeros(shape, bool)
        self.beta = np.zeros(shape, np.int64)
        self.edge_count = 0
        self.previous = np.zeros(parallelism, np.int64)

    def __call__(self, rec: SampledEdge, collect) -> None:
        edge = rec.edge
        self.edge_count = rec.edge_count
        k, i = rec.subtask, rec.instance
        if rec.resample:
            self.src[k, i] = edge.source
            self.trg[k, i] = edge.target
            third = int(self.rng.integers(0, self.v))
            for _ in range(64):  # bounded redraw (degenerate v <= 2)
                if third not in (edge.source, edge.target):
                    break
                third = int(self.rng.integers(0, self.v))
            self.third[k, i] = third
            self.src_found[k, i] = False
            self.trg_found[k, i] = False
            self.beta[k, i] = 0

        found = False
        if self.beta[k, i] == 0:
            s, t = edge.source, edge.target
            if ((self.src[k, i] == s and self.third[k, i] == t)
                    or (self.src[k, i] == t and self.third[k, i] == s)):
                self.src_found[k, i] = True
            if ((self.trg[k, i] == s and self.third[k, i] == t)
                    or (self.trg[k, i] == t and self.third[k, i] == s)):
                self.trg_found[k, i] = True
            found = bool(self.src_found[k, i] and self.trg_found[k, i])
            self.beta[k, i] = 1 if found else 0

        if found:
            beta_sum = int(self.beta[k].sum())
            if beta_sum != self.previous[k]:
                self.previous[k] = beta_sum
                collect(TriangleEstimate(k, self.edge_count, beta_sum))


def incidence_sampling_triangle_count(edges: DataStream, samples: int,
                                      vertex_count: int,
                                      parallelism: int = 1) -> DataStream:
    """Incidence-sampling pipeline
    (reference: IncidenceSamplingTriangleCount.java:38-45)."""
    local = max(1, samples // parallelism)
    routed = edges.flat_map(
        EdgeSampleRouter(local, parallelism)
    ).set_parallelism(1)
    estimates = routed.key_by(0).flat_map(
        RoutedTriangleSampler(local, vertex_count, parallelism)
    )
    return estimates.flat_map(
        TriangleSummer(local * parallelism, vertex_count)
    ).set_parallelism(1)

"""Graph data model: edges, vertices, directions, and Java-parity formatting.

Mirrors the data model the reference borrows from Flink Gelly:
`Edge<K,EV>` is a (source, target, value) triple, `Vertex<K,VV>` is an
(id, value) pair, `EdgeDirection` selects neighborhood orientation
(reference: SimpleEdgeStream.java:59, GraphStream.java:38).

Formatting helpers reproduce the exact text output of the reference's
CSV/text sinks so golden-output tests transfer verbatim
(e.g. `NullValue` prints as "(null)", tuples as "(a,b)").
"""

from __future__ import annotations

import enum
from typing import Any, NamedTuple


class NullValue:
    """Singleton placeholder value (Flink `NullValue`); prints as "(null)"."""

    _instance = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    @staticmethod
    def get_instance() -> "NullValue":
        return NullValue()

    def __repr__(self) -> str:
        return "(null)"

    def __eq__(self, other) -> bool:
        return isinstance(other, NullValue)

    def __hash__(self) -> int:
        return hash(NullValue)


NULL = NullValue.get_instance()


class EdgeDirection(enum.Enum):
    """Neighborhood orientation (reference: Gelly `EdgeDirection`)."""

    IN = "in"
    OUT = "out"
    ALL = "all"


class Edge(NamedTuple):
    """Directed edge (source, target, value) — reference: Gelly `Edge<K,EV>`."""

    source: Any
    target: Any
    value: Any = NULL

    def reverse(self) -> "Edge":
        return Edge(self.target, self.source, self.value)

    def get_source(self):
        return self.source

    def get_target(self):
        return self.target

    def get_value(self):
        return self.value


class Vertex(NamedTuple):
    """Vertex (id, value) — reference: Gelly `Vertex<K,VV>`."""

    id: Any
    value: Any = NULL

    def get_id(self):
        return self.id

    def get_value(self):
        return self.value


def java_str(value: Any) -> str:
    """Render a value the way Java's `toString` would in the reference's sinks.

    - tuples → "(a,b)"  (Flink Tuple.toString)
    - NullValue → "(null)"
    - booleans → "true"/"false"
    - dict → "{k1=v1, k2=v2}" (java.util Map.toString)
    - list → "[a, b]"
    """
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, tuple):
        return "(" + ",".join(java_str(f) for f in value) + ")"
    if isinstance(value, dict):
        return "{" + ", ".join(f"{java_str(k)}={java_str(v)}" for k, v in value.items()) + "}"
    if isinstance(value, list):
        return "[" + ", ".join(java_str(v) for v in value) + "]"
    return str(value)


def csv_line(value: Any) -> str:
    """Render one record as the reference's `writeAsCsv` would (top-level
    tuple fields comma-joined, nested values via `java_str`)."""
    if isinstance(value, tuple):
        return ",".join(java_str(f) for f in value)
    return java_str(value)


def text_line(value: Any) -> str:
    """Render one record as the reference's `writeAsText` would (toString)."""
    return java_str(value)

"""DataStream API — the slice of Flink's streaming surface the reference uses.

Covers exactly the transformation set inventoried in SURVEY.md §1/L1:
map, flatMap, filter, keyBy, project, union, broadcast, setParallelism,
print, writeAsText/Csv, timeWindow, timeWindowAll(+sum), iterate/closeWith.
(reference usage: SimpleEdgeStream.java throughout; WindowTriangles.java:61-66;
IterativeConnectedComponents.java:56-58).

These are thin lazy wrappers over `plan.OpNode`; the runtime executes them.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from .gtime import Time
from .plan import KeySpec, OpNode


class DataStream:
    def __init__(self, env, node: OpNode):
        self.env = env
        self.node = node

    # ------------------------------------------------------------------
    # stateless transformations
    # ------------------------------------------------------------------
    def map(self, fn: Callable[[Any], Any]) -> "DataStream":
        return DataStream(self.env, OpNode("map", [self.node], fn=fn))

    def flat_map(self, fn: Callable[[Any, Callable], None]) -> "DataStream":
        """fn(value, collect) — `collect(out)` emits one record downstream.

        Stateful flat-mappers (objects with per-key dicts, like the
        reference's Rich functions) are supported: fn may be a callable
        object holding state. State lives for the life of the plan — a
        plan is a one-shot job, like a Flink program; `execute()` refuses
        to run twice.
        """
        return DataStream(self.env, OpNode("flat_map", [self.node], fn=fn))

    def filter(self, fn: Callable[[Any], bool]) -> "DataStream":
        return DataStream(self.env, OpNode("filter", [self.node], fn=fn))

    def project(self, *fields: int) -> "DataStream":
        return DataStream(self.env, OpNode("project", [self.node], fields=fields))

    def union(self, *others: "DataStream") -> "DataStream":
        return DataStream(
            self.env, OpNode("union", [self.node] + [o.node for o in others])
        )

    def broadcast(self) -> "DataStream":
        """Replicate the stream to every parallel subtask
        (reference: BroadcastTriangleCount.java:42)."""
        return DataStream(self.env, OpNode("broadcast", [self.node]))

    def parallel_flat_map(self, fn_factory: Callable[[], Any],
                          parallelism: int) -> "DataStream":
        """`parallelism` independent stateful flat-map instances, each
        seeing this (typically broadcast) stream in full — the
        broadcast + parallel RichFlatMapFunction pattern
        (reference: BroadcastTriangleCount.java:42-45)."""
        return DataStream(
            self.env,
            OpNode("parallel_flat_map", [self.node],
                   parallelism=parallelism, fn_factory=fn_factory),
        )

    def set_parallelism(self, parallelism: int) -> "DataStream":
        self.node.parallelism = parallelism
        return self

    # ------------------------------------------------------------------
    # keying / windowing
    # ------------------------------------------------------------------
    def key_by(self, *fields, selector: Optional[Callable] = None) -> "KeyedStream":
        spec = KeySpec(selector=selector) if selector else KeySpec(fields=fields)
        return KeyedStream(self.env, self.node, spec)

    def time_window_all(self, size: Time,
                        slide: Optional[Time] = None) -> "AllWindowedStream":
        return AllWindowedStream(self.env, self.node, size, slide)

    # ------------------------------------------------------------------
    # iteration (reference: IterativeConnectedComponents.java:56-58)
    # ------------------------------------------------------------------
    def iterate(self, max_iterations: int = 1000) -> "IterativeStream":
        head = OpNode("iterate", [self.node], max_iterations=max_iterations)
        return IterativeStream(self.env, head)

    # ------------------------------------------------------------------
    # sinks
    # ------------------------------------------------------------------
    def print_(self) -> "DataStream":
        node = OpNode("sink", [self.node], mode="print")
        self.env._register_sink(node)
        return DataStream(self.env, node)

    # Alias matching examples written against the reference naming.
    print = print_

    def write_as_csv(self, path: str, overwrite: bool = True) -> "DataStream":
        node = OpNode("sink", [self.node], mode="csv", path=path, overwrite=overwrite)
        self.env._register_sink(node)
        return DataStream(self.env, node)

    def write_as_text(self, path: str, overwrite: bool = True) -> "DataStream":
        node = OpNode("sink", [self.node], mode="text", path=path, overwrite=overwrite)
        self.env._register_sink(node)
        return DataStream(self.env, node)

    def collect(self) -> "DataStream":
        """Buffer results in memory; retrieve after execute() via
        `env.results_of(stream)`. (Test convenience; the reference reads
        back CSV files instead.)"""
        node = OpNode("sink", [self.node], mode="collect")
        self.env._register_sink(node)
        return DataStream(self.env, node)


class KeyedStream:
    """Stream hash-partitioned by key — the process/network boundary in the
    reference (SURVEY.md §3 'PROCESS/NETWORK BOUNDARY')."""

    def __init__(self, env, parent: OpNode, key_spec: KeySpec):
        self.env = env
        self.parent = parent
        self.key_spec = key_spec
        self.node = OpNode("key_by", [parent], key_spec=key_spec)

    def time_window(self, size: Time,
                    slide: Optional[Time] = None) -> "WindowedStream":
        """Tumbling windows by default; pass `slide` for SLIDING windows
        (Flink's KeyedStream.timeWindow(size, slide) — part of the
        substrate surface, though the reference's examples only ever
        use the tumbling form, SimpleEdgeStream.java:159-167)."""
        return WindowedStream(self.env, self.node, self.key_spec, size,
                              slide)

    def map(self, fn) -> DataStream:
        """Keyed stateful map: fn(value) -> value; fn may be a callable object
        holding per-key state (reference: DegreeMapFunction,
        SimpleEdgeStream.java:465-482)."""
        return DataStream(self.env, OpNode("keyed_map", [self.node], fn=fn))

    def flat_map(self, fn) -> DataStream:
        return DataStream(self.env, OpNode("keyed_flat_map", [self.node], fn=fn))

    def filter(self, fn) -> DataStream:
        """Keyed stateful filter (reference: FilterDistinctVertices,
        SimpleEdgeStream.java:194-206)."""
        return DataStream(self.env, OpNode("keyed_filter", [self.node], fn=fn))


class WindowedStream:
    """Tumbling (slide=None) or sliding time windows over a keyed stream
    (reference: KeyedStream.timeWindow → WindowedStream)."""

    def __init__(self, env, parent: OpNode, key_spec: KeySpec, size: Time,
                 slide: Optional[Time] = None):
        self.env = env
        self.parent = parent
        self.key_spec = key_spec
        self.size = size
        self.slide = slide

    def _slide_ms(self) -> Optional[int]:
        return self.slide.milliseconds if self.slide is not None else None

    def fold(self, initial: Any, fn: Callable[[Any, Any], Any]) -> DataStream:
        """Incremental per-(key,window) fold, arrival order
        (reference: GraphWindowStream.java:63, WindowGraphAggregation.java:58)."""
        node = OpNode(
            "window", [self.parent], key_spec=self.key_spec,
            size_ms=self.size.milliseconds, slide_ms=self._slide_ms(),
            op="fold", initial=initial, fn=fn,
        )
        return DataStream(self.env, node)

    def reduce(self, fn: Callable[[Any, Any], Any]) -> DataStream:
        node = OpNode(
            "window", [self.parent], key_spec=self.key_spec,
            size_ms=self.size.milliseconds, slide_ms=self._slide_ms(),
            op="reduce", fn=fn,
        )
        return DataStream(self.env, node)

    def apply(self, fn) -> DataStream:
        """Buffered window apply: fn(key, window, values, collect)
        (reference: WindowedStream.apply, GraphWindowStream.java:131)."""
        node = OpNode(
            "window", [self.parent], key_spec=self.key_spec,
            size_ms=self.size.milliseconds, slide_ms=self._slide_ms(),
            op="apply", fn=fn,
        )
        return DataStream(self.env, node)

    def sum(self, field: int) -> DataStream:
        node = OpNode(
            "window", [self.parent], key_spec=self.key_spec,
            size_ms=self.size.milliseconds, slide_ms=self._slide_ms(),
            op="sum", field=field,
        )
        return DataStream(self.env, node)


class AllWindowedStream:
    """Non-keyed tumbling/sliding windows
    (reference: WindowTriangles.java:66)."""

    def __init__(self, env, parent: OpNode, size: Time,
                 slide: Optional[Time] = None):
        self.env = env
        self.parent = parent
        self.size = size
        self.slide = slide

    def _slide_ms(self) -> Optional[int]:
        return self.slide.milliseconds if self.slide is not None else None

    def sum(self, field: int) -> DataStream:
        node = OpNode(
            "window_all", [self.parent], size_ms=self.size.milliseconds,
            slide_ms=self._slide_ms(), op="sum", field=field,
        )
        return DataStream(self.env, node)

    def apply(self, fn) -> DataStream:
        node = OpNode(
            "window_all", [self.parent], size_ms=self.size.milliseconds,
            slide_ms=self._slide_ms(), op="apply", fn=fn,
        )
        return DataStream(self.env, node)


class IterativeStream(DataStream):
    """Feedback loop (reference: DataStream.iterate()/closeWith,
    IterativeConnectedComponents.java:56-58).

    Records entering the loop are processed by the body; records fed back
    via close_with() re-enter until quiescence (finite-stream fixpoint).
    """

    def __init__(self, env, head: OpNode):
        super().__init__(env, head)
        self._head = head

    def close_with(self, feedback: DataStream) -> DataStream:
        self._head.params["feedback"] = feedback.node
        return feedback

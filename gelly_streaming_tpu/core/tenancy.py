"""Multi-tenant cohort scheduler: N independent graph streams, ONE
vmapped dispatch per window cohort.

The observatory's committed verdict (PERF_cpu.json `cost_model`, ISSUE
10) is that every hot program is bytes/launch-bound — the fused scan
runs at 0.096% of roofline and the wall is per-DISPATCH, not
per-stream. The ROADMAP north star ("millions of users") is thousands
of SMALL independent streams, so the biggest available lever is
amortizing each dispatch across many of them: this module admits N
tenants, right-pads each tenant's next window(s) into a cohort slab
`[N, W, eb]`, and issues one leading-axis-vmapped dispatch over the
SAME fused scan body every summary engine runs
(ops/scan_analytics.build_cohort_scan — the trick the sharded path
already plays for panes, applied to tenants). Per-tenant results are
bit-identical to N separate StreamSummaryEngine runs by construction
(padded rows/windows fold as no-ops against the carry), asserted by
tools/tenancy_ab.py and tests/test_tenancy.py.

The serving pieces around the slab:

- **Admission & backpressure.** `admit()` is capped at GS_TENANT_MAX
  (typed `TenantRejected` + durable `tenant_rejected` event past it);
  each tenant owns a bounded ingest queue of GS_TENANT_QUEUE_WINDOWS
  windows, and `feed()` past capacity either raises a typed
  `TenantBackpressure` (policy `reject`, the default — the caller owns
  retry) or sheds the overflow with a durable event + counter (policy
  `drop`). Slab prep for the NEXT dispatch batch rides the resident
  tier's ingest ring (ops/resident_engine.IngestRing over the shared
  ingress worker pool), so its bounded slots are the admission queue
  between the host and the device — while batch k computes, batch
  k+1's slab fills.
- **Per-tenant state.** Each tenant carries its own (degrees, labels,
  cover) slabs in the engine-shared checkpoint layout, so
  `tenant_state_dict()` is interchangeable with
  StreamSummaryEngine.load_state_dict at equal buckets — the
  cohort→single demotion ladder and the per-tenant
  checkpoint/kill→resume drills (tools/chaos_run.py tenant leg) are
  layout conversions, not translations. Tenants may declare their own
  vertex bucket: the cohort groups tenants by bucket signature and
  dispatches one slab per group.
- **Per-tenant demotion.** A tenant whose slab prep fails (poisoned
  input, injected fault) demotes ALONE to its own single-tenant
  StreamSummaryEngine seeded from its live carry
  (utils/resilience.record_demotion stamps the `tenant` label); the
  cohort keeps dispatching the healthy tenants. The sick tenant's
  stream continues on the single tier — same summaries, its own
  dispatches — and its checkpoints stay engine-interchangeable.
- **Resident cohort tier.** With
  ops/resident_engine.resolve_resident_cohort selected
  (GS_COHORT_RESIDENT pin or committed `tenancy_ab`/`cohort_resident`
  parity+speedup rows), the per-(vb, kb) group's carries live as ONE
  stacked `[N, ...]` pytree on device between rounds, updated in
  place by a donated super-batch program
  (`jax.jit(..., donate_argnums)` where the backend honors donation)
  that folds up to GS_RESIDENT_SPB windows per tenant per dispatch —
  no per-round restack, no per-tenant carry h2d. Per-tenant
  checkpoints gather their slice at super-batch boundaries
  (`_carry_of`), so the ISSUE-11/12 checkpoint, WAL-replay,
  bulkhead-bisect, and demotion contracts hold per tenant unchanged;
  membership changes (admit/close/quarantine/demote/restore) break
  residency and restack. The window body itself may additionally be
  the tenant-axis Pallas megakernel
  (ops/pallas_window.maybe_cohort_body, its own GS_COHORT_PALLAS
  gate).
- **Autotuning.** The dispatch autotuner (ops/autotune.DispatchTuner,
  family `tenant_cohort`, keyed by eb/vb AND the live cohort bucket
  Nb — a grown cohort re-keys instead of inheriting stale optima)
  gains a tenants-per-dispatch arm (× windows-per-superbatch on the
  resident tier): pump rounds chunk the ready tenants into
  `tpd`-sized vmapped dispatches and feed the measured edges/s back.
  GS_TENANT_TPD pins the arm; GS_AUTOTUNE=0 dispatches all ready
  tenants in one slab.
- **Observability.** Every finalized tenant window marks
  metrics.mark_window(tenant=...) — per-tenant window/edge counters
  and staleness rows on /healthz + /metrics under the registry's
  cardinality bound (past GS_METRICS_SERIES, new tenants collapse
  into one `overflow` row instead of growing the registry) — and
  cohort dispatches run under `cohort.dispatch` spans whose
  tenant/window attrs tools/explain_perf.py aggregates.

Windowed reduce rides the same cohort shape via
ops/windowed_reduce.WindowedEdgeReduce.cohort_step (N tenants' windows
as one [N, eb] segment-kernel stack); triangle counts (and the exact
K-overflow recount) are inside the fused scan body itself.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..ops import ingress_pipeline
from ..ops import resident_engine
from ..ops import scan_analytics
from ..ops import segment as seg_ops
from ..ops import triangles as tri_ops
from ..utils import checkpoint
from ..utils import faults
from ..utils import knobs
from ..utils import latency
from ..utils import metrics
from ..utils import provenance
from ..utils import resilience
from ..utils import sanitize as sanitize_mod
from ..utils import telemetry
from ..utils import wal as wal_mod


# ----------------------------------------------------------------------
# knobs (utils/knobs.py registry; live per-call reads)
# ----------------------------------------------------------------------
def max_tenants() -> int:
    """Admission cap of the cohort (GS_TENANT_MAX, default 64)."""
    return knobs.get_int("GS_TENANT_MAX")


def queue_windows() -> int:
    """Per-tenant ingest-queue depth in windows
    (GS_TENANT_QUEUE_WINDOWS, default 8): queue capacity in edges is
    depth x edge_bucket."""
    return knobs.get_int("GS_TENANT_QUEUE_WINDOWS")


def admission_policy() -> str:
    """Queue-overflow policy (GS_TENANT_ADMISSION): `reject` (default)
    raises typed TenantBackpressure accepting nothing; `drop` accepts
    what fits and sheds the rest with a durable event."""
    return knobs.get_str("GS_TENANT_ADMISSION")


def pinned_tpd() -> int:
    """GS_TENANT_TPD: tenants per vmapped dispatch; 0 = auto (the
    tuner's arm, or all ready tenants with GS_AUTOTUNE=0)."""
    return knobs.get_int("GS_TENANT_TPD")


def quarantine_windows() -> int:
    """GS_QUARANTINE_WINDOWS: clean solo probation windows before a
    quarantined tenant re-enters the cohort (0 = permanent)."""
    return knobs.get_int("GS_QUARANTINE_WINDOWS")


def ooo_bound() -> int:
    """GS_OOO_BOUND: bounded out-of-orderness (event-time ns) of the
    per-tenant reorder buffer ahead of the monotonic guard; 0 = off
    (ts must arrive non-decreasing exactly as before)."""
    return knobs.get_int("GS_OOO_BOUND")


# ----------------------------------------------------------------------
# typed admission errors (durable-stamped like StageError)
# ----------------------------------------------------------------------
class TenantError(RuntimeError):
    """Base of the typed tenancy failures; `tenant` names the stream.
    Construction stamps a durable `tenant_rejected` flight-recorder
    event — an admission refusal is exactly the serving evidence the
    run ledger exists for, and stamping here covers every raise
    site by construction."""

    EVENT = "tenant_rejected"

    def __init__(self, message: str, tenant, _record: bool = True,
                 _durable: bool = True, **attrs):
        super().__init__(message)
        self.tenant = tenant
        if _record:
            telemetry.event(self.EVENT, durable=_durable,
                            tenant=str(tenant),
                            kind=type(self).__name__, **attrs)
            metrics.counter_inc("gs_tenant_rejections_total",
                                kind=type(self).__name__)


class TenantRejected(TenantError):
    """Admission refused: the cohort is at GS_TENANT_MAX, the id is
    unknown/closed, or a duplicate admit."""


class TenantBackpressure(TenantError):
    """A feed() overflowed the tenant's bounded queue under the
    `reject` policy. Carries `queued` and `capacity` (edges) so the
    caller can size its retry. The durable (fsync'd) ledger stamp
    fires once per overflow EPISODE (reset when the queue drains) —
    a producer retry loop against a full queue must not become
    fsync-bound or flood the post-mortem ledger with identical
    records; subsequent rejections in the episode stamp buffered
    (non-durable) events."""

    def __init__(self, message: str, tenant, queued: int,
                 capacity: int, _durable: bool = True):
        super().__init__(message, tenant, _durable=_durable,
                         queued=queued, capacity=capacity)
        self.queued = queued
        self.capacity = capacity


class TenantQuarantined(TenantRejected):
    """A feed() reached a quarantined tenant — the cohort bulkhead
    suspended the stream after a poisoned dispatch, and admission
    stays refused until the probation ladder re-admits it (or forever
    with GS_QUARANTINE_WINDOWS=0). Carries `probation_left` so a
    client can tell a permanent quarantine from a recovering one.
    Events stamp buffered (non-durable): the quarantine itself already
    wrote the durable record, and a hostile client retry-flooding a
    quarantined stream must not become fsync-bound."""

    def __init__(self, message: str, tenant, probation_left: int):
        super().__init__(message, tenant, _durable=False,
                         reason="quarantined",
                         probation_left=probation_left)
        self.probation_left = probation_left


class PoisonOutput(RuntimeError):
    """A cohort dispatch finalized implausible output (negative or
    out-of-domain analytics) for specific slab rows. Internal signal
    of the bulkhead: `tenants` names the poisoned stream(s), and the
    dispatch loop quarantines exactly those and re-runs the rest —
    never raised to callers."""

    def __init__(self, message: str, tenants):
        super().__init__(message)
        self.tenants = list(tenants)


class _Tenant:
    """One admitted stream: its bounded ingest queue, carried state in
    the engine-shared layout, cursors, and (after demotion) its own
    single-tenant engine."""

    __slots__ = ("tid", "vb", "kb", "src", "dst", "carry",
                 "windows_done", "closed_partial", "closing", "closed",
                 "tier", "engine", "ckpt_policy", "dropped_edges",
                 "bp_stamped", "fed_offset", "probation",
                 "quarantine_reason", "last_report", "res_row",
                 "last_ts", "ooo_src", "ooo_dst", "ooo_ts")

    def __init__(self, tid: str, vb: int, kb: int):
        self.tid = tid
        self.vb = vb
        self.kb = kb
        self.src = np.zeros(0, np.int32)
        self.dst = np.zeros(0, np.int32)
        self.bp_stamped = False    # durable-once-per-overflow-episode
        self.carry = None          # lazy: built at first dispatch
        self.res_row = None        # row in the resident cohort stack
                                   # (carry lives THERE, not here)
        self.last_ts = None        # newest accepted event-time stamp
        # GS_OOO_BOUND reorder buffer: edges held (sorted by ts)
        # until the tenant's watermark passes them — host-side, ahead
        # of the monotonic guard, never journaled until released
        self.ooo_src = np.zeros(0, np.int64)
        self.ooo_dst = np.zeros(0, np.int64)
        self.ooo_ts = np.zeros(0, np.int64)
        self.windows_done = 0
        self.closed_partial = False
        self.closing = False
        self.closed = False
        self.tier = "cohort"       # "cohort" | "single" | "quarantined"
        self.engine = None         # demoted/probation engine
        self.ckpt_policy = None    # per-tenant CheckpointPolicy
        self.dropped_edges = 0
        self.fed_offset = 0        # cumulative fed edges incl. rejects
                                   # (the DLQ's source-offset domain)
        self.probation = 0         # clean solo windows since quarantine
        self.quarantine_reason = None
        self.last_report = None    # last feed()'s SanitizeReport

    @property
    def queued(self) -> int:
        return len(self.src)


class TenantCohort:
    """N independent graph streams through one vmapped fused-scan
    dispatch per window cohort. See the module docstring for the
    serving model; the API in driver order:

        cohort = TenantCohort(edge_bucket=4096, vertex_bucket=8192)
        cohort.admit("user-1"); cohort.admit("user-2", vertex_bucket=2048)
        cohort.feed("user-1", src, dst)     # bounded; may reject
        results = cohort.pump()             # {tenant: [summary, ...]}
        results = cohort.close("user-1")    # flush the partial window

    Summaries are the fused summary engines' dicts (max_degree /
    num_components / odd_cycle / triangles), bit-identical per tenant
    to a single StreamSummaryEngine fed the same stream."""

    # windows of ONE tenant folded per dispatch ceiling: deep queues
    # catch up wc windows per slab row instead of one round per window
    MAX_WINDOWS_PER_DISPATCH = 8

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 k_bucket: int = 0,
                 windows_per_dispatch: Optional[int] = None):
        self.eb = seg_ops.bucket_size(edge_bucket)
        self.default_vb = seg_ops.bucket_size(vertex_bucket)
        self._kb_arg = k_bucket
        self.wc = seg_ops.bucket_size(
            windows_per_dispatch if windows_per_dispatch
            else self.MAX_WINDOWS_PER_DISPATCH)
        self.tenants: Dict[str, _Tenant] = {}
        self._programs = {}        # (vb, kb, nb, wb) -> jitted cohort scan
        self._pad_carries = {}     # (vb,) -> fresh host carry template
        self._tri_redo = {}        # (vb, kb) -> escalated exact kernel
        self._tuners = {}          # (vb,) -> DispatchTuner (tpd arm)
        self._tuner_nb = {}        # (vb,) -> Nb the tuner was keyed at
        # resident cohort tier (ops/resident_engine
        # .resolve_resident_cohort): per (vb, kb) group, the stacked
        # [N, ...] carry pytree kept ON DEVICE between rounds —
        # {"nb": rows, "rows": (tid|None, ...), "carry": 3-tuple} —
        # updated in place by the donated super-batch program and
        # restacked only when membership changes; per-tenant
        # checkpoint gathers slice it at super-batch boundaries
        self._res = {}
        self._res_programs = {}    # (vb, kb, nb, wb) -> donated program
        self.resident_dispatches = 0  # dispatches through the tier
        self._round_spb = 0        # this round's windows-per-superbatch arm
        self._ring = resident_engine.IngestRing()
        self._ckpt_dir = None
        self._ckpt_every_n = 0
        self._ckpt_every_s = 0.0
        self._round_no = 0
        self._wal = None           # utils/wal.WriteAheadLog when armed
        self._wal_dir = None
        # latency plane (utils/latency.py): the serving front-end
        # flips this so finalized windows defer their latency record
        # to the results-sink write (serve._emit stamps `deliver`);
        # direct pump() callers emit at finalize (deliver = 0)
        self.defer_delivery = False
        # GS_WAL_RETAIN bookkeeping: journal truncation at the
        # checkpoint_all() flush boundary, floored per tenant at the
        # older kept generation (utils/wal.RetentionCursor)
        self._wal_retention = wal_mod.RetentionCursor()
        # the async serving pump's queue lock (GS_PUMP=async,
        # core/serve.py): feed() appends and the pump's finalize
        # prefix-drops under it, so ingest threads and ONE pump
        # thread can run concurrently — the queues are append-only
        # from feed and consume-only from pump, and every
        # read-modify-write of (src, dst) is atomic under this lock.
        # Under GS_PUMP=sync (the default) the lock is uncontended
        # and the path is bit-identical to the pre-pump build.
        self._qlock = threading.RLock()

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------
    def admit(self, tenant_id, vertex_bucket: Optional[int] = None,
              k_bucket: Optional[int] = None) -> None:
        """Admit one stream under the GS_TENANT_MAX cap. Tenants may
        declare their own vertex bucket (the cohort groups slabs by
        bucket signature); the k bucket follows the engines' tuned
        default for the cohort's edge bucket."""
        tid = str(tenant_id)
        if tid in self.tenants:
            raise TenantRejected(
                "tenant %r is already admitted" % tid, tid,
                reason="duplicate")
        cap = max_tenants()
        live = sum(1 for t in self.tenants.values() if not t.closed)
        if live >= cap:
            raise TenantRejected(
                "cohort is at its GS_TENANT_MAX=%d admission cap; "
                "tenant %r refused" % (cap, tid), tid,
                reason="max_tenants", cap=cap)
        vb = seg_ops.bucket_size(vertex_bucket if vertex_bucket
                                 else self.default_vb)
        kb = seg_ops.bucket_size(
            k_bucket if k_bucket else
            (self._kb_arg if self._kb_arg else tri_ops._tuned_kb(self.eb)))
        t = _Tenant(tid, vb, kb)
        if self._ckpt_every_n or self._ckpt_every_s:
            t.ckpt_policy = checkpoint.CheckpointPolicy(
                every_n_windows=self._ckpt_every_n,
                every_seconds=self._ckpt_every_s)
        with self._qlock:
            # async pump: admissions land from ingest threads while
            # the pump thread iterates its _tids() snapshot — the
            # insert and every snapshot share the queue lock
            self.tenants[tid] = t
        telemetry.event("tenant_admitted", tenant=tid, vb=vb)
        metrics.on_stream_start("cohort", tenant=tid)

    def _tids(self) -> list:
        """Sorted snapshot of the tenant ids — the only safe way to
        iterate the roster once the async serving pump runs dispatch
        on its own thread while admissions keep landing (a bare
        sorted(self.tenants) can see the dict resize mid-iteration)."""
        with self._qlock:
            return sorted(self.tenants)

    def _tenant(self, tenant_id, for_feed: bool = False) -> _Tenant:
        tid = str(tenant_id)
        t = self.tenants.get(tid)
        if t is None:
            # record only on the serving surface (the feed path): a
            # typo'd id in read-only introspection must not stamp
            # ledger events or inflate the rejection counter
            raise TenantRejected("unknown tenant %r (admit() first)"
                                 % tid, tid, _record=for_feed,
                                 reason="unknown")
        if for_feed and (t.closed or t.closing):
            raise TenantRejected(
                "tenant %r is closed — its final (partial) window was "
                "already cut" % tid, tid, reason="closed")
        if for_feed and t.tier == "quarantined" \
                and quarantine_windows() <= 0:
            # permanent quarantine (GS_QUARANTINE_WINDOWS=0): refuse
            # typed — nothing would ever drain the queue. With
            # probation enabled, feeds stay ACCEPTED into the bounded
            # queue (it is what the solo probation windows consume to
            # earn re-admission); the serving front-end surfaces the
            # quarantined flag on the feed reply instead.
            raise TenantQuarantined(
                "tenant %r is quarantined (%s); "
                "GS_QUARANTINE_WINDOWS=0 — permanent for this process"
                % (tid, t.quarantine_reason), tid, probation_left=-1)
        return t

    # ------------------------------------------------------------------
    # feed / backpressure
    # ------------------------------------------------------------------
    def _check_event_time(self, t: _Tenant, src, ts):
        """Per-tenant event-time monotonicity (the cohort-aware
        guard): the optional ts column must align with the batch, be
        non-decreasing WITHIN it, and start at or after the tenant's
        newest accepted stamp. Checked independently per tenant — a
        slab interleaving tenants with disjoint time ranges is the
        normal serving shape, so nothing here ever compares clocks
        ACROSS tenants. Returns the validated int64 column (None when
        no column was given); raises ValueError naming the tenant on
        a regression, consuming nothing."""
        if ts is None:
            return None
        col = np.asarray(ts, np.int64)  # gslint: disable=host-sync (host-input normalization: feed() takes numpy/lists, never device values)
        if col.shape != (len(src),):
            raise ValueError(
                "tenant %r ts column length %d != batch length %d"
                % (t.tid, col.size, len(src)))
        if col.size == 0:
            return col
        if col.size > 1 and bool(np.any(np.diff(col) < 0)):  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
            raise ValueError(
                "tenant %r event-time regression WITHIN the batch: "
                "ts must be non-decreasing per tenant" % t.tid)
        if t.last_ts is not None and int(col[0]) < t.last_ts:  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
            raise ValueError(
                "tenant %r event-time regression: batch starts at "
                "%d but the tenant's stream already reached %d"
                % (t.tid, int(col[0]), t.last_ts))  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
        return col

    def feed(self, tenant_id, src, dst, ts=None) -> int:
        """Append edges to one tenant's bounded queue. Returns the
        number of edges accepted. Past capacity
        (GS_TENANT_QUEUE_WINDOWS x edge_bucket edges), the
        GS_TENANT_ADMISSION policy decides: `reject` raises typed
        TenantBackpressure accepting NOTHING (the caller owns retry —
        an atomic refusal can't split a window across a retry
        boundary), `drop` accepts what fits and sheds the rest with a
        durable event + counter.

        `ts` is an optional per-edge EVENT-TIME column (int64,
        non-decreasing). Tenants sharing a slab legitimately carry
        disjoint, interleaved time ranges, so the monotonicity guard
        keys on THE TENANT, never the batch or the slab: the column
        must be non-decreasing within the batch AND start at or after
        this tenant's newest accepted stamp. A regression refuses the
        whole batch (ValueError, nothing consumed) for that tenant
        only — other tenants' clocks are untouched.

        With the latency plane armed (GS_LATENCY=1), the accepted
        batch is stamped with a monotonic admission timestamp at THIS
        boundary — carried through the journal's ts column so
        replayed edges keep their original admission time — and the
        per-tenant queue-age gauge updates."""
        lat = latency.enabled()
        t_admit = latency.clock() if lat else 0.0
        t = self._tenant(tenant_id, for_feed=True)
        if t.closed_partial:
            # the engines' partial-window-must-be-final guard: a
            # tenant restored from a checkpoint taken after its short
            # final window was cut must not fold more windows on a
            # carry whose boundaries are already misaligned
            raise ValueError(
                "tenant %r already closed a partial window (length "
                "not a multiple of edge_bucket); it cannot accept "
                "more of the stream" % t.tid)
        # "admit" fault site: chaos/tests poison the raw parsed arrays
        # at the admission boundary — UPSTREAM of the sanitizer — the
        # way the "parse" site tears file bytes (utils/faults)
        got = faults.fire("admit", (t.tid, src, dst))
        if got is not None:
            _tid, src, dst = got
        bound = ooo_bound()
        if ts is not None and bound > 0:
            # GS_OOO_BOUND reorder buffer, AHEAD of the monotonic
            # guard: the batch merges (ts-sorted) into the tenant's
            # host-side hold, and only the prefix the watermark
            # (newest stamp − bound) has passed releases into the
            # normal admission path below — the released stream is
            # non-decreasing by construction, so the per-tenant
            # guard keeps holding. Held edges are NOT yet accepted:
            # not journaled, not queued, not in the return value.
            with self._qlock:
                src, dst, ts = self._ooo_insert(t, src, dst, ts,
                                                bound)
            if len(src) == 0:
                return 0
            try:
                return self._feed_accepted(t, src, dst, ts, lat,
                                           t_admit)
            except TenantBackpressure:
                # atomic refusal: the released prefix returns to the
                # buffer FRONT (its stamps precede every held one),
                # so the caller's retry re-releases it exactly
                with self._qlock:
                    self._ooo_unrelease(t, src, dst, ts)
                raise
        return self._feed_accepted(t, src, dst, ts, lat, t_admit)

    def _feed_accepted(self, t: _Tenant, src, dst, ts, lat,
                       t_admit) -> int:
        """The admission path past the reorder buffer: monotonic
        guard → sanitize → capacity gate → journal → enqueue. Queue
        mutations run under _qlock so the async pump's finalize can
        prefix-drop concurrently (GS_PUMP=async)."""
        # cohort-aware event-time guard: validated against THIS
        # tenant's clock before anything is consumed — a regression
        # refuses the batch atomically (last_ts advances only below,
        # once the batch clears the capacity gate)
        ts_col = self._check_event_time(t, src, ts)
        t.last_report = None
        report = None
        if sanitize_mod.enabled():
            # armed admission: structurally invalid records peel off
            # to the dead-letter journal (typed reason codes, absolute
            # source offsets) and the accepted remainder — every id
            # proven in [0, vb) — continues; the legacy hard-refusal
            # path below stays bit-identical with GS_SANITIZE=off.
            # commit=False: journaling + the offset advance happen
            # only once the batch clears the capacity gate below — a
            # backpressure-refused feed accepts NOTHING, so its
            # retry must not double-journal the rejects.
            try:
                report = sanitize_mod.sanitize(
                    src, dst, t.vb, tenant=t.tid, origin="feed",
                    offset=t.fed_offset,
                    dlq=sanitize_mod.resolve_dlq(), commit=False)
            except sanitize_mod.BatchRejected as e:
                # whole-batch refusals are terminal (never retried
                # as-is): already journaled, the offset domain moves
                t.fed_offset += e.size
                raise
            src = report.src.astype(np.int32)
            dst = report.dst.astype(np.int32)
        else:
            src = np.asarray(src, np.int32)  # gslint: disable=host-sync (host-input normalization: feed() takes numpy/lists, never device values)
            dst = np.asarray(dst, np.int32)  # gslint: disable=host-sync (host-input normalization: feed() takes numpy/lists, never device values)
            if len(src) != len(dst):
                raise ValueError("src/dst length mismatch")
            if len(src) and (int(src.max()) >= t.vb  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary id check)
                             or int(dst.max()) >= t.vb  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary id check)
                             or int(src.min()) < 0 or int(dst.min()) < 0):  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary id check)
                raise ValueError(
                    "tenant %r ids must be dense in [0, %d) — "
                    "out-of-range ids would scatter into another "
                    "slot's carried state" % (t.tid, t.vb))
        # the capacity gate and the enqueue are ONE atomic section
        # under the queue lock: a concurrent pump finalize may shrink
        # the queue (more room, never less), and feed's append of
        # (src, dst) must be indivisible against its prefix-drop
        with self._qlock:
            capacity = queue_windows() * self.eb
            room = capacity - t.queued
            take = len(src)
            if take > room:
                durable = not t.bp_stamped  # once per overflow episode
                t.bp_stamped = True
                if admission_policy() == "reject":
                    raise TenantBackpressure(
                        "tenant %r queue is full (%d queued of %d edge "
                        "capacity; GS_TENANT_QUEUE_WINDOWS); pump() the "
                        "cohort or retry later" % (t.tid, t.queued,
                                                   capacity),
                        t.tid, queued=t.queued, capacity=capacity,
                        _durable=durable)
                take = max(0, room)
                shed = len(src) - take
                t.dropped_edges += shed
                telemetry.event("tenant_rejected", durable=durable,
                                tenant=t.tid, kind="drop", shed=shed)
                metrics.counter_inc("gs_tenant_dropped_edges_total",
                                    shed, tenant=t.tid)
            # the batch is now CONSUMED (fully, or drop-policy
            # partially — either way the caller will not retry it
            # as-is): journal the sanitizer's rejects and advance the
            # source-offset domain. A backpressure-reject raised above
            # commits nothing, so the retried batch journals its
            # rejects exactly once.
            if report is not None:
                sanitize_mod.commit_report(
                    report, tenant=t.tid, origin="feed",
                    dlq=sanitize_mod.resolve_dlq())
                t.fed_offset += report.accepted + report.rejected
                t.last_report = report
            else:
                t.fed_offset += len(src)
            if ts_col is not None and len(ts_col):
                # the batch is consumed (fully, or drop-policy
                # partially — shed edges are gone either way): this
                # tenant's event clock advances to the batch's newest
                # validated stamp
                t.last_ts = int(ts_col[-1])  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
            if take:
                if self._wal is not None:
                    # durability boundary: the accepted edges hit the
                    # journal BEFORE the queue, so a kill anywhere past
                    # this point (including between journal append and
                    # enqueue — the wal_enqueue fault site below) is
                    # recoverable by replay; a rejected feed() journals
                    # nothing, keeping replay and the caller's view of
                    # what was accepted identical
                    self._wal.append(
                        t.tid, src[:take], dst[:take],
                        # admission stamp riding the ts column (int64
                        # ns, monotonic domain): recovery re-seeds the
                        # latency marks with the ORIGINAL admission
                        # time
                        np.full(take, latency.admit_ns(t_admit),
                                np.int64)
                        if lat else None)
                    faults.fire("wal_enqueue", t.tid)
                t.src = np.concatenate([t.src, src[:take]])
                t.dst = np.concatenate([t.dst, dst[:take]])
                if lat:
                    latency.on_admit(t.tid, take, t0=t_admit)
        metrics.gauge_set("gs_tenant_queue_edges", t.queued,
                          tenant=t.tid)
        return take

    # ------------------------------------------------------------------
    # GS_OOO_BOUND reorder buffer (event-time groundwork)
    # ------------------------------------------------------------------
    def _ooo_insert(self, t: _Tenant, src, dst, ts, bound: int):
        """Merge one batch into the tenant's ts-sorted hold and peel
        off the releasable prefix: everything at or before the
        watermark (newest stamp seen − bound). Caller holds _qlock.
        Raises ValueError (buffer untouched, nothing consumed) on a
        misaligned column or an edge older than the already-released
        frontier — with the buffer armed, "too late" means BEYOND the
        bound, not merely out of order."""
        src = np.asarray(src)  # gslint: disable=host-sync (host-input normalization: feed() takes numpy/lists, never device values)
        dst = np.asarray(dst)  # gslint: disable=host-sync (host-input normalization: feed() takes numpy/lists, never device values)
        col = np.asarray(ts, np.int64)  # gslint: disable=host-sync (host-input normalization: feed() takes numpy/lists, never device values)
        if len(src) != len(dst) or col.shape != (len(src),):
            raise ValueError(
                "tenant %r src/dst/ts length mismatch (%d/%d/%d)"
                % (t.tid, len(src), len(dst), col.size))
        if col.size and t.last_ts is not None \
                and int(col.min()) < t.last_ts:  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
            raise ValueError(
                "tenant %r event-time regression past the "
                "GS_OOO_BOUND=%d horizon: batch reaches back to %d "
                "but the watermark already released through %d"
                % (t.tid, bound, int(col.min()), t.last_ts))  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
        m_src = np.concatenate([t.ooo_src, src.astype(np.int64)])
        m_dst = np.concatenate([t.ooo_dst, dst.astype(np.int64)])
        m_ts = np.concatenate([t.ooo_ts, col])
        order = np.argsort(m_ts, kind="stable")
        m_src, m_dst, m_ts = m_src[order], m_dst[order], m_ts[order]
        if m_ts.size:
            wm = int(m_ts[-1]) - bound  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
            k = int(np.searchsorted(m_ts, wm, side="right"))  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
        else:
            k = 0
        t.ooo_src, t.ooo_dst, t.ooo_ts = (m_src[k:], m_dst[k:],
                                          m_ts[k:])
        self._note_watermark(t)
        return m_src[:k], m_dst[:k], m_ts[:k]

    def _ooo_unrelease(self, t: _Tenant, src, dst, ts) -> None:
        """Return a refused released prefix to the buffer front (its
        stamps precede every held one, so sort order is preserved).
        Caller holds _qlock."""
        t.ooo_src = np.concatenate([np.asarray(src, np.int64),  # gslint: disable=host-sync (the reorder hold is host numpy, never device values)
                                    t.ooo_src])
        t.ooo_dst = np.concatenate([np.asarray(dst, np.int64),  # gslint: disable=host-sync (the reorder hold is host numpy, never device values)
                                    t.ooo_dst])
        t.ooo_ts = np.concatenate([np.asarray(ts, np.int64),  # gslint: disable=host-sync (the reorder hold is host numpy, never device values)
                                   t.ooo_ts])
        self._note_watermark(t)

    def _note_watermark(self, t: _Tenant) -> None:
        """Report the tenant's TRUE event-time watermark lag to the
        latency plane (seconds between the newest stamp seen and the
        oldest edge still held), repointing the per-tenant age gauge
        while the reorder buffer is armed. Caller holds _qlock."""
        if t.ooo_ts.size:
            high = max(int(t.ooo_ts[-1]),  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
                       t.last_ts if t.last_ts is not None else 0)
            lag = max(0.0, (high - int(t.ooo_ts[0])) / 1e9)  # gslint: disable=host-sync (numpy-on-numpy: the admission-boundary event-time check)
        else:
            lag = 0.0
        latency.note_watermark(t.tid, lag, held=int(t.ooo_ts.size))

    def _ooo_flush(self, t: _Tenant) -> List[dict]:
        """Release the tenant's ENTIRE hold regardless of watermark —
        the close() boundary: a final window must not strand edges
        the bound never passed. Feeds in capacity-sized slices,
        pumping this tenant between slices when the queue is full;
        returns any summaries those interleaved pumps finalized."""
        out: List[dict] = []
        lat = latency.enabled()
        while t.ooo_ts.size:
            with self._qlock:
                room = max(0, queue_windows() * self.eb - t.queued)
                k = min(room, int(t.ooo_ts.size))
                src, dst, col = (t.ooo_src[:k], t.ooo_dst[:k],
                                 t.ooo_ts[:k])
                t.ooo_src = t.ooo_src[k:]
                t.ooo_dst = t.ooo_dst[k:]
                t.ooo_ts = t.ooo_ts[k:]
                self._note_watermark(t)
            if k:
                try:
                    self._feed_accepted(
                        t, src, dst, col, lat,
                        latency.clock() if lat else 0.0)
                except TenantBackpressure:
                    # a concurrent feeder filled the queue between the
                    # room check and the enqueue: put the slice back
                    # and drain below
                    with self._qlock:
                        self._ooo_unrelease(t, src, dst, col)
            if t.ooo_ts.size:
                # queue full: drain this tenant's full windows (the
                # queue capacity is ≥ one window, so progress is
                # guaranteed for a pumpable tenant) and keep flushing
                before = t.queued
                out.extend(self.pump(only=t.tid).get(t.tid, []))
                if k == 0 and t.queued >= before:
                    # nothing drains (a permanently quarantined
                    # tenant): stop — the hold stays buffered rather
                    # than spinning; close() still cuts the queue
                    break
        return out

    # ------------------------------------------------------------------
    # cohort programs / carries
    # ------------------------------------------------------------------
    def _fresh_carry(self, vb: int):
        """One tenant's zero-stream carry in the engine-shared layout
        (SummaryEngineBase._init_carry)."""
        key = (vb,)
        tpl = self._pad_carries.get(key)
        if tpl is None:
            tpl = self._pad_carries[key] = (
                np.zeros(vb + 1, np.int32),
                np.arange(vb + 1, dtype=np.int32),
                np.arange(2 * (vb + 1), dtype=np.int32))
        return tuple(jnp.asarray(a) for a in tpl)

    def _program(self, vb: int, kb: int, nb: int, wb: int):
        """The jitted cohort program at this slab shape (one per
        power-of-two (tenants, windows) bucket — ragged cohorts reuse
        O(log N x log W) programs, never one per population). Wrapped
        by the compile watch / cost observatory as `cohort_scan`. The
        window body is the vmapped XLA scan, or the tenant-axis
        Pallas megakernel when its own gate clears
        (scan_analytics.build_cohort_scan's nb path)."""
        key = (vb, kb, nb, wb)
        fn = self._programs.get(key)
        if fn is None:
            import jax

            run = scan_analytics.build_cohort_scan(self.eb, vb, kb,
                                                   nb=nb)
            fn = self._programs[key] = metrics.wrap_jit(
                "cohort_scan", jax.jit(run))
        return fn

    def _res_program(self, vb: int, kb: int, nb: int, wb: int):
        """The RESIDENT cohort program: the same cohort scan jitted
        with explicit donation of the stacked carry argument
        (resident_engine.donate_kw — in-place slab updates where the
        backend honors donation, bit-identical undonated elsewhere).
        Kept a separate program cache from _program: donation changes
        the jit signature, never the math."""
        key = (vb, kb, nb, wb)
        fn = self._res_programs.get(key)
        if fn is None:
            import jax

            run = scan_analytics.build_cohort_scan(self.eb, vb, kb,
                                                   nb=nb)
            fn = self._res_programs[key] = metrics.wrap_jit(
                "cohort_resident",
                jax.jit(run, **resident_engine.donate_kw()))
        return fn

    def _carry_of(self, t: _Tenant):
        """One tenant's live carry wherever it resides: its row of
        the resident cohort stack when the resident tier holds it,
        else its per-tenant carry, else the fresh zero-stream state.
        Pure read — never mutates tenant or stack state."""
        if t.res_row is not None:
            entry = self._res[(t.vb, t.kb)]
            return tuple(a[t.res_row] for a in entry["carry"])
        return (t.carry if t.carry is not None
                else self._fresh_carry(t.vb))

    def _evict_resident(self, key) -> None:
        """Materialize EVERY tenant out of one (vb, kb) resident stack
        and drop it. MUST run before anything replaces the stack at
        this key: a tenant whose res_row still points into a replaced
        stack would materialize a stranger's (or a pad row's fresh)
        carry and silently lose its own. Slicing the stacked leaves
        is the per-tenant gather the super-batch boundary contract
        names."""
        entry = self._res.pop(key, None)
        if entry is None:
            return
        for tid in entry["rows"]:
            other = self.tenants.get(tid) if tid else None
            if other is not None and other.res_row is not None:
                other.carry = tuple(a[other.res_row]
                                    for a in entry["carry"])
                other.res_row = None

    def _break_residency(self, t: _Tenant) -> None:
        """Materialize every tenant out of the resident stack this
        tenant shares, then drop the stack: membership is about to
        change (checkpoint restore, quarantine, demotion, probation
        absorb), so the next resident dispatch must restack from
        per-tenant carries."""
        if t.res_row is None:
            return
        self._evict_resident((t.vb, t.kb))
        t.res_row = None

    def _redo_kernel(self, vb: int, kb: int):
        """The escalated exact triangle recount of one K-overflowing
        window — the same 4x-K fallback every summary engine keeps."""
        key = (vb, kb)
        k = self._tri_redo.get(key)
        if k is None:
            k = self._tri_redo[key] = tri_ops.TriangleWindowKernel(
                edge_bucket=self.eb, vertex_bucket=vb,
                k_bucket=4 * kb)
        return k

    def _cohort_nb(self, vb: int) -> int:
        """Power-of-two bucket of the live cohort-tier population at
        this vertex bucket, capped at the admission cap — the slab's
        row dimension, and the arm-family key's N term."""
        n = sum(1 for t in self.tenants.values()
                if t.tier == "cohort" and not t.closed and t.vb == vb)
        cap = seg_ops.bucket_size(max_tenants())
        return min(seg_ops.bucket_size(max(1, n)), cap)

    def _tuner_space(self, nb: int) -> dict:
        """The `tenant_cohort` arm space at cohort bucket Nb:
        tenants-per-dispatch rungs under the live bucket, plus the
        windows-per-superbatch arm when the resident cohort tier is
        selected (its rungs divide the GS_RESIDENT_SPB bucket)."""
        space = {"tpd": sorted({max(1, nb // 4), max(1, nb // 2),
                                nb})}
        if resident_engine.resolve_resident_cohort():
            spb = seg_ops.bucket_size(
                resident_engine.resident_spb(self.eb))
            space["spb"] = sorted({max(1, spb // 4),
                                   max(1, spb // 2), spb})
        return space

    def _tuner(self, vb: int):
        """The cohort dispatch arms (ops/autotune.DispatchTuner,
        family `tenant_cohort`): tenants-per-dispatch (× windows-per-
        superbatch on the resident tier); pump rounds chunk ready
        tenants into tpd-sized dispatches and feed measured edges/s
        back. None when the tuner is disabled (GS_AUTOTUNE=0) or
        GS_TENANT_TPD pins.

        The arm-family key includes the COHORT BUCKET Nb
        (`tenant_cohort:eb=…:vb=…:N=…`): a tenant admitted during a
        vertex-bucket grow changes the slab's row dimension, and a
        grown cohort inheriting the old bucket's tenants-per-dispatch
        optimum (with its stale EMAs) would exploit a measurement
        taken on a different program shape — so a bucket change
        REKEYS the family (ops/autotune.DispatchTuner.rekey:
        incumbent and persisted-cache seed carry over only where the
        new space sanctions them, EMAs reset)."""
        from ..ops import autotune

        if pinned_tpd() > 0 or not autotune.enabled():
            return None
        key = (vb,)
        nb = self._cohort_nb(vb)
        tuner = self._tuners.get(key)
        if tuner is not None and self._tuner_nb.get(key) == nb:
            return tuner
        space = self._tuner_space(nb)
        init = {k: v[-1] for k, v in space.items()}
        name = "tenant_cohort:eb=%d:vb=%d:N=%d" % (self.eb, vb, nb)
        if tuner is None:
            tuner = self._tuners[key] = autotune.DispatchTuner(
                name, space, init)
        else:
            tuner.rekey(name, space=space, initial=init)
        self._tuner_nb[key] = nb
        return tuner

    def _resolve_tpd(self, vb: int, n_ready: int):
        """(tpd, tuner_arm): the dispatch-batch width this round. Pin
        wins; else the tuner's arm; else every ready tenant in one
        slab (the GS_AUTOTUNE=0 static form)."""
        pin = pinned_tpd()
        if pin > 0:
            return pin, None
        tuner = self._tuner(vb)
        if tuner is None:
            return n_ready, None
        arm = (tuner.best() if ingress_pipeline.forced_sync_active()
               else tuner.next_round())
        return arm["tpd"], arm

    # ------------------------------------------------------------------
    # the pump: rounds of vmapped cohort dispatches
    # ------------------------------------------------------------------
    def _window_ceiling(self) -> int:
        """Windows of ONE tenant folded per dispatch: the static wc
        on the scan tier; on the RESIDENT cohort tier the super-batch
        depth (the GS_RESIDENT_SPB bucket, narrowed by the tuner's
        windows-per-superbatch arm when one is live this round) — one
        donated dispatch folds a whole super-batch per tenant instead
        of wc windows. Chunking never changes summaries (window
        boundaries are count-based), so the ceiling is a pure
        throughput lever."""
        if not resident_engine.resolve_resident_cohort():
            return self.wc
        spb = seg_ops.bucket_size(
            resident_engine.resident_spb(self.eb))
        if self._round_spb:
            spb = min(spb, seg_ops.bucket_size(self._round_spb))
        return max(self.wc, spb)

    def _take_windows(self, t: _Tenant) -> int:
        """Full windows this tenant contributes to the next slab (plus
        the final partial one once closing)."""
        if t.tier != "cohort" or t.closed:
            return 0
        wc = self._window_ceiling()
        full = t.queued // self.eb
        if t.closing and t.queued % self.eb and full < wc:
            return min(full + 1, wc)
        return min(full, wc)

    def _prep_slab(self, batch: List[_Tenant], wins: List[int]):
        """Right-pad each tenant's next `wins` windows into the cohort
        slab [nb, wb, eb] (+ per-tenant failures for demotion). Runs
        on the ingress worker pool via the ingest ring when available;
        reads queues only — consumption happens at finalize."""
        st = latency.stamps()
        latency.stamp(st, "start")  # queue-wait ends: prep begins
        nb = seg_ops.bucket_size(len(batch))
        wb = seg_ops.bucket_size(max(wins))
        vb = batch[0].vb
        s = np.full((nb, wb, self.eb), vb, np.int32)
        d = np.full((nb, wb, self.eb), vb, np.int32)
        valid = np.zeros((nb, wb, self.eb), bool)
        real = []   # (tenant, row, windows, edges) actually packed
        failed = []  # (tenant, repr(error)) -> demotion at finalize
        for row, (t, w) in enumerate(zip(batch, wins)):
            try:
                faults.fire("tenant_prep", t.tid)
                # a consistent (src, dst) snapshot under the queue
                # lock: concurrent feeds only APPEND (atomically, both
                # arrays under _qlock), so the prefix this slab packs
                # is stable — the pump is the sole consumer
                with self._qlock:
                    n = min(w * self.eb, t.queued)
                    t_src, t_dst = t.src, t.dst
                flat_s = s[row].reshape(-1)
                flat_d = d[row].reshape(-1)
                flat_v = valid[row].reshape(-1)
                flat_s[:n] = t_src[:n]
                flat_d[:n] = t_dst[:n]
                flat_v[:n] = True
                real.append((t, row, w, n))
            except faults.InjectedFault as e:
                if e.fatal:
                    raise  # the simulated hard kill: never isolated
                failed.append((t, "%s: %s" % (type(e).__name__, e)))
            except Exception as e:  # gslint: disable=except-hygiene (captured per tenant: finalize demotes the sick tenant via record_demotion and the cohort keeps dispatching)
                failed.append((t, "%s: %s" % (type(e).__name__, e)))
        latency.stamp(st, "prep")
        return (nb, wb, s, d, valid, real, failed, st)

    def _dispatch_batch(self, vb: int, kb: int, slab, out: dict,
                        staged: list) -> int:
        """One vmapped cohort dispatch + finalize. Returns the number
        of edges covered (the tuner's measurement unit)."""
        nb, wb, s, d, valid, real, failed, st = slab
        for t, err in failed:
            self._demote(t, "slab prep failed: %s" % err)
        if not real:
            return 0
        res_on = resident_engine.resolve_resident_cohort()
        res_key = (vb, kb)
        # the resident stack's row signature for THIS dispatch: the
        # tenant at each slab row (None = pad row)
        sig = [None] * nb
        for t, row, _w, _n in real:
            sig[row] = t.tid
        sig = tuple(sig)
        entry = self._res.get(res_key) if res_on else None
        if entry is not None and entry["nb"] == nb \
                and entry["rows"] == sig \
                and all(t.res_row == row for t, row, _w, _n in real):
            # the steady-state resident hit: the cohort's carries are
            # already stacked ON DEVICE from the previous super-batch
            # — no per-tenant restack, no h2d of N carry slabs
            stacked = entry["carry"]
        else:
            # membership changed (admit/close/demote/restore) or the
            # tier just turned on: evict the WHOLE stale stack at this
            # key — not just this batch's tenants — so absent tenants
            # (closing peers, drained queues) materialize their rows
            # BEFORE the commit below replaces the stack under them;
            # then restack from wherever each carry lives (with the
            # tier off this also materializes rows stranded by a
            # flipped pin — a no-op when no stack exists)
            self._evict_resident(res_key)
            carries = [self._carry_of(t) for t, _r, _w, _n in real]
            by_row = {row: i
                      for i, (_t, row, _w, _n) in enumerate(real)}
            # pad rows (demoted-mid-prep or a non-power-of-two
            # cohort) carry a fresh zero-stream state — built only
            # when the slab actually has them
            pad = (self._fresh_carry(vb) if len(by_row) < nb
                   else None)
            stacked = tuple(
                jnp.stack([carries[by_row[r]][leaf] if r in by_row
                           else pad[leaf] for r in range(nb)])
                for leaf in range(3))
        run = (self._res_program(vb, kb, nb, wb) if res_on
               else self._program(vb, kb, nb, wb))
        edges = sum(n for _t, _row, _w, n in real)

        def _dispatch():
            # h2d INSIDE the guarded call (a wedged transfer must
            # surface as the typed StageTimeout, not hang the pump);
            # the boundary stamp closes the h2d stage right after
            sj, dj, vj = (jnp.asarray(s), jnp.asarray(d),
                          jnp.asarray(valid))
            latency.stamp(st, "h2d")
            return run(stacked, sj, dj, vj)

        with telemetry.span("cohort.dispatch", tenants=len(real),
                            windows=sum(w for _t, _r, w, _n in real),
                            edges=edges) as sp:
            faults.fire("cohort_dispatch",
                        tuple(t.tid for t, _r, _w, _n in real))
            new_carries, outs = resilience.call_guarded(
                "dispatch", ("cohort", self._round_no), _dispatch,
                retries=0)  # carry-mutating: deadline only, never re-run
        # the program-identity tags wrap_jit bound inside the dispatch
        # key the costmodel entry this span's bytes come from; pop
        # BEFORE the redo kernels below can rebind them
        tags = telemetry.pop_dispatch_tags()
        mats = tuple(np.array(x) for x in outs)  # gslint: disable=host-sync (sanctioned finalize boundary: the cohort's ONE batched d2h per dispatch)
        latency.stamp(st, "dispatch")  # device wait ends with the d2h
        mdeg, ncomp, odd, tri, ovf = mats
        # the bulkhead's output gate: BEFORE any tenant state mutates,
        # refuse implausible analytics per slab row (negative counts,
        # components past the bucket, non-finite values) — a poisoned
        # carry must never be folded back, and naming the rows lets
        # the dispatch loop quarantine exactly the poison tenants and
        # re-run the rest of the round
        poisoned = []
        for t, row, w, _n in real:
            bad = False
            redo = np.asarray(ovf[row, :w]) != 0  # gslint: disable=host-sync (numpy-on-numpy after the batched materialize)
            for arr, hi, skip in ((mdeg, None, None),
                                  (ncomp, t.vb + 1, None),
                                  (tri, None, redo)):
                v = np.asarray(arr[row, :w])  # gslint: disable=host-sync (numpy-on-numpy after the batched materialize)
                if v.dtype.kind == "f" and not np.isfinite(v).all():
                    bad = True
                ok = np.ones(w, bool) if skip is None else ~skip
                if (v[ok] < 0).any() \
                        or (hi is not None and (v[ok] > hi).any()):
                    bad = True
            if bad:
                poisoned.append(t.tid)
        if poisoned:
            raise PoisonOutput(
                "cohort dispatch finalized implausible analytics for "
                "tenant(s) %s" % ", ".join(poisoned), poisoned)
        if res_on:
            # commit the resident stack only now, PAST the poison
            # gate: a refused dispatch leaves the previous stack (and
            # every per-tenant carry) untouched for the bulkhead's
            # re-prep of the healthy remainder
            self._res[res_key] = {"nb": nb, "rows": sig,
                                  "carry": new_carries}
            self.resident_dispatches += 1
        # per-tenant cost attribution: split this dispatch's measured
        # wall seconds (and the program's modeled bytes) across the
        # REAL rows proportionally by valid-edge count — pad rows
        # attribute zero, and the shares reconcile exactly to the
        # span's total (pinned by test)
        metrics.attribute_dispatch(
            sp.elapsed, [(t.tid, n) for t, _r, _w, n in real],
            program=tags.get("program"), sig=tags.get("sig"))
        prov_tier = "cohort_resident" if res_on else "cohort"
        for t, row, w, n in real:
            summaries = []
            for j in range(w):
                lo = j * self.eb
                tri_w = int(tri[row, j])  # gslint: disable=host-sync (numpy-on-numpy after the batched materialize)
                if int(ovf[row, j]):  # gslint: disable=host-sync (numpy-on-numpy after the batched materialize)
                    tri_w = self._redo_kernel(t.vb, t.kb).count(
                        t.src[lo:min(lo + self.eb, n)],
                        t.dst[lo:min(lo + self.eb, n)])
                summaries.append({
                    "max_degree": int(mdeg[row, j]),  # gslint: disable=host-sync (numpy-on-numpy after the batched materialize)
                    "num_components": int(ncomp[row, j]),  # gslint: disable=host-sync (numpy-on-numpy after the batched materialize)
                    "odd_cycle": bool(odd[row, j]),
                    "triangles": int(tri_w),  # gslint: disable=host-sync (numpy-on-numpy after the batched materialize)
                })
            if res_on:
                # the carry stays IN the device-resident stack; the
                # tenant keeps only its row cursor (checkpoints and
                # demotions gather their slice via _carry_of)
                t.res_row = row
                t.carry = None
            else:
                t.carry = tuple(a[row] for a in new_carries)
            with self._qlock:
                t.src = t.src[n:]
                t.dst = t.dst[n:]
                t.bp_stamped = False  # queue drained: new episode
            if st is not None:
                # per-window ingest→deliver record: join each window
                # back to the admission mark of its completing edge;
                # the serving front-end defers emission to its sink
                # write (serve._emit stamps the `deliver` stage)
                for j in range(w):
                    latency.on_window(
                        t.tid,
                        edges=min((j + 1) * self.eb, n) - j * self.eb,
                        st=st, ordinal=t.windows_done + j,
                        defer=self.defer_delivery)
            if provenance.armed():
                # the recorded span is the tenant's own WAL cursor
                # (windows_done × eb — the checkpoint contract), so
                # replay_window can stream exactly these edges back
                # through the host twin and re-derive the digest
                for j in range(w):
                    lo = (t.windows_done + j) * self.eb
                    provenance.emit(
                        tenant=t.tid, window=t.windows_done + j,
                        wal_lo=lo,
                        wal_hi=lo + min((j + 1) * self.eb, n)
                        - j * self.eb,
                        tier=prov_tier, program="cohort_scan",
                        sig=tags.get("sig"),
                        summary=summaries[j])
            t.windows_done += w
            if n < w * self.eb:      # the final short window just cut
                t.closed_partial = True
            if t.closing and t.queued == 0:
                t.closed = True
            out.setdefault(t.tid, []).extend(summaries)
            metrics.mark_window(w, n, engine="cohort", tier="cohort",
                                tenant=t.tid)
            metrics.gauge_set("gs_tenant_queue_edges", t.queued,
                              tenant=t.tid)
            if st is not None:
                metrics.gauge_set("gs_tenant_queue_age_s",
                                  latency.queue_age(t.tid) or 0.0,
                                  tenant=t.tid)
            self._stage_ckpt(t, staged)
        return edges

    def _dispatch_guarded(self, vb: int, kb: int, batch, wins, slab,
                          out: dict, staged: list) -> int:
        """The cohort bulkhead around one dispatch batch. A dispatch
        that fails (typed StageError from the guard, a non-fatal
        injected fault) or finalizes implausible output is BISECTED to
        the poison tenant(s): the offending streams are quarantined
        (durable event, suspended feeds, solo probation — see
        _quarantine) and the remaining tenants re-dispatch THE SAME
        round from their untouched queues and carries, so one hostile
        stream can never take the cohort down.

        Quarantine requires DISCRIMINATING evidence — a failure that
        follows a strict subset of the tenants. If every tenant of
        the batch fails alone (a dead device, a wedged transfer: the
        failure follows the hardware, not the data), the quarantines
        are revoked and the typed error propagates exactly as it did
        before the bulkhead existed. Fatal injected faults (the chaos
        kill) and KeyboardInterrupt/SystemExit pass through — a kill
        must stay a kill. Exception-free dispatches run exactly the
        pre-bulkhead path (no re-prep, no overhead)."""
        errors = []  # (tenant_id, err) per singleton-failure quarantine
        edges = self._dispatch_bulkhead(vb, kb, batch, wins, slab,
                                        out, staged, errors)
        failed = {tid for tid, _e in errors}
        if errors and failed == {t.tid for t in batch}:
            for t in batch:
                if t.tid in failed:
                    self._unquarantine(
                        t, "systemic dispatch failure — every tenant "
                           "failed alone")
            raise errors[-1][1]
        return edges

    def _dispatch_bulkhead(self, vb: int, kb: int, batch, wins, slab,
                           out: dict, staged: list,
                           errors: list) -> int:
        try:
            return self._dispatch_batch(vb, kb, slab, out, staged)
        except PoisonOutput as e:
            # row-attributed evidence: quarantine exactly the named
            # tenants (no bisect, and never revoked as systemic —
            # the verdict names rows, not the whole dispatch)
            bad = set(e.tenants)
            for t in batch:
                if t.tid in bad:
                    self._quarantine(t, "implausible dispatch output")
            keep = [(t, w) for t, w in zip(batch, wins)
                    if t.tid not in bad]
        except faults.InjectedFault as e:
            if e.fatal:
                raise
            keep = self._bisect_split(batch, wins, e, errors)
            if keep is None:
                return 0
            # keep is (first_half, second_half): dispatch each
            return sum(
                self._dispatch_bulkhead(vb, kb, b, w,
                                        self._prep_slab(b, w), out,
                                        staged, errors)
                for b, w in keep if b)
        except resilience.StageError as e:
            keep = self._bisect_split(batch, wins, e, errors)
            if keep is None:
                return 0
            return sum(
                self._dispatch_bulkhead(vb, kb, b, w,
                                        self._prep_slab(b, w), out,
                                        staged, errors)
                for b, w in keep if b)
        # PoisonOutput path: re-run the named-healthy remainder once
        if not keep:
            return 0
        b = [t for t, _w in keep]
        w = [x for _t, x in keep]
        return self._dispatch_bulkhead(vb, kb, b, w,
                                       self._prep_slab(b, w), out,
                                       staged, errors)

    def _bisect_split(self, batch, wins, err, errors: list):
        """Halve a failing batch for fault attribution; a singleton
        failing batch IS the implicated tenant — quarantine it,
        record the evidence for the systemic-failure check, and stop
        (returns None)."""
        if len(batch) == 1:
            self._quarantine(batch[0], "poison dispatch: %s: %s"
                             % (type(err).__name__, err))
            errors.append((batch[0].tid, err))
            return None
        mid = len(batch) // 2
        telemetry.event("cohort_bisect", tenants=len(batch),
                        error=type(err).__name__)
        metrics.counter_inc("gs_cohort_bisects_total")
        return ((batch[:mid], wins[:mid]), (batch[mid:], wins[mid:]))

    def _unquarantine(self, t: _Tenant, reason: str) -> None:
        """Revoke a quarantine this round imposed without
        discriminating evidence (the systemic-failure path)."""
        if t.tier != "quarantined":
            return
        t.tier = "cohort"
        t.engine = None
        t.probation = 0
        t.quarantine_reason = None
        telemetry.event("quarantine_revoked", durable=True,
                        tenant=t.tid, reason=reason)
        metrics.gauge_set("gs_tenant_quarantined", 0, tenant=t.tid)

    def pump(self, max_rounds: Optional[int] = None,
             only: Optional[str] = None) -> Dict[str, list]:
        """Dispatch window cohorts while any tenant has a full window
        queued (plus the final partial window of closing tenants);
        demoted tenants run their own single-tenant engine alongside.
        Returns {tenant: [summary dict, ...]} for every window
        finalized by this call; due checkpoints are written at clean
        return (the delivery boundary — the engines' staged
        at-least-once contract). `only` restricts the pump to one
        tenant (close()'s drain — other tenants' windows must never
        be consumed by a call whose caller only reads one stream)."""
        out: Dict[str, list] = {}
        staged: list = []
        rounds = 0
        while max_rounds is None or rounds < max_rounds:
            self._pump_singles(out, staged, only=only)
            probed = self._pump_probation(out, staged, only=only)
            by_group: Dict[tuple, list] = {}
            for tid in self._tids():
                if only is not None and tid != only:
                    continue
                t = self.tenants[tid]
                if self._take_windows(t) > 0:
                    by_group.setdefault((t.vb, t.kb), []).append(t)
            if not by_group:
                if probed:
                    # probation made progress (a quarantined tenant
                    # finalized clean solo windows, possibly
                    # re-entering the cohort) — keep pumping; failing
                    # probes return 0, so this can never spin
                    rounds += 1
                    continue
                break
            rounds += 1
            self._round_no += 1
            for (vb, kb), ready in sorted(by_group.items()):
                tpd, arm = self._resolve_tpd(vb, len(ready))
                # the windows-per-superbatch arm (resident tier only)
                # narrows this round's per-tenant window ceiling
                self._round_spb = int((arm or {}).get("spb") or 0)
                batches = [ready[i:i + tpd]
                           for i in range(0, len(ready), tpd)]
                descs = [(b, [self._take_windows(t) for t in b])
                         for b in batches]
                with telemetry.span(
                        "cohort.round", vb=vb, tenants=len(ready),
                        edges=sum(min(w * self.eb, t.queued)
                                  for b, ws in descs
                                  for t, w in zip(b, ws))) as sp:
                    edges = self._run_batches(vb, kb, descs, out,
                                              staged)
                if arm is not None and edges:
                    tuner = self._tuner(vb)
                    if tuner is not None:
                        tuner.record(arm, edges, sp.elapsed)
        for (vb,), tuner in self._tuners.items():
            if not ingress_pipeline.forced_sync_active():
                tuner.save()
        for t, snap in staged:
            checkpoint.save(self._ckpt_path(t.tid), snap)
        return out

    def _run_batches(self, vb: int, kb: int, descs, out: dict,
                     staged: list) -> int:
        """Dispatch one round's batches with the ingest ring prepping
        batch k+1's slab on the worker pool while batch k computes
        (batches within a round cover DISJOINT tenants, so lookahead
        prep reads only queues no earlier batch consumes; across
        rounds the pump re-plans after finalize)."""
        edges = 0
        if len(descs) == 1:
            # one batch: a worker-pool round trip buys nothing — build
            # the slab inline (the serving-shape hot path)
            batch, wins = descs[0]
            return self._dispatch_guarded(vb, kb, batch, wins,
                                          self._prep_slab(batch, wins),
                                          out, staged)
        pending = {}
        try:
            for i, (batch, wins) in enumerate(descs):
                if i not in pending:
                    if not self._ring.submit(
                            lambda bw: self._prep_slab(*bw), i,
                            (batch, wins)):
                        pending[i] = None  # inline fallback
                    else:
                        pending[i] = "ring"
                # lookahead: top the ring up with the NEXT slab before
                # dispatching this one (classic double buffering)
                if i + 1 < len(descs) and i + 1 not in pending:
                    if self._ring.submit(
                            lambda bw: self._prep_slab(*bw), i + 1,
                            descs[i + 1]):
                        pending[i + 1] = "ring"
                if pending[i] == "ring":
                    fut, _item = self._ring.pop(i)
                    slab = fut.result()
                else:
                    slab = self._prep_slab(*descs[i])
                edges += self._dispatch_guarded(
                    vb, kb, descs[i][0], descs[i][1], slab, out,
                    staged)
        except BaseException:
            # a mid-round failure (stage timeout, fatal injected kill)
            # must not strand prepped slabs in the ring — the NEXT
            # pump re-plans from the queues, which finalize never
            # consumed for undispatched batches
            self._ring.drain()
            raise
        return edges

    def _pump_singles(self, out: dict, staged: list,
                      only: Optional[str] = None) -> None:
        """Demoted tenants: their queued full windows (and the final
        partial once closing) run through their OWN single-tenant
        engine — per-tenant dispatches, identical summaries. The
        engine marks the global health plane itself; the cohort adds
        the per-tenant row."""
        for tid in self._tids():
            if only is not None and tid != only:
                continue
            t = self.tenants[tid]
            if t.tier != "single" or t.closed:
                continue
            # the demoted engine's delivered rows keep the serving
            # contract: mirror the cohort's delivery deferral per
            # pump (serve restores defer_delivery=False at drain)
            t.engine._lat_defer = self.defer_delivery
            with self._qlock:
                n = (t.queued // self.eb) * self.eb
                if t.closing:
                    n = t.queued
                src, dst = t.src[:n], t.dst[:n]
            if n == 0:
                if t.closing:
                    t.closed = True
                continue
            with telemetry.span("tenant.single", tenant=t.tid,
                                edges=int(n)) as sp:
                summaries = t.engine.process(src, dst)
            # a demoted tenant owns its whole dispatch: 100% share
            metrics.attribute_dispatch(
                sp.elapsed, [(t.tid, int(n))],
                program=telemetry.pop_dispatch_tags().get("program"))
            with self._qlock:
                t.src = t.src[n:]
                t.dst = t.dst[n:]
                t.bp_stamped = False  # queue drained: new episode
            t.windows_done = t.engine.windows_done
            t.closed_partial = t.engine._closed_partial
            if t.closing and t.queued == 0:
                t.closed = True
            out.setdefault(t.tid, []).extend(summaries)
            metrics.mark_tenant(t.tid, len(summaries), int(n),
                                tier="single")
            self._stage_ckpt(t, staged)

    def _pump_probation(self, out: dict, staged: list,
                        only: Optional[str] = None) -> int:
        """Quarantined tenants' probation ladder: with
        GS_QUARANTINE_WINDOWS > 0, each pump gives every quarantined
        tenant with a full window queued ONE solo window through an
        isolated single-tenant engine (seeded from its last-good
        carry). A clean window advances probation (its exact summaries
        are delivered — isolation, not deletion); a failing or
        implausible one resets probation and discards the probe engine
        (the next probe re-seeds from the untouched last-good carry —
        the failed fold never sticks). After GS_QUARANTINE_WINDOWS
        consecutive clean windows the tenant re-enters the cohort
        tier. Returns windows finalized (0 on every-probe-failed, so
        pump()'s loop can never spin on a still-poisoned stream)."""
        qw = quarantine_windows()
        if qw <= 0:
            return 0  # permanent quarantine: truly suspended
        done = 0
        for tid in self._tids():
            if only is not None and tid != only:
                continue
            t = self.tenants[tid]
            if t.tier != "quarantined" or t.closed:
                continue
            with self._qlock:
                n = (self.eb if t.queued >= self.eb
                     else (t.queued if t.closing else 0))
            if n == 0:
                if t.closing:
                    t.closed = True
                continue
            if t.engine is None:
                eng = scan_analytics.StreamSummaryEngine(
                    edge_bucket=self.eb, vertex_bucket=t.vb,
                    k_bucket=t.kb)
                eng.load_state_dict(self.tenant_state_dict(t.tid))
                eng._lat_lane = t.tid
                eng._lat_admit = False
                t.engine = eng
            t.engine._lat_defer = self.defer_delivery
            with self._qlock:
                src, dst = t.src[:n], t.dst[:n]
            try:
                with telemetry.span("tenant.probation", tenant=t.tid,
                                    edges=int(n)) as sp:
                    summaries = t.engine.process(src, dst)
                metrics.attribute_dispatch(
                    sp.elapsed, [(t.tid, int(n))],
                    program=telemetry.pop_dispatch_tags()
                    .get("program"))
                if any(s["max_degree"] < 0 or s["num_components"] < 0
                       or s["num_components"] > t.vb + 1
                       or s["triangles"] < 0 for s in summaries):
                    raise PoisonOutput(
                        "probation window finalized implausible "
                        "analytics", [t.tid])
            except (KeyboardInterrupt, SystemExit):
                raise
            except faults.InjectedFault as e:
                if e.fatal:
                    raise
                self._probation_failed(t, e)
                continue
            except Exception as e:  # gslint: disable=except-hygiene (captured per probe: _probation_failed stamps the event and resets the ladder; the cohort keeps serving)
                self._probation_failed(t, e)
                continue
            # absorb the probe engine's state as the new last-good
            # carry (bit-exact: the engine layout IS the cohort's)
            est = t.engine.state_dict()
            self._break_residency(t)
            t.carry = tuple(jnp.asarray(a) for a in est["carry"])
            with self._qlock:
                t.src = t.src[n:]
                t.dst = t.dst[n:]
                t.bp_stamped = False
            t.windows_done = t.engine.windows_done
            t.closed_partial = t.engine._closed_partial
            if t.closing and t.queued == 0:
                t.closed = True
            t.probation += len(summaries)
            done += len(summaries)
            out.setdefault(t.tid, []).extend(summaries)
            metrics.mark_tenant(t.tid, len(summaries), int(n),
                                tier="quarantined")
            telemetry.event("quarantine_probe", tenant=t.tid,
                            clean=t.probation, required=qw)
            self._stage_ckpt(t, staged)
            if t.probation >= qw:
                t.tier = "cohort"
                t.engine = None
                t.quarantine_reason = None
                t.probation = 0
                telemetry.event("quarantine_released", durable=True,
                                tenant=t.tid,
                                windows_done=t.windows_done)
                metrics.counter_inc(
                    "gs_tenant_quarantine_releases_total")
                metrics.gauge_set("gs_tenant_quarantined", 0,
                                  tenant=t.tid)
        return done

    def _probation_failed(self, t: _Tenant, err) -> None:
        t.probation = 0
        t.engine = None  # re-seed from the last-good carry next probe
        telemetry.event("quarantine_probe_failed", durable=True,
                        tenant=t.tid,
                        error="%s: %s" % (type(err).__name__,
                                          str(err)[:200]))
        metrics.counter_inc("gs_tenant_probation_failures_total")

    def close(self, tenant_id) -> List[dict]:
        """Cut the tenant's final (possibly partial) window and retire
        it. Drains ONLY this tenant (pump(only=...)) — other tenants'
        queued windows stay queued for the next pump(), so a close()
        can never consume summaries its caller doesn't read."""
        t = self._tenant(tenant_id)
        if t.closed:
            return []
        # event-time hold flush: edges the GS_OOO_BOUND watermark
        # never passed release NOW (in ts order) — the final window
        # must not strand them
        early = self._ooo_flush(t) if t.ooo_ts.size else []
        t.closing = True
        if t.queued == 0 and t.tier == "cohort":
            t.closed = True
            return early
        out = self.pump(only=t.tid)
        return early + out.get(t.tid, [])

    # ------------------------------------------------------------------
    # quarantine (the bulkhead's suspended state)
    # ------------------------------------------------------------------
    def _quarantine(self, t: _Tenant, reason: str) -> None:
        """Suspend one poison stream: no cohort dispatches, feeds
        refused with typed TenantQuarantined, queued edges kept for
        the probation ladder (or the operator's DLQ triage). The
        durable `quarantine` event + demotion record are the
        post-mortem evidence; per-tenant /healthz + metrics rows ride
        the existing cardinality-bounded tenant labels."""
        if t.tier == "quarantined":
            return
        self._break_residency(t)  # its carry leaves the device stack
        from_tier = t.tier
        t.tier = "quarantined"
        t.engine = None
        t.probation = 0
        t.quarantine_reason = str(reason)[:200]
        telemetry.event("quarantine", durable=True, tenant=t.tid,
                        reason=t.quarantine_reason,
                        windows_done=t.windows_done)
        metrics.counter_inc("gs_tenant_quarantines_total")
        metrics.gauge_set("gs_tenant_quarantined", 1, tenant=t.tid)
        resilience.record_demotion(
            "tenant:%s" % t.tid, from_tier, "quarantined",
            t.windows_done, t.quarantine_reason, tenant=t.tid)

    def quarantine(self, tenant_id, reason: str = "operator") -> None:
        """Operator hook: suspend one tenant by hand (the same state a
        poisoned dispatch lands in)."""
        self._quarantine(self._tenant(tenant_id), reason)

    def quarantined(self) -> List[str]:
        """Currently quarantined tenant ids (the /healthz cell)."""
        return [tid for tid in self._tids()
                if self.tenants[tid].tier == "quarantined"]

    # ------------------------------------------------------------------
    # demotion (cohort → single-tenant engine)
    # ------------------------------------------------------------------
    def _demote(self, t: _Tenant, reason: str) -> None:
        if t.tier == "single":
            return
        self._break_residency(t)  # its carry leaves the device stack
        eng = scan_analytics.StreamSummaryEngine(
            edge_bucket=self.eb, vertex_bucket=t.vb, k_bucket=t.kb)
        eng.load_state_dict(self.tenant_state_dict(t.tid))
        # latency-plane lane continuity: the demoted engine records
        # its windows on THIS tenant's lane and must not re-stamp
        # admission (the cohort's feed() already did at the boundary)
        eng._lat_lane = t.tid
        eng._lat_admit = False
        t.engine = eng
        t.tier = "single"
        resilience.record_demotion(
            "tenant:%s" % t.tid, "cohort", "single",
            t.windows_done, reason, tenant=t.tid)

    def demote(self, tenant_id, reason: str = "operator") -> None:
        """Operator hook: pull one tenant off the cohort tier onto its
        own single-tenant engine (seeded from its live carry — exact).
        The cohort keeps dispatching everyone else."""
        self._demote(self._tenant(tenant_id), reason)

    # ------------------------------------------------------------------
    # checkpoints (per tenant; engine-interchangeable layout)
    # ------------------------------------------------------------------
    def tenant_state_dict(self, tenant_id) -> dict:
        """One tenant's resumable state in EXACTLY the summary
        engines' layout (ops/scan_analytics state_dict), so a cohort
        checkpoint restores into a single-tenant StreamSummaryEngine
        (the demotion ladder) and vice versa at equal buckets."""
        t = self._tenant(tenant_id)
        if t.tier == "single" or (t.tier == "quarantined"
                                  and t.engine is not None):
            state = t.engine.state_dict()
        else:
            # _carry_of gathers the tenant's slice out of the
            # resident stack when that tier holds it — the per-tenant
            # checkpoint gather at super-batch boundaries
            carry = self._carry_of(t)
            deg, labels, cover = (np.array(x) for x in carry)  # gslint: disable=host-sync (sanctioned checkpoint boundary: the tenant state_dict's one d2h)
            state = {
                "edge_bucket": self.eb,
                "vertex_bucket": t.vb,
                "windows_done": int(t.windows_done),
                "closed_partial": bool(t.closed_partial),
                # the journal offset at this finalized-window boundary
                # (cumulative edges folded into the carry): recover()
                # replays the WAL strictly past it — the
                # offset/checkpoint contract of DESIGN.md §18
                "wal_offset": int(t.windows_done) * self.eb,
                "carry": (deg, labels, cover),
            }
        if t.tier == "quarantined":
            # the bulkhead state rides the checkpoint (an engine
            # restoring this layout ignores the extra key): a killed
            # cohort must come back still-quarantined with its
            # probation progress, never silently re-admitting a
            # poison stream
            state["quarantine"] = {
                "probation": int(t.probation),
                "reason": t.quarantine_reason or "",
            }
        return state

    def load_tenant_state_dict(self, tenant_id, state: dict) -> None:
        t = self._tenant(tenant_id)
        if state["edge_bucket"] != self.eb \
                or state["vertex_bucket"] != t.vb:
            raise ValueError(
                "bucket mismatch: checkpoint was taken at eb=%d vb=%d, "
                "tenant %r runs eb=%d vb=%d" % (
                    state["edge_bucket"], state["vertex_bucket"],
                    t.tid, self.eb, t.vb))
        t.windows_done = int(state["windows_done"])  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
        t.closed_partial = bool(state["closed_partial"])
        woff = state.get("wal_offset")
        if woff is not None and int(woff) > t.windows_done * self.eb:
            # a journal offset AHEAD of the window cursor would make
            # recover() skip edges never folded — refuse loudly
            raise ValueError(
                "checkpoint wal_offset %d exceeds its own window "
                "coverage (%d windows x eb=%d)" % (
                    int(woff), t.windows_done, self.eb))
        # the checkpoint is authoritative: a tenant restored while
        # the resident stack holds its carry leaves the stack (and
        # the stack restacks without it next dispatch)
        self._break_residency(t)
        t.carry = tuple(jnp.asarray(a) for a in state["carry"])
        q = state.get("quarantine")
        if q is not None:
            t.tier = "quarantined"
            t.engine = None  # probes re-seed from the restored carry
            t.probation = int(q.get("probation", 0))  # gslint: disable=host-sync (checkpoint payloads are host scalars, never device values)
            t.quarantine_reason = q.get("reason") or "restored"
            metrics.gauge_set("gs_tenant_quarantined", 1,
                              tenant=t.tid)
        elif t.tier == "quarantined":
            # the checkpoint is authoritative: restoring a generation
            # taken before the quarantine rewinds the bulkhead too
            t.tier = "cohort"
            t.engine = None
            t.probation = 0
            t.quarantine_reason = None
            metrics.gauge_set("gs_tenant_quarantined", 0,
                              tenant=t.tid)
        if t.tier == "single":
            t.engine.load_state_dict(state)

    def state_dict(self) -> dict:
        """The whole cohort (cohort→cohort resume): per-tenant states
        in the shared layout under their ids."""
        return {
            "edge_bucket": self.eb,
            "tenants": {tid: self.tenant_state_dict(tid)
                        for tid in self._tids()},
        }

    def load_state_dict(self, state: dict) -> None:
        if state["edge_bucket"] != self.eb:
            raise ValueError(
                "bucket mismatch: cohort checkpoint was taken at "
                "eb=%d, this cohort runs eb=%d"
                % (state["edge_bucket"], self.eb))
        for tid, tstate in state["tenants"].items():
            if tid not in self.tenants:
                self.admit(tid,
                           vertex_bucket=tstate["vertex_bucket"])  # gslint: disable=ckpt-symmetry (read from the PER-TENANT sub-state, which tenant_state_dict always writes — the cohort's own top level carries only edge_bucket + tenants)
            self.load_tenant_state_dict(tid, tstate)

    def enable_auto_checkpoint(self, directory: str,
                               every_n_windows: int = 16,
                               every_seconds: float = 0.0) -> None:
        """Per-tenant auto-snapshots (`tenant_<id>.npz` under
        `directory`, atomic + last-2 rotation — utils/checkpoint) on a
        per-tenant CheckpointPolicy cadence, staged at dispatch
        boundaries and flushed at pump()'s clean return (the delivery
        boundary). A killed cohort resumes each tenant independently:
        resume_all() / try_resume(tenant)."""
        if every_n_windows <= 0 and every_seconds <= 0:
            raise ValueError("checkpoint policy has no trigger enabled")
        os.makedirs(directory, exist_ok=True)
        self._ckpt_dir = directory
        self._ckpt_every_n = max(0, every_n_windows)
        self._ckpt_every_s = max(0.0, every_seconds)
        for t in self.tenants.values():
            if t.ckpt_policy is None:
                t.ckpt_policy = checkpoint.CheckpointPolicy(
                    every_n_windows=self._ckpt_every_n,
                    every_seconds=self._ckpt_every_s)
                t.ckpt_policy.mark(t.windows_done)

    def _ckpt_path(self, tid: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in tid)
        return os.path.join(self._ckpt_dir, "tenant_%s.npz" % safe)

    def _stage_ckpt(self, t: _Tenant, staged: list) -> None:
        if self._ckpt_dir is None or t.ckpt_policy is None:
            return
        if t.ckpt_policy.due(t.windows_done):
            t.ckpt_policy.mark(t.windows_done)
            staged.append((t, self.tenant_state_dict(t.tid)))

    # ------------------------------------------------------------------
    # write-ahead journal (utils/wal.py): durable live ingest
    # ------------------------------------------------------------------
    def enable_wal(self, directory: str) -> bool:
        """Journal every accepted feed() batch under `directory`
        before it enters the tenant's queue, so a kill at ANY point
        loses nothing the caller was told was accepted: recover()
        replays the un-checkpointed suffix bit-exactly. Returns False
        (a no-op) under the GS_WAL=0 kill switch."""
        if not wal_mod.enabled():
            return False
        self._wal_dir = directory
        self._wal = wal_mod.WriteAheadLog(directory)
        return True

    def seal_wal(self) -> None:
        """Durably close the journal (the graceful-drain marker). The
        caller drains the queues and flushes checkpoints first —
        core/serve.StreamServer.drain() owns that ordering."""
        if self._wal is not None:
            self._wal.seal()

    def recover(self) -> dict:
        """Crash recovery over an armed journal: discover journaled
        tenants (admitting any unknown), resume each from its newest
        checkpoint generation, then replay each tenant's journal
        suffix past its checkpointed `wal_offset` straight into the
        queues (bypassing admission capacity — these edges were
        already accepted once). The next pump() then produces windows
        bit-identical to a never-killed run."""
        if self._wal_dir is None:
            raise ValueError("enable_wal() first: recover() replays "
                             "the journal the crashed process wrote")
        info = wal_mod.scan(self._wal_dir)
        for tid in sorted(info["offsets"]):
            if tid not in self.tenants:
                self.admit(tid)
        resumed = self.resume_all()
        offsets = {tid: self.resume_offset(tid)
                   for tid in self.tenants}
        replayed: Dict[str, int] = {}
        for tid, _start, src, dst, ts in wal_mod.replay(
                self._wal_dir, offsets):
            t = self.tenants.get(tid)
            if t is None or t.closed:
                continue
            with self._qlock:
                t.src = np.concatenate([t.src, src])
                t.dst = np.concatenate([t.dst, dst])
            # re-seed the latency plane's admission marks with the
            # journaled ORIGINAL stamps: the replayed windows report
            # their honest, larger latency, never reset-to-zero
            latency.on_replay(tid, len(src), ts)
            replayed[tid] = replayed.get(tid, 0) + len(src)
        telemetry.event("wal_replayed", durable=True,
                        component="cohort", dir=self._wal_dir,
                        tenants=len(replayed),
                        edges=sum(replayed.values()),
                        sealed=info["sealed"])
        metrics.counter_inc("gs_wal_replayed_edges_total",
                            sum(replayed.values()))
        return {"resumed": resumed, "replayed_edges": replayed,
                "sealed": info["sealed"]}

    def checkpoint_all(self) -> int:
        """Force-flush a checkpoint for every live tenant NOW,
        regardless of cadence — the graceful-drain boundary
        (core/serve.StreamServer.drain: queues are already dry, so
        each snapshot covers the tenant's whole delivered stream).
        No-op without enable_auto_checkpoint. Returns tenants saved."""
        if self._ckpt_dir is None:
            return 0
        saved = 0
        for tid in self._tids():
            t = self.tenants[tid]
            checkpoint.save(self._ckpt_path(tid),
                            self.tenant_state_dict(tid))
            if t.ckpt_policy is not None:
                t.ckpt_policy.mark(t.windows_done)
            saved += 1
        # journal retention at the flush boundary (GS_WAL_RETAIN):
        # every tenant's floor moves in ONE truncate_covered call —
        # a per-tenant call would see the other tenants' records as
        # uncovered and never delete a shared segment
        self._wal_retention.flushed_many(
            self._wal, {tid: self.tenants[tid].windows_done * self.eb
                        for tid in self.tenants})
        return saved

    def try_resume(self, tenant_id) -> bool:
        """Restore one tenant from its newest intact checkpoint
        generation (rotation fallback — utils/checkpoint.load_latest);
        False when nothing usable exists. After a True return, feed
        the tenant from `resume_offset(tenant)` edges in."""
        import warnings

        t = self._tenant(tenant_id)
        if self._ckpt_dir is None:
            return False
        try:
            got = checkpoint.load_latest(self._ckpt_path(t.tid))
        except checkpoint.CheckpointCorrupt as e:
            warnings.warn(f"{e}; no intact generation — tenant "
                          f"{t.tid!r} starts fresh")
            return False
        if got is None:
            return False
        state, used = got
        self.load_tenant_state_dict(t.tid, state)
        if t.ckpt_policy is not None:
            t.ckpt_policy.mark(t.windows_done)
        telemetry.event("resume", durable=True, component="tenant",
                        tenant=t.tid, path=used,
                        windows_done=t.windows_done)
        return True

    def resume_all(self) -> Dict[str, bool]:
        """try_resume every admitted tenant; {tenant: resumed}."""
        return {tid: self.try_resume(tid)
                for tid in self._tids()}

    def resume_offset(self, tenant_id) -> int:
        """Edges already folded into the tenant's carried state (the
        windows_done cursor — windows are count-based eb-sized)."""
        return self._tenant(tenant_id).windows_done * self.eb

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def tenant_tier(self, tenant_id) -> str:
        return self._tenant(tenant_id).tier

    def queued_edges(self, tenant_id) -> int:
        return self._tenant(tenant_id).queued

    def windows_done(self, tenant_id) -> int:
        return self._tenant(tenant_id).windows_done


# ----------------------------------------------------------------------
# the GNN cohort (ops/gnn_window on the tenant axis)
# ----------------------------------------------------------------------
class GnnTenantCohort:
    """N tenants' windowed GNN rounds on ONE vmapped dispatch — the
    multi-tenant serving shape for the MXU workload (fresh per-window
    embeddings pushed to N tenants' subscriptions): each tenant owns a
    `[vb+1, F]` feature slab; pump rounds stack the ready tenants'
    full windows into `[N, W, eb]` slabs and fold them through
    ops/gnn_window.build_gnn_cohort_scan under one `gnn_cohort`
    program per power-of-two (tenants, windows) bucket. The cohort
    shares ONE snapped weight layer (the production recommendation
    shape — one model, many streams); per-tenant results are
    bit-identical to N separate GnnSummaryEngine runs by the lattice
    argument plus the cohort scan's live-window mask (tools/gnn_ab.py
    asserts it), and `tenant_state_dict()` is interchangeable with the
    GNN engines' load_state_dict at equal buckets and feature width —
    the cohort→single and cohort→host demotion ladders are layout
    conversions, not translations.

    Deliberately a focused scheduler next to TenantCohort: admission
    cap and typed rejects, per-tenant queues and window accounting,
    the bucketed program cache — without the analytics cohort's
    residency/quarantine/autotune machinery, which is specialized to
    the 3-slab analytics carry. Those rungs graduate here the same way
    they did there: behind committed A/B rows."""

    def __init__(self, edge_bucket: int, vertex_bucket: int,
                 feature_dim: int = None, activation: str = None):
        from ..ops import gnn_window as gnn_ops
        from ..ops import pallas_window

        self._gnn = gnn_ops
        self._pallas_window = pallas_window
        self.eb = seg_ops.bucket_size(edge_bucket)
        self.vb = seg_ops.bucket_size(vertex_bucket)
        self.F = int(feature_dim if feature_dim
                     else knobs.get_int("GS_GNN_F"))
        self.act = str(activation if activation
                       else (knobs.get_str("GS_GNN_ACT") or "relu"))
        self._w_units, self._b_units = gnn_ops.snap_weights(
            *gnn_ops.default_weights(self.F), self.F)
        self._wdev = None  # refreshed lazily after set_weights
        self._bdev = None
        self._tenants: Dict[str, dict] = {}
        self._order: List[str] = []
        self._programs: Dict[tuple, object] = {}
        self._lock = threading.RLock()

    # -- membership ----------------------------------------------------
    def admit(self, tenant_id, features=None,
              feature_units=None) -> None:
        tid = str(tenant_id)
        with self._lock:
            if tid in self._tenants:
                raise TenantRejected("tenant %r already admitted"
                                     % tid, tid)
            if len(self._tenants) >= max_tenants():
                raise TenantRejected(
                    "cohort full: GS_TENANT_MAX=%d tenants admitted"
                    % max_tenants(), tid)
            if feature_units is not None:
                slab = np.asarray(feature_units, np.float32)  # gslint: disable=host-sync (host-input normalization: admit payloads are numpy)
                if slab.shape != (self.vb + 1, self.F):
                    raise ValueError(
                        "unit slab must be [vb+1=%d, F=%d]; got %s"
                        % (self.vb + 1, self.F, slab.shape))
            elif features is not None:
                slab = self._gnn.snap_features(features, self.vb,
                                               self.F)
            else:
                slab = np.zeros((self.vb + 1, self.F), np.float32)
            self._tenants[tid] = {
                "carry": jnp.asarray(slab),
                "src": [], "dst": [], "queued": 0,
                "windows_done": 0,
            }
            self._order.append(tid)
        telemetry.event("tenant_admitted", tenant=tid,
                        workload="gnn")

    def _tenant(self, tenant_id) -> dict:
        t = self._tenants.get(str(tenant_id))
        if t is None:
            raise TenantError("unknown tenant %r" % tenant_id,
                              str(tenant_id))
        return t

    # -- weights -------------------------------------------------------
    def set_weights(self, W, b=None) -> None:
        """Adopt the cohort's shared dense layer, snapped onto the
        lattice (ops/gnn_window.snap_weights — the bit-exactness
        contract). Never recompiles: weights ride every dispatch as
        broadcast arguments."""
        if b is None:
            b = np.zeros(self.F, np.float32)
        with self._lock:
            self._w_units, self._b_units = self._gnn.snap_weights(
                W, b, self.F)
            self._wdev = None

    def weights(self):
        return self._w_units.copy(), self._b_units.copy()

    # -- ingest --------------------------------------------------------
    def feed(self, tenant_id, src, dst) -> int:
        src = np.asarray(src, np.int32)  # gslint: disable=host-sync (host-input normalization: feed payloads are numpy)
        dst = np.asarray(dst, np.int32)  # gslint: disable=host-sync (host-input normalization: feed payloads are numpy)
        with self._lock:
            t = self._tenant(tenant_id)
            t["src"].append(src)
            t["dst"].append(dst)
            t["queued"] += len(src)
            return t["queued"]

    def queued_edges(self, tenant_id) -> int:
        return self._tenant(tenant_id)["queued"]

    def windows_done(self, tenant_id) -> int:
        return self._tenant(tenant_id)["windows_done"]

    # -- the dispatch --------------------------------------------------
    def _program(self, nb: int, wb: int):
        """One jitted cohort program per power-of-two (tenants,
        windows) bucket — ragged cohorts reuse O(log N × log W)
        programs. Wrapped by the compile watch / cost observatory as
        `gnn_cohort`; the analytic slab model registers at the same
        label (armed only) so ledger spans join a stated cost."""
        key = (nb, wb)
        fn = self._programs.get(key)
        if fn is None:
            import jax

            run = self._gnn.build_gnn_cohort_scan(
                self.eb, self.vb, self.F, self.act)
            fn = self._programs[key] = metrics.wrap_jit(
                "gnn_cohort", jax.jit(run))
            self._pallas_window.register_gnn_cost_model(
                self.eb, self.vb, self.F, nb=nb)
        return fn

    def _take_windows(self, t: dict, drain: bool):
        """Cut the tenant's queue at the window boundary: every FULL
        window now, the sub-window remainder only when draining
        (close) — the same cut GnnSummaryEngine.process makes, so the
        per-tenant window sequence matches the single-engine run
        edge-for-edge."""
        if not t["queued"]:
            return None
        src = np.concatenate(t["src"]) if len(t["src"]) != 1 \
            else t["src"][0]
        dst = np.concatenate(t["dst"]) if len(t["dst"]) != 1 \
            else t["dst"][0]
        take = len(src) if drain else (len(src) // self.eb) * self.eb
        if not take:
            return None
        t["src"] = [src[take:]] if take < len(src) else []
        t["dst"] = [dst[take:]] if take < len(src) else []
        t["queued"] = len(src) - take
        return seg_ops.window_stack(src[:take], dst[:take], self.eb,
                                    sentinel=self.vb)

    def _dispatch(self, batch: List[str], taken: dict,
                  out: Dict[str, list]) -> None:
        import jax

        nb = seg_ops.bucket_size(len(batch))
        wb = seg_ops.bucket_size(max(t[0] for t in taken.values()))
        src = np.full((nb, wb, self.eb), self.vb, np.int32)
        dst = np.full((nb, wb, self.eb), self.vb, np.int32)
        valid = np.zeros((nb, wb, self.eb), bool)
        carries = []
        for i, tid in enumerate(batch):
            num_w, s, d, v = taken[tid]
            src[i, :num_w] = s
            dst[i, :num_w] = d
            valid[i, :num_w] = v
            carries.append(self._tenants[tid]["carry"])
        zero = jnp.zeros((self.vb + 1, self.F), jnp.float32)
        carries.extend([zero] * (nb - len(batch)))
        if self._wdev is None:
            self._wdev = jnp.asarray(self._w_units)
            self._bdev = jnp.asarray(self._b_units)
        # padded rows/windows are all-invalid and therefore inert
        # (the round's empty-window-holds rule); their summary rows
        # are dropped below
        with telemetry.span("cohort.dispatch", tenants=len(batch),
                            windows=sum(t[0] for t in taken.values())
                            ) as sp:
            hs, ys = self._program(nb, wb)(
                jnp.stack(carries), self._wdev, self._bdev,
                jnp.asarray(src), jnp.asarray(dst),
                jnp.asarray(valid))
            maxf, active, csum, nmsg = (np.array(y) for y in ys)  # gslint: disable=host-sync (sanctioned finalize boundary: the cohort's ONE batched d2h per pump round)
        tags = telemetry.pop_dispatch_tags()
        metrics.attribute_dispatch(
            sp.elapsed,
            [(tid, int(np.sum(taken[tid][3]))) for tid in batch],  # gslint: disable=host-sync (numpy-on-numpy: the host-built validity stack)
            program=tags.get("program"), sig=tags.get("sig"))
        for i, tid in enumerate(batch):
            t = self._tenants[tid]
            t["carry"] = hs[i]
            num_w = taken[tid][0]
            rows = out.setdefault(tid, [])
            for w in range(num_w):
                rows.append({
                    "max_feat": int(maxf[i, w]),  # gslint: disable=host-sync (numpy-on-numpy after the batched d2h)
                    "active_vertices": int(active[i, w]),  # gslint: disable=host-sync (numpy-on-numpy after the batched d2h)
                    "feat_checksum": int(csum[i, w]),  # gslint: disable=host-sync (numpy-on-numpy after the batched d2h)
                    "msg_edges": int(nmsg[i, w]),  # gslint: disable=host-sync (numpy-on-numpy after the batched d2h)
                })
            edges = int(np.sum(taken[tid][3]))  # gslint: disable=host-sync (numpy-on-numpy: the host-built validity stack)
            if provenance.armed():
                vrows = taken[tid][3]
                for w in range(num_w):
                    lo = (t["windows_done"] + w) * self.eb
                    provenance.emit(
                        tenant=tid,
                        window=t["windows_done"] + w,
                        wal_lo=lo,
                        wal_hi=lo + int(np.sum(vrows[w])),  # gslint: disable=host-sync (numpy-on-numpy: the host-built validity stack)
                        tier="gnn_cohort", program="gnn_round",
                        summary=rows[len(rows) - num_w + w])
            t["windows_done"] += num_w
            metrics.mark_window(num_w, edges, engine="GnnTenantCohort",
                                tier="gnn_cohort", tenant=tid)

    def pump(self) -> Dict[str, list]:
        """Fold every tenant's FULL queued windows in one vmapped
        dispatch; returns {tenant_id: [summary, ...]} for the windows
        folded this round. Sub-window remainders stay queued for the
        next feed or close — window cuts are the engine's."""
        with self._lock:
            taken = {}
            batch = []
            for tid in self._order:
                got = self._take_windows(self._tenants[tid],
                                         drain=False)
                if got is not None:
                    taken[tid] = got
                    batch.append(tid)
            out: Dict[str, list] = {}
            if batch:
                self._dispatch(batch, taken, out)
            return out

    def close(self, tenant_id) -> List[dict]:
        """Drain the tenant's remainder (its final padded window, if
        any), remove it from the cohort, return the last summaries.
        The carry is gone afterwards — checkpoint first
        (tenant_state_dict) to keep the slab."""
        tid = str(tenant_id)
        with self._lock:
            t = self._tenant(tid)
            out: Dict[str, list] = {}
            got = self._take_windows(t, drain=True)
            if got is not None:
                self._dispatch([tid], {tid: got}, out)
            del self._tenants[tid]
            self._order.remove(tid)
            return out.get(tid, [])

    # -- checkpoint / demotion ladder ----------------------------------
    def tenant_state_dict(self, tenant_id) -> dict:
        """One tenant's slice in the GNN ENGINE checkpoint layout —
        loadable by GnnSummaryEngine/GnnHostEngine.load_state_dict at
        equal buckets and feature width (the demotion ladder)."""
        with self._lock:
            t = self._tenant(tenant_id)
            h = np.array(t["carry"])  # gslint: disable=host-sync (sanctioned checkpoint boundary: tenant_state_dict's one d2h)
            return {
                "edge_bucket": self.eb,
                "vertex_bucket": self.vb,
                "windows_done": int(t["windows_done"]),  # gslint: disable=host-sync (cohort bookkeeping int, no device value in sight)
                "closed_partial": False,
                "wal_offset": int(t["windows_done"]) * self.eb,  # gslint: disable=host-sync (cohort bookkeeping int, no device value in sight)
                "carry": (h,),
                "gnn": {
                    "feat_dim": self.F,
                    "act": self.act,
                    "weights": self._w_units.copy(),
                    "bias": self._b_units.copy(),
                },
            }

    def load_tenant_state_dict(self, tenant_id, state: dict) -> None:
        """Adopt an engine checkpoint as a tenant's slab (the
        promotion direction of the same ladder)."""
        g = state.get("gnn") or {}
        if (int(state["edge_bucket"]) != self.eb  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
                or int(state["vertex_bucket"]) != self.vb  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
                or int(g.get("feat_dim", self.F)) != self.F):  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
            raise ValueError(
                "checkpoint shape (eb=%s, vb=%s, F=%s) does not match "
                "cohort (eb=%d, vb=%d, F=%d)"
                % (state.get("edge_bucket"), state.get("vertex_bucket"),
                   g.get("feat_dim"), self.eb, self.vb, self.F))
        with self._lock:
            t = self._tenant(tenant_id)
            (h,) = state["carry"]
            t["carry"] = jnp.asarray(np.asarray(h, np.float32))  # gslint: disable=host-sync (host-input normalization: checkpoint payloads are numpy)
            t["windows_done"] = int(state.get("windows_done", 0))  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)

    def demote(self, tenant_id):
        """Pop the tenant out of the cohort onto its own
        GnnSummaryEngine, seeded from its live slab — per-tenant
        isolation without touching the cohort's other streams.

        Returns ``(engine, folded, (src, dst))``: FULL queued windows
        are folded through the engine during the hand-off and their
        summaries returned (never dropped); the sub-window remainder
        comes back UNFOLDED — prepend it to the continued stream so
        window cuts stay edge-for-edge with the no-demotion timeline
        (the engine's process() would CLOSE a partial trailing
        window, which only a stream's end may do)."""
        tid = str(tenant_id)
        with self._lock:
            t = self._tenant(tid)
            state = self.tenant_state_dict(tid)
            pend_s = (np.concatenate(t["src"]) if t["src"]
                      else np.empty(0, np.int32))
            pend_d = (np.concatenate(t["dst"]) if t["dst"]
                      else np.empty(0, np.int32))
            del self._tenants[tid]
            self._order.remove(tid)
        eng = self._gnn.GnnSummaryEngine(
            self.eb, self.vb, feature_dim=self.F,
            activation=self.act)
        eng.load_state_dict(state)
        resilience.record_demotion(
            "tenant:%s" % tid, "gnn_cohort", "gnn_scan",
            int(state["windows_done"]), "operator", tenant=tid)  # gslint: disable=host-sync (checkpoint payloads are host numpy, never device values)
        full = (len(pend_s) // self.eb) * self.eb
        folded = eng.process(pend_s[:full], pend_d[:full]) \
            if full else []
        return eng, folded, (pend_s[full:], pend_d[full:])

    def tenants(self) -> List[str]:
        return list(self._order)

    def state(self, tenant_id) -> np.ndarray:
        """[vb, F] feature snapshot in lattice units."""
        with self._lock:
            t = self._tenant(tenant_id)
            return np.asarray(t["carry"])[: self.vb].copy()  # gslint: disable=host-sync (sanctioned snapshot boundary: the cohort's state() d2h)

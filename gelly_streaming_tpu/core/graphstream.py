"""Core graph-stream API: GraphStream / SimpleEdgeStream / GraphWindowStream.

TPU-native re-design of the reference's L2 layer
(GraphStream.java:38-141, SimpleEdgeStream.java:59-539,
GraphWindowStream.java:47-182). A graph stream never materializes the
graph — only distributed summaries held in operator state (reference
README "A Graph Streaming Model"); windows become columnar COO batches
executed as XLA programs (ops/neighborhood.py).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..ops import neighborhood
from .datastream import DataStream
from .gtime import AscendingTimestampExtractor, Time, TimeCharacteristic
from .plan import OpNode
from .types import NULL, Edge, EdgeDirection, Vertex


class GraphStream:
    """Abstract contract of any graph stream (reference: GraphStream.java:43-140)."""

    def get_context(self):
        raise NotImplementedError

    def get_vertices(self) -> DataStream:
        raise NotImplementedError

    def get_edges(self) -> DataStream:
        raise NotImplementedError

    def map_edges(self, fn) -> "GraphStream":
        raise NotImplementedError

    def filter_vertices(self, fn) -> "GraphStream":
        raise NotImplementedError

    def filter_edges(self, fn) -> "GraphStream":
        raise NotImplementedError

    def distinct(self) -> "GraphStream":
        raise NotImplementedError

    def get_degrees(self) -> DataStream:
        raise NotImplementedError

    def get_in_degrees(self) -> DataStream:
        raise NotImplementedError

    def get_out_degrees(self) -> DataStream:
        raise NotImplementedError

    def number_of_edges(self) -> DataStream:
        raise NotImplementedError

    def number_of_vertices(self) -> DataStream:
        raise NotImplementedError

    def undirected(self) -> "GraphStream":
        raise NotImplementedError

    def reverse(self) -> "GraphStream":
        raise NotImplementedError

    def aggregate(self, graph_aggregation) -> DataStream:
        raise NotImplementedError


class SimpleEdgeStream(GraphStream):
    """The one concrete graph stream: wraps a DataStream of Edge records
    (reference: SimpleEdgeStream.java:59-94; ingestion-time ctor at :73-77,
    event-time ctor with ascending-timestamp extractor at :90-94)."""

    def __init__(self, edges: DataStream, env=None,
                 timestamp_extractor: Optional[AscendingTimestampExtractor] = None):
        self.env = env if env is not None else edges.env
        if timestamp_extractor is not None:
            self.env.set_stream_time_characteristic(TimeCharacteristic.EVENT_TIME)
            node = OpNode("assign_timestamps", [edges.node],
                          extractor=timestamp_extractor)
            edges = DataStream(self.env, node)
        self.edges = edges

    def get_context(self):
        return self.env

    def get_edges(self) -> DataStream:
        return self.edges

    def get_vertices(self) -> DataStream:
        """Distinct vertex stream: emit both endpoints, keep first occurrence
        (reference: EmitSrcAndTarget + FilterDistinctVertices,
        SimpleEdgeStream.java:120-125,185-206)."""

        def emit_src_and_target(edge, collect):
            collect(Vertex(edge.source, NULL))
            collect(Vertex(edge.target, NULL))

        seen = set()

        def filter_distinct(vertex):
            if vertex.id in seen:
                return False
            seen.add(vertex.id)
            return True

        return (self.edges.flat_map(emit_src_and_target)
                .key_by(0).filter(filter_distinct))

    # ------------------------------------------------------------------
    # transformations
    # ------------------------------------------------------------------
    def map_edges(self, fn: Callable[[Edge], Any]) -> "SimpleEdgeStream":
        """Map each edge's value (reference: SimpleEdgeStream.java:221-251)."""
        return SimpleEdgeStream(
            self.edges.map(lambda e: Edge(e.source, e.target, fn(e))), self.env
        )

    def filter_edges(self, fn: Callable[[Edge], bool]) -> "SimpleEdgeStream":
        return SimpleEdgeStream(self.edges.filter(fn), self.env)

    def filter_vertices(self, fn: Callable[[Vertex], bool]) -> "SimpleEdgeStream":
        """Keep an edge iff the filter accepts both endpoints
        (reference: ApplyVertexFilterToEdges, SimpleEdgeStream.java:268-285)."""
        return SimpleEdgeStream(
            self.edges.filter(
                lambda e: fn(Vertex(e.source, NULL)) and fn(Vertex(e.target, NULL))
            ),
            self.env,
        )

    def distinct(self) -> "SimpleEdgeStream":
        """Per-source neighbor dedup, first occurrence wins
        (reference: DistinctEdgeMapper, SimpleEdgeStream.java:305-327)."""
        seen: dict = {}

        def dedup(edge, collect):
            nbrs = seen.setdefault(edge.source, set())
            if edge.target not in nbrs:
                nbrs.add(edge.target)
                collect(edge)

        return SimpleEdgeStream(
            self.edges.key_by(0).flat_map(dedup), self.env
        )

    def reverse(self) -> "SimpleEdgeStream":
        return SimpleEdgeStream(self.edges.map(lambda e: e.reverse()), self.env)

    def undirected(self) -> "SimpleEdgeStream":
        """Emit each edge and its reverse (reference: SimpleEdgeStream.java:347-365)."""

        def both(edge, collect):
            collect(edge)
            collect(edge.reverse())

        return SimpleEdgeStream(self.edges.flat_map(both), self.env)

    def union(self, other: "SimpleEdgeStream") -> "SimpleEdgeStream":
        return SimpleEdgeStream(self.edges.union(other.edges), self.env)

    # ------------------------------------------------------------------
    # continuous properties
    # ------------------------------------------------------------------
    def _degree_stream(self, collect_in: bool, collect_out: bool) -> DataStream:
        """Continuous degree stream: one improving update per contributing
        edge (reference: DegreeTypeSeparator + DegreeMapFunction,
        SimpleEdgeStream.java:444-482)."""

        def separator(edge, collect):
            if collect_out:
                collect(Vertex(edge.source, 1))
            if collect_in:
                collect(Vertex(edge.target, 1))

        counts: dict = {}

        def running_count(vertex):
            counts[vertex.id] = counts.get(vertex.id, 0) + vertex.value
            return Vertex(vertex.id, counts[vertex.id])

        return self.aggregate(separator, running_count)

    def get_degrees(self) -> DataStream:
        return self._degree_stream(True, True)

    def get_in_degrees(self) -> DataStream:
        return self._degree_stream(True, False)

    def get_out_degrees(self) -> DataStream:
        return self._degree_stream(False, True)

    def number_of_vertices(self) -> DataStream:
        """Continuously improving distinct-vertex count
        (reference: SimpleEdgeStream.java:370-387)."""
        seen: set = set()

        def vertex_count(vertex, collect):
            seen.add(vertex.id)
            collect(len(seen))

        def separator(edge, collect):
            collect(Vertex(edge.source, 1))
            collect(Vertex(edge.target, 1))

        return self.global_aggregate(separator, vertex_count, True)

    def number_of_edges(self) -> DataStream:
        """Running total edge count, duplicates included
        (reference: TotalEdgeCountMapper, SimpleEdgeStream.java:392-408)."""
        state = {"n": 0}

        def count(_edge):
            state["n"] += 1
            return state["n"]

        return self.edges.map(count).set_parallelism(1)

    def global_aggregate(self, edge_mapper, vertex_mapper,
                         collect_updates: bool) -> DataStream:
        """Parallelism-1 global aggregate pipeline; optionally emit only on
        value change (reference: SimpleEdgeStream.java:509-539)."""
        result = (self.edges.flat_map(edge_mapper).set_parallelism(1)
                  .flat_map(vertex_mapper).set_parallelism(1))
        if collect_updates:
            prev = {"v": object()}

            def on_change(value, collect):
                if value != prev["v"]:
                    prev["v"] = value
                    collect(value)

            result = result.flat_map(on_change).set_parallelism(1)
        return result

    # ------------------------------------------------------------------
    # aggregation & discretization
    # ------------------------------------------------------------------
    def aggregate(self, graph_aggregation_or_edge_mapper,
                  vertex_mapper=None) -> DataStream:
        """Two overloads, matching the reference's:

        aggregate(graph_aggregation) — run a summary aggregation
        (reference: SimpleEdgeStream.java:104-106).

        aggregate(edge_mapper, vertex_mapper) — the generic continuous
        aggregate: flat-map each edge into keyed vertex records, then
        apply a stateful per-key map emitting one improving update per
        input (reference: SimpleEdgeStream.java:493-498; basis of the
        degree streams, :417-498).
        """
        if vertex_mapper is None:
            return graph_aggregation_or_edge_mapper.run(self.get_edges())
        return (self.edges.flat_map(graph_aggregation_or_edge_mapper)
                .key_by(0).map(vertex_mapper))

    def slice(self, size: Time,
              direction: EdgeDirection = EdgeDirection.OUT,
              slide: "Time" = None) -> "GraphWindowStream":
        """Discretize into tumbling windows keyed so a vertex's whole
        neighborhood lands in one partition
        (reference: SimpleEdgeStream.java:139-171: IN → reverse() then key
        by source; OUT → key by source; ALL → undirected() doubling then
        key by source).

        Pass `slide` for SLIDING windows — each edge then contributes to
        every window whose [start, start+size) span covers its
        timestamp. The substrate (Flink timeWindow(size, slide))
        supports this; the reference's examples only use tumbling."""
        if direction == EdgeDirection.IN:
            stream = self.reverse()
        elif direction == EdgeDirection.OUT:
            stream = self
        elif direction == EdgeDirection.ALL:
            stream = self.undirected()
        else:
            raise ValueError("Illegal edge direction")
        return GraphWindowStream(self.env, stream.get_edges(), size, slide)


class GraphWindowStream:
    """A stream of discrete graphs: tumbling windows over keyed edges
    (reference: GraphWindowStream.java:47-53).

    Neighborhood ops execute per window as one device program over the
    window's COO batch — fold/reduce are incremental segment kernels,
    apply materializes padded neighborhoods (SURVEY.md §3.2).
    """

    def __init__(self, env, keyed_edges: DataStream, size: Time,
                 slide: Time = None):
        self.env = env
        self.edges = keyed_edges
        self.size = size
        self.slide = slide

    def _window_node(self, kernel) -> DataStream:
        node = OpNode("window_batch", [self.edges.node],
                      size_ms=self.size.milliseconds,
                      slide_ms=(self.slide.milliseconds
                                if self.slide is not None else None),
                      kernel=kernel)
        return DataStream(self.env, node)

    def fold_neighbors(self, initial_or_fold, fold_udf=None) -> DataStream:
        """Per-(vertex, window) incremental fold
        (reference: GraphWindowStream.java:62-87).

        Call as fold_neighbors(initial, EdgesFold) for the host path or
        fold_neighbors(JaxEdgesFold) for the device path.
        """
        spec = initial_or_fold if fold_udf is None else (initial_or_fold, fold_udf)
        return self._window_node(neighborhood.make_fold_kernel(spec))

    def reduce_on_edges(self, reduce_udf) -> DataStream:
        """Per-(vertex, window) reduce of edge values, projected to
        (vertexId, value) (reference: GraphWindowStream.java:101-121)."""
        return self._window_node(neighborhood.make_reduce_kernel(reduce_udf))

    def apply_on_neighbors(self, apply_udf) -> DataStream:
        """Buffered whole-neighborhood apply, 0..n outputs per vertex
        (reference: GraphWindowStream.java:130-182)."""
        return self._window_node(neighborhood.make_apply_kernel(apply_udf))

"""Host-side executor for the lazy operator DAG.

Executes the plan built by the DataStream layer: pushes timestamped
records through operators, groups tumbling windows, runs fixpoint
iteration, and hands columnar window batches to device kernels
("window_batch" nodes — the TPU hot path).

Semantics notes (parity with the reference's runtime behavior):
- Finite sources → every window fires at end-of-stream, in ascending
  window-end order; records within a (key, window) keep arrival order.
  This matches the reference tests, which pin parallelism=1 "to ensure
  total ordering for windows" (ConnectedComponentsTest.java:62).
- Window results carry timestamp = window.maxTimestamp() = end - 1
  (Flink TimeWindow semantics; WindowTriangles.java:137).
- `iterate`/`close_with` runs the loop body to quiescence — the
  finite-stream fixpoint of the reference's feedback queue
  (IterativeConnectedComponents.java:56-58).
"""

from __future__ import annotations

import copy
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple

from .plan import OpNode
from .types import csv_line, text_line
from ..utils import metrics

Record = Tuple[Any, int]  # (value, timestamp_ms)


class RuntimeContext:
    """Subtask info for rich functions
    (reference: RichMapFunction.getRuntimeContext().getIndexOfThisSubtask(),
    WindowGraphAggregation.java:74, BroadcastTriangleCount.java:131)."""

    def __init__(self, subtask_index: int, num_subtasks: int):
        self.subtask_index = subtask_index
        self.num_subtasks = num_subtasks

    def get_index_of_this_subtask(self) -> int:
        return self.subtask_index

    def get_number_of_subtasks(self) -> int:
        return self.num_subtasks


def _open(fn: Any, subtask: int = 0, num_subtasks: int = 1) -> Any:
    if hasattr(fn, "open"):
        fn.open(RuntimeContext(subtask, num_subtasks))
    return fn


class Executor:
    def __init__(self, env):
        self.env = env
        self.memo: Dict[int, List[Record]] = {}
        self._timing_stack: List[float] = []

    # ------------------------------------------------------------------
    def run(self) -> Dict[int, List[Record]]:
        metrics.on_stream_start("runtime")
        # Run iteration loops first: their fixpoint evaluation memoizes
        # every body node's accumulated output, so no sink path can later
        # re-execute a stateful body operator with already-mutated state.
        for sink in self.env._sinks:
            for node in self._ancestors(sink):
                if node.kind == "iterate":
                    self.eval(node)
        results: Dict[int, List[Record]] = {}
        for sink in self.env._sinks:
            records = self.eval(sink.parents[0])
            results[sink.id] = records
            self._emit(sink, records)
        return results

    def _ancestors(self, node: OpNode) -> List[OpNode]:
        seen, order, stack = set(), [], [node]
        while stack:
            n = stack.pop()
            if n.id in seen:
                continue
            seen.add(n.id)
            order.append(n)
            stack.extend(n.parents)
            fb = n.params.get("feedback")
            if fb is not None:
                stack.append(fb)
        return order

    def _emit(self, sink: OpNode, records: List[Record]) -> None:
        mode = sink.params["mode"]
        if mode == "print":
            for value, _ts in records:
                print(text_line(value))
        elif mode in ("csv", "text"):
            import os

            fmt = csv_line if mode == "csv" else text_line
            path = sink.params["path"]
            if not sink.params.get("overwrite", True) and os.path.exists(path):
                raise FileExistsError(
                    f"sink target exists and overwrite=False: {path}"
                )
            with open(path, "w") as f:
                for value, _ts in records:
                    f.write(fmt(value) + "\n")
        # "collect" needs no side effect; records are in the results dict.

    # ------------------------------------------------------------------
    def eval(self, node: OpNode, overrides: Optional[Dict[int, List[Record]]] = None,
             cache: Optional[Dict[int, List[Record]]] = None) -> List[Record]:
        """Evaluate a node to its full record list (memoized).

        `overrides`/`cache` support iteration subgraph re-evaluation with
        the loop head's output replaced by the pending feedback records.
        """
        memo = cache if cache is not None else self.memo
        if overrides and node.id in overrides:
            return overrides[node.id]
        # Nodes upstream of a loop head are already fully evaluated in the
        # global memo — reuse them instead of re-running (possibly stateful)
        # operators once per loop pass.
        if cache is not None and node.id in self.memo:
            return self.memo[node.id]
        if node.id in memo:
            return memo[node.id]
        if node.kind == "iterate" and overrides is None:
            self._run_iteration(node)
            return self.memo[node.id]
        timer = getattr(self.env, "timer", None)
        if timer is None:
            records = self._apply(node, overrides, cache)
        else:
            # exclusive per-operator timing: subtract time spent
            # evaluating parents inside _apply
            from ..utils import telemetry

            sw = telemetry.stopwatch()  # unnamed: pure measurement
            self._timing_stack.append(0.0)
            records = self._apply(node, overrides, cache)
            elapsed = sw.stop()
            child_time = self._timing_stack.pop()
            if self._timing_stack:
                self._timing_stack[-1] += elapsed
            timer.add(f"{node.kind}#{node.id}", elapsed - child_time,
                      len(records))
            # the flight-recorder span carries the EXCLUSIVE time (the
            # inclusive interval would double-count parents that
            # already recorded their own spans via this same path)
            telemetry.record_span(f"op.{node.kind}", sw.t0,
                                  elapsed - child_time, node=node.id,
                                  records=len(records))
        memo[node.id] = records
        return records

    # ------------------------------------------------------------------
    def _apply(self, node: OpNode, overrides, cache) -> List[Record]:
        kind = node.kind
        ev = lambda n: self.eval(n, overrides, cache)

        if kind == "source":
            return self._eval_source(node)

        if kind == "assign_timestamps":
            extractor = node.params["extractor"]
            return [
                (v, int(extractor.extract_ascending_timestamp(v)))
                for (v, _ts) in ev(node.parents[0])
            ]

        if kind == "map":
            fn = _open(node.params["fn"])
            return [(fn(v), ts) for (v, ts) in ev(node.parents[0])]

        if kind in ("flat_map", "keyed_flat_map"):
            fn = _open(node.params["fn"])
            out: List[Record] = []
            for v, ts in ev(node.parents[0]):
                fn(v, _collector(out, ts))
            return out

        if kind in ("filter", "keyed_filter"):
            fn = _open(node.params["fn"])
            return [(v, ts) for (v, ts) in ev(node.parents[0]) if fn(v)]

        if kind == "keyed_map":
            fn = _open(node.params["fn"])
            return [(fn(v), ts) for (v, ts) in ev(node.parents[0])]

        if kind == "project":
            fields = node.params["fields"]
            out = []
            for v, ts in ev(node.parents[0]):
                if len(fields) == 1:
                    out.append((v[fields[0]], ts))
                else:
                    out.append((tuple(v[f] for f in fields), ts))
            return out

        if kind == "union":
            merged: List[Record] = []
            for p in node.parents:
                merged.extend(ev(p))
            merged.sort(key=lambda r: r[1])  # stable: ties keep source order
            return merged

        if kind in ("broadcast", "key_by"):
            # Single-driver execution: partitioning is a no-op reordering-wise;
            # keying/broadcast semantics are honored by downstream operators.
            return ev(node.parents[0])

        if kind == "partition_tag":
            # Tag records with a round-robin subtask index 0..p-1 — emulates
            # the reference's rebalance → RichMapFunction subtask tagging
            # (WindowGraphAggregation.java:68-81).
            p = node.params.get("parallelism") or self.env.parallelism
            out = []
            for i, (v, ts) in enumerate(ev(node.parents[0])):
                out.append(((i % p, v), ts))
            return out

        if kind == "parallel_flat_map":
            # p independent stateful instances each seeing the full input —
            # the broadcast + parallel RichFlatMapFunction pattern
            # (BroadcastTriangleCount.java:42-45).
            p = node.params.get("parallelism") or self.env.parallelism
            proto = node.params["fn_factory"]
            out: List[Record] = []
            for i in range(p):
                fn = _open(proto(), i, p)
                for v, ts in ev(node.parents[0]):
                    fn(v, _collector(out, ts))
            out.sort(key=lambda r: r[1])
            return out

        if kind == "window":
            return self._eval_window(node, ev(node.parents[0]))

        if kind == "window_all":
            return self._eval_window_all(node, ev(node.parents[0]))

        if kind == "window_batch":
            return self._eval_window_batch(node, ev(node.parents[0]))

        if kind == "custom":
            return node.params["run"](ev(node.parents[0]) if node.parents else [])

        if kind == "iterate":
            # Inside a subgraph evaluation the head must have been overridden.
            raise RuntimeError("iterate head evaluated without override")

        raise ValueError(f"unknown op kind: {kind}")

    # ------------------------------------------------------------------
    def _eval_source(self, node: OpNode) -> List[Record]:
        items = node.params.get("items")
        if items is None:
            items = list(node.params["items_fn"]())
        clock = self.env.clock
        return [(item, clock.now_ms()) for item in items]

    # ------------------------------------------------------------------
    @staticmethod
    def _window_starts(ts: int, size: int, slide) -> List[int]:
        """Starts of every window containing ts. Tumbling (slide=None):
        the single aligned window. Sliding: all starts in (ts-size, ts]
        aligned to the slide (Flink's SlidingEventTimeWindows
        assignment), ascending."""
        if slide is None or slide == size:
            return [ts - ts % size]
        starts = []
        s = ts - ts % slide
        while s > ts - size:
            starts.append(s)
            s -= slide
        starts.reverse()
        return starts

    def _eval_window(self, node: OpNode, records: List[Record]) -> List[Record]:
        key_spec = node.params["key_spec"]
        size = node.params["size_ms"]
        slide = node.params.get("slide_ms")
        groups: Dict[Tuple[Any, int], List[Record]] = defaultdict(list)
        order: List[Tuple[Any, int]] = []
        for v, ts in records:
            for wstart in self._window_starts(ts, size, slide):
                k = (key_spec.key_of(v), wstart)
                if k not in groups:
                    order.append(k)
                groups[k].append((v, ts))
        # fire in ascending window end; ties by first arrival
        order.sort(key=lambda kw: kw[1])
        out: List[Record] = []
        for key, wstart in order:
            wmax = wstart + size - 1
            values = [v for v, _ in groups[(key, wstart)]]
            out.extend(
                (v, wmax) for v in self._run_window_fn(node, key, wmax, values)
            )
        return out

    def _eval_window_all(self, node: OpNode, records: List[Record]) -> List[Record]:
        size = node.params["size_ms"]
        slide = node.params.get("slide_ms")
        groups: Dict[int, List[Any]] = defaultdict(list)
        for v, ts in records:
            for wstart in self._window_starts(ts, size, slide):
                groups[wstart].append(v)
        out: List[Record] = []
        for wstart in sorted(groups):
            wmax = wstart + size - 1
            out.extend(
                (v, wmax)
                for v in self._run_window_fn(node, None, wmax, groups[wstart])
            )
        return out

    def _run_window_fn(self, node: OpNode, key, wmax: int, values: List[Any]) -> List[Any]:
        op = node.params["op"]
        if op == "fold":
            acc = copy.deepcopy(node.params["initial"])
            fn = node.params["fn"]
            for v in values:
                acc = fn(acc, v)
            return [acc]
        if op == "reduce":
            fn = node.params["fn"]
            acc = values[0]
            for v in values[1:]:
                acc = fn(acc, v)
            return [acc]
        if op == "apply":
            fn = node.params["fn"]
            out: List[Any] = []
            window = _Window(wmax)
            fn(key, window, values, out.append)
            return out
        if op == "sum":
            field = node.params["field"]
            total = sum(v[field] for v in values)
            first = list(values[0])
            first[field] = total
            return [tuple(first)]
        raise ValueError(f"unknown window op {op}")

    # ------------------------------------------------------------------
    def _eval_window_batch(self, node: OpNode, records: List[Record]) -> List[Record]:
        """Device hot path: group records into windows and hand each
        window to a columnar kernel: kernel(values, window_max_ts) -> [(v, ts)].

        Sliding windows (slide_ms set): when the kernel advertises a
        pane path (`kernel.pane_kernel`) and the slide divides the
        size, records are grouped ONCE by slide-sized pane and all
        windows are computed in one batched device dispatch from
        per-pane partials — no edge duplication. Otherwise each record
        is assigned to every covering window (factor size/slide
        duplication, always correct).
        """
        size = node.params["size_ms"]
        slide = node.params.get("slide_ms")
        kernel = node.params["kernel"]
        pane_kernel = getattr(kernel, "pane_kernel", None)
        if (slide is not None and slide != size and pane_kernel is not None
                and size % slide == 0):
            panes: Dict[int, List[Any]] = defaultdict(list)
            for v, ts in records:
                panes[ts - ts % slide].append(v)
            out = pane_kernel(panes, size, slide)
            if metrics.enabled():  # arg is an O(n) pass over `out`
                metrics.mark_window(len({ts for _, ts in out}),
                                    len(records), engine="runtime")
            return out
        groups: Dict[int, List[Any]] = defaultdict(list)
        for v, ts in records:
            for wstart in self._window_starts(ts, size, slide):
                groups[wstart].append(v)
        out: List[Record] = []
        for wstart in sorted(groups):
            out.extend(kernel(groups[wstart], wstart + size - 1))
        metrics.mark_window(len(groups), len(records), engine="runtime")
        return out

    # ------------------------------------------------------------------
    def _run_iteration(self, head: OpNode) -> None:
        feedback = head.params.get("feedback")
        if feedback is None:
            raise RuntimeError("iterate() without close_with()")
        max_iter = head.params.get("max_iterations", 1000)
        pending = self.eval(head.parents[0])
        head_all: List[Record] = []
        body_all: Dict[int, List[Record]] = defaultdict(list)
        # Stateful fns in the body persist across loop passes (user fn
        # objects hold their own state); every body node's per-pass output
        # accumulates so sinks on any branch of the loop body see the full
        # stream, not just the feedback edge.
        for _ in range(max_iter):
            if not pending:
                break
            head_all.extend(pending)
            cache: Dict[int, List[Record]] = {}
            fed = self.eval(feedback, overrides={head.id: pending}, cache=cache)
            for nid, recs in cache.items():
                body_all[nid].extend(recs)
            pending = fed
        else:
            if pending:
                raise RuntimeError(
                    f"iteration did not converge within {max_iter} passes "
                    f"({len(pending)} records still pending)"
                )
        self.memo[head.id] = head_all
        for nid, recs in body_all.items():
            self.memo[nid] = recs


class _Window:
    def __init__(self, max_timestamp: int):
        self._max = max_timestamp

    def max_timestamp(self) -> int:
        return self._max


def _collector(out: List[Record], ts: int) -> Callable[[Any], None]:
    return lambda value: out.append((value, ts))


def execute(env) -> Dict[int, List[Record]]:
    return Executor(env).run()

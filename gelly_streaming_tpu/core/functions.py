"""Neighborhood UDF interfaces.

Host forms mirror the reference's three UDF interfaces exactly
(EdgesFold.java:46, EdgesReduce.java:42, EdgesApply.java:47).
Jax* forms are their device-compiled counterparts: jax-traceable
functions lowered to segment kernels (ops/segment.py) — the TPU-native
way to run a neighborhood aggregation as one XLA program per window.
"""

from __future__ import annotations

from typing import Any, Callable, Optional


class EdgesFold:
    """Host fold UDF: fn(accum, vertex_id, neighbor_id, edge_value) -> accum
    (reference: EdgesFold.java:46)."""

    def __init__(self, fn: Callable[[Any, Any, Any, Any], Any] = None):
        self._fn = fn

    def fold_edges(self, accum, vertex_id, neighbor_id, edge_value):
        if self._fn is None:
            raise NotImplementedError
        return self._fn(accum, vertex_id, neighbor_id, edge_value)


class EdgesReduce:
    """Host reduce UDF: fn(a, b) -> value (reference: EdgesReduce.java:42)."""

    def __init__(self, fn: Callable[[Any, Any], Any] = None):
        self._fn = fn

    def reduce_edges(self, a, b):
        if self._fn is None:
            raise NotImplementedError
        return self._fn(a, b)


class EdgesApply:
    """Host apply UDF: fn(vertex_id, neighbors, collect) with `neighbors`
    an iterable of (neighbor_id, edge_value) (reference: EdgesApply.java:47)."""

    def __init__(self, fn: Callable[[Any, Any, Callable], None] = None):
        self._fn = fn

    def apply_on_edges(self, vertex_id, neighbors, collect):
        if self._fn is None:
            raise NotImplementedError
        return self._fn(vertex_id, neighbors, collect)


# ----------------------------------------------------------------------
# device-compiled forms
# ----------------------------------------------------------------------

class JaxEdgesFold:
    """Device fold: jax-traceable fn(acc_tree, vertex_id, neighbor_id,
    edge_value) -> acc_tree over scalars; `init` is the accumulator pytree.

    Runs as a segmented `lax.scan` in arrival order — semantics identical
    to the reference's incremental pane fold (GraphWindowStream.java:77-80),
    compiled once per shape bucket.
    """

    def __init__(self, init, fn, emit=None):
        self.init = init
        self.fn = fn
        # optional host-side post-map from (vertex_id, acc_tree) to the
        # emitted record; default emits the accumulator tuple itself.
        self.emit = emit


class JaxEdgesReduce:
    """Device reduce of neighborhood edge values.

    Three performance tiers (fastest first):
    - `name` ('sum'|'min'|'max') — fully-parallel named monoid
      segment kernel;
    - `fn` + `associative=True` — O(log E) flagged associative scan
      (the combine tree reorders applications, which associativity
      licenses);
    - `fn` alone — O(E) sequential segmented scan in exact arrival
      order (the reference's incremental pane semantics,
      GraphWindowStream.java:107-121); correct for any fn, but
      latency-bound — prefer the tiers above when the fn qualifies.
    """

    def __init__(self, fn=None, name: Optional[str] = None,
                 associative: bool = False):
        if fn is None and name is None:
            raise ValueError("need fn or name")
        self.fn = fn
        self.name = name
        self.associative = associative


class JaxEdgesApply:
    """Device apply over a padded neighborhood view.

    fn(vertex_id, neighbor_ids[max_deg], edge_values[max_deg],
       mask[max_deg]) -> output pytree of fixed shape; vmapped over the
    window's vertices. For variable-arity outputs use the host EdgesApply
    or a fused workload kernel (ops/triangles.py).
    """

    def __init__(self, fn, emit=None):
        self.fn = fn
        self.emit = emit

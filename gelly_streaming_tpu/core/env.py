"""Stream execution environment.

The TPU-native counterpart of the reference's use of
`StreamExecutionEnvironment` (SimpleEdgeStream.java:74,91;
WindowTriangles.java:175,188; GraphStreamTestUtils.java:32-37):
program context, sources, time characteristic, parallelism, execute().
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional

from .datastream import DataStream
from .gtime import Clock, SystemClock, TimeCharacteristic
from .plan import OpNode


class StreamEnvironment:
    def __init__(self, clock: Optional[Clock] = None):
        self.clock: Clock = clock or SystemClock()
        self.time_characteristic = TimeCharacteristic.INGESTION_TIME
        self.parallelism = 1
        self._sinks: List[OpNode] = []
        self._results: dict = {}
        self._last_runtime_ms: Optional[float] = None

    # ------------------------------------------------------------------
    @staticmethod
    def get_execution_environment(clock: Optional[Clock] = None) -> "StreamEnvironment":
        return StreamEnvironment(clock=clock)

    def set_parallelism(self, parallelism: int) -> "StreamEnvironment":
        self.parallelism = parallelism
        return self

    def set_stream_time_characteristic(self, tc: TimeCharacteristic) -> "StreamEnvironment":
        self.time_characteristic = tc
        return self

    # ------------------------------------------------------------------
    # sources (reference: fromCollection / readTextFile / generateSequence)
    # ------------------------------------------------------------------
    def from_collection(self, items: Iterable[Any]) -> DataStream:
        return DataStream(self, OpNode("source", (), items=list(items)))

    def read_text_file(self, path: str) -> DataStream:
        def _read():
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if line:
                        yield line

        return DataStream(self, OpNode("source", (), items_fn=_read))

    def generate_sequence(self, start: int, end: int) -> DataStream:
        return DataStream(self, OpNode("source", (), items=list(range(start, end + 1))))

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _register_sink(self, node: OpNode) -> None:
        self._sinks.append(node)

    def execute(self, job_name: str = "job") -> "JobExecutionResult":
        import time as _t

        from . import runtime

        if self._last_runtime_ms is not None:
            # Operator closures hold state for the life of the plan (like a
            # Flink program instance); re-running would silently reuse it.
            raise RuntimeError(
                "this environment was already executed; build a new "
                "StreamEnvironment per job"
            )
        start = _t.time()
        self._results = runtime.execute(self)
        self._last_runtime_ms = (_t.time() - start) * 1000
        return JobExecutionResult(self._last_runtime_ms)

    def results_of(self, stream: DataStream) -> List[Any]:
        """Records collected by a `.collect()` sink (values only)."""
        return [v for (v, _ts) in self._results.get(stream.node.id, [])]

    # ------------------------------------------------------------------
    # tracing / metrics (utils/tracing.py; absent in the reference,
    # SURVEY.md §5.1/§5.5)
    # ------------------------------------------------------------------
    def enable_tracing(self) -> "StreamEnvironment":
        from ..utils.tracing import StepTimer

        self.timer = StepTimer()
        return self

    def trace_report(self) -> List[dict]:
        timer = getattr(self, "timer", None)
        return timer.report() if timer else []


class JobExecutionResult:
    """Mirror of the reference's use of `JobExecutionResult.getNetRuntime()`
    (CentralizedWeightedMatching.java:62-64)."""

    def __init__(self, runtime_ms: float):
        self._runtime_ms = runtime_ms

    def get_net_runtime(self) -> float:
        return self._runtime_ms

"""Summary-aggregation framework — the merge-tree engine.

Re-design of the reference's L3 layer (GraphAggregation.java:19-118,
WindowGraphAggregation.java:30-105): per-partition windowed folds of
edges into a summary state S, merged by a single non-blocking Merger
that emits an improved global state after every incoming partial.

TPU shape: the per-partition window fold is the parallel part — with a
device `fold_kernel` it runs as one XLA program per window batch
(e.g. array union-find, ops/unionfind.py), and in multi-chip mode the
partials are merged with collectives (the psum/pmin merges fused into
the window programs in parallel/sharded.py) instead of the host
Merger.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Optional

from .datastream import DataStream
from .gtime import Time
from .plan import OpNode


class GraphAggregation:
    """Abstract incremental aggregation
    (reference: GraphAggregation.java:28-56).

    update_fun(state, src, trg, value) -> state   — per-edge fold
    combine_fun(partial, current) -> state        — merge partials
    transform(state) -> output                    — optional post-map
    """

    def __init__(self, update_fun: Callable, combine_fun: Callable,
                 initial_value: Any, transient_state: bool,
                 transform: Optional[Callable] = None):
        self.update_fun = update_fun
        self.combine_fun = combine_fun
        self.initial_value = initial_value
        self.transient_state = transient_state
        self.transform = transform

    def run(self, edge_stream: DataStream) -> DataStream:
        raise NotImplementedError

    def make_merger(self) -> Callable:
        """Non-blocking incremental merger: combine each partial into the
        running global state and emit it; reset when transient
        (reference: Merger, GraphAggregation.java:90-117). Emissions are
        snapshots (deep copies) — the reference serializes at emission
        time, which our in-memory sinks must reproduce."""
        initial = self.initial_value
        combine = self.combine_fun
        transient = self.transient_state
        state = {"current": copy.deepcopy(initial)}

        def merger(partial, collect):
            state["current"] = combine(partial, state["current"])
            collect(copy.deepcopy(state["current"]))
            if transient:
                state["current"] = copy.deepcopy(initial)

        return merger


class WindowGraphAggregation(GraphAggregation):
    """Merge-tree summary aggregation
    (reference: WindowGraphAggregation.java:47-65): tag each edge with
    its partition index, fold per (partition, window), funnel all
    partials through one merger.

    With `fold_kernel` set, the per-window fold runs as a device kernel
    over the window's columnar edge batch: kernel(edges, wmax) -> S.
    """

    def __init__(self, update_fun: Callable, combine_fun: Callable,
                 initial_value: Any, time_millis: int,
                 transient_state: bool = False,
                 transform: Optional[Callable] = None,
                 fold_kernel: Optional[Callable] = None):
        super().__init__(update_fun, combine_fun, initial_value,
                         transient_state, transform)
        self.time_millis = time_millis
        self.fold_kernel = fold_kernel

    def run(self, edge_stream: DataStream) -> DataStream:
        env = edge_stream.env
        if self.fold_kernel is not None:
            # Device path: one kernel invocation per window batch.
            kernel = self.fold_kernel

            def window_kernel(edges, wmax):
                return [(kernel(edges, wmax), wmax)]

            node = OpNode("window_batch", [edge_stream.node],
                          size_ms=self.time_millis, kernel=window_kernel)
            partials = DataStream(env, node)
        else:
            update = self.update_fun
            tagged = DataStream(
                env, OpNode("partition_tag", [edge_stream.node],
                            parallelism=env.parallelism)
            )
            partials = tagged.key_by(0).time_window(
                Time.milliseconds_of(self.time_millis)
            ).fold(
                self.initial_value,
                lambda s, rec: update(s, rec[1].source, rec[1].target,
                                      rec[1].value),
            )

        merged = partials.flat_map(self.make_merger()).set_parallelism(1)
        if self.transform is not None:
            return merged.map(self.transform)
        return merged

"""Durable async serving front-end: live traffic in, windows out,
survives being killed mid-window.

`StreamServer` wraps a multi-tenant cohort (core/tenancy.TenantCohort)
with the pieces a deployable stream service needs and the ROADMAP's
"async serving front-end" item names:

- **Sources.** A 127.0.0.1 TCP accept loop speaking newline-delimited
  JSON requests (admit / feed / pump / close / status — see _OPS), and
  `attach_file_tail()` threads that follow growing edge files
  (io/sources.tail_edge_file). Both feed the SAME guarded
  `cohort.feed()` admission path, so every accepted edge hits the
  write-ahead journal (utils/wal.py) before any queue.
- **Typed wire responses.** `TenantRejected` / `TenantBackpressure`
  come back as `{"ok": false, "error": <type>, ...}` with a
  deterministic `retry_after_s` hint (utils/resilience.backoff_s —
  the SAME GS_STAGE_BACKOFF_S ladder the in-process stage guard
  sleeps, doubling per consecutive rejection and resetting on the
  first accepted feed), so a polite client and the internal retry
  pace identically. With the sanitizer armed (GS_SANITIZE,
  utils/sanitize): feed replies carry the typed rejection counts of
  edges peeled off to the dead-letter journal, oversized batches come
  back as `BatchRejected` with the reason code, a quarantined
  tenant's refusal surfaces as `TenantQuarantined`, and
  status//healthz add DLQ depth + the quarantined-tenant list.
- **Deadlines.** Per-connection idle timeout (GS_SERVE_IDLE_S) on the
  receive side; every response send runs under
  `resilience.call_guarded` with the same deadline and retries=0 — a
  slow client that stops reading is SHED (durable `serve_client_shed`
  event, connection closed) instead of wedging the thread that also
  pumps. The accept backlog is bounded (listen backlog + a
  max-connections cap answered with a typed `ServerBusy`).
- **Graceful drain.** SIGTERM (or `request_drain()`): stop accepting,
  finish in-flight requests under GS_SERVE_DRAIN_S, stop the tails,
  pump every tenant queue dry, flush a checkpoint per tenant, SEAL
  the journal (durable `wal_sealed` + `serve_drain` events), exit 0 —
  zero queued windows lost, proven by the drain leg of
  tools/chaos_run.py (drain digest ≡ keep-running digest).
- **Recovery.** A killed server restarts with `--recover` /
  `recover()`: tenants are re-admitted from the journal, each resumes
  its newest checkpoint, and the un-checkpointed journal suffix
  replays into the queues — the next pumps re-produce exactly the
  windows the crash swallowed (`wal_replayed` durable event;
  replay-exactness asserted by the chaos serve leg and
  tests/test_checkpoint_roundtrip.py).
- **Observability.** `gs_serve_*` counters/gauges, a `serve` section
  on `/healthz` (metrics.register_health_section), and durable ledger
  events for drain/seal/replay/shed. With the latency plane armed
  (GS_LATENCY=1, utils/latency.py): every delivered results row
  carries `latency_s` (ingest→deliver, the sink write stamped as the
  `deliver` stage) + `queue_edges`, and the `status`/`serve` section
  adds per-tenant queue depth+age and the latency percentiles — the
  client self-throttle loop.

Run one standalone:

    python -m gelly_streaming_tpu.core.serve --edge-bucket 512 \
        --vertex-bucket 1024 --wal wal/ --ckpt ckpt/ \
        --results results.jsonl [--recover] [--port-file port.txt]

The process prints its bound port, pumps continuously, appends every
finalized window summary to the results file as one JSON line
(tenant, window ordinal, summary — at-least-once across a
kill/recover: consumers keep the LAST record per (tenant, window)),
and exits 0 on SIGTERM after a clean drain.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from ..utils import knobs
from ..utils import latency
from ..utils import metrics
from ..utils import provenance
from ..utils import resilience
from ..utils import sanitize as sanitize_mod
from ..utils import telemetry
from ..utils.faults import InjectedFault
from ..ops.resident_engine import Mailbox
from .tenancy import TenantBackpressure, TenantCohort, TenantRejected

_OPS = ("admit", "feed", "pump", "close", "status", "subscribe")


def serve_port() -> int:
    """GS_SERVE_PORT (0 = OS-assigned ephemeral; `.port` holds the
    bound one)."""
    return knobs.get_int("GS_SERVE_PORT")


def pump_mode() -> str:
    """GS_PUMP: `sync` (default) pumps inline under the request lock —
    bit-identical to the pre-pump build; `async` runs slab prep → h2d
    → dispatch → finalize on a dedicated pump thread so ingest
    (sanitize → WAL → enqueue, under the cohort's queue lock) overlaps
    compute. Same digests either way — only `queue_wait` moves."""
    return knobs.get_str("GS_PUMP")


def sub_queue_cap() -> int:
    """GS_SUB_QUEUE: bounded per-connection queue of the `subscribe`
    op; a subscriber whose queue overflows is SHED (durable
    `serve_client_shed`), never allowed to wedge the pump."""
    return knobs.get_int("GS_SUB_QUEUE")


def drain_deadline_s() -> float:
    """GS_SERVE_DRAIN_S: how long drain waits for in-flight requests
    before force-closing their connections (0 = forever)."""
    return knobs.get_float("GS_SERVE_DRAIN_S")


def idle_timeout_s() -> float:
    """GS_SERVE_IDLE_S: per-connection receive-idle AND response-send
    deadline."""
    return knobs.get_float("GS_SERVE_IDLE_S")


class StreamServer:
    """One cohort behind one accept loop. All cohort access is
    serialized by `_lock` (the cohort is not thread-safe); response
    sends happen OUTSIDE it, so a slow client can stall only its own
    connection thread — never the pump."""

    def __init__(self, cohort: TenantCohort,
                 host: str = "127.0.0.1",
                 port: Optional[int] = None,
                 backlog: int = 16,
                 max_connections: int = 32,
                 results_path: Optional[str] = None):
        self.cohort = cohort
        # latency plane: the cohort defers each finalized window's
        # record so _emit can stamp the DELIVERY boundary (the sink
        # write) — ingest→deliver, not ingest→finalize
        cohort.defer_delivery = True
        self._lock = threading.RLock()
        # --- async serving pump (GS_PUMP) lock discipline ---
        # sync:  _ingest_lock and _pump_mutex BOTH alias _lock — every
        #        acquisition pattern collapses to the legacy single
        #        re-entrant lock, bit-identical behavior.
        # async: ingest (admit/feed, socket + tails) serializes on
        #        _ingest_lock only; the pump thread owns _pump_mutex
        #        for prep → h2d → dispatch → finalize + _emit. The two
        #        sides meet ONLY at the cohort's internal queue lock
        #        (TenantCohort._qlock), so enqueue overlaps dispatch.
        #        close/drain take _pump_mutex BEFORE _ingest_lock —
        #        the one place both are held.
        self.pump_mode = pump_mode()
        if self.pump_mode == "async":
            self._ingest_lock = threading.RLock()
            self._pump_mutex = threading.RLock()
        else:
            self._ingest_lock = self._lock
            self._pump_mutex = self._lock
        self._pump_thread: Optional[threading.Thread] = None
        self._pump_stop = threading.Event()
        # bounded wake channel: feed drops a token, the pump thread
        # wakes; a full mailbox just means the pump is already awake
        self._pump_wake = Mailbox(capacity=64)
        self._pump_busy = threading.Event()  # dispatch in flight
        # result subscriptions: cid -> (conn, mailbox, tenant filter);
        # each subscribed connection gets a sender thread draining its
        # bounded mailbox so a slow subscriber sheds instead of
        # stalling the pump
        self._subs: Dict[int, tuple] = {}
        self._send_locks: Dict[int, threading.Lock] = {}
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, serve_port()
                             if port is None else port))
        self._listener.listen(backlog)
        self.host = host
        self.port = self._listener.getsockname()[1]
        self.max_connections = max_connections
        self._accept_thread = None
        self._conns: Dict[int, socket.socket] = {}
        self._conn_threads: List[threading.Thread] = []
        self._conn_seq = 0
        self._draining = threading.Event()
        self._drain_req = threading.Event()
        self._drained = None          # drain() summary, once run
        self._drain_lock = threading.Lock()
        self.fatal = False            # a fatal InjectedFault landed
        self._bp_attempts: Dict[str, int] = {}  # consecutive rejects
        self._tails: List[tuple] = []  # (thread, stop_event)
        self._results_path = results_path
        self._results_file = (open(results_path, "a")
                              if results_path else None)
        self.results: Dict[str, list] = {}  # tenant -> summaries
        self._stats = {"connections": 0, "requests": 0, "shed": 0,
                       "rejections": 0, "busy": 0, "windows": 0,
                       # overlap proof: ingest batches accepted WHILE
                       # the async pump had a dispatch in flight (the
                       # smoke gate asserts this is nonzero)
                       "overlap_feeds": 0,
                       "subscribers": 0, "pushed": 0}
        metrics.register_health_section("serve", self._health_section)
        telemetry.event("serve_started", port=self.port)

    # ------------------------------------------------------------------
    # accept loop
    # ------------------------------------------------------------------
    def start(self) -> "StreamServer":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="gs-serve")
        self._accept_thread.start()
        if self.pump_mode == "async" and self._pump_thread is None:
            self._pump_thread = threading.Thread(
                target=self._pump_loop, daemon=True,
                name="gs-serve-pump")
            self._pump_thread.start()
        return self

    def _pump_loop(self, interval_s: float = 0.02) -> None:
        """The dedicated pump thread (GS_PUMP=async): wake on a feed
        token (or the interval fallback), dispatch every ready window
        under _pump_mutex — never under _ingest_lock, so the accept
        loop and file tails keep admitting while slabs prep, transfer
        and compute. Rounds are bounded (max_rounds=1) so summaries
        emit as each cohort round finalizes — an unbounded pump would
        hold every result until the queues ran dry, turning a steady
        arrival stream into one end-of-run delivery burst. A fatal
        injected kill mid-pump leaves the exact SIGKILL shape the
        chaos pump leg recovers from."""
        while not self._pump_stop.is_set():
            self._pump_wake.get(timeout=interval_s)
            if self._pump_stop.is_set():
                return
            if not self._any_ready():
                continue
            try:
                self.pump_once(max_rounds=1)
            except InjectedFault as e:
                if e.fatal:
                    self.fatal = True
                    try:
                        self._listener.close()
                    except OSError:
                        pass
                    return
                telemetry.event("serve_pump_failed",
                                error=repr(e)[:200])
            except (TenantRejected, TenantBackpressure):
                pass  # a racing close/admission: re-plan next wake

    def _join_pump(self) -> None:
        """Stop + join the async pump thread (drain/close preamble);
        idempotent, no-op in sync mode."""
        self._pump_stop.set()
        self._pump_wake.close()
        t = self._pump_thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join()

    def _accept_loop(self) -> None:
        while not self._draining.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed: drain began
            with self._lock:
                self._stats["connections"] += 1
                active = len(self._conns)
            metrics.counter_inc("gs_serve_connections_total")
            if active >= self.max_connections:
                # bounded backlog: answer with a typed busy + the
                # deterministic retry hint, never queue unboundedly
                self._stats["busy"] += 1
                metrics.counter_inc("gs_serve_rejections_total",
                                    kind="ServerBusy")
                try:
                    conn.sendall((json.dumps({
                        "ok": False, "error": "ServerBusy",
                        "retry_after_s": resilience.backoff_s(0),
                    }) + "\n").encode())
                except OSError:
                    pass
                conn.close()
                continue
            with self._lock:
                self._conn_seq += 1
                cid = self._conn_seq
                self._conns[cid] = conn
            t = threading.Thread(target=self._handle_conn,
                                 args=(cid, conn), daemon=True,
                                 name="gs-serve-conn-%d" % cid)
            # prune finished threads: only the accept thread touches
            # this list, and without the filter a long-lived server
            # would keep every dead connection thread forever (and
            # drain() would join the whole graveyard)
            self._conn_threads = [x for x in self._conn_threads
                                  if x.is_alive()]
            self._conn_threads.append(t)
            t.start()

    def _handle_conn(self, cid: int, conn: socket.socket) -> None:
        gauge = len(self._conns)
        metrics.gauge_set("gs_serve_active_connections", gauge)
        conn.settimeout(idle_timeout_s())
        buf = b""
        try:
            while not self._draining.is_set():
                nl = buf.find(b"\n")
                if nl < 0:
                    try:
                        chunk = conn.recv(1 << 20)
                    except socket.timeout:
                        telemetry.event("serve_idle_closed", conn=cid)
                        metrics.counter_inc(
                            "gs_serve_idle_closed_total")
                        return
                    if not chunk:
                        return  # client hung up
                    buf += chunk
                    continue
                line, buf = buf[:nl], buf[nl + 1:]
                if not line.strip():
                    continue
                resp = self._handle_request(cid, line)
                if not self._send(cid, conn, resp):
                    return
        except InjectedFault as e:
            if e.fatal:
                # the simulated hard kill: the whole server is dead —
                # close the listener so no further accept succeeds,
                # exactly the shape a real SIGKILL leaves behind
                self.fatal = True
                try:
                    self._listener.close()
                except OSError:
                    pass
                raise
            telemetry.event("serve_request_failed", conn=cid,
                            error=repr(e)[:200])
        except OSError:
            return  # connection reset: the client's problem
        finally:
            self._drop_sub(cid)
            with self._lock:
                self._conns.pop(cid, None)
                self._send_locks.pop(cid, None)
            metrics.gauge_set("gs_serve_active_connections",
                              len(self._conns))
            try:
                conn.close()
            except OSError:
                pass

    def _send(self, cid: int, conn: socket.socket,
              resp: dict) -> bool:
        """Send one response under the per-connection deadline: a
        client that stops reading long enough to stall the send is
        SHED (durable event + close) — the stall never reaches the
        pump, whose lock is not held here."""
        data = (json.dumps(resp) + "\n").encode()
        # a subscribed connection has TWO writers (its request thread
        # and its subscription sender) — one lock per connection keeps
        # whole lines whole
        with self._lock:
            slock = self._send_locks.setdefault(cid, threading.Lock())

        def _do_send():
            from ..utils import faults

            faults.fire("serve_send", cid)
            with slock:
                conn.sendall(data)

        try:
            resilience.call_guarded("serve_send", cid, _do_send,
                                    retries=0,
                                    timeout=idle_timeout_s())
            return True
        except (resilience.StageError, OSError):
            self._stats["shed"] += 1
            telemetry.event("serve_client_shed", durable=True,
                            conn=cid, bytes=len(data))
            metrics.counter_inc("gs_serve_shed_total")
            try:
                conn.close()
            except OSError:
                pass
            return False

    # ------------------------------------------------------------------
    # requests
    # ------------------------------------------------------------------
    def _handle_request(self, cid: int, line: bytes) -> dict:
        try:
            req = json.loads(line)
            op = req.get("op")
            if op not in _OPS:
                raise ValueError("unknown op %r (one of %s)"
                                 % (op, "/".join(_OPS)))
        except ValueError as e:
            return {"ok": False, "error": "BadRequest",
                    "message": str(e)[:500]}
        self._stats["requests"] += 1
        metrics.counter_inc("gs_serve_requests_total", op=op)
        req["_cid"] = cid  # subscribe binds to the connection
        try:
            return getattr(self, "_op_" + op)(req)
        except TenantBackpressure as e:
            # typed backpressure with the deterministic retry hint:
            # doubles per consecutive rejection of this tenant,
            # resets on the first accepted feed
            with self._lock:
                n = self._bp_attempts.get(e.tenant, 0)
                self._bp_attempts[e.tenant] = n + 1
            self._stats["rejections"] += 1
            metrics.counter_inc("gs_serve_rejections_total",
                                kind="TenantBackpressure")
            return {"ok": False, "error": "TenantBackpressure",
                    "tenant": e.tenant, "queued": e.queued,
                    "capacity": e.capacity,
                    "retry_after_s": resilience.backoff_s(n)}
        except TenantRejected as e:
            # type(e).__name__, not a fixed string: a quarantined
            # tenant's refusal must surface as TenantQuarantined so
            # the client can tell the bulkhead from a capacity refusal
            self._stats["rejections"] += 1
            metrics.counter_inc("gs_serve_rejections_total",
                                kind=type(e).__name__)
            resp = {"ok": False, "error": type(e).__name__,
                    "tenant": e.tenant, "message": str(e)[:500]}
            left = getattr(e, "probation_left", None)
            if left is not None:
                resp["probation_left"] = left
            return resp
        except sanitize_mod.BatchRejected as e:
            # the sanitizer's whole-batch refusal (GS_MAX_BATCH_EDGES
            # or a structurally unusable batch): typed, with the
            # reason code and the journal's recoverability promise
            self._stats["rejections"] += 1
            metrics.counter_inc("gs_serve_rejections_total",
                                kind="BatchRejected")
            return {"ok": False, "error": "BatchRejected",
                    "tenant": e.tenant, "reason": e.reason,
                    "size": e.size, "limit": e.limit,
                    "message": str(e)[:500]}
        except InjectedFault:
            raise  # the chaos kill must look like a kill, not a 500
        except (ValueError, KeyError, TypeError) as e:
            # malformed payloads (missing tenant/src/dst, wrong
            # shapes) must come back as the typed BadRequest the
            # protocol promises, never kill the connection thread
            return {"ok": False, "error": "BadRequest",
                    "message": "%s: %s" % (type(e).__name__,
                                           str(e)[:500])}

    def _op_admit(self, req: dict) -> dict:
        with self._ingest_lock:
            self.cohort.admit(req["tenant"],
                              vertex_bucket=req.get("vertex_bucket"))
        return {"ok": True, "tenant": str(req["tenant"])}

    def _op_feed(self, req: dict) -> dict:
        if sanitize_mod.enabled():
            # raw arrays reach the cohort UN-narrowed: the sanitizer
            # must see the hostile 2^40 id, not its silently
            # int32-wrapped ghost
            src = np.asarray(req["src"])
            dst = np.asarray(req["dst"])
        else:
            # disarmed: the EXACT legacy pre-cast — a python-int list
            # with an out-of-int32 value raises here (OverflowError),
            # it must never reach cohort.feed as an int64 array whose
            # int32 re-cast would wrap silently into a plausible id
            src = np.asarray(req["src"], np.int32)
            dst = np.asarray(req["dst"], np.int32)
        ts = req.get("ts")
        if ts is not None:
            ts = np.asarray(ts, np.int64)
        with self._ingest_lock:
            if self._pump_busy.is_set():
                # the overlap the pump exists for: this batch
                # sanitizes/journals/enqueues WHILE a dispatch is in
                # flight on the pump thread
                self._stats["overlap_feeds"] += 1
            accepted = self.cohort.feed(req["tenant"], src, dst,
                                        ts=ts)
            self._bp_attempts.pop(str(req["tenant"]), None)
            t = self.cohort.tenants.get(str(req["tenant"]))
            rep = t.last_report if t is not None else None
            quarantined = (t is not None
                           and t.tier == "quarantined")
        self._wake_pump()
        resp = {"ok": True, "accepted": int(accepted)}
        if rep is not None:
            # typed rejection surface: reason-code counts for the
            # edges the sanitizer peeled off to the dead-letter
            # journal ({} on a clean batch — replies stay identical)
            resp.update(rep.wire_fields())
        if quarantined:
            resp["quarantined"] = True
        return resp

    def _op_pump(self, req: dict) -> dict:
        results = self.pump_once()
        return {"ok": True, "results": results}

    def _op_close(self, req: dict) -> dict:
        # close() both flushes ingest-side state (the reorder buffer)
        # and pumps the final windows: it must exclude BOTH sides.
        # Lock order pump → ingest is the global one (see __init__);
        # in sync mode each `with` re-enters the same RLock.
        with self._pump_mutex:
            with self._ingest_lock:
                summaries = self.cohort.close(req["tenant"])
            out = self._emit({str(req["tenant"]): summaries}) \
                if summaries else {}
        return {"ok": True,
                "results": out.get(str(req["tenant"]), [])}

    def _op_status(self, req: dict) -> dict:
        return {"ok": True, "serve": self._health_section()}

    def _op_subscribe(self, req: dict) -> dict:
        """Register THIS connection for a tenant's WindowResult rows
        (`tenant` "*" = every stream). Rows are pushed as
        `{"ok": true, "event": "window", ...}` lines from a dedicated
        sender thread draining a bounded per-connection mailbox
        (GS_SUB_QUEUE); overflow or a stalled send SHEDS the
        subscriber via the serve_client_shed path."""
        cid = int(req["_cid"])
        tenant = str(req.get("tenant", "*"))
        with self._lock:
            conn = self._conns.get(cid)
            if conn is None:
                raise ValueError("subscribe on a vanished connection")
            ent = self._subs.get(cid)
            if ent is not None:
                ent[2].add(tenant)
                return {"ok": True, "subscribed": sorted(ent[2])}
            mb = Mailbox(capacity=sub_queue_cap())
            self._subs[cid] = (conn, mb, {tenant})
            self._stats["subscribers"] += 1
        threading.Thread(target=self._sub_sender_loop,
                         args=(cid, conn, mb), daemon=True,
                         name="gs-serve-sub-%d" % cid).start()
        metrics.counter_inc("gs_serve_subscribes_total")
        return {"ok": True, "subscribed": [tenant]}

    def _sub_sender_loop(self, cid: int, conn, mb: Mailbox) -> None:
        while True:
            row = mb.get(timeout=0.5)
            if row is None:
                if mb.closed and not len(mb):
                    return
                continue
            if not self._send(cid, conn, row):
                self._drop_sub(cid)
                return

    def _drop_sub(self, cid: int) -> None:
        with self._lock:
            ent = self._subs.pop(cid, None)
        if ent is not None:
            ent[1].close()

    def _fanout(self, rows: Dict[str, list]) -> None:
        """Push freshly emitted rows into every matching subscriber's
        mailbox. put() never blocks: a full mailbox means the
        subscriber fell behind its GS_SUB_QUEUE budget — shed it
        (durable serve_client_shed + close), the pump never waits."""
        with self._lock:
            subs = list(self._subs.items())
        if not subs:
            return
        for cid, (conn, mb, tenants) in subs:
            for tid, trows in rows.items():
                if "*" not in tenants and tid not in tenants:
                    continue
                for row in trows:
                    if mb.put({"ok": True, "event": "window", **row}):
                        self._stats["pushed"] += 1
                        continue
                    self._stats["shed"] += 1
                    telemetry.event("serve_client_shed", durable=True,
                                    conn=cid, reason="sub_overflow",
                                    depth=len(mb))
                    metrics.counter_inc("gs_serve_shed_total")
                    self._drop_sub(cid)
                    try:
                        conn.close()
                    except OSError:
                        pass
                    break
                else:
                    continue
                break

    # ------------------------------------------------------------------
    # pumping & results
    # ------------------------------------------------------------------
    def _wake_pump(self) -> None:
        """Nudge the async pump thread (no-op in sync mode; a full
        wake mailbox means it is already awake — drop the token)."""
        if self.pump_mode == "async" and not self._pump_stop.is_set():
            self._pump_wake.put(1)

    def pump_once(self,
                  max_rounds: Optional[int] = None) -> Dict[str, list]:
        """One cohort pump under the pump mutex (the request lock in
        sync mode); summaries are emitted to the results sink (with
        per-tenant window ordinals) and returned keyed by tenant.
        `max_rounds` bounds the cohort rounds per call (the async
        pump's incremental-delivery knob); None drains every ready
        window — the sync legacy contract."""
        with self._pump_mutex:
            self._pump_busy.set()
            try:
                results = self.cohort.pump(max_rounds=max_rounds)
            finally:
                self._pump_busy.clear()
            return self._emit(results)

    def _emit(self, results: Dict[str, list]) -> Dict[str, list]:
        out = {}
        for tid, summaries in results.items():
            if not summaries:
                continue
            base = self.cohort.windows_done(tid) - len(summaries)
            rows = [{"tenant": tid, "window": base + i, "summary": s}
                    for i, s in enumerate(summaries)]
            # DELIVERY boundary of the latency plane: close each
            # window's deferred record here so its waterfall includes
            # the pump→sink gap, and let clients self-throttle off
            # the row itself (latency_s + current queue depth). Keys
            # appear only armed — disarmed rows are bit-identical.
            if latency.enabled():
                queued = self.cohort.queued_edges(tid)
                for row in rows:
                    rec = latency.delivered(tid, row["window"])
                    if rec is not None:
                        row["latency_s"] = round(rec["e2e_s"], 6)
                        row["queue_edges"] = int(queued)
            if provenance.armed():
                # the DELIVERY record: digest covers the summary only
                # (never the armed-only latency keys), so it matches
                # the compute tier's record for the same window; the
                # span is the nominal eb-aligned window — the exact
                # covered span (short final window) lives in the
                # compute-tier record
                eb = self.cohort.eb
                for row in rows:
                    provenance.emit(
                        tenant=tid, window=row["window"],
                        wal_lo=row["window"] * eb,
                        wal_hi=(row["window"] + 1) * eb,
                        tier="serve", program="serve",
                        summary=row["summary"])
            out[tid] = rows
            self.results.setdefault(tid, []).extend(rows)
            self._stats["windows"] += len(rows)
            if self._results_file is not None:
                for row in rows:
                    self._results_file.write(json.dumps(row) + "\n")
                self._results_file.flush()
        if out:
            self._fanout(out)
        return out

    def _any_ready(self) -> bool:
        # quarantined tenants never count as ready: their queues are
        # suspended (probation drains them opportunistically when the
        # healthy tenants pump), and drain() must terminate even with
        # a still-poisoned stream's backlog queued — those edges are
        # safe in the WAL/DLQ, not lost
        with self._lock:
            return any(t.queued >= self.cohort.eb or
                       (t.closing and t.queued)
                       for t in self.cohort.tenants.values()
                       if not t.closed and t.tier != "quarantined")

    # ------------------------------------------------------------------
    # file-tail sources
    # ------------------------------------------------------------------
    def attach_file_tail(self, path: str, tenant,
                         poll_s: float = 0.2) -> None:
        """Follow a growing edge file into one tenant's queue through
        the same journaled feed path the socket uses. Backpressure is
        ridden politely (sleep the deterministic hint, retry); the
        tail stops at drain (its final partial line flushes first)."""
        from ..io import sources

        with self._ingest_lock:
            if str(tenant) not in self.cohort.tenants:
                self.cohort.admit(tenant)
        stop = threading.Event()

        def _tail():
            attempt = 0
            for s, d, _ts in sources.tail_edge_file(
                    path, stop, poll_s=poll_s):
                s = np.asarray(s, np.int32)
                d = np.asarray(d, np.int32)
                while True:
                    try:
                        with self._ingest_lock:
                            if self._pump_busy.is_set():
                                self._stats["overlap_feeds"] += 1
                            self.cohort.feed(tenant, s, d)
                        self._wake_pump()
                        attempt = 0
                        break
                    except TenantBackpressure:
                        time.sleep(resilience.backoff_s(attempt))
                        attempt += 1
                        if stop.is_set():
                            telemetry.event(
                                "serve_tail_dropped", durable=True,
                                tenant=str(tenant), path=path,
                                edges=int(len(s)))
                            return

        t = threading.Thread(target=_tail, daemon=True,
                             name="gs-serve-tail")
        t.start()
        self._tails.append((t, stop))

    # ------------------------------------------------------------------
    # drain & shutdown
    # ------------------------------------------------------------------
    def request_drain(self) -> None:
        """Signal-safe drain request (the SIGTERM handler body);
        `serve_until_drained()` notices and runs drain()."""
        self._drain_req.set()

    def drain(self, deadline_s: Optional[float] = None) -> dict:
        """Graceful shutdown: stop accepting, let in-flight requests
        finish (force-close past the deadline), stop the tails, pump
        every queue dry, flush a checkpoint per tenant, seal the
        journal. Idempotent; returns a summary dict."""
        with self._drain_lock:
            if self._drained is not None:
                return self._drained
            deadline = (drain_deadline_s() if deadline_s is None
                        else deadline_s)
            telemetry.event("serve_drain", durable=True,
                            phase="begin", port=self.port)
            self._draining.set()
            try:
                self._listener.close()
            except OSError:
                pass
            t0 = time.monotonic()
            for t in list(self._conn_threads):
                left = (None if deadline <= 0
                        else max(0.0, deadline
                                 - (time.monotonic() - t0)))
                t.join(left)
            forced = 0
            with self._lock:
                for conn in self._conns.values():
                    forced += 1
                    try:
                        conn.close()
                    except OSError:
                        pass
                self._conns.clear()
            for t, stop in self._tails:
                stop.set()
            for t, _stop in self._tails:
                t.join()
            # async pump: every ingest source is now quiet — stop the
            # pump thread BEFORE the dry loop so exactly one pumper
            # (this thread) runs the tail of the stream
            self._join_pump()
            # pump the queues DRY: every window that was accepted is
            # finalized and delivered to the sink before we seal
            drained_windows = 0
            while self._any_ready():
                drained_windows += sum(
                    len(v) for v in self.pump_once().values())
            # subscribers saw every drained row (fan-out runs inside
            # _emit); close their mailboxes so sender threads exit
            for cid in list(self._subs):
                self._drop_sub(cid)
            with self._pump_mutex:
                with self._ingest_lock:
                    self.cohort.checkpoint_all()
                    self.cohort.seal_wal()
                # hand the cohort back to the direct-pump shape: a
                # cohort outliving its server must emit latency
                # records at finalize again, and nothing still
                # pending will ever see a delivered() call. Settle is
                # LANE-SCOPED to this cohort's tenants — another
                # server's pending records are not ours to flush.
                self.cohort.defer_delivery = False
                lanes = list(self.cohort.tenants)
            for tid in lanes:
                latency.settle(tid)
            if self._results_file is not None:
                self._results_file.flush()
                os.fsync(self._results_file.fileno())
            summary = {
                "drained_windows": drained_windows,
                "forced_connections": forced,
                "windows_total": self._stats["windows"],
                "sealed": True,
            }
            telemetry.event("serve_drain", durable=True,
                            phase="sealed", **summary)
            metrics.counter_inc("gs_serve_drains_total")
            self._drained = summary
            return summary

    def serve_until_drained(self,
                            pump_interval_s: float = 0.02) -> dict:
        """The standalone main loop: install the SIGTERM→drain hook,
        pump whenever any tenant has a window ready, drain when
        asked. Returns drain()'s summary (the caller exits 0)."""
        import signal

        def _on_term(signum, frame):
            # flag only — drain runs on this (main) thread below, and
            # we deliberately do NOT chain the prior handler: the
            # whole point is to exit 0 after a clean drain, not to
            # re-deliver a fatal SIGTERM
            self.request_drain()

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except ValueError:
            pass  # not the main thread: caller owns the signal story
        if self._accept_thread is None:
            self.start()
        while not self._drain_req.is_set() and not self.fatal:
            if self.pump_mode != "async" and self._any_ready():
                # sync: the main loop IS the pump; async: the pump
                # thread owns dispatch and this loop only waits
                self.pump_once()
            else:
                time.sleep(pump_interval_s)
        return self.drain()

    def close(self) -> None:
        """Hard teardown for tests (no drain semantics)."""
        self._draining.set()
        self._join_pump()
        for cid in list(self._subs):
            self._drop_sub(cid)
        try:
            self._listener.close()
        except OSError:
            pass
        for _t, stop in self._tails:
            stop.set()
        with self._lock:
            for conn in self._conns.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()
        if self._results_file is not None:
            try:
                self._results_file.close()
            except OSError:
                pass
        # see drain(): a cohort outliving this server emits at
        # finalize again, and still-pending records of ITS lanes
        # settle now (lane-scoped — never another server's)
        self.cohort.defer_delivery = False
        for tid in list(self.cohort.tenants):
            latency.settle(tid)
        metrics.unregister_health_section("serve")

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def _health_section(self) -> dict:
        with self._lock:
            stats = dict(self._stats)
            active = len(self._conns)
            wal = self.cohort._wal
            # per-tenant queue DEPTH and AGE (the self-throttle
            # signal: depth says how much is queued, age says how
            # stale the oldest queued edge already is)
            queues = {
                tid: {"edges": int(t.queued),
                      "age_s": (None if t.queued == 0
                                else latency.queue_age(tid))}
                for tid, t in self.cohort.tenants.items()
                if not t.closed}
        sec = {
            "port": self.port,
            "pump": self.pump_mode,
            "draining": self._draining.is_set(),
            "active_connections": active,
            "tails": len(self._tails),
            "queues": queues,
            "latency": latency.health_section(),
            **stats,
        }
        with self._lock:
            quarantined = self.cohort.quarantined()
        if quarantined:
            sec["quarantined"] = quarantined
        dlq = sanitize_mod.dlq_status()
        if dlq is not None:
            # DLQ depth on the status surface: how many rejected
            # records an operator has to triage (tools/dlq_report.py)
            sec["dlq"] = dlq
        if sanitize_mod.enabled():
            sec["sanitize"] = sanitize_mod.mode()
        if wal is not None:
            offs = wal.offsets()
            sec["wal"] = {"tenants": len(offs),
                          "edges": sum(offs.values()),
                          "sealed": wal.sealed}
        return sec


class ServeClient:
    """Minimal loopback client of the wire protocol (tests, the CI
    smoke gate, tools/chaos_run.py serve leg)."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout: float = 30.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self._buf = b""
        import collections
        self._events = collections.deque()  # queued subscription rows

    def request(self, **req) -> dict:
        self.sock.sendall((json.dumps(req) + "\n").encode())
        while True:
            nl = self._buf.find(b"\n")
            if nl >= 0:
                line, self._buf = self._buf[:nl], self._buf[nl + 1:]
                resp = json.loads(line)
                if resp.get("event") == "window":
                    # a push raced this request's reply: keep it for
                    # next_window(), keep reading for the reply
                    self._events.append(resp)
                    continue
                return resp
            chunk = self.sock.recv(1 << 20)
            if not chunk:
                raise ConnectionError(
                    "server closed the connection mid-request "
                    "(killed, shed, or draining)")
            self._buf += chunk

    def admit(self, tenant, **kw) -> dict:
        return self.request(op="admit", tenant=tenant, **kw)

    def feed(self, tenant, src, dst, ts=None) -> dict:
        req = dict(op="feed", tenant=tenant,
                   src=np.asarray(src).tolist(),
                   dst=np.asarray(dst).tolist())
        if ts is not None:
            req["ts"] = np.asarray(ts).tolist()
        return self.request(**req)

    def pump(self) -> dict:
        return self.request(op="pump")

    def subscribe(self, tenant="*") -> dict:
        """Arm this connection for pushed WindowResult rows; pushes
        that interleave with later request/response pairs are queued
        and returned by next_window()."""
        return self.request(op="subscribe", tenant=tenant)

    def next_window(self, timeout: Optional[float] = None) -> dict:
        """Block for the next pushed `event: window` row (queued
        pushes first). Raises socket.timeout past `timeout`."""
        if self._events:
            return self._events.popleft()
        old = self.sock.gettimeout()
        if timeout is not None:
            self.sock.settimeout(timeout)
        try:
            while True:
                nl = self._buf.find(b"\n")
                if nl >= 0:
                    line, self._buf = (self._buf[:nl],
                                       self._buf[nl + 1:])
                    resp = json.loads(line)
                    if resp.get("event") == "window":
                        return resp
                    continue  # a stale response: not ours to keep
                chunk = self.sock.recv(1 << 20)
                if not chunk:
                    raise ConnectionError(
                        "server closed the subscription")
                self._buf += chunk
        finally:
            self.sock.settimeout(old)

    def close_tenant(self, tenant) -> dict:
        return self.request(op="close", tenant=tenant)

    def status(self) -> dict:
        return self.request(op="status")

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# ----------------------------------------------------------------------
# standalone runner
# ----------------------------------------------------------------------
def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0])
    ap.add_argument("--edge-bucket", type=int, default=512)
    ap.add_argument("--vertex-bucket", type=int, default=1024)
    ap.add_argument("--port", type=int, default=None,
                    help="TCP port (default GS_SERVE_PORT; 0 = "
                         "ephemeral)")
    ap.add_argument("--port-file", default=None,
                    help="write the bound port here (ephemeral-port "
                         "coordination for harnesses)")
    ap.add_argument("--wal", default=None,
                    help="write-ahead journal directory (arms "
                         "durable ingest)")
    ap.add_argument("--ckpt", default=None,
                    help="per-tenant checkpoint directory")
    ap.add_argument("--ckpt-every", type=int, default=4,
                    help="checkpoint cadence in windows")
    ap.add_argument("--results", default=None,
                    help="append finalized window summaries here "
                         "(JSONL; at-least-once across recovery)")
    ap.add_argument("--recover", action="store_true",
                    help="resume checkpoints + replay the journal "
                         "suffix before serving")
    ap.add_argument("--tail", action="append", default=[],
                    metavar="PATH:TENANT",
                    help="file-tail source (repeatable)")
    args = ap.parse_args(argv)

    cohort = TenantCohort(edge_bucket=args.edge_bucket,
                          vertex_bucket=args.vertex_bucket)
    if args.wal:
        cohort.enable_wal(args.wal)
    if args.ckpt:
        cohort.enable_auto_checkpoint(
            args.ckpt, every_n_windows=args.ckpt_every)
    if args.recover:
        if not args.wal:
            ap.error("--recover needs --wal")
        info = cohort.recover()
        print("recovered: %s" % json.dumps(
            {k: v for k, v in info.items() if k != "resumed"}))
    server = StreamServer(cohort, port=args.port,
                          results_path=args.results).start()
    print("serving on %s:%d" % (server.host, server.port),
          flush=True)
    if args.port_file:
        with open(args.port_file, "w") as f:
            f.write(str(server.port))
    for spec in args.tail:
        path, _, tenant = spec.rpartition(":")
        server.attach_file_tail(path, tenant)
    summary = server.serve_until_drained()
    print("drained: %s" % json.dumps(summary), flush=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())

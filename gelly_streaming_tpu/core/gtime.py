"""Time primitives for the streaming runtime.

Covers the slice of Flink time semantics the reference uses
(reference: SimpleEdgeStream.java:74,90-94 — IngestionTime default,
EventTime with an ascending timestamp extractor; windowing via
`timeWindow(Time.of(...))`).
"""

from __future__ import annotations

import dataclasses
import enum
import time as _pytime


class TimeCharacteristic(enum.Enum):
    """How records acquire timestamps (reference: SimpleEdgeStream.java:74,91)."""

    INGESTION_TIME = "ingestion"
    EVENT_TIME = "event"


@dataclasses.dataclass(frozen=True)
class Time:
    """A duration in milliseconds (reference: Flink `Time.of(n, unit)`)."""

    milliseconds: int

    @staticmethod
    def of(value: int, unit: str = "ms") -> "Time":
        factor = {
            "ms": 1,
            "milliseconds": 1,
            "s": 1000,
            "seconds": 1000,
            "min": 60_000,
            "minutes": 60_000,
            "h": 3_600_000,
            "hours": 3_600_000,
            "d": 86_400_000,
            "days": 86_400_000,
        }[unit]
        return Time(int(value) * factor)

    @staticmethod
    def milliseconds_of(value: int) -> "Time":
        return Time(int(value))

    @staticmethod
    def seconds(value: int) -> "Time":
        return Time(int(value) * 1000)

    @staticmethod
    def minutes(value: int) -> "Time":
        return Time(int(value) * 60_000)

    @staticmethod
    def hours(value: int) -> "Time":
        return Time(int(value) * 3_600_000)

    @staticmethod
    def days(value: int) -> "Time":
        return Time(int(value) * 86_400_000)


class Clock:
    """Source of ingestion timestamps (ms)."""

    def now_ms(self) -> int:
        raise NotImplementedError


class SystemClock(Clock):
    def now_ms(self) -> int:
        return int(_pytime.time() * 1000)


class ManualClock(Clock):
    """Deterministic clock for tests: fixed time, advanced explicitly."""

    def __init__(self, start_ms: int = 0):
        self._now = start_ms

    def now_ms(self) -> int:
        return self._now

    def advance(self, delta_ms: int) -> None:
        self._now += delta_ms


def window_start(timestamp_ms: int, size_ms: int) -> int:
    """Tumbling-window start for a timestamp (Flink TimeWindow semantics)."""
    return timestamp_ms - (timestamp_ms % size_ms)


def window_end(timestamp_ms: int, size_ms: int) -> int:
    return window_start(timestamp_ms, size_ms) + size_ms


def window_max_timestamp(timestamp_ms: int, size_ms: int) -> int:
    """Flink `TimeWindow.maxTimestamp()` = end - 1 (WindowTriangles.java:137)."""
    return window_end(timestamp_ms, size_ms) - 1


class AscendingTimestampExtractor:
    """Event-time extractor base (reference: SimpleEdgeStream.java:90-94).

    Subclasses (or instances constructed with `fn`) return the event-time
    timestamp in ms for each element; timestamps are assumed ascending.
    """

    def __init__(self, fn=None):
        self._fn = fn

    def extract_ascending_timestamp(self, element) -> int:
        if self._fn is None:
            raise NotImplementedError
        return self._fn(element)

"""Lazy operator DAG for the host-side stream driver.

The reference builds a Flink operator graph and submits it with
`env.execute()` (e.g. WindowTriangles.java:57-74). We keep the same lazy
programming model: transformations append `OpNode`s to a DAG; execution
(core/runtime.py) pushes timestamped record batches through it. Hot
operators carry a device ("jax") execution spec and run as compiled XLA
kernels over columnar window batches instead of per-record host code.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional, Sequence

_ids = itertools.count()


class OpNode:
    """One operator in the dataflow plan.

    kind: source | map | flat_map | filter | key_by | window | window_all |
          union | broadcast | project | iterate | iterate_body | sink |
          neighborhood | graph_aggregation | custom
    """

    def __init__(self, kind: str, parents: Sequence["OpNode"] = (), **params: Any):
        self.id = next(_ids)
        self.kind = kind
        self.parents = list(parents)
        self.params = params
        self.parallelism: Optional[int] = None

    def __repr__(self) -> str:
        return f"OpNode({self.kind}#{self.id})"


class KeySpec:
    """How a keyed exchange extracts keys — field positions or a selector fn.

    Mirrors Flink `keyBy(fields...)` / `keyBy(KeySelector)` as used at
    SimpleEdgeStream.java:159-167 and WindowTriangles.java:64.
    """

    def __init__(self, fields: Optional[Sequence[int]] = None,
                 selector: Optional[Callable[[Any], Any]] = None):
        if (fields is None) == (selector is None):
            raise ValueError("exactly one of fields/selector required")
        self.fields = tuple(fields) if fields is not None else None
        self.selector = selector

    def key_of(self, value: Any) -> Any:
        if self.selector is not None:
            return self.selector(value)
        if len(self.fields) == 1:
            return value[self.fields[0]]
        return tuple(value[f] for f in self.fields)

"""Backend/platform selection helpers.

The TPU-tunnel PJRT plugin in this environment registers itself in
every interpreter and is initialized even when JAX_PLATFORMS=cpu, so a
CPU-only run can still block on the (single) hardware chip. `use_cpu()`
pins a hermetic CPU backend — used by tests and by example CLIs when
the hardware isn't wanted; `cpu_mesh(n)` additionally requests an
n-device virtual host platform for sharding tests (must be called
before jax creates a backend).
"""

from __future__ import annotations

import os


def use_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        # Import pallas while the TPU-family platform is still registered:
        # its lowering-rule registration needs the 'tpu' mlir platform,
        # which disappears once the tunnel factory is popped below.
        try:
            import jax.experimental.pallas  # noqa: F401
            import jax.experimental.pallas.tpu  # noqa: F401
        except Exception:  # gslint: disable=except-hygiene (optional pallas probe: absence just skips TPU lowering registration)
            pass
        from jax._src import xla_bridge

        for name in [n for n in xla_bridge._backend_factories if n != "cpu"]:
            xla_bridge._backend_factories.pop(name, None)
    except Exception:  # gslint: disable=except-hygiene (pre-jax env pin: on failure jax keeps its own backend selection)
        pass


def cpu_mesh(n_devices: int = 8) -> None:
    """Virtual n-device CPU platform (the multi-chip test rig)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    use_cpu()

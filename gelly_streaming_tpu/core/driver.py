"""Columnar streaming analytics driver — the production ingest→device
path.

The record-level DataStream runtime (core/runtime.py) reproduces the
reference's per-record operator semantics for API parity; this driver
is the TPU-first way to run the same analytics at stream rate
(SURVEY.md §7 design stance): no per-edge Python objects anywhere.

    file → native parse (native/ingest.cpp)
         → tumbling event-time window assignment (Flink TimeWindow
           floor semantics, SimpleEdgeStream.java:90-94)
         → incremental vertex interning (C++ hash map)
         → per-window fixed-shape device kernels with carried state:
             degrees   — running degree vector
                         (continuous semantics of SimpleEdgeStream.java:465-482,
                          emitted per window batch)
             cc        — carried min-label components
                         (library/ConnectedComponents.java via ops/unionfind)
             bipartite — carried double-cover 2-coloring
                         (library/BipartitenessCheck.java)
             triangles — exact per-window count
                         (example/WindowTriangles.java via ops/triangles)

Single chip by default; pass a `jax.sharding.Mesh` to run every kernel
sharded over it (parallel/sharded.py — P1 edges, P2/P6 collective
merges). Vertex and edge buckets grow by doubling, so an unbounded
stream triggers only O(log V) recompiles.
"""

from __future__ import annotations

import contextlib
import dataclasses
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import List, Optional, Sequence

import numpy as np

from .. import native
from ..ops import delta_egress
from ..ops import host_snapshot
from ..ops import ingress_pipeline
from ..ops import segment as seg_ops
from ..ops import triangles as tri_ops
from ..ops import unionfind
from ..utils import checkpoint
from ..utils import faults
from ..utils import knobs
from ..utils import latency
from ..utils import metrics
from ..utils import provenance
from ..utils import resilience
from ..utils import sanitize as sanitize_mod
from ..utils import telemetry
from ..utils import wal as wal_mod
from ..utils.interning import make_interner, parallel_intern_arrays
from ..utils.tracing import StepTimer


def _snapshot_view(a: np.ndarray, row_size: int = 0) -> np.ndarray:
    """Read-only array with snapshot semantics. When the slice covers
    most of its backing row (steady state: nv ≈ vb — exactly when the
    per-window copy is what costs, ~20% of the 10M-edge driver leg's
    host time), return a frozen VIEW: the backing stack is fresh per
    chunk and never written after extraction (the device scan's
    outputs are already immutable; the native tier's are np.empty
    slabs the kernel filled before extraction). When the slice is
    small relative to `row_size` (early stream), return an owned copy
    instead — a tiny window's field must not pin its chunk's whole
    [W, vb] stack in memory for consumers that retain results. Every
    returned array is read-only, so the 'fields are snapshots, never
    live state' contract is uniform across tiers and paths."""
    if row_size and 4 * a.size < row_size:
        a = a.copy()
    else:
        a = a[:]
    a.flags.writeable = False
    return a


def _frozen_delta(idx: np.ndarray, vals: np.ndarray) -> tuple:
    """Freeze a (changed ids, new values) delta pair — the delta
    streams share the WindowResult read-only snapshot contract (both
    arrays are fresh fancy-indexed copies, never aliases of carried
    state, so freezing costs nothing)."""
    idx.flags.writeable = False
    vals.flags.writeable = False
    return (idx, vals)


def _build_snapshot_scan(vb: int, analytics: tuple,
                         deltas: bool = False, egress: str = "full",
                         cap: int = 0, donate: bool = False):
    """One jitted lax.scan over a [W, eb] window stack, carrying
    (degrees, cc labels, double-cover labels) and emitting PER-WINDOW
    snapshots — the driver's batched single-chip fast path (sharded
    meshes use parallel.sharded.make_sharded_snapshot_scan): one
    dispatch + one d2h per run_arrays call instead of one per analytic
    per window (dispatch latency through a tunneled chip ~0.2s
    dominates per-window economics). Cover layout matches the driver's
    host state: (+) = v, (−) = vb + v, sentinel slot 2vb.

    With `deltas`, each analytic also emits a per-window changed-slot
    bool mask over [:vb] (new state vs the scan carry — computed
    on-device, so a consumer of the reference's improving streams
    (SimpleEdgeStream.java:473-481) can reconstruct per-update records
    from snapshot + mask without diffing full vectors on host).

    With egress="delta" (ops/delta_egress), the per-window output is
    the COMPACT changed-slot wire instead of full vectors: per analytic
    an int32 count plus [cap]-sized (indices, new values) rows —
    2-3 orders of magnitude fewer d2h bytes on settled streams; the
    driver reconstructs full snapshots from its host mirrors, and a
    count exceeding `cap` routes the chunk to the bit-exact host fold.
    The full masks are then NOT emitted (the wire subsumes them).

    With `donate` (the RESIDENT tier, ops/resident_engine), the carry
    argument is donated where the backend honors donation — the
    ResidentState slabs update in place across super-batches instead
    of being re-allocated per dispatch — and under delta egress the
    final cover row is emitted as an explicit FRESH output
    (`cover_final`): the next super-batch donates the carry buffers,
    so the drain must never alias them."""
    import jax
    import jax.numpy as jnp

    from ..ops import delta_egress
    from ..ops import unionfind as uf

    want_deg = "degrees" in analytics
    want_cc = "cc" in analytics
    want_bip = "bipartite" in analytics
    delta_out = egress == "delta"

    def body(carry, xs):
        deg, labels, cover = carry
        src, dst, valid = xs
        s = jnp.where(valid, src, vb)
        d = jnp.where(valid, dst, vb)
        outs = {}
        if want_deg:
            new_deg = deg.at[s].add(1).at[d].add(1)  # slot vb: pads
            chg = (new_deg[:vb] != deg[:vb]) \
                if (deltas or delta_out) else None
            if delta_out:
                (outs["deg_cnt"], outs["deg_idx"],
                 outs["deg_val"]) = delta_egress.compact_changed(
                    chg, new_deg[:vb], cap, 0)
            else:
                if deltas:
                    outs["deg_chg"] = chg
                outs["deg"] = new_deg
            deg = new_deg
        if want_cc:
            new_labels = uf.cc_fixpoint(labels, s, d)
            chg = (new_labels[:vb] != labels[:vb]) \
                if (deltas or delta_out) else None
            if delta_out:
                (outs["labels_cnt"], outs["labels_idx"],
                 outs["labels_val"]) = delta_egress.compact_changed(
                    chg, new_labels[:vb], cap, 0)
            else:
                if deltas:
                    outs["labels_chg"] = chg
                outs["labels"] = new_labels
            labels = new_labels
        if want_bip:
            sent2 = 2 * vb
            s2 = jnp.concatenate([
                jnp.where(valid, s, sent2),
                jnp.where(valid, s + vb, sent2)])
            d2 = jnp.concatenate([
                jnp.where(valid, d + vb, sent2),
                jnp.where(valid, d, sent2)])
            new_cover = uf.cc_fixpoint(cover, s2, d2)
            if deltas or delta_out:
                # the consumer-visible value is the odd flag, so the
                # mask (and the delta wire) tracks IT, not raw labels
                new_odd = new_cover[:vb] == new_cover[vb:2 * vb]
                chg = new_odd != (cover[:vb] == cover[vb:2 * vb])
            if delta_out:
                (outs["cover_cnt"], outs["cover_idx"],
                 outs["cover_val"]) = delta_egress.compact_changed(
                    chg, new_odd, cap, 0)
            else:
                if deltas:
                    outs["cover_chg"] = chg
                outs["cover"] = new_cover
            cover = new_cover
        return (deg, labels, cover), outs

    if donate:
        from ..ops import resident_engine

        def run_fn(carry, s_w, d_w, valid_w):
            new_carry, outs = jax.lax.scan(body, carry,
                                           (s_w, d_w, valid_w))
            if delta_out and want_bip:
                # explicit copy primitive — a folded-away no-op like
                # `+ 0` would leave this the same HLO value as the
                # carry's cover, whose donated buffer super-batch N+1
                # overwrites before chunk N's finalize reads it
                outs["cover_final"] = jnp.copy(new_carry[2])
            return new_carry, outs

        return jax.jit(run_fn, **resident_engine.donate_kw())

    @jax.jit
    def run(carry, s_w, d_w, valid_w):
        return jax.lax.scan(body, carry, (s_w, d_w, valid_w))

    return run


_SNAPSHOT_TIER = None  # resolved once per process (reset below)


def _reset_snapshot_tier() -> None:
    """Test hook: forget the memoized snapshot-tier selection."""
    global _SNAPSHOT_TIER
    _SNAPSHOT_TIER = None


def resolve_snapshot_tier() -> str:
    """Batched snapshot-analytics tier: the device scan by default;
    the RESIDENT megakernel (ops/resident_engine) when the GS_RESIDENT
    pin or committed backend-matched `resident_ab` rows select it —
    that gate compares resident against the best committed alternative
    (scan AND native), so adopting it can never regress a stream
    native already serves faster; else the native C++ carried
    union-find (native.snapshot_windows) only when (a) this process
    runs a CPU backend — on chip the scan always stands — and (b)
    committed backend-matched `host_snapshot` rows
    (tools/profile_kernels.py) all show parity and a ≥5% win, and
    (c) the library exports the symbol. The same measured-default
    policy as ops/triangles._resolve_stream_impl."""
    global _SNAPSHOT_TIER
    if _SNAPSHOT_TIER is not None:
        return _SNAPSHOT_TIER
    tier = "scan"
    try:
        from ..ops import resident_engine

        if resident_engine.resolve_resident():
            _SNAPSHOT_TIER = "resident"
            return _SNAPSHOT_TIER
        if _jax_backend() == "cpu":
            perf = tri_ops._load_matching_perf("cpu")
            if (tri_ops.rows_clear_bar(
                    (perf or {}).get("host_snapshot", []),
                    "native_edges_per_s", "scan_edges_per_s")
                    and native.snapshot_available()):
                tier = "native"
    except Exception as e:
        telemetry.event("selection.fallback", durable=True,
                        component="snapshot_tier", fallback=tier,
                        error="%s: %s" % (type(e).__name__, e))
    _SNAPSHOT_TIER = tier
    return tier


def _jax_backend() -> str:
    import jax as _jax

    return _jax.default_backend()


@dataclasses.dataclass
class WindowResult:
    """Per-window analytics snapshot. Vertex-indexed arrays are in dense
    slot order; `vertex_ids[slot]` maps back to external ids.

    EVERY array field is a READ-ONLY snapshot — vertex_ids, degrees,
    cc_labels, bipartite_odd, and the arrays inside the delta_*
    tuples, uniformly across tiers (device scan / native / sharded)
    and dispatch paths (batched / per-window). They are often
    zero-copy views of the chunk's output stacks (_snapshot_view) and
    never alias live carried state, so consecutive windows' snapshots
    are independently stable; consumers that need a mutable array
    call `.copy()`. The contract is documented in README.md
    ("WindowResult snapshots are read-only")."""

    window_start: int
    num_edges: int
    vertex_ids: np.ndarray                      # external id per slot
    degrees: Optional[np.ndarray] = None        # running, per slot
    cc_labels: Optional[np.ndarray] = None      # carried min-label slots
    bipartite_odd: Optional[np.ndarray] = None  # carried odd-cycle flag
    triangles: Optional[int] = None             # exact, this window only
    # emit_deltas=True: per-window changed-slot streams — (slot ids,
    # new values) for every slot whose value differs from the previous
    # window's snapshot (the per-update improving-stream analog of
    # SimpleEdgeStream.java:473-481; start states are all-zero degrees,
    # identity labels, all-False odd). Slots are in the same dense slot
    # space as the snapshot arrays.
    delta_degrees: Optional[tuple] = None       # (int32 ids, int64 vals)
    delta_cc: Optional[tuple] = None            # (int32 ids, int32 vals)
    delta_bipartite: Optional[tuple] = None     # (int32 ids, bool vals)
    # latency plane (utils/latency.py, GS_LATENCY=1): this window's
    # ingest→deliver record — {"e2e_s", "stages": {...}, "replayed"} —
    # joined back to the admission stamp of its completing edge.
    # Always None disarmed (the digest-parity contract).
    latency: Optional[dict] = None


class StreamingAnalyticsDriver:
    ANALYTICS = ("degrees", "cc", "bipartite", "triangles")

    def __init__(self, window_ms: int,
                 analytics: Sequence[str] = ANALYTICS,
                 vertex_bucket: int = 1 << 12,
                 edge_bucket: int = 1 << 12,
                 mesh=None, tracing: bool = False,
                 emit_deltas: bool = False,
                 snapshot_tier: str = None,
                 egress: str = None,
                 tenant: str = None,
                 slide: int = None):
        unknown = set(analytics) - set(self.ANALYTICS)
        if unknown:
            raise ValueError(f"unknown analytics: {sorted(unknown)}")
        if snapshot_tier not in (None, "resident", "scan", "native",
                                 "host"):
            raise ValueError(f"unknown snapshot_tier: {snapshot_tier!r}")
        if snapshot_tier == "resident" and mesh is not None:
            raise ValueError(
                "the resident tier is single-chip: a mesh session's "
                "base tier is the sharded engine (its demotion ladder "
                "re-enters on scan, never resident)")
        if snapshot_tier == "native" and not native.snapshot_available():
            raise ValueError("native snapshot tier pinned but "
                             "libgsnative lacks gs_snapshot_windows")
        if egress not in (None, "full", "delta"):
            raise ValueError(f"unknown egress: {egress!r}")
        # d2h egress of the batched snapshot scan: explicit pin (tests,
        # tools/egress_ab.py) or committed-evidence resolution
        # (ops/delta_egress.resolve_egress); sharded meshes always full
        self._egress_pin = egress
        # multi-tenant label (core/tenancy.py serving story): when this
        # driver serves ONE stream of a multi-tenant deployment, its
        # health-plane marks and demotion records carry the tenant so
        # /healthz per-tenant liveness and the degradations evidence
        # name the stream, not just "driver"
        self.tenant = None if tenant is None else str(tenant)
        self.window_ms = window_ms
        self.analytics = tuple(analytics)
        # batched snapshot analytics tier: explicit pin (tests, the
        # profiler's A/B) or committed-evidence resolution
        self._snapshot_tier = snapshot_tier
        self.emit_deltas = bool(emit_deltas)
        self.mesh = mesh
        self.timer = StepTimer() if tracing else None
        self.interner = make_interner(np.array([0]))
        self._ext_ids = np.zeros(0, np.int64)  # slot → external id cache
        self.vb = seg_ops.bucket_size(vertex_bucket)
        self.eb = seg_ops.bucket_size(edge_bucket)
        # sliding windows via pane composition (DESIGN.md §22): the
        # count-based path cuts PANE-sized emissions (`slide` edges)
        # and recomputes the per-window triangle count off the composed
        # slab of the last panes_per_window panes — each edge is folded
        # into the cumulative analytics exactly once. slide=None (and
        # GS_SLIDE=0) keeps the tumbling legacy path bit-identical.
        if slide is None:
            slide = knobs.get_int("GS_SLIDE") or 0
        slide = int(slide) or None
        if slide is not None:
            if mesh is not None:
                raise ValueError(
                    "sliding windows are single-chip: the sharded "
                    "engine cuts whole-window slabs across the mesh "
                    "(compose panes upstream or drop slide=)")
            if (seg_ops.bucket_size(slide) != slide
                    or self.eb % slide != 0):
                raise ValueError(
                    "slide must be a power of two dividing the window "
                    "size (%d), got %d" % (self.eb, slide))
        self.slide = slide
        self._wp = (self.eb // slide) if slide else 1
        self._pane_ring: list = []  # last ≤ wp−1 interned (s, d) panes
        self._degrees = np.zeros(0, np.int64)
        self._deg_state = None    # device-carried degrees (single-chip)
        self._cc = np.zeros(0, np.int32)
        self._bip = np.zeros(0, np.int32)
        self._tri_kernel = None
        self._engine = None       # sharded: ShardedWindowEngine
        self._sh_tri = None       # sharded: ShardedTriangleWindowKernel
        self._tri_pending = None  # batched-dispatch collector (transient)
        self.windows_done = 0     # survives checkpoints: resume cursor
        self.edges_done = 0       # count-based window_start offset
        self._closed_partial = False  # count-based misuse guard
        self._ckpt_path = None
        self._ckpt_policy = None  # utils.checkpoint.CheckpointPolicy
        self._pending_ckpt = []  # staged (windows_done, state) — see
        self._emitted = None     # _stage_ckpt; not-None inside stream_file
        # write-ahead edge journal (utils/wal.py): armed by
        # enable_wal(), appended to in run_arrays BEFORE windowing —
        # the durable source the live (non-file) feed path never had.
        # _in_stream suppresses journaling under stream_file: a
        # file-backed source is already replayable, and journaling it
        # would double-replay on recovery.
        self._wal = None
        self._wal_dir = None
        # GS_WAL_RETAIN bookkeeping: journal truncation at checkpoint
        # FLUSH boundaries, floored at the older kept generation
        self._wal_retention = wal_mod.RetentionCursor()
        self._in_stream = 0
        # cumulative fed edges incl. sanitizer rejects: the DLQ's
        # source-offset domain for this driver's admission boundary
        self._fed_edges = 0
        # tier demotion (utils/resilience): a persistent device failure
        # in the batched snapshot path demotes scan→native→host
        # mid-stream instead of killing the job; None = not demoted
        self._demoted_tier = None
        self._demoted_at = 0      # windows_done when last demoted
        self._demotions = []      # event dicts (also in the registry)
        # online dispatch tuner of the batched snapshot scan
        # (ops/autotune; built lazily, None with GS_AUTOTUNE=0)
        self._scan_tuner = None
        # resident tier (ops/resident_engine): its own tuner family
        # (windows-per-superbatch arms) and a per-call flag the scan
        # program cache keys on (_scan_key) — the resident programs
        # are a DISTINCT donated family
        self._resident_tuner = None
        self._resident_now = False

    def reset(self) -> None:
        """Clear all carried stream state (interner, analytics vectors,
        cursors) while keeping every compiled kernel, so warmup windows
        can be discarded without polluting a measured run."""
        self.interner = make_interner(np.array([0]))
        self._ext_ids = np.zeros(0, np.int64)
        self._degrees = np.zeros(0, np.int64)
        self._deg_state = None
        self._cc = np.zeros(0, np.int32)
        self._bip = np.zeros(0, np.int32)
        self.windows_done = 0
        self.edges_done = 0
        self._closed_partial = False
        self._pane_ring = []
        self._pending_ckpt = []
        if self._ckpt_policy is not None:
            # re-anchor the cadence: the cursor just rewound to 0, so
            # a stale high-water mark would suppress every due() until
            # the stream re-passed it
            self._ckpt_policy.mark(0)
        if self._engine is not None:
            self._engine.reset()

    # ------------------------------------------------------------------
    # bucket growth (O(log V) recompiles over an unbounded stream)
    # ------------------------------------------------------------------
    def _ensure_buckets(self, num_vertices: int, window_edges: int) -> None:
        vb_grew = eb_grew = False
        while num_vertices > self.vb:
            self.vb *= 2
            vb_grew = True
        while window_edges > self.eb:
            self.eb *= 2
            eb_grew = True
        first = self._tri_kernel is None and self._engine is None
        if not (vb_grew or eb_grew or first):
            return
        if (vb_grew or eb_grew) and self._scan_tuner is not None:
            # bucket growth changes the per-chunk economics the scan
            # tuner measured (and its cache identity): re-key it — the
            # incumbent survives as the prior, stale rates reset, and
            # the persisted cache re-seeds the new key when this shape
            # was tuned in an earlier run
            cap = self._scan_chunk()
            self._scan_tuner.rekey(
                self._scan_tuner_key(),
                space={"wb": sorted({max(1, cap // 4),
                                     max(1, cap // 2), cap})},
                initial={"wb": cap})
        if (vb_grew or eb_grew) and self._resident_tuner is not None:
            # same re-key-instead-of-discard contract for the resident
            # tier's windows-per-superbatch tuner: the incumbent
            # survives as the prior under the new bucket identity and
            # the persisted cache re-seeds it — without this, bucket
            # growth silently FROZE the resident arm at a dead key
            # (the ISSUE-9 arm-freezing fix, pinned by
            # tests/operations/test_resident.py)
            cap = self._resident_chunk()
            self._resident_tuner.rekey(
                self._resident_tuner_key(),
                space={"wb": sorted({max(1, cap // 4),
                                     max(1, cap // 2), cap})},
                initial={"wb": cap})
        if self.mesh is not None:
            from ..parallel.sharded import (ShardedTriangleWindowKernel,
                                            ShardedWindowEngine)

            if vb_grew or first:  # eb growth alone keeps the engine
                old = self._engine
                self._engine = ShardedWindowEngine(
                    self.mesh, num_vertices_bucket=self.vb)
                if old is not None:  # carry state into the wider bucket
                    st = old.state_dict()
                    new = self._engine.state_dict()
                    # np.array: the fresh state leaf is a read-only
                    # device view; only degree_state is patched in place
                    new["degree_state"] = np.array(new["degree_state"])
                    n_deg = len(st["degree_state"]) - 2
                    new["degree_state"][:n_deg] = st["degree_state"][:-2]
                    lab = np.arange(self.vb + 2, dtype=np.int32)
                    lab[:n_deg] = st["labels"][:-2]
                    new["labels"] = lab
                    if "bip_labels" in st:
                        # same cover re-layout as the single-chip path,
                        # plus the engine's two trailing sentinel slots
                        new["bip_labels"] = np.concatenate([
                            self._grow_cover(
                                np.asarray(st["bip_labels"][:-2]),
                                self.vb),
                            np.arange(2 * self.vb, 2 * self.vb + 2,
                                      dtype=np.int32)])
                    self._engine.load_state_dict(new)
            if "triangles" in self.analytics:
                self._sh_tri = ShardedTriangleWindowKernel(
                    self.mesh, edge_bucket=self.eb,
                    vertex_bucket=self.vb)
                if self._mesh_live():
                    # every TRIANGLE stream-chunk program compiles at
                    # (re)build time, never mid-stream; the final-flush
                    # analytics programs still first-compile at the
                    # flush — the one violation window scale_run's
                    # assert tolerates. A DEMOTED mesh skips the warm:
                    # compiling against a dead mesh is exactly the
                    # failure we demoted away from (re-promotion warms
                    # on first use instead).
                    self._sh_tri.warm_chunks()
        elif "triangles" in self.analytics:
            self._tri_kernel = tri_ops.TriangleWindowKernel(
                edge_bucket=self.eb, vertex_bucket=self.vb)
            self._tri_kernel.warm_chunks()
        if self.mesh is None:
            # keyed: triangle-less configs keep `first` True forever
            # (no kernel object exists to flip it), and re-warming per
            # window would put 1-3 empty dispatches on the hot path
            key = (self.vb, self.eb, self.analytics)
            if getattr(self, "_warmed_tail", None) != key:
                self._warm_tail_programs()
                self._warmed_tail = key

    def _warm_tail_programs(self) -> None:
        """Compile the per-window analytics programs at the steady
        (eb, vb) shapes by running each once on an EMPTY padded batch.

        Steady-state windows ride the batched snapshot scan; only a
        stream's final partial window falls onto the per-window path —
        which, unwarmed, first-compiled degree_update + cc_fixpoint
        (+ the double-cover form) at the stream TAIL, violating the
        zero-steady-state-compile discipline tools/endurance_run.py
        asserts. The empty batches hit exactly the runtime shapes (the
        edge_bucket clamp pads 0 edges up to eb) and touch no state."""
        import jax.numpy as jnp

        empty = np.zeros(0, np.int32)
        if "degrees" in self.analytics:
            sp = seg_ops.pad_to(empty, self.eb, fill=self.vb)
            seg_ops.degree_update(
                jnp.zeros(self.vb + 1, jnp.int32),
                jnp.asarray(sp), jnp.asarray(sp))
        if "cc" in self.analytics:
            unionfind.connected_components_with_labels(
                empty, empty, empty, 0, vertex_bucket=self.vb,
                edge_bucket=self.eb)
        if "bipartite" in self.analytics:
            unionfind.connected_components_with_labels(
                empty, empty, empty, 0, vertex_bucket=2 * self.vb,
                edge_bucket=2 * self.eb)

    # ------------------------------------------------------------------
    def run_file(self, path: str) -> List[WindowResult]:
        src, dst, ts = native.parse_edge_file(path)
        return self.run_arrays(src, dst, ts)

    def stream_file(self, path: str, chunk_bytes: int = 1 << 26,
                    resume: bool = False):
        """Generator over WindowResults for an arbitrarily large file,
        in bounded memory: the file is parsed in `chunk_bytes` pieces
        (default 64MB ≈ tens of windows per piece, so the batched
        fast path gets full dispatch batches; prefetched ahead in a
        producer thread)
        (io/sources.iter_edge_chunks) and the still-open final window
        of each piece is held back until the next piece closes it —
        tumbling windows never split at chunk boundaries.

        resume=True (after try_resume) skips the `edges_done` edges the
        restored checkpoint already folded into carried state, so
        re-feeding the same file never double-counts.

        Auto-checkpoints taken during the stream are staged and only
        flushed once every window they cover has been YIELDED to the
        consumer (_stage_ckpt) — a crash mid-stream therefore re-emits
        windows on resume (at-least-once) instead of silently dropping
        computed-but-never-delivered ones."""
        from ..io.sources import iter_edge_chunks

        if self._wal is not None:
            # wal_offset is DEFINED as edges_done, and stream_file
            # edges are deliberately never journaled (the file is its
            # own journal) — mixing the two sources would advance the
            # cursor past journaled live edges and make recovery skip
            # them. One driver, one source model.
            raise ValueError(
                "stream_file() on a journal-armed driver would skew "
                "the wal_offset/edges_done contract: use run_arrays "
                "(journaled live feed) OR file streaming, not both "
                "on one driver")
        to_skip = self.edges_done if resume else 0
        pend = (np.zeros(0, np.int64),) * 3
        timestamped = None
        self._emitted = self.windows_done
        self._in_stream += 1  # file source: already replayable, no WAL
        try:
            for src, dst, ts in iter_edge_chunks(path, chunk_bytes):
                if to_skip:
                    drop = min(to_skip, len(src))
                    src, dst, ts = src[drop:], dst[drop:], ts[drop:]
                    to_skip -= drop
                    if not len(src):
                        continue
                chunk_timestamped = bool(len(ts)) and int(ts.max()) >= 0
                if timestamped is None:
                    timestamped = chunk_timestamped
                elif timestamped != chunk_timestamped:
                    raise ValueError(
                        "mixed timestamped and untimestamped chunks")
                src = np.concatenate([pend[0], src])
                dst = np.concatenate([pend[1], dst])
                ts = np.concatenate([pend[2], ts])
                if timestamped:
                    if int(ts.min()) < 0:
                        raise ValueError(
                            "mixed timestamped and untimestamped rows")
                    starts = native.assign_windows(ts, self.window_ms)
                    open_from = int(np.searchsorted(starts, starts[-1]))
                else:
                    open_from = len(src) - (len(src) % self.eb)
                done = slice(0, open_from)
                if open_from:
                    yield from self._emit(self.run_arrays(
                        src[done], dst[done],
                        _starts=starts[done] if timestamped else None))
                pend = (src[open_from:], dst[open_from:], ts[open_from:])
            if len(pend[0]):
                yield from self._emit(self.run_arrays(
                    pend[0], pend[1],
                    pend[2] if timestamped else None))
        finally:
            # abandonment or completion: still-staged checkpoints
            # cover windows the consumer never received — drop them
            # (the last FLUSHED checkpoint stays ≤ what was delivered)
            self._pending_ckpt = []
            self._emitted = None
            self._in_stream -= 1

    def run_arrays(self, src: np.ndarray, dst: np.ndarray,
                   ts: Optional[np.ndarray] = None,
                   _starts: Optional[np.ndarray] = None
                   ) -> List[WindowResult]:
        """Process a (possibly partial) stream. With no timestamps,
        windows are count-based `edge_bucket`-sized chunks (the
        ingestion-time analog at a fixed batch rate). `_starts` lets
        stream_file pass its already-computed window assignment."""
        metrics.on_stream_start("driver", tenant=self.tenant)
        # latency plane: every batch is stamped at THIS admission
        # boundary (the driver's live-feed entry). The WAL ts column
        # stays reserved for EVENT time on this path (replay feeds it
        # back through event-time windowing), so driver replays
        # re-stamp at the replay moment rather than overload it.
        lat_t0 = latency.clock() if latency.enabled() else None
        # "admit" fault site + armed sanitizer (utils/sanitize): the
        # driver's admission boundary. Runs BEFORE the journal below,
        # so a journaled batch is always clean and replay can never
        # re-raise a rejection; the keep-mask filters the aligned
        # ts/_starts columns so event-time windowing stays consistent.
        # GS_SANITIZE=off (default) skips straight to the legacy path.
        got = faults.fire("admit", (self.tenant or "driver", src, dst))
        if got is not None:
            _t, src, dst = got
        if sanitize_mod.enabled():
            try:
                rep = sanitize_mod.sanitize(
                    # vb=None: the driver's ids are EXTERNAL int64
                    # keys the interner densifies (the bucket grows),
                    # so only representability/policy checks apply
                    src, dst, None,
                    tenant=self.tenant or "driver", origin="driver",
                    offset=self._fed_edges,
                    dlq=sanitize_mod.resolve_dlq())
            except sanitize_mod.BatchRejected as e:
                self._fed_edges += e.size
                raise
            self._fed_edges += rep.accepted + rep.rejected
            src, dst = rep.src, rep.dst
            if rep.rejected:
                if ts is not None and len(np.atleast_1d(ts)):
                    ts = np.asarray(ts)[rep.keep]
                if _starts is not None:
                    _starts = np.asarray(_starts)[rep.keep]
        else:
            self._fed_edges += len(np.atleast_1d(np.asarray(src)))
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)

        def _journal(ts_arr=None):
            # durability boundary of the LIVE feed path: journal
            # (with timestamps when event-time) AFTER validation —
            # a rejected batch must leave no journal record, or the
            # journal offsets skew against edges_done and replay
            # re-raises the rejection — and BEFORE any window is cut,
            # so a kill past this point is recoverable by
            # resume_and_replay(). stream_file never journals: its
            # file is its own journal (and enable_wal refuses the
            # mixed-source mode outright).
            if self._wal is not None and len(src) \
                    and not self._in_stream:
                self._wal.append(self.tenant or "driver", src, dst,
                                 ts_arr)
                faults.fire("wal_enqueue", self.tenant or "driver")
        if _starts is not None or (
                ts is not None and len(ts) and int(np.max(ts)) >= 0):
            if self._wp > 1:
                raise ValueError(
                    "sliding windows (slide=) are count-based: "
                    "event-time streams window by window_ms (panes "
                    "over event time need an upstream assigner)")
            if _starts is not None:
                starts = _starts
            else:
                ts = np.asarray(ts, np.int64)
                if int(np.min(ts)) < 0:
                    raise ValueError(
                        "mixed timestamped and untimestamped rows: every "
                        "edge needs a timestamp for event-time windows "
                        "(rows without a third column parse as ts=-1)")
                starts = native.assign_windows(ts, self.window_ms)
            if np.any(np.diff(starts) < 0):
                raise ValueError(
                    "timestamps must be ascending (the reference's "
                    "AscendingTimestampExtractor contract, "
                    "SimpleEdgeStream.java:90-94)")
            bounds = np.flatnonzero(np.diff(starts)) + 1
            slices = np.split(np.arange(len(src)), bounds)
            windows = [(int(starts[idx[0]]), src[idx], dst[idx])
                       for idx in slices if len(idx)]
            _journal(np.asarray(ts, np.int64)
                     if ts is not None and len(np.atleast_1d(ts))
                     else None)
            if lat_t0 is not None:
                latency.on_admit(self.tenant or "driver", len(src),
                                 t0=lat_t0)
            return self._dispatch_windows(windows)
        # count-based: window_start = absolute stream offset; the
        # edges_done cursor advances per window (inside _window, so
        # checkpoints carry it), making chunked calls accumulate
        if self._closed_partial:
            # same guard as scan_analytics.SummaryEngineBase.process:
            # a previous call already closed a short window, so feeding
            # more edges would silently shift every subsequent window
            # boundary relative to a single whole-stream call
            raise ValueError(
                "a previous count-based run closed a partial window "
                "(length not a multiple of edge_bucket); chunked "
                "count-based feeding must use edge_bucket multiples")
        _journal()
        if lat_t0 is not None:
            latency.on_admit(self.tenant or "driver", len(src),
                             t0=lat_t0)
        windows = []
        at = self.edges_done
        cut = self._cut_size()
        for i in range(0, len(src), cut):
            idx = slice(i, min(i + cut, len(src)))
            windows.append((at, src[idx], dst[idx]))
            at += idx.stop - idx.start
        return self._dispatch_windows(windows, count_based=True)

    def _cut_size(self) -> int:
        """Emission granularity of the count-based path: the pane size
        under sliding windows (each `slide`-edge pane fires one
        emission whose triangle slab composes the ring — _window), the
        whole edge bucket otherwise."""
        return self.slide if self._wp > 1 else self.eb

    def _dispatch_windows(self, windows,
                          count_based: bool = False
                          ) -> List[WindowResult]:
        """Route a call's windows: the batched snapshot-scan fast path
        on multi-window calls (single-chip jit or shard_map over the
        mesh), the per-window path (with
        batched triangle dispatch) otherwise."""
        # sliding: pane emissions stay on the per-window path — the
        # batched snapshot scan pads whole-eb slabs and its triangle
        # stack counts the raw window, not the composed pane ring (the
        # per-window path still batches triangle dispatches through
        # _batched_triangles, so it is one count_windows flush per call)
        batched_ok = len(windows) > 1 and self._wp == 1
        if batched_ok and self.mesh is not None:
            from ..parallel.mesh import shard_count

            # shard_map splits the edge axis: the stack's eb must
            # divide evenly (power-of-two buckets on power-of-two
            # meshes always do)
            batched_ok = self.eb % shard_count(self.mesh) == 0
        cut = self._cut_size()
        with self._batched_triangles():
            if batched_ok:
                return self._run_batched(
                    windows,
                    closes_partial=(count_based
                                    and len(windows[-1][1]) < cut))
            out = []
            for wstart, s, d in windows:
                if count_based and len(s) < cut:
                    # set ONLY when the short final window is actually
                    # being emitted, so a checkpoint taken by an
                    # earlier window of this call never persists a
                    # closed_partial the restored state hasn't seen
                    self._closed_partial = True
                out.append(self._window(wstart, s, d))
            return out

    # ------------------------------------------------------------------
    # batched fast path (single-chip or sharded): all of a call's
    # windows in one
    # snapshot-scan dispatch (+ one count_windows dispatch)
    # ------------------------------------------------------------------
    _SCAN_CHUNK = 64  # max windows per dispatch; W pads to buckets

    def _scan_chunk(self) -> int:
        """Windows per snapshot-scan dispatch: _SCAN_CHUNK, compile-
        size-capped on the tunneled chip per-PROGRAM (the
        multi-analytic snapshot scan wedges at sizes the triangle
        program compiles; ops/triangles.compile_cap
        "snapshot_scan")."""
        return min(self._SCAN_CHUNK,
                   tri_ops.capped_chunk(self.eb, "snapshot_scan"))

    def _scan_tuner_key(self) -> str:
        return ("snapshot_scan:eb=%d:vb=%d:%s"
                % (self.eb, self.vb, "+".join(self.analytics)))

    # ------------------------------------------------------------------
    # resident tier (ops/resident_engine): windows-per-superbatch cap,
    # its own tuner family, and the chunk cap the scan-cache helpers
    # read — the donated program family dispatches super-batches of
    # GS_RESIDENT_SPB windows instead of _SCAN_CHUNK chunks
    # ------------------------------------------------------------------
    def _resident_chunk(self) -> int:
        from ..ops import resident_engine

        return resident_engine.resident_spb(self.eb)

    def _chunk_cap(self) -> int:
        """Windows-per-dispatch cap of the ACTIVE program family:
        the resident super-batch while the resident tier runs, the
        compile-capped scan chunk otherwise."""
        return (self._resident_chunk() if self._resident_now
                else self._scan_chunk())

    def _resident_tuner_key(self) -> str:
        return ("resident_scan:eb=%d:vb=%d:%s"
                % (self.eb, self.vb, "+".join(self.analytics)))

    def _ensure_resident_tuner(self):
        """The resident tier's windows-per-superbatch tuner
        (ops/autotune): power-of-two rungs under the resident cap,
        keyed as its own family so scan-tier rates never cross-seed
        it. None when GS_AUTOTUNE=0 — the static super-batch stepping
        then runs bit-identically."""
        from ..ops import autotune

        if not autotune.enabled():
            return None
        if getattr(self, "_resident_tuner", None) is None:
            cap = self._resident_chunk()
            wbs = sorted({max(1, cap // 4), max(1, cap // 2), cap})
            self._resident_tuner = autotune.DispatchTuner(
                self._resident_tuner_key(), {"wb": wbs}, {"wb": cap})
        return self._resident_tuner

    def _ensure_scan_tuner(self):
        """The driver's online windows-per-dispatch tuner for the
        batched snapshot scan (ops/autotune): arms are power-of-two
        rungs under the compile-capped _scan_chunk(). None when
        GS_AUTOTUNE=0 — the static stepping then runs bit-identically."""
        from ..ops import autotune

        if not autotune.enabled():
            return None
        if getattr(self, "_scan_tuner", None) is None:
            cap = self._scan_chunk()
            wbs = sorted({max(1, cap // 4), max(1, cap // 2), cap})
            self._scan_tuner = autotune.DispatchTuner(
                self._scan_tuner_key(), {"wb": wbs}, {"wb": cap})
        return self._scan_tuner

    def _warm_scan_arm(self, wb: int) -> None:
        """Compile (and execute once, on an all-padding stack against a
        throwaway carry) the W-bucket program an arm needs BEFORE its
        first measured chunk, so exploration never compiles — and the
        warm run never touches carried state. Keyed like the scan
        cache; re-warms after bucket growth invalidates it."""
        import jax.numpy as jnp

        wb = seg_ops.bucket_size(wb)
        warmed = getattr(self, "_warmed_scan_arms", None)
        key3 = self._scan_key()
        if warmed is None or warmed[0] != key3:
            warmed = self._warmed_scan_arms = (key3, set())
        if wb in warmed[1]:
            return
        # prime the program cache for THIS bucket (bypassing _scan_wb's
        # bigger-bucket reuse — the arm must compile its own size)
        if getattr(self, "_scan_cache_key", None) != key3:
            self._scan_cache = {}
            self._scan_cache_key = key3
        fn = self._scan_fn_at(wb)
        vb = self.vb
        carry = (jnp.zeros(vb + 1, jnp.int32),
                 jnp.arange(vb + 1, dtype=jnp.int32),
                 jnp.arange(2 * vb + 1, dtype=jnp.int32))
        s_w = jnp.full((wb, self.eb), vb, jnp.int32)
        valid = jnp.zeros((wb, self.eb), jnp.bool_)
        out = fn(carry, s_w, s_w, valid)
        np.asarray(out[0][0])  # block: the compile must finish here
        warmed[1].add(wb)

    def _scan_wb(self, num_w: int) -> int:
        """The W-bucket the snapshot scan will run `num_w` windows at
        — the bucket selection WITHOUT building a program, so the
        ingress pipeline's prep worker can size a chunk's stacks off
        the main thread. A W-bucket with no compiled program reuses
        the smallest already-compiled LARGER bucket instead (sentinel
        window rows are no-ops, outputs are read per real row), so a
        long stream's ragged final chunk never compiles at the tail
        (tools/endurance_run.py's steady-state assert); right-sized
        programs still compile for callers whose FIRST batch is small
        (the per-window dispatch mode)."""
        wb = seg_ops.bucket_size(min(num_w, self._chunk_cap()))
        key3 = self._scan_key()
        if getattr(self, "_scan_cache_key", None) != key3:
            self._scan_cache = {}
            self._scan_cache_key = key3
        if wb not in self._scan_cache:
            bigger = [b for b in self._scan_cache if b > wb]
            if bigger:
                wb = min(bigger)
        return wb

    def _scan_key(self):
        """Identity of the compiled snapshot-scan program family —
        bucket growth, analytics, the egress format (a delta program
        emits a different out tree), mesh liveness (a demotion off
        the sharded tier switches the family to the single-chip
        programs; re-promotion switches back) AND the resident flag
        (the resident tier's donated programs are a distinct family —
        a demotion to scan must never dispatch a donating program)
        invalidate the cache."""
        return (self.vb, self.eb, self.analytics, self._scan_egress(),
                self._mesh_live(), self._resident_now)

    def _scan_egress(self) -> str:
        """The batched scan's d2h egress format: the constructor pin,
        else the committed-evidence resolution (ops/delta_egress).
        Sharded meshes always run full-vector egress — their snapshots
        ride replicated shard_map outputs, symmetric to compact
        ingress staying off the mesh path."""
        if self.mesh is not None:
            return "full"
        return self._egress_pin or delta_egress.resolve_egress()

    def _scan_fn_at(self, wb: int):
        """Jitted snapshot scan for exactly W-bucket `wb` (selection
        already applied by _scan_wb), cached per
        (vb, eb, analytics, egress, W-bucket) — O(log) programs
        total."""
        if wb not in self._scan_cache:
            name = "snapshot_scan"
            if self._mesh_live():
                from ..parallel.sharded import make_sharded_snapshot_scan

                fn = make_sharded_snapshot_scan(
                    self.mesh, self.vb, self.analytics,
                    deltas=self.emit_deltas)
            else:
                fn = _build_snapshot_scan(
                    self.vb, self.analytics, deltas=self.emit_deltas,
                    egress=self._scan_egress(),
                    cap=delta_egress.egress_cap(self.eb, self.vb),
                    donate=self._resident_now)
                if self._resident_now:
                    name = "resident_scan"
            # compile watch (utils/metrics): every distinct abstract
            # signature this program family sees counts against the
            # O(log V) recompile envelope
            self._scan_cache[wb] = metrics.wrap_jit(name, fn)
        return self._scan_cache[wb]

    def _run_batched(self, windows,
                     closes_partial: bool = False) -> List[WindowResult]:
        """Process [(wstart, src, dst), ...] with ONE snapshot-scan
        dispatch per _SCAN_CHUNK windows and one batched triangle
        dispatch, instead of per-window per-analytic round trips.
        Semantics identical to the per-window path (same fixpoint
        kernels and carried state; single-chip carries the host
        mirrors, sharded carries the ShardedWindowEngine's state).

        Consistency unit = one chunk: cursors, host mirrors, and the
        auto-checkpoint all advance together at each chunk boundary, so
        an exception mid-call leaves the driver exactly at the last
        completed chunk (resumable), never with cursors ahead of
        mirrors."""
        import jax.numpy as jnp

        # intern everything first: buckets grow ONCE for the call. The
        # per-element hash-map work rides the ingress prep pool
        # (utils/interning.parallel_intern_arrays: first-occurrence
        # uniques + dense scatter run parallel, slot ASSIGNMENT stays
        # sequential over the tiny unique lists — slots are identical
        # to the sequential loop at every pool size); sizes[] gives
        # each window's post-intern vertex cursor for snapshot slicing
        total_edges = sum(len(s) for _w, s, _d in windows)
        with self._step("intern", 2 * total_edges):
            flat = []
            for _wstart, src, dst in windows:
                flat.append(src)
                flat.append(dst)
            dense, sizes = parallel_intern_arrays(self.interner, flat)
        interned = [
            (windows[i][0], dense[2 * i], dense[2 * i + 1],
             sizes[2 * i + 1])
            for i in range(len(windows))]
        nv_final = len(self.interner)
        max_len = max(len(s) for _w, s, _d, _n in interned)
        self._ensure_buckets(nv_final, max_len)

        # tier-demotion loop: chunks are the consistency unit (mirrors,
        # cursors, checkpoints move together at each boundary), so a
        # persistent device failure at chunk k leaves the driver
        # resumable at the last finalized chunk — demote the snapshot
        # tier (scan→native→host), rebuild the carried state from the
        # mirrors, and continue with the NOT-yet-finalized windows.
        results: List[WindowResult] = []
        while True:
            tier = self._effective_tier()
            try:
                self._scan_interned(interned[len(results):], results,
                                    closes_partial, tier)
                return results
            except resilience.StageError as e:
                if not self._maybe_demote(tier, e):
                    raise

    def _base_tier(self) -> str:
        """The tier this driver runs on when nothing is demoted: the
        mesh when one was given, else the pinned/resolved single-chip
        snapshot tier."""
        if self.mesh is not None:
            return "sharded"
        return self._snapshot_tier or resolve_snapshot_tier()

    def _mesh_live(self) -> bool:
        """True while the sharded engines are the active tier: a mesh
        was configured AND no demotion has pushed the stream onto the
        single-chip ladder (re-promotion restores it)."""
        return self.mesh is not None and self._demoted_tier is None

    def _mesh_shape(self):
        """Device counts per mesh axis (degradations provenance);
        None on a single-chip driver."""
        if self.mesh is None:
            return None
        return [int(x) for x in self.mesh.devices.shape]

    def _effective_tier(self) -> str:
        """The snapshot tier the next chunk runs on: a live demotion
        wins over the pin/resolution; after GS_TIER_RETRY_WINDOWS
        windows of probation on the demoted tier, one re-promotion
        probe runs the higher tier again (a repeat failure re-demotes
        and restarts probation). Re-promoting a mesh session back to
        the sharded tier pushes the host mirrors — which carried the
        stream through probation — back into the engine state."""
        if self._demoted_tier is not None:
            n = resilience.tier_retry_windows()
            if n and self.windows_done - self._demoted_at >= n:
                prev = self._demoted_tier
                if self.mesh is not None:
                    # stage the slabs BEFORE declaring the probe: a
                    # mesh still dead at probe time fails the h2d
                    # here — record the failed probe, restart
                    # probation, and stay on the demoted tier (the
                    # documented repeat-failure-re-demotes contract)
                    # instead of crashing the stream
                    try:
                        self._sync_engine_from_mirrors()
                    except Exception as e:
                        if not isinstance(e, (RuntimeError, OSError,
                                              MemoryError)):
                            # same filter as _maybe_demote: a semantic
                            # error is a programming bug, never a dead
                            # mesh — surface it, don't mask it as a
                            # failed probe forever
                            raise
                        event = resilience.record_demotion(
                            "snapshot", prev, prev, self.windows_done,
                            "re-promotion probe failed (%s: %s); "
                            "probation restarted"
                            % (type(e).__name__, e),
                            mesh_shape=self._mesh_shape(),
                            tenant=self.tenant)
                        self._demotions.append(event)
                        self._demoted_at = self.windows_done
                        return prev
                event = resilience.record_demotion(
                    "snapshot", prev, self._base_tier(),
                    self.windows_done,
                    "re-promotion probe after %d probation windows"
                    % (self.windows_done - self._demoted_at),
                    mesh_shape=self._mesh_shape(),
                    tenant=self.tenant)
                self._demotions.append(event)
                if self.timer:
                    self.timer.event("tier_repromotion", event)
                self._demoted_tier = None
            else:
                return self._demoted_tier
        return self._base_tier()

    def _maybe_demote(self, tier: str, err: BaseException) -> bool:
        """Decide whether `err` on `tier` demotes to the next ladder
        rung. Only failure shapes a tier change can plausibly cure
        demote — stage timeouts and wrapped runtime/OS-level failures
        (a wedged tunnel, a dead device or shard, an injected fault);
        semantic errors (ValueError/TypeError/...) re-raise so a
        programming bug is never silently 'fixed' by falling off the
        fast tier.

        The full ladder is sharded → resident → scan → native → host
        (resident — the donated megakernel above scan — demotes TO
        scan but is never itself a demotion target):
        a mesh session that loses a shard degrades to one device (the
        engine's gathered replicated state becomes the host mirrors —
        the same chunk-boundary sources the single-chip rungs re-enter
        from) instead of wedging; GS_MESH_DEMOTE=0 pins the mesh rung
        specifically, GS_TIER_DEMOTE=0 pins them all."""
        if not resilience.tier_demotion_enabled():
            return False
        if tier == "sharded" and not resilience.mesh_demotion_enabled():
            return False
        cause = err.__cause__
        if not isinstance(err, resilience.StageTimeout):
            if not isinstance(cause, (RuntimeError, OSError,
                                      MemoryError)):
                return False
        order = ("sharded", "resident", "scan", "native", "host")
        shard_id = getattr(cause, "shard", None)
        for nxt in order[order.index(tier) + 1:]:
            if nxt == "resident":
                # never a demotion TARGET: resident is the tier ABOVE
                # scan — a device failure on any rung lands on the
                # proven scan rung, not on a bigger donated program
                # against the same ailing device
                continue
            if nxt == "native" and not native.snapshot_available():
                continue
            event = resilience.record_demotion(
                "snapshot", tier, nxt, self.windows_done,
                "%s: %s" % (type(err).__name__, err),
                mesh_shape=self._mesh_shape(), shard_id=shard_id,
                tenant=self.tenant)
            self._demotions.append(event)
            if self.timer:
                self.timer.event("tier_demotion", event)
            if tier == "sharded":
                # leaving the mesh: the engine's gathered state
                # becomes the host mirrors every lower rung re-enters
                # from (and the twin a resumed host session loads)
                self._absorb_engine_state()
            self._demoted_tier = nxt
            self._demoted_at = self.windows_done
            return True
        return False

    # ------------------------------------------------------------------
    # mesh ↔ mirror state conversion (the demotion/re-promotion and
    # cross-mode resume hand-off): the engine's slabs are replicated
    # (parallel/sharded state_dict docstring), so the gathered copy IS
    # the single-chip layout up to slicing — degrees/labels [:nv],
    # cover [:2vb] (both layouts place (−) at vb + v)
    # ------------------------------------------------------------------
    def _absorb_engine_state(self, engine_state: dict = None) -> None:
        """Engine slabs → host mirrors (every sharded finalized
        boundary, mesh demotion, sharded checkpoint resumed off-mesh).
        Called with no state at DEMOTION time, the gather from the
        failing mesh is best-effort only: the batched and per-window
        sharded paths both refresh the mirrors at their boundaries, so
        when the d2h itself died with the mesh the mirrors already
        hold the last finalized state and the demotion proceeds
        without touching the mesh at all."""
        st = engine_state
        if st is None:
            if self._engine is None:
                return
            try:
                st = self._engine.state_dict()
            except Exception as e:
                telemetry.event(
                    "mesh_gather_failed", durable=True,
                    window=self.windows_done,
                    error="%s: %s" % (type(e).__name__, e))
                return  # mirrors are the truth (see above)
        nv = len(self.interner)
        deg = np.asarray(st["degree_state"])
        self._degrees = deg[:nv].astype(np.int64)
        self._deg_state = None
        self._cc = np.asarray(st["labels"])[:nv].astype(np.int32)
        if "bip_labels" in st:
            # engine cover is [2vb+2] ((−) at vb + v, two trailing
            # sentinels); the mirror keeps the [2vb] meaningful run
            cov = np.asarray(st["bip_labels"])
            self._bip = cov[:len(cov) - 2].astype(np.int32)

    def _engine_state_from_mirrors(self) -> dict:
        """Engine-layout state assembled PURELY from the host mirrors
        — no mesh access, so a demoted session can checkpoint (and a
        re-promotion can stage its slabs) without touching the dead
        mesh. Sentinel slots reset to their identities — they absorb
        padding and feed no output, so results are unchanged."""
        vb = self.vb
        st = {"vb": vb, "mesh_shape": self._mesh_shape()}
        deg = np.zeros(vb + 2, np.int32)
        deg[:len(self._degrees)] = self._degrees
        st["degree_state"] = deg
        lab = np.arange(vb + 2, dtype=np.int32)
        lab[:len(self._cc)] = self._cc
        st["labels"] = lab
        if len(self._bip):
            if len(self._bip) != 2 * vb:
                self._bip = self._grow_cover(self._bip, vb)
            cov = np.arange(2 * vb + 2, dtype=np.int32)
            cov[:2 * vb] = self._bip
            st["bip_labels"] = cov
        return st

    def _sync_engine_from_mirrors(self) -> None:
        """Host mirrors → engine slabs (mesh re-promotion /
        single-chip checkpoint resumed onto a mesh)."""
        if self._engine is None:
            return
        self._engine.load_state_dict(self._engine_state_from_mirrors())

    def demotion_log(self) -> List[dict]:
        """Demotion/re-promotion events of this driver's lifetime (the
        process-global registry in utils/resilience feeds PERF.json's
        `degradations` section; this is the per-driver view)."""
        return list(self._demotions)

    def _scan_interned(self, interned, results, closes_partial: bool,
                       tier: str) -> None:
        """Run the batched snapshot analytics over `interned`
        [(wstart, s, d, nv)] on `tier`, appending per-window results.
        Carried state is (re)built from the chunk-boundary sources —
        host mirrors / engine state — at entry, which is what makes
        the call re-enterable after a mid-stream tier demotion."""
        import jax.numpy as jnp

        if not interned:
            return
        vb = self.vb
        run_scan = any(a in self.analytics
                       for a in ("degrees", "cc", "bipartite"))
        # tier-driven, NOT engine-driven: a mesh session demoted off
        # the sharded rung runs the single-chip programs against the
        # host mirrors (populated by _absorb_engine_state) even though
        # its engine object still exists for the re-promotion path
        sharded = tier == "sharded"
        # resident tier (ops/resident_engine): the device-scan branch
        # with the donated super-batch program family, prep+h2d on the
        # ingest ring, and one dispatch per GS_RESIDENT_SPB windows.
        # The flag keys the program cache (_scan_key), so a demotion
        # to scan mid-call switches families cleanly at re-entry.
        resident = tier == "resident"
        self._resident_now = resident
        # native/host tiers of the snapshot stage: carried union-find
        # + degree fold (C++ or numpy — bit-exact twins) producing the
        # SAME per-window `outs`
        # stacks as the scan — including, under emit_deltas, the
        # changed-slot masks (host-diffed against the chunk-start
        # snapshot below, same semantics as the scan's device masks).
        native_state = None
        if run_scan and not sharded and tier in ("native", "host"):
            # COPIES (never aliases of the mirrors): the C++/numpy
            # kernels fold unions in place mid-chunk, and mirrors must
            # only move at chunk boundaries (the consistency unit —
            # an exception mid-chunk leaves them resumable)
            native_state = self._chunk_start_state()
        carry = None
        if run_scan and sharded:
            # carried state straight from the engine (its layouts:
            # deg/labels [vb+2], cover [2vb+2])
            st = self._engine.state_dict()
            cov0 = (st["bip_labels"] if "bip_labels" in st
                    else np.arange(2 * vb + 2, dtype=np.int32))
            carry = (jnp.asarray(st["degree_state"]),
                     jnp.asarray(st["labels"]), jnp.asarray(cov0))
        elif run_scan and native_state is None:
            # carried state from the host mirrors (same sources the
            # per-window path uses)
            deg0 = np.zeros(vb + 1, np.int32)
            deg0[:len(self._degrees)] = self._degrees
            lab0 = np.arange(vb + 1, dtype=np.int32)
            lab0[:len(self._cc)] = self._cc
            if "bipartite" in self.analytics \
                    and len(self._bip) != 2 * vb:
                self._bip = self._grow_cover(self._bip, vb)
            cov0 = np.arange(2 * vb + 1, dtype=np.int32)
            cov0[:len(self._bip)] = self._bip
            carry = (jnp.asarray(deg0), jnp.asarray(lab0),
                     jnp.asarray(cov0))

        num_w = len(interned)
        scan_chunk = self._chunk_cap()
        # Depth-2 pipeline over the DEVICE scan branch: the scan carry
        # is a device array, so chunk i+1's dispatch needs only the
        # un-materialized carry — chunk i's d2h + extraction + chunk-
        # boundary bookkeeping run while the device executes chunk
        # i+1. The consistency unit is unchanged: a chunk's mirrors/
        # cursors/checkpoint still move only in its finalize, in chunk
        # order; an exception mid-call still leaves the driver at the
        # last FINALIZED chunk (resumable). The host/native tier stays
        # synchronous — one core, nothing to overlap with.
        disp_t = [None]  # dispatch-boundary stamp of the pending chunk

        def _boundary(at, chunk):
            # chunk boundary: cursors, the partial flag, and the
            # checkpoint move together (mirrors moved just before)
            edges = sum(len(s) for _w, s, _d, _n in chunk)
            if latency.enabled():
                # per-window ingest→deliver records (the driver's
                # coarse decomposition: its dispatch boundary folds
                # prep+h2d+device wait; synchronous tiers have no
                # dispatch stamp and report admission+finalize only).
                # Results were appended by this chunk's finalize, so
                # each record attaches to its WindowResult.
                st = ({"dispatch": disp_t[0]}
                      if disp_t[0] is not None else None)
                disp_t[0] = None
                lane = self.tenant or "driver"
                for i, (_w, s, _d, _n) in enumerate(chunk):
                    rec = latency.on_window(
                        lane, edges=len(s), st=st,
                        ordinal=self.windows_done + i)
                    if rec is not None \
                            and len(results) >= len(chunk):
                        res = results[len(results) - len(chunk) + i]
                        res.latency = {
                            "e2e_s": rec["e2e_s"],
                            "stages": dict(rec["stages"]),
                            "replayed": rec["replayed"],
                        }
            if provenance.armed() and len(results) >= len(chunk):
                # this chunk's finalize just appended its results;
                # wal_lo/hi follow the edges_done cursor (== the
                # checkpoint's wal_offset contract)
                lane = self.tenant or "driver"
                lo = self.edges_done
                for i, (_w, s, _d, _n) in enumerate(chunk):
                    res = results[len(results) - len(chunk) + i]
                    provenance.emit(
                        tenant=lane, window=self.windows_done + i,
                        wal_lo=lo, wal_hi=lo + len(s),
                        tier=tier, program="driver",
                        digest=provenance.result_digest(res))
                    lo += len(s)
            self.windows_done += len(chunk)
            self.edges_done += edges
            metrics.mark_window(len(chunk), edges, engine="driver",
                                tier=tier,
                                mesh_shape=self._mesh_shape(),
                                tenant=self.tenant)
            if closes_partial and at + len(chunk) >= num_w:
                # the short final window lives in this chunk: the flag
                # joins this boundary's state (and its checkpoint),
                # never an earlier one's
                self._closed_partial = True
            if self._ckpt_due():
                self._stage_ckpt()

        def _finalize_chunk(at, chunk, outs):
            if any(key in outs for key in
                   ("deg_cnt", "labels_cnt", "cover_cnt")):
                # delta-compacted egress (ops/delta_egress)
                if self._delta_overflowed(outs):
                    # a label cascade outran the changed-slot cap:
                    # the chunk refolds on the bit-exact host twin
                    # and takes the full-vector extraction below
                    outs = self._refold_chunk_outs(chunk)
                else:
                    self._emit_delta_chunk(chunk, outs, results)
                    _boundary(at, chunk)
                    return
            nv_chunk = chunk[-1][3]
            last = len(chunk) - 1
            for i, (wstart, s, d, nv) in enumerate(chunk):
                res = WindowResult(
                    window_start=wstart, num_edges=len(s),
                    vertex_ids=self._vertex_ids(nv))
                if "deg" in outs:
                    snap = outs["deg"][i][:nv].astype(np.int64)
                    self._check_degree_width(snap)
                    res.degrees = _snapshot_view(snap)
                    if "deg_chg" in outs:
                        idx = np.nonzero(
                            outs["deg_chg"][i][:nv])[0].astype(np.int32)
                        res.delta_degrees = _frozen_delta(idx, snap[idx])
                if "labels" in outs:
                    res.cc_labels = _snapshot_view(
                        outs["labels"][i][:nv], self.vb)
                    if "labels_chg" in outs:
                        idx = np.nonzero(
                            outs["labels_chg"][i][:nv])[0].astype(
                                np.int32)
                        res.delta_cc = _frozen_delta(
                            idx, res.cc_labels[idx])
                if "cover" in outs:
                    if "_odd_rows" in outs:  # native delta path: the
                        # odd matrix was already computed for the mask
                        res.bipartite_odd = _snapshot_view(
                            outs["_odd_rows"][i][:nv], self.vb)
                    else:
                        plus = outs["cover"][i][:vb]
                        minus = outs["cover"][i][vb:2 * vb]
                        res.bipartite_odd = _snapshot_view(
                            (plus == minus)[:nv])
                    if "cover_chg" in outs:
                        idx = np.nonzero(
                            outs["cover_chg"][i][:nv])[0].astype(
                                np.int32)
                        res.delta_bipartite = _frozen_delta(
                            idx, np.asarray(res.bipartite_odd)[idx])
                if "triangles" in self.analytics:
                    # _batched_triangles (always active around this
                    # path when triangles are on) flushes these in one
                    # count_windows dispatch on clean exit
                    self._tri_pending.append(
                        (res, np.asarray(s, np.int32),
                         np.asarray(d, np.int32)))
                results.append(res)

            # ---- chunk boundary: mirrors, cursors, checkpoint move
            # together. Mirror values come from the chunk's LAST
            # window row (== the carry, no extra d2h).
            if sharded and run_scan:
                # engine.state_dict() is a full d2h sync — fetch it
                # only for keys the scan did NOT produce (all enabled
                # analytics come from `outs`, so usually never)
                st = {"vb": vb}
                cur = None
                for key, out_key in (("degree_state", "deg"),
                                     ("labels", "labels")):
                    if out_key in outs:
                        st[key] = outs[out_key][last]
                    else:
                        cur = cur or self._engine.state_dict()
                        st[key] = cur[key]
                if "cover" in outs:
                    st["bip_labels"] = outs["cover"][last]
                else:
                    cur = cur or self._engine.state_dict()
                    if "bip_labels" in cur:
                        st["bip_labels"] = cur["bip_labels"]
                self._engine.load_state_dict(st)
                # host mirrors track every finalized boundary in
                # sharded mode too: the demotion hand-off must never
                # depend on a d2h gather from the very mesh that just
                # failed, and the rows here are already materialized
                # host arrays — the copies are O(vb)
                self._absorb_engine_state(st)
            else:
                if "deg" in outs:
                    self._degrees = outs["deg"][last][:nv_chunk].astype(
                        np.int64)
                    self._deg_state = None  # per-window path: rebuild
                if "labels" in outs:
                    self._cc = outs["labels"][last][:nv_chunk].copy()
                if "cover" in outs:
                    self._bip = outs["cover"][last][:2 * vb].copy()
            _boundary(at, chunk)

        pending = None  # (at, chunk, device outs, superbatch stopwatch)

        def finalize_pending():
            nonlocal pending
            if pending is None:
                return
            f_at, f_chunk, f_outs, f_sw = pending
            pending = None
            with self._step("snapshot_wait",
                            sum(len(s) for _w, s, _d, _n in f_chunk)):
                # the materialize leg of the snapshot path: a hung d2h
                # through a wedged tunnel surfaces as a typed
                # StageTimeout (deadline only — the d2h is a pure read,
                # but a retry would re-block on the same dead transfer)
                def _mat(f_outs=f_outs):
                    faults.fire("finalize")
                    return {k: np.asarray(v) for k, v in f_outs.items()}

                f_outs = resilience.call_guarded(
                    "finalize", f_at, _mat, retries=0)
            # the dispatch boundary of this chunk's waterfall closes
            # with the materialize (device execute + d2h, observed)
            disp_t[0] = (latency.clock() if latency.enabled()
                         else None)
            _finalize_chunk(f_at, f_chunk, f_outs)
            if f_sw is not None:
                # the resident super-batch span closes at its DRAIN —
                # dispatch through materialize + extraction (the
                # owner-rule mark_window already fired in _boundary)
                f_sw.stop(windows=len(f_chunk))

        # prep stage of the device-scan branch: the [wb, eb] stack
        # build for chunk i+1 runs on the ingress prep pool while
        # chunk i executes on device (the scan carry forces dispatches
        # sequential, so only prep — and, on the resident tier, h2d —
        # pipelines). The scan tier keeps its single lookahead; the
        # RESIDENT tier runs a GS_RESIDENT_SLOTS ingest ring whose
        # worker task is prep + h2d of a whole super-batch, so slot
        # N+1 transfers while super-batch N computes. The W-bucket is
        # chosen on the MAIN thread at submit time (item = (chunk,
        # wb)): _scan_wb reads/mutates the jit cache, which a pool
        # worker must never touch concurrently with _scan_fn_at's
        # insertions.
        def _build_stack(item):
            chunk, wb = item
            s_w, d_w, valid = seg_ops.stack_window_rows(
                [(s, d) for _w, s, d, _n in chunk], wb, self.eb, vb)
            return wb, s_w, d_w, valid

        def _build_dev(item):
            # resident ring task: prep + h2d on the worker
            # (jnp.asarray is thread-safe — the run_pipeline h2d
            # contract), so the main thread's only steady-state job is
            # dispatching and draining
            wb, s_w, d_w, valid = _build_stack(item)
            return (wb, jnp.asarray(s_w), jnp.asarray(d_w),
                    jnp.asarray(valid))

        build_job = _build_dev if resident else _build_stack
        from ..ops import resident_engine

        ring = resident_engine.IngestRing(
            slots=resident_engine.ring_slots() if resident else 1)
        fold = (native.snapshot_windows if tier == "native"
                else host_snapshot.snapshot_windows)

        # online wb tuning of the DEVICE scan branch (ops/autotune):
        # each chunk is one measurement round; the arm (its window
        # count) is decided — and its W-bucket program warmed — at the
        # chunk's PREP-submit point, so exploration never compiles
        # mid-measurement. GS_AUTOTUNE=0 (or the native/host/sharded
        # branches) keeps the static scan_chunk stepping
        # bit-identically. The resident tier tunes its own family —
        # windows-per-superbatch rungs under the resident cap.
        tuner = ((self._ensure_resident_tuner() if resident
                  else self._ensure_scan_tuner())
                 if run_scan and not sharded and native_state is None
                 else None)
        decided = {}  # chunk start -> (take, arm)

        def _decide(pos):
            if pos not in decided:
                if tuner is None:
                    decided[pos] = (scan_chunk, None)
                else:
                    arm = (tuner.best()
                           if ingress_pipeline.forced_sync_active()
                           else tuner.next_round())
                    take = min(arm["wb"], num_w - pos)
                    # warm the bucket the round will ACTUALLY dispatch
                    # (not the arm's full rung): pre-compiling an
                    # oversized bucket would hand _scan_wb's
                    # bigger-bucket reuse to every small call, making
                    # 2-window pieces pay a full-rung scan of
                    # sentinel rows
                    self._warm_scan_arm(take)
                    decided[pos] = (take, arm)
            return decided[pos]

        meas = None  # (arm, edges, stopwatch) of the last dispatch

        def _meas_flush():
            nonlocal meas
            if meas is not None and tuner is not None \
                    and not ingress_pipeline.forced_sync_active():
                arm, edges, sw = meas
                if arm is not None:
                    # the telemetry stopwatch closes the dispatch-to-
                    # dispatch round (recording the span when armed)
                    tuner.record(arm, edges, sw.stop(edges=edges))
            meas = None

        next_submit = 0  # first window position not yet on the ring

        def _chunk_loop():
          nonlocal carry, native_state, pending, meas, next_submit
          at = 0
          while at < num_w:
            take, cur_arm = _decide(at)
            chunk = interned[at:at + take]
            outs = {}
            if run_scan and native_state is not None:
                flat_s = np.concatenate(
                    [s for _w, s, _d, _n in chunk])
                flat_d = np.concatenate(
                    [d for _w, _s, d, _n in chunk])
                offs = np.zeros(len(chunk) + 1, np.int64)
                offs[1:] = np.cumsum(
                    [len(s) for _w, s, _d, _n in chunk])
                prevs = (tuple(a.copy() if a is not None else None
                               for a in native_state)
                         if self.emit_deltas else None)
                with self._step("snapshot_scan", len(flat_s)):
                    # guarded, NEVER retried: the fold mutates the
                    # chunk-local carried copies in place. A failure
                    # demotes (native→host) and _scan_interned
                    # re-enters with fresh copies off the mirrors.
                    def _fold(flat_s=flat_s, flat_d=flat_d, offs=offs):
                        faults.fire("dispatch")
                        return fold(flat_s, flat_d, offs, self.vb,
                                    *native_state)

                    outs = resilience.call_guarded(
                        "dispatch", at, _fold, retries=0)
                if prevs is not None:
                    self._host_mask_outs(outs, prevs)
            elif run_scan:
                got = ring.pop(at)
                if got is not None:
                    fut, item = got
                    timeout = resilience.stage_timeout_s()
                    try:
                        wb, s_w, d_w, valid = fut.result(
                            timeout=2 * timeout if timeout > 0
                            else None)
                    except BaseException as e:
                        # interrupts and the simulated hard kill pass
                        # through; any other failure (a hung worker's
                        # _FutureTimeout, a transient PrepError) gets
                        # the guard's retry budget — prep (and the
                        # resident ring's h2d) is pure, so the inline
                        # rebuild is always safe
                        if (not isinstance(e, Exception)
                                or ingress_pipeline._is_fatal(e)
                                or not resilience.guard_active()):
                            raise  # inert knobs keep legacy fail-fast
                        wb, s_w, d_w, valid = resilience.call_guarded(
                            "prep", at,
                            lambda: build_job(item))
                else:
                    wb, s_w, d_w, valid = build_job(
                        (chunk, self._scan_wb(len(chunk))))
                if next_submit <= at:
                    next_submit = at + take
                fn = self._scan_fn_at(wb)
                # close the previous chunk's measurement BEFORE the
                # next arm's decide: an exploration arm's warm-up
                # (compile + throwaway dispatch inside _decide →
                # _warm_scan_arm) must never bleed into the
                # incumbent's recorded interval
                _meas_flush()
                # top the ingest ring up only after this chunk's
                # program is in the cache, so the ragged final chunk's
                # bigger-bucket reuse sees it (no tail compile) and
                # the worker itself never touches the cache. The scan
                # tier's ring is one slot (the legacy single
                # lookahead); the resident ring keeps GS_RESIDENT_SLOTS
                # super-batches prepped+transferred ahead.
                while next_submit < num_w and not ring.full:
                    s_take, _ = _decide(next_submit)
                    s_chunk = interned[next_submit:
                                       next_submit + s_take]
                    s_item = (s_chunk, self._scan_wb(len(s_chunk)))
                    if not ring.submit(build_job, next_submit, s_item):
                        break  # pipelining disabled: build inline
                    next_submit += s_take
                # one measurement round per chunk (the dispatch-to-
                # dispatch interval is the pipelined steady state's
                # per-chunk wall time). Recorded only when the chunk
                # is full-rung OR the whole call fits under the rung
                # (small stream_file pieces: ragged IS their real
                # economics); a long call's final ragged tail is
                # skipped — its amortization would drag the arm's EMA
                if tuner is not None and cur_arm is not None \
                        and len(chunk) == min(cur_arm["wb"], num_w):
                    meas = (cur_arm,
                            sum(len(s) for _w, s, _d, _n in chunk),
                            telemetry.stopwatch("driver.scan_round",
                                                window=at,
                                                wb=cur_arm["wb"]))
                sw = (telemetry.stopwatch(
                          "resident.superbatch", window=at, wb=take,
                          edges=sum(len(s)
                                    for _w, s, _d, _n in chunk))
                      if resident else None)
                telemetry.pop_dispatch_tags()  # drop stale warm-up tags
                with self._step("snapshot_scan",
                                sum(len(s) for _w, s, _d, _n in chunk)) \
                        as scan_sp:
                    # async dispatch: returns device arrays without
                    # blocking; the d2h lands in this chunk's finalize
                    # (snapshot_wait), AFTER the next chunk is queued.
                    # Guarded WITH retries on the scan tier: the
                    # jitted scan is pure (carry in, new carry out —
                    # rebound only on success), so re-dispatching a
                    # failed chunk is safe. The RESIDENT tier is
                    # deadline-only (retries=0): its program DONATES
                    # the carry buffers, so a failed attempt may
                    # already have consumed them — the failure demotes
                    # to scan and _run_batched re-enters from the
                    # mirrors instead. Exhausted budgets surface as
                    # typed StageFailed/StageTimeout either way.
                    # the wrapped scan program binds its program/sig
                    # tags (utils/costmodel) in the TLS of whichever
                    # thread runs the dispatch — the watchdog helper
                    # when GS_STAGE_TIMEOUT_S is armed — so they are
                    # captured inside _disp and carried back through
                    # the closure onto the step span
                    disp_tags = {}

                    def _disp(s_w=s_w, d_w=d_w, valid=valid,
                              carry_in=carry):
                        faults.fire("dispatch")
                        if sharded:
                            # mesh fault hooks + optional wire check
                            # INSIDE the guarded fn, so a transient
                            # corrupt wire / stalled dispatch is
                            # retried with a fresh firing
                            from ..parallel import sharded as _sh
                            from ..parallel.mesh import shard_count

                            nsh = shard_count(self.mesh)
                            s_w, d_w = _sh.guard_wire(
                                (s_w, d_w), nsh, self.vb + 1)
                            _sh.fire_shard_dispatch(nsh)
                        out = fn(carry_in, jnp.asarray(s_w),
                                 jnp.asarray(d_w), jnp.asarray(valid))
                        disp_tags.update(telemetry.pop_dispatch_tags())
                        return out

                    carry, outs = resilience.call_guarded(
                        "dispatch", at, _disp,
                        retries=(0 if resident
                                 else resilience.stage_retries()))
                    if scan_sp is not None:
                        scan_sp.attrs.update(disp_tags)
                    if "cover_cnt" in outs \
                            and "cover_final" not in outs:
                        # delta egress ships odd-flag deltas, which
                        # cannot resync the cover-label mirror; the
                        # chunk's final cover IS the carry — one
                        # [2vb+1] d2h per chunk instead of [W, 2vb].
                        # (The donated resident program already emits
                        # a fresh cover_final — aliasing the donated
                        # carry here would read a consumed buffer.)
                        outs["cover_final"] = carry[2]
                finalize_pending()
                pending = (at, chunk, outs, sw)
                at += take
                continue
            # only the device-scan branch (which `continue`s above)
            # ever sets `pending`, and branch selection is fixed for
            # the whole call — the sync tiers never have one in flight
            assert pending is None
            _finalize_chunk(at, chunk, outs)
            at += take

        try:
            _chunk_loop()
        except Exception:
            # drain the in-flight chunk before surfacing: its outputs
            # were already dispatched, so materialize + finalize them
            # best-effort — mirrors/cursors then sit at the last chunk
            # the device actually completed, which is what makes the
            # demotion re-entry (and an operator resume) exact
            ring.drain()
            try:
                finalize_pending()
            except Exception as drain_err:
                pending = None
                try:
                    telemetry.event(
                        "drain_failed", durable=True,
                        component="driver",
                        error="%s: %s" % (type(drain_err).__name__,
                                          drain_err))
                except Exception:  # gslint: disable=except-hygiene (a failing ledger write must not replace the typed StageError the demotion ladder keys on)
                    pass
            raise
        finalize_pending()
        _meas_flush()
        if tuner is not None:
            tuner.save()

    def _host_mask_outs(self, outs: dict, prevs: tuple) -> None:
        """Changed-slot masks vs the previous window's snapshot
        (row -1 = the chunk-start carried state in `prevs`) for
        full-vector `outs` from the host/native fold — the scan tier's
        mask semantics: raw values for degrees/labels, the
        consumer-visible ODD flag for the cover."""
        pd, pl, pc = prevs
        if "deg" in outs:
            outs["deg_chg"] = outs["deg"] != np.concatenate(
                [pd[None], outs["deg"][:-1]])
        if "labels" in outs:
            outs["labels_chg"] = (
                outs["labels"] != np.concatenate(
                    [pl[None], outs["labels"][:-1]]))
        if "cover" in outs:
            odd = (outs["cover"][:, :self.vb]
                   == outs["cover"][:, self.vb:])
            podd = (pc[:self.vb] == pc[self.vb:])[None]
            outs["cover_chg"] = odd != np.concatenate(
                [podd, odd[:-1]])
            outs["_odd_rows"] = odd  # reused at extraction

    # ------------------------------------------------------------------
    # delta-compacted d2h egress (ops/delta_egress): decode + fallback
    # ------------------------------------------------------------------
    @staticmethod
    def _delta_overflowed(outs: dict) -> bool:
        """True when any window's changed count exceeded the wire's
        [cap]-sized index row (its idx/val rows are then truncated —
        the chunk must refold on the host twin)."""
        for key in ("deg", "labels", "cover"):
            if key + "_cnt" in outs and int(
                    np.max(outs[key + "_cnt"])
                    ) > outs[key + "_idx"].shape[1]:
                return True
        return False

    def _chunk_start_state(self):
        """Fresh int32 copies of the carried mirrors in the host-fold
        layouts — the (deg, cc, cov) a chunk's host/native fold (or a
        delta-egress refold) may mutate freely without moving the real
        mirrors before the chunk boundary."""
        vb = self.vb
        deg32 = lab = cov = None
        if "degrees" in self.analytics:
            deg32 = np.zeros(vb, np.int32)
            deg32[:len(self._degrees)] = self._degrees
        if "cc" in self.analytics:
            lab = np.arange(vb, dtype=np.int32)
            lab[:len(self._cc)] = self._cc
        if "bipartite" in self.analytics:
            if len(self._bip) != 2 * vb:
                self._bip = self._grow_cover(self._bip, vb)
            cov = self._bip.astype(np.int32)
        return deg32, lab, cov

    def _refold_chunk_outs(self, chunk) -> dict:
        """Delta-egress overflow fallback: recompute one chunk's FULL
        snapshot rows with the bit-exact numpy twin
        (ops/host_snapshot) from the chunk-start mirrors. Rare by
        construction (a label cascade wider than the cap); exactness
        therefore never depends on the cap choice."""
        deg32, lab, cov = self._chunk_start_state()
        prevs = (tuple(a.copy() if a is not None else None
                       for a in (deg32, lab, cov))
                 if self.emit_deltas else None)
        flat_s = np.concatenate([s for _w, s, _d, _n in chunk])
        flat_d = np.concatenate([d for _w, _s, d, _n in chunk])
        offs = np.zeros(len(chunk) + 1, np.int64)
        offs[1:] = np.cumsum([len(s) for _w, s, _d, _n in chunk])
        outs = host_snapshot.snapshot_windows(
            flat_s, flat_d, offs, self.vb, deg32, lab, cov)
        if prevs is not None:
            self._host_mask_outs(outs, prevs)
        return outs

    def _emit_delta_chunk(self, chunk, outs: dict,
                          results: List[WindowResult]) -> None:
        """Decode one chunk's delta-egress wire: apply each window's
        (idx, vals) pairs to working copies of the carried mirrors —
        the working copy after window w IS window w's snapshot — and
        advance the mirrors to the chunk end. Bit-identical to the
        full-vector extraction: a changed-mask applied to the previous
        snapshot is exactly the next snapshot, and slots the window
        never touched are unchanged by definition."""
        vb = self.vb
        want_deg = "deg_cnt" in outs
        want_cc = "labels_cnt" in outs
        want_bip = "cover_cnt" in outs
        if want_deg:
            deg_work = np.zeros(vb, np.int64)
            deg_work[:len(self._degrees)] = self._degrees
        if want_cc:
            lab_work = np.arange(vb, dtype=np.int32)
            lab_work[:len(self._cc)] = self._cc
        if want_bip:
            if len(self._bip) != 2 * vb:
                self._bip = self._grow_cover(self._bip, vb)
            odd_work = self._bip[:vb] == self._bip[vb:2 * vb]
        for i, (wstart, s, d, nv) in enumerate(chunk):
            res = WindowResult(
                window_start=wstart, num_edges=len(s),
                vertex_ids=self._vertex_ids(nv))
            if want_deg:
                k = int(outs["deg_cnt"][i])
                idx = outs["deg_idx"][i][:k].copy()
                vals = outs["deg_val"][i][:k].astype(np.int64)
                self._check_degree_width(vals)
                delta_egress.apply_delta(deg_work, k, idx, vals)
                res.degrees = _snapshot_view(deg_work[:nv].copy())
                if self.emit_deltas:
                    res.delta_degrees = _frozen_delta(idx, vals)
            if want_cc:
                k = int(outs["labels_cnt"][i])
                idx = outs["labels_idx"][i][:k].copy()
                vals = outs["labels_val"][i][:k].copy()
                delta_egress.apply_delta(lab_work, k, idx, vals)
                res.cc_labels = _snapshot_view(lab_work[:nv].copy())
                if self.emit_deltas:
                    res.delta_cc = _frozen_delta(idx, vals)
            if want_bip:
                k = int(outs["cover_cnt"][i])
                idx = outs["cover_idx"][i][:k].copy()
                vals = outs["cover_val"][i][:k].copy()
                delta_egress.apply_delta(odd_work, k, idx, vals)
                res.bipartite_odd = _snapshot_view(odd_work[:nv].copy())
                if self.emit_deltas:
                    res.delta_bipartite = _frozen_delta(idx, vals)
            if "triangles" in self.analytics:
                self._tri_pending.append(
                    (res, np.asarray(s, np.int32),
                     np.asarray(d, np.int32)))
            results.append(res)
        # chunk boundary: mirrors advance to the chunk's final state —
        # degree/label mirrors ARE the fully-applied working copies;
        # the cover mirror resyncs from the carry the dispatch attached
        nv_chunk = chunk[-1][3]
        if want_deg:
            self._degrees = deg_work[:nv_chunk].copy()
            self._deg_state = None  # per-window path: rebuild
        if want_cc:
            self._cc = lab_work[:nv_chunk].copy()
        if want_bip:
            self._bip = np.asarray(outs["cover_final"])[:2 * vb].copy()

    def _stage_ckpt(self) -> None:
        """Stage a due auto-checkpoint instead of saving it inline.

        The batched path processes (and used to checkpoint) windows the
        stream consumer has not been handed yet; a crash after such a
        save silently DROPPED the un-yielded windows' results on resume
        (the skip cursor jumps past them — at-most-once delivery, found
        by tools/endurance_run.py phase B). Staged checkpoints are
        flushed only once every window they cover has been yielded
        (stream_file's _emit), so a crash can only ever re-emit
        already-computed windows from a deterministic re-feed —
        at-least-once, the reference's Flink checkpoint contract. One
        snapshot is queued per crossed boundary (a batch can cross
        several); staging itself happens at scan-chunk boundaries, so
        the flushed checkpoint can lag the consumer by up to one
        checkpoint interval PLUS one scan chunk. Outside a streaming
        generator (direct
        run_arrays callers get the whole result list in the same
        action) the stage flushes immediately — the old behavior."""
        self._ckpt_policy.mark(self.windows_done)
        snap = (self.windows_done, self.state_dict())
        if self._emitted is None:
            with self._step("checkpoint", 0):
                checkpoint.save(self._ckpt_path, snap[1])
            self._wal_retention.flushed(
                self._wal, self.tenant or "driver",
                int(snap[1]["wal_offset"]))
        else:
            self._pending_ckpt.append(snap)

    def _emit(self, results):
        """Yield a batch's WindowResults one by one, flushing each
        staged checkpoint the moment its coverage has been fully
        emitted (never before)."""
        for res in results:
            yield res
            self._emitted += 1
            flushed = None
            while (self._pending_ckpt
                    and self._pending_ckpt[0][0] <= self._emitted):
                flushed = self._pending_ckpt.pop(0)
            if flushed is not None:
                with self._step("checkpoint", 0):
                    checkpoint.save(self._ckpt_path, flushed[1])
                # journal-armed drivers refuse stream_file (so this
                # flush site normally has no journal), but the
                # retention contract holds wherever a flush lands
                self._wal_retention.flushed(
                    self._wal, self.tenant or "driver",
                    int(flushed[1]["wal_offset"]))

    @contextlib.contextmanager
    def _batched_triangles(self):
        """Collect the enclosed windows' triangle work and flush it as
        one batched count_windows dispatch. Flushes only on clean exit:
        an exception mid-call leaves the incomplete windows' `triangles`
        None rather than counts for windows the caller never saw."""
        if "triangles" not in self.analytics \
                or self._tri_pending is not None:
            yield
            return
        self._tri_pending = []
        try:
            yield
            pending = self._tri_pending
            if pending:
                edges = sum(len(s) for _r, s, _d in pending)
                windows = [(s, d) for _r, s, d in pending]
                with self._step("triangles", edges):
                    counts = self._flush_triangle_windows(windows)
                for (res, _s, _d), c in zip(pending, counts):
                    res.triangles = c
        finally:
            self._tri_pending = None

    def _flush_triangle_windows(self, windows) -> list:
        """Count the flush's windows down the SAME demotion ladder as
        the snapshot stage: the sharded kernel while the mesh lives,
        the single-chip device kernel after a mesh demotion, the
        pure-numpy twin once the device itself is gone — each rung's
        typed stage failure demotes and the next rung recounts only
        the windows the failed rung had not finalized (the sharded
        kernel drains its finalized counts)."""
        done: list = []
        while True:
            kern = self._tri_kern()
            try:
                return done + kern.count_windows(windows[len(done):])
            except resilience.StageError as e:
                tier = ("sharded"
                        if kern is self._sh_tri and self._mesh_live()
                        else self._demoted_tier or self._base_tier())
                if not self._maybe_demote(tier, e):
                    raise
                done += list(getattr(kern, "drained_counts", None)
                             or [])

    def _tri_kern(self):
        """The triangle kernel of the CURRENT tier: the sharded kernel
        while the mesh is live; the single-chip device kernel after a
        mesh demotion to the scan tier (built + warmed lazily, rebuilt
        if buckets grew since); the pure-numpy host twin
        (parallel/host_twin.HostTriangleWindowKernel) once the session
        has demoted past the device entirely (native/host rungs) —
        the availability floor must not compile against the dead
        backend it demoted away from."""
        if self._mesh_live() and self._sh_tri is not None:
            return self._sh_tri
        if self._demoted_tier in ("native", "host"):
            k = getattr(self, "_host_tri", None)
            if k is None or (k.eb, k.vb) != (self.eb, self.vb):
                from ..parallel.host_twin import HostTriangleWindowKernel

                k = self._host_tri = HostTriangleWindowKernel(
                    edge_bucket=self.eb, vertex_bucket=self.vb)
            return k
        if (self._tri_kernel is None or self._tri_kernel.eb != self.eb
                or self._tri_kernel.vb != self.vb):
            self._tri_kernel = tri_ops.TriangleWindowKernel(
                edge_bucket=self.eb, vertex_bucket=self.vb)
            self._tri_kernel.warm_chunks()
        return self._tri_kernel

    def _step(self, name: str, num_records: int):
        """Driver step timing: through the StepTimer when tracing is
        on (it forwards to the flight recorder), straight to a
        telemetry span otherwise — the recorder sees the driver's
        dispatch/materialize decomposition whether or not per-op
        tracing was requested (a no-op stopwatch when disarmed)."""
        if self.timer:
            return self.timer.step(name, num_records)
        return telemetry.span("step." + name, records=num_records)

    def _vertex_ids(self, nv: int) -> np.ndarray:
        """Slot → external-id table; slots are assigned once, so the
        cache only extends by the slots added since the last window
        (O(new) per window, not O(V))."""
        have = len(self._ext_ids)
        if nv > have:
            fresh = np.asarray(self.interner.ids_of(
                np.arange(have, nv, dtype=np.int32)))
            self._ext_ids = np.concatenate([self._ext_ids, fresh])
        # read-only view: the cache only ever grows by REALLOCATION
        # (np.concatenate), so an earlier window's view keeps pointing
        # at its own immutable snapshot of the table
        return _snapshot_view(self._ext_ids[:nv])

    def _prev_snapshots(self) -> dict:
        """Previous-window snapshot values for host-side delta diffing
        on the per-window path (the batched path gets its masks from
        the device scan instead). Single-chip reads the host mirrors;
        sharded syncs the engine state (one extra d2h — the per-window
        path already pays several per window)."""
        if self._mesh_live() and self._engine is not None:
            st = self._engine.state_dict()
            vb = st["vb"]
            prev = {"deg": np.asarray(st["degree_state"])[:vb].astype(
                np.int64),
                "cc": np.asarray(st["labels"])[:vb]}
            if "bip_labels" in st:
                cov = np.asarray(st["bip_labels"])
                prev["odd"] = cov[:vb] == cov[vb:2 * vb]
            return prev
        prev = {"deg": self._degrees, "cc": self._cc.copy()}
        if len(self._bip):
            _, _, odd = unionfind.decode_double_cover(
                self._bip, len(self._bip) // 2)
            prev["odd"] = odd
        return prev

    @staticmethod
    def _host_delta(new: np.ndarray, prev: np.ndarray, init):
        """(changed ids, new values) of `new` vs `prev` padded to its
        length with the analytic's start value (0 degrees / identity
        labels / False odd)."""
        full = np.empty(len(new), new.dtype)
        if init == "identity":
            full[:] = np.arange(len(new))
        else:
            full[:] = init
        n = min(len(prev), len(new))
        full[:n] = prev[:n]
        idx = np.nonzero(new != full)[0].astype(np.int32)
        return _frozen_delta(idx, new[idx])

    def _attach_host_deltas(self, res: WindowResult,
                            prev: dict) -> None:
        if res.degrees is not None:
            res.delta_degrees = self._host_delta(
                res.degrees, prev.get("deg", ()), 0)
        if res.cc_labels is not None:
            res.delta_cc = self._host_delta(
                res.cc_labels, prev.get("cc", ()), "identity")
        if res.bipartite_odd is not None:
            res.delta_bipartite = self._host_delta(
                np.asarray(res.bipartite_odd),
                np.asarray(prev.get("odd", ()), bool), False)

    def _window(self, wstart: int, src: np.ndarray,
                dst: np.ndarray) -> WindowResult:
        prev = self._prev_snapshots() if self.emit_deltas else None
        with self._step("intern", 2 * len(src)):
            s = self.interner.intern_array(src)
            d = self.interner.intern_array(dst)
        nv = len(self.interner)
        self._ensure_buckets(nv, len(src))
        res = WindowResult(
            window_start=wstart, num_edges=len(src),
            vertex_ids=self._vertex_ids(nv),
        )
        for name in self.analytics:
            if name == "triangles" and self._tri_pending is not None:
                # deferred to the batched flush, which logs the real
                # 'triangles' step — timing the append here would
                # double-count the records at a near-infinite rate
                self._run_one(name, s, d, nv, res)
            else:
                with self._step(name, len(src)):
                    self._run_one_laddered(name, s, d, nv, res)
        if prev is not None:
            self._attach_host_deltas(res, prev)
        if self._wp > 1:
            # rotate the pane ring AFTER the analytics saw the prior
            # panes: the next emission's triangle slab is the last
            # wp−1 panes plus its own
            self._pane_ring.append((np.asarray(s, np.int32).copy(),
                                    np.asarray(d, np.int32).copy()))
            del self._pane_ring[:-(self._wp - 1)]
        if latency.enabled():
            rec = latency.on_window(self.tenant or "driver",
                                    edges=len(src),
                                    ordinal=self.windows_done)
            if rec is not None:
                res.latency = {"e2e_s": rec["e2e_s"],
                               "stages": dict(rec["stages"]),
                               "replayed": rec["replayed"]}
        if provenance.armed():
            provenance.emit(
                tenant=self.tenant or "driver",
                window=self.windows_done,
                wal_lo=self.edges_done,
                wal_hi=self.edges_done + len(src),
                tier=self._demoted_tier or self._base_tier(),
                program="driver",
                digest=provenance.result_digest(res))
        self.windows_done += 1
        self.edges_done += len(src)
        metrics.mark_window(
            1, len(src), engine="driver",
            tier=self._demoted_tier or self._base_tier(),
            mesh_shape=self._mesh_shape(),
            tenant=self.tenant)
        if self._ckpt_due():
            self._stage_ckpt()
        return res

    @staticmethod
    def _check_degree_width(snap: np.ndarray) -> None:
        """Device degree state is int32 (TPUs run 32-bit; x64 is a
        global jax switch). A window adds < 2^32 endpoint counts, so a
        vertex crossing 2^31 shows up negative at the very next
        snapshot — fail loudly there instead of persisting a wrapped
        count into checkpoints."""
        if len(snap) and int(snap.min()) < 0:
            raise OverflowError(
                "a vertex's running degree crossed 2^31 (int32 device "
                "state); shard the stream or reset windows before any "
                "single vertex accumulates that many incident edges")

    @staticmethod
    def _grow_cover(old: np.ndarray, vb: int) -> np.ndarray:
        """Re-lay a double-cover labeling out over a wider vertex
        bucket: (−) slots move from old_vb+v to vb+v, labels pointing
        into the (−) half shift with them, new slots are identity."""
        old_vb = len(old) // 2
        cover = np.arange(2 * vb, dtype=np.int32)
        if old_vb:
            shifted = np.where(old >= old_vb, old + (vb - old_vb),
                               old).astype(np.int32)
            cover[:old_vb] = shifted[:old_vb]
            cover[vb:vb + old_vb] = shifted[old_vb:]
        return cover

    def _run_one_laddered(self, name: str, s: np.ndarray,
                          d: np.ndarray, nv: int,
                          res: WindowResult) -> None:
        """One per-window analytic under the SAME demotion ladder as
        the batched path: a typed stage failure on the mesh demotes
        and re-runs THIS analytic on the single-chip tier (the mirrors
        — refreshed per window — already hold every earlier analytic's
        state, and the failed dispatch itself is pure-rebind, so the
        retry never double-applies). Event-time mesh sessions
        therefore degrade instead of wedging, same as run_arrays."""
        try:
            self._run_one(name, s, d, nv, res)
        except resilience.StageError as e:
            if not (self._mesh_live()
                    and self._maybe_demote("sharded", e)):
                raise
            self._run_one(name, s, d, nv, res)

    def _run_one(self, name: str, s: np.ndarray, d: np.ndarray,
                 nv: int, res: WindowResult) -> None:
        # tier-aware: a mesh session demoted off the sharded rung runs
        # the single-chip per-window kernels against the host mirrors
        sharded = self._mesh_live() and self._engine is not None
        if name == "degrees":
            if sharded:
                snap = np.array(self._engine.degrees(s, d)[:nv])
                self._check_degree_width(snap)
                # mirror tracks every window boundary (host-side copy
                # of an already-gathered array): the demotion hand-off
                # never needs a gather from a failing mesh
                self._degrees = snap.astype(np.int64)
                res.degrees = _snapshot_view(snap)
            else:
                import jax.numpy as jnp

                # carried device state (length vb+1; slot vb is the
                # padding sentinel), lazily (re)built from the host
                # mirror after construction, reset, resume, or growth.
                # int32 on device — the same width the sharded engine
                # carries; snapshots widen back to the int64 contract.
                if (self._deg_state is None
                        or len(self._deg_state) != self.vb + 1):
                    st = np.zeros(self.vb + 1, np.int32)
                    st[:len(self._degrees)] = self._degrees
                    self._deg_state = jnp.asarray(st)
                # clamp small batches UP to the steady edge bucket: the
                # stream's final partial window reuses the compiled
                # steady-state program instead of a fresh tiny-bucket
                # ladder at the tail (tools/endurance_run.py)
                nb = seg_ops.bucket_size(max(len(s), self.eb))
                sp = seg_ops.pad_to(np.asarray(s, np.int32), nb,
                                    fill=self.vb)
                dp = seg_ops.pad_to(np.asarray(d, np.int32), nb,
                                    fill=self.vb)
                self._deg_state = seg_ops.degree_update(
                    self._deg_state, jnp.asarray(sp), jnp.asarray(dp))
                # slice on the HOST: jnp's [:nv] would trace a fresh
                # dynamic_slice program for every distinct vertex count
                # (one recompile per window on a growing stream)
                snap = np.asarray(self._deg_state)[:nv].astype(np.int64)
                self._check_degree_width(snap)
                self._degrees = snap  # host mirror: checkpoint source
                res.degrees = _snapshot_view(snap.copy())
        elif name == "cc":
            if sharded:
                lab = np.array(self._engine.cc_labels(s, d)[:nv])
                self._cc = lab.copy()  # mirror: see degrees above
                res.cc_labels = _snapshot_view(lab)
            else:
                if len(self._cc) < nv:
                    self._cc = np.concatenate([
                        self._cc,
                        np.arange(len(self._cc), nv, dtype=np.int32)])
                self._cc = unionfind.connected_components_with_labels(
                    s, d, self._cc, nv, vertex_bucket=self.vb,
                    edge_bucket=self.eb)
                res.cc_labels = _snapshot_view(self._cc.copy())
        elif name == "bipartite":
            if sharded:
                _, _, odd = self._engine.bipartite(s, d)
                # mirror: see degrees above (one extra d2h of the
                # cover labels — the per-window path already pays
                # several per window)
                self._bip = np.asarray(
                    self._engine._bip_labels)[:2 * self.vb].astype(
                        np.int32)
                res.bipartite_odd = _snapshot_view(np.array(odd[:nv]))
            else:
                # cover layout is VERTEX-BUCKET based ((+) = v,
                # (−) = vb + v), so the kernel shape depends only on
                # buckets: re-layout happens on the O(log V) bucket
                # doublings, not on every window's new vertex count
                # (which recompiled cc_fixpoint per window in round 2)
                if len(self._bip) != 2 * self.vb:
                    self._bip = self._grow_cover(self._bip, self.vb)
                s2, d2 = unionfind.double_cover_edges(s, d, self.vb)
                self._bip = unionfind.connected_components_with_labels(
                    s2, d2, self._bip, 2 * self.vb,
                    edge_bucket=2 * self.eb)
                _, _, odd = unionfind.decode_double_cover(self._bip,
                                                          self.vb)
                res.bipartite_odd = _snapshot_view(odd[:nv])
        elif name == "triangles":
            # sliding: the per-window count runs on the COMPOSED slab
            # (ring panes + this pane, ≤ eb edges) — the emission's
            # window is the last panes_per_window panes, and triangles
            # is the only non-cumulative analytic so only it recomputes
            s, d = self._tri_window_edges(s, d)
            if self._tri_pending is not None:
                # batched mode (run_arrays): defer — all of the call's
                # windows go to the device in ONE count_windows stack
                # dispatch instead of one dispatch per window (dispatch
                # latency through a tunneled chip ~0.2s dominates)
                self._tri_pending.append(
                    (res, np.asarray(s, np.int32), np.asarray(d, np.int32)))
            else:
                res.triangles = self._tri_kern().count(s, d)

    def _tri_window_edges(self, s: np.ndarray, d: np.ndarray):
        """The triangle window slab of the CURRENT emission under
        sliding windows: the ring's ≤ panes_per_window−1 prior interned
        panes concatenated with this pane (≤ eb edges total, so the
        full-window kernels fit unchanged). Interned slot ids are
        stable across windows, so ring panes stay valid as the
        vocabulary grows. Tumbling (wp == 1) passes through."""
        if self._wp == 1 or not self._pane_ring:
            return s, d
        ss = [ps for ps, _pd in self._pane_ring]
        dd = [pd for _ps, pd in self._pane_ring]
        ss.append(np.asarray(s, np.int32))
        dd.append(np.asarray(d, np.int32))
        return np.concatenate(ss), np.concatenate(dd)

    # ------------------------------------------------------------------
    # checkpoint / resume + failure recovery (utils/checkpoint.py)
    # ------------------------------------------------------------------
    def enable_auto_checkpoint(self, path: str,
                               every_n_windows: int = 16,
                               every_seconds: float = 0.0,
                               policy=None) -> None:
        """Snapshot all carried state to `path` (atomic replace +
        last-2 rotation, utils/checkpoint.save) on a
        `CheckpointPolicy` cadence: every N processed windows and/or
        every T seconds, whichever comes first — the failure-recovery
        hook the reference's combine-fn javadoc alludes to but never
        implements (library/ConnectedComponents.java:117-118). Pass
        `policy` (a utils.checkpoint.CheckpointPolicy) to inject a
        deterministic clock.

        Granularity: the per-window path checks the cadence on every
        window; the batched fast path at its chunk boundaries (every
        _SCAN_CHUNK=64 windows) — a crash loses at most
        max(N, 64) windows of work (or one time interval plus a
        chunk)."""
        if policy is None:
            if every_n_windows < 1 and every_seconds <= 0:
                raise ValueError(
                    "need every_n_windows >= 1 and/or every_seconds > 0")
            policy = checkpoint.CheckpointPolicy(
                every_n_windows=max(0, every_n_windows),
                every_seconds=every_seconds)
        if not policy.enabled():
            raise ValueError("checkpoint policy has no trigger enabled")
        self._ckpt_path = path
        self._ckpt_policy = policy

    def _ckpt_due(self) -> bool:
        return (self._ckpt_path is not None
                and self._ckpt_policy.due(self.windows_done))

    def try_resume(self, path: str) -> bool:
        """Restore from `path` if a readable checkpoint exists; returns
        whether state was restored. After resume, `windows_done` is the
        cursor of fully-processed windows — feed the stream from there.

        An UNREADABLE generation (truncated/corrupt — possible only
        through external damage, since save() writes atomically via
        tmp+rename) falls back to the rotated previous checkpoint
        (`checkpoint.load_latest`); only when every generation is
        damaged does resume behave like a missing checkpoint: warn and
        return False, so the caller reprocesses from the start, which
        is always correct. SEMANTIC mismatches (cross-mode, window
        size) still raise from load_state_dict — those need an
        operator decision, not a silent full reprocess — and so do
        OPERATIONAL failures (PermissionError / EIO / out-of-memory):
        the file may be intact, and silently reprocessing a
        multi-million-edge stream would mask a fixable problem."""
        import warnings

        try:
            got = checkpoint.load_latest(path)
        except checkpoint.CheckpointCorrupt as e:
            warnings.warn(f"{e}; no intact generation — starting fresh")
            return False
        if got is None:
            return False
        state, used = got
        if used != path:
            warnings.warn(
                f"checkpoint {path!r} is corrupt; resumed from the "
                f"rotated previous generation {used!r}")
        self.load_state_dict(state)
        # durable stamp: pairs with the pre-kill spans under the
        # process's one trace ID, so a crash/resume reads as a single
        # timeline in the run ledger (asserted by tools/chaos_run.py)
        telemetry.event("resume", durable=True, component="driver",
                        path=used, windows_done=self.windows_done)
        return True

    def enable_wal(self, directory: str) -> bool:
        """Journal every LIVE run_arrays() feed under `directory`
        (utils/wal.py) before any window is cut — the durable,
        replayable source the file path already is and the live path
        never was. After a kill, `resume_and_replay(ckpt)` restores
        the newest checkpoint and re-feeds the journal suffix past
        its `wal_offset`, reproducing the lost windows bit-exactly.
        A journal-armed driver REFUSES stream_file() (the file is its
        own journal; mixing the sources would skew the offset
        contract). Returns False (a no-op) under GS_WAL=0."""
        if not wal_mod.enabled():
            return False
        self._wal_dir = directory
        self._wal = wal_mod.WriteAheadLog(directory)
        return True

    def seal_wal(self) -> None:
        """Durably close the journal (the clean-drain marker)."""
        if self._wal is not None:
            self._wal.seal()

    def resume_and_replay(self, ckpt_path: str) -> List[WindowResult]:
        """Kill recovery for a journal-armed driver: try_resume the
        newest checkpoint generation, then replay the journal suffix
        past the checkpointed `wal_offset` through run_arrays().
        Returns the replayed WindowResults — every window the crashed
        process computed (or had accepted) but never delivered."""
        self.try_resume(ckpt_path)
        if self._wal_dir is None:
            return []
        tenant = self.tenant or "driver"
        off = self.edges_done
        parts = []
        for tid, _start, src, dst, ts in wal_mod.replay(
                self._wal_dir, {tenant: off}):
            if tid == tenant:
                parts.append((src, dst, ts))
        edges = sum(len(p[0]) for p in parts)
        telemetry.event("wal_replayed", durable=True,
                        component="driver", dir=self._wal_dir,
                        edges=edges)
        metrics.counter_inc("gs_wal_replayed_edges_total", edges)
        if not edges:
            return []
        src = np.concatenate([p[0] for p in parts])
        dst = np.concatenate([p[1] for p in parts])
        ts = (np.concatenate([p[2] for p in parts])
              if all(p[2] is not None for p in parts) else None)
        # suspend journaling for the replay feed: these edges are
        # already in the journal
        live, self._wal = self._wal, None
        try:
            return self.run_arrays(src, dst, ts)
        finally:
            self._wal = live

    def state_dict(self) -> dict:
        state = {
            "window_ms": self.window_ms,
            "analytics": list(self.analytics),
            "sharded": self.mesh is not None,
            "mesh_shape": self._mesh_shape(),  # gslint: disable=ckpt-symmetry (provenance: load converts cross-mesh, never needs the source shape back)
            "windows_done": self.windows_done,
            "edges_done": self.edges_done,
            # journal offset at this finalized-window boundary (the
            # edges_done cursor IS the cumulative live-feed edge
            # count): resume_and_replay() re-feeds the WAL strictly
            # past it (DESIGN.md §18)
            "wal_offset": self.edges_done,
            "edge_bucket": self.eb,
            "vertex_bucket": self.vb,
            "closed_partial": self._closed_partial,
            "vertex_ids": np.array(self._vertex_ids(len(self.interner))),
            "degrees": self._degrees.copy(),
            "cc": self._cc.copy(),
            "bip": self._bip.copy(),
        }
        if self._wp > 1:
            # sliding: the pane ring rides the checkpoint so a resumed
            # stream's next emissions compose the SAME triangle slabs
            # the killed run would have (interned ids are stable:
            # load re-interns vertex_ids in insertion order)
            state["slide"] = self.slide
            state["pane_ring_src"] = [s.copy()
                                      for s, _d in self._pane_ring]
            state["pane_ring_dst"] = [d.copy()
                                      for _s, d in self._pane_ring]
        if self._engine is not None:
            # demoted mesh session: the host mirrors carried the
            # stream since the demotion — the checkpoint assembles
            # the engine slabs from them on the HOST, never touching
            # (or persisting stale state from) the dead mesh
            state["engine"] = (self._engine.state_dict()
                               if self._mesh_live()
                               else self._engine_state_from_mirrors())
        if getattr(self, "_scan_tuner", None) is not None:
            # the learned dispatch configuration rides the checkpoint
            # so a resumed stream keeps its optimum (ops/autotune)
            state["autotune"] = self._scan_tuner.state_dict()
        if getattr(self, "_resident_tuner", None) is not None:
            # the resident tier's windows-per-superbatch tuner rides
            # beside it under its own key (distinct arm space)
            state["autotune_resident"] = \
                self._resident_tuner.state_dict()
        return state

    def load_state_dict(self, state: dict) -> None:
        if state["window_ms"] != self.window_ms:
            raise ValueError("window size mismatch")
        if tuple(state["analytics"]) != self.analytics:
            raise ValueError(
                f"analytics mismatch: checkpoint has "
                f"{state['analytics']}, driver runs {list(self.analytics)}")
        self.interner = make_interner(np.array([0]))
        self._ext_ids = np.zeros(0, np.int64)
        self.windows_done = int(state.get("windows_done", 0))
        self.edges_done = int(state.get("edges_done", 0))
        woff = state.get("wal_offset")
        if woff is not None and int(woff) != self.edges_done:
            # the journal offset is DEFINED as the folded-edge cursor;
            # divergence means a hand-edited checkpoint that would
            # replay a hole or a double-fold — refuse loudly
            raise ValueError(
                "checkpoint wal_offset %d disagrees with its own "
                "edges_done cursor %d" % (int(woff), self.edges_done))
        # persist the misuse guard: a checkpoint taken after a partial
        # count-based window must refuse further unaligned feeding just
        # like the live driver would
        self._closed_partial = bool(state.get("closed_partial", False))
        ckpt_slide = state.get("slide")
        if (int(ckpt_slide) if ckpt_slide else None) != self.slide:
            # pane-boundary math is governed by slide exactly as window
            # cuts are by eb: a mismatched resume would silently shift
            # every subsequent emission's slab — refuse loudly
            raise ValueError(
                "slide mismatch: checkpoint has %r, driver runs %r"
                % (ckpt_slide, self.slide))
        self._pane_ring = [
            (np.asarray(s, np.int32), np.asarray(d, np.int32))
            for s, d in zip(state.get("pane_ring_src", []),
                            state.get("pane_ring_dst", []))]
        if "edge_bucket" in state:
            # count-based windowing is governed by eb exactly as event
            # time is by window_ms: restore it so resumed streams cut
            # the same windows the checkpointed run would have
            self.eb = int(state["edge_bucket"])
        if "vertex_bucket" in state:
            # adopt the checkpointed capacity up front; without this a
            # sharded resume built with a different vertex_bucket dies
            # deep in ShardedWindowEngine.load_state_dict with a
            # 'vertex bucket mismatch' that never names the parameter.
            # Single-chip keeps a LARGER pre-sized constructor bucket
            # (carried host mirrors re-lay out lazily), so resuming a
            # small checkpoint doesn't re-introduce the bucket-doubling
            # recompiles the caller pre-sized to avoid.
            ckpt_vb = int(state["vertex_bucket"])
            self.vb = (ckpt_vb if self.mesh is not None
                       else max(self.vb, ckpt_vb))
            # force rebuild of everything compiled at the old capacity
            self._engine = None
            self._tri_kernel = None
            self._sh_tri = None
        self.interner.intern_array(np.asarray(state["vertex_ids"],
                                              np.int64))
        self._degrees = np.array(state["degrees"])
        self._deg_state = None  # rebuilt from the mirror on next window
        self._cc = np.array(state["cc"])
        self._bip = np.array(state["bip"])
        self._ensure_buckets(len(state["vertex_ids"]), 1)
        # cross-MODE resume: the engine's carried slabs are gathered
        # replicated state (shard-count independent — parallel/sharded
        # state_dict), so a mesh checkpoint converts to the single-chip
        # mirrors and vice versa. A 4-shard checkpoint therefore
        # resumes on any mesh width, on 1 device, or on the host tier.
        ckpt_sharded = bool(state.get("sharded", False))
        if self._engine is not None:
            if "engine" in state:
                self._engine.load_state_dict(state["engine"])
            elif not ckpt_sharded:
                # single-chip checkpoint onto a mesh: mirrors → slabs
                self._sync_engine_from_mirrors()
        elif ckpt_sharded and "engine" in state:
            # mesh checkpoint onto a single-chip driver: slabs →
            # mirrors (the checkpointed mirrors are empty in sharded
            # mode — the engine state is the truth)
            self._absorb_engine_state(state["engine"])
        # .get: checkpoints predating the autotune key restore cleanly;
        # with GS_AUTOTUNE=0 the state is carried nowhere (inert)
        if state.get("autotune") is not None and self.mesh is None:
            tuner = self._ensure_scan_tuner()
            if tuner is not None:
                tuner.load_state_dict(state["autotune"])
        if state.get("autotune_resident") is not None \
                and self.mesh is None:
            tuner = self._ensure_resident_tuner()
            if tuner is not None:
                tuner.load_state_dict(state["autotune_resident"])

    def trace_report(self) -> List[dict]:
        return self.timer.report() if self.timer else []
